"""trino_tpu — a TPU-native distributed SQL query engine.

A brand-new engine with the capabilities of Trino (reference:
core/trino-main, core/trino-spi of the Java engine), re-designed
TPU-first:

- Columnar data lives as device-resident struct-of-arrays ``Page``s
  (JAX arrays + validity masks + host-side string dictionaries) — the
  analog of the reference's ``io.trino.spi.Page`` / sealed ``Block``
  hierarchy (SPI/Page.java:31, SPI/block/Block.java:26).
- Expressions compile to jitted JAX functions per (expression, layout) —
  the analog of runtime bytecode generation in
  core/trino-main/.../sql/gen/ExpressionCompiler.java:56.
- Relational operators are shape-stable XLA/Pallas computations
  (sort-based segment reduction for aggregation, searchsorted probes for
  hash joins) rather than per-row loops.
- Distribution is a stage DAG whose hash/broadcast exchanges lower to
  ``lax.all_to_all`` / ``all_gather`` over an ICI ``jax.sharding.Mesh``
  (replacing the reference's HTTP page shuffle,
  MAIN/operator/DirectExchangeClient.java:56).
"""

import jax

# SQL semantics need 64-bit integers (BIGINT) and binary64 doubles. The
# engine owns the process the way a Trino server owns its JVM, so we
# enable x64 globally at import.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from trino_tpu.types import (  # noqa: E402
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    INTEGER,
    REAL,
    SMALLINT,
    TINYINT,
    VARCHAR,
    DecimalType,
    DataType,
)
from trino_tpu.page import Column, Page  # noqa: E402

__all__ = [
    "BIGINT",
    "BOOLEAN",
    "DATE",
    "DOUBLE",
    "INTEGER",
    "REAL",
    "SMALLINT",
    "TINYINT",
    "VARCHAR",
    "DecimalType",
    "DataType",
    "Column",
    "Page",
]
