"""Process-wide device-resident scan-page cache.

The serving layer runs many concurrent queries in one process, and the
hot TPC tables they scan are identical. Historically each executor
owned its own ``_scan_cache`` dict, so every executor paid its own
host->HBM transfer for the same table — fine when one long-lived
executor served every statement, wasteful the moment several engines
coexist (a coordinator's embedded runner, per-group fleet planners,
test fixtures). This module hoists that storage to a single
process-wide cache, the analog of the reference's worker-shared memory
connector pages.

Keying is by *connector fingerprint* (cache.connector_fingerprint):
connectors that implement ``cache_fingerprint()`` — parquet, whose
ident is the root path and whose content digests footer sizes+mtimes —
share entries across connector INSTANCES over the same files, and an
out-of-band rewrite flips the content digest, dropping every stale
page at the next probe. Connectors without the hook get a per-instance
token, preserving the historical isolation contract (two TpchConnector
fixtures with independently mutated tables never share), with a weak
finalizer so dropping the last connector reference frees its device
pages.

DML invalidation routes here too: a write through ANY executor drops
the shared entry, so a concurrent reader re-scans instead of serving
pages observed before the write.

Hit/miss traffic surfaces as ``trino_scan_cache_{hits,misses}_total``.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict

__all__ = ["ScanPageCache", "SplitBatchCache", "SHARED", "SHARED_SPLITS"]


def _fingerprint(connector) -> tuple[str, str]:
    from trino_tpu.cache import connector_fingerprint

    return connector_fingerprint(connector)


class ScanPageCache:
    """connector fingerprint -> (schema, table) -> per-table page dict.

    The per-table dict is the same shape executors always used:
    column-cache-key -> device Column, ``""`` -> validity mask,
    ``"#rows"`` -> row count. Callers mutate it in place under the
    engine's execution serialization; this class only guards the
    *map* structure with its own lock so concurrent executors can
    resolve tables without racing the map.
    """

    def __init__(self):
        self._lock = threading.Lock()
        #: ident -> [content, {(schema, table): page dict}]
        self._by_ident: dict[str, list] = {}
        #: idents with a registered instance finalizer
        self._watched: set[str] = set()

    def table(self, connector, schema: str, table: str) -> dict:
        """The live page dict for one table (created empty on first
        use). Records a hit when the table is already device-resident
        (mask present — columns may still be added lazily), a miss
        when this call created the entry."""
        from trino_tpu import telemetry

        ident, content = _fingerprint(connector)
        with self._lock:
            ent = self._by_ident.get(ident)
            if ent is not None and ent[0] != content:
                # on-disk content changed out-of-band: every page
                # under this ident was observed against old bytes
                ent = None
            if ent is None:
                ent = self._by_ident[ident] = [content, {}]
                if ident.startswith("id:") and ident not in self._watched:
                    # instance-keyed entries die with the connector
                    self._watched.add(ident)
                    weakref.finalize(connector, self._drop_ident, ident)
            tables = ent[1]
            cache = tables.get((schema, table))
            if cache is not None and "" in cache:
                telemetry.SCAN_CACHE_HITS.inc(table=table)
            else:
                telemetry.SCAN_CACHE_MISSES.inc(table=table)
            if cache is None:
                cache = tables[(schema, table)] = {}
            return cache

    def _drop_ident(self, ident: str) -> None:
        with self._lock:
            self._watched.discard(ident)
            self._by_ident.pop(ident, None)

    def invalidate(self, connector, schema: str, table: str) -> None:
        """Drop one table's pages (after DML through any executor)."""
        ident, _content = _fingerprint(connector)
        with self._lock:
            ent = self._by_ident.get(ident)
            if ent is not None:
                ent[1].pop((schema, table), None)

    def resident_tables(self, connector) -> list[tuple[str, str]]:
        """(schema, table) pairs currently device-resident for one
        connector (observability/tests)."""
        ident, content = _fingerprint(connector)
        with self._lock:
            ent = self._by_ident.get(ident)
            if ent is None or ent[0] != content:
                return []
            return [k for k, v in ent[1].items() if "" in v]

    def snapshot(self) -> dict:
        """entries/bytes across every resident table page dict
        (system.runtime.caches feed)."""
        entries = 0
        nbytes = 0
        with self._lock:
            for _content, tables in self._by_ident.values():
                for cache in tables.values():
                    if "" not in cache:
                        continue
                    entries += 1
                    for k, v in cache.items():
                        if k == "#rows":
                            continue
                        if k == "":
                            nbytes += getattr(v, "nbytes", 0) or 0
                        else:
                            nbytes += getattr(
                                getattr(v, "data", None), "nbytes", 0
                            ) or 0
                            valid = getattr(v, "valid", None)
                            if valid is not None:
                                nbytes += getattr(valid, "nbytes", 0) or 0
        return {"entries": entries, "bytes": nbytes}

    def clear(self) -> None:
        with self._lock:
            self._by_ident.clear()


class SplitBatchCache:
    """Byte-bounded LRU for streamed-split host batches.

    Whole-table identity caching (above) is exactly wrong for
    out-of-core scans: pinning every page of an SF100 table would
    recreate the memory problem streaming exists to avoid. Streamed
    reads instead cache per-(fingerprint, schema, table, row-range,
    columns) host batches with an LRU bounded by total bytes, so a hot
    working set (dimension tables, re-scanned probe splits) stays warm
    while a single pass over a huge fact table churns through without
    accumulating. Fingerprint keying shares batches across connector
    instances over the same files; instance-keyed idents carry a weak
    finalizer that drops the connector's entries when it is collected —
    same isolation contract as ScanPageCache without pinning the
    connector alive."""

    def __init__(self, max_bytes: int = 256 << 20):
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._bytes = 0
        self._watched: set[str] = set()
        #: ident -> content digest its entries were observed under
        self._content: dict[str, str] = {}

    @staticmethod
    def _size(batch: dict) -> int:
        total = 0
        for v in batch.values():
            if isinstance(v, tuple):
                for a in v:
                    total += getattr(a, "nbytes", 0) or 0
            else:
                total += getattr(v, "nbytes", 0) or 0
        return total

    def _sync_content_locked(self, ident: str, content: str) -> None:
        """Drop an ident's entries when its on-disk content changed."""
        if self._content.get(ident, content) != content:
            for k in [k for k in self._entries if k[0] == ident]:
                self._bytes -= self._size(self._entries.pop(k))
        self._content[ident] = content

    def get(self, connector, schema, table, start, count, columns):
        from trino_tpu import telemetry

        ident, content = _fingerprint(connector)
        k = (ident, schema, table, start, count, tuple(columns))
        with self._lock:
            self._sync_content_locked(ident, content)
            batch = self._entries.get(k)
            if batch is not None:
                self._entries.move_to_end(k)
                telemetry.SCAN_CACHE_HITS.inc(table=table)
                return batch
        telemetry.SCAN_CACHE_MISSES.inc(table=table)
        return None

    def put(self, connector, schema, table, start, count, columns, batch):
        size = self._size(batch)
        if size > self.max_bytes:
            return  # a batch bigger than the cache would evict everything
        ident, content = _fingerprint(connector)
        k = (ident, schema, table, start, count, tuple(columns))
        with self._lock:
            self._sync_content_locked(ident, content)
            if ident.startswith("id:") and ident not in self._watched:
                self._watched.add(ident)
                weakref.finalize(connector, self._drop_ident, ident)
            old = self._entries.pop(k, None)
            if old is not None:
                self._bytes -= self._size(old)
            self._entries[k] = batch
            self._bytes += size
            while self._bytes > self.max_bytes and self._entries:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= self._size(evicted)

    def _drop_ident(self, ident: str) -> None:
        with self._lock:
            self._watched.discard(ident)
            self._content.pop(ident, None)
            for k in [k for k in self._entries if k[0] == ident]:
                self._bytes -= self._size(self._entries.pop(k))

    def invalidate(self, connector, schema: str, table: str) -> None:
        ident, _content = _fingerprint(connector)
        with self._lock:
            dead = [
                k for k in self._entries
                if k[0] == ident and k[1:3] == (schema, table)
            ]
            for k in dead:
                self._bytes -= self._size(self._entries.pop(k))

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._content.clear()


#: the process-wide cache every LocalExecutor scans through
SHARED = ScanPageCache()

#: the process-wide streamed-split host-batch cache
SHARED_SPLITS = SplitBatchCache()
