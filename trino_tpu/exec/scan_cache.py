"""Process-wide device-resident scan-page cache.

The serving layer runs many concurrent queries in one process, and the
hot TPC tables they scan are identical. Historically each executor
owned its own ``_scan_cache`` dict, so every executor paid its own
host->HBM transfer for the same table — fine when one long-lived
executor served every statement, wasteful the moment several engines
coexist (a coordinator's embedded runner, per-group fleet planners,
test fixtures). This module hoists that storage to a single
process-wide cache, the analog of the reference's worker-shared memory
connector pages: scanned device pages are keyed by the *connector
instance* that produced them, so any executor scanning the same
connector reuses the resident pages.

Identity keying is the isolation contract: two TpchConnector instances
with independently mutated tables (different test fixtures, different
catalogs) never share entries because the connector object itself is
the key. A ``WeakKeyDictionary`` makes the connector's lifetime the
cache's lifetime — dropping the last metadata reference frees its
device pages without an explicit close hook.

DML invalidation routes here too: a write through ANY executor drops
the shared entry, so a concurrent reader re-scans instead of serving
pages observed before the write.

Hit/miss traffic surfaces as ``trino_scan_cache_{hits,misses}_total``.
"""

from __future__ import annotations

import threading
import weakref

__all__ = ["ScanPageCache", "SHARED"]


class ScanPageCache:
    """connector instance -> (schema, table) -> per-table page dict.

    The per-table dict is the same shape executors always used:
    column-cache-key -> device Column, ``""`` -> validity mask,
    ``"#rows"`` -> row count. Callers mutate it in place under the
    engine's execution serialization; this class only guards the
    *map* structure with its own lock so concurrent executors can
    resolve tables without racing the weak map.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._by_connector: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )

    def table(self, connector, schema: str, table: str) -> dict:
        """The live page dict for one table (created empty on first
        use). Records a hit when the table is already device-resident
        (mask present — columns may still be added lazily), a miss
        when this call created the entry."""
        from trino_tpu import telemetry

        with self._lock:
            tables = self._by_connector.get(connector)
            if tables is None:
                tables = {}
                self._by_connector[connector] = tables
            cache = tables.get((schema, table))
            if cache is not None and "" in cache:
                telemetry.SCAN_CACHE_HITS.inc(table=table)
            else:
                telemetry.SCAN_CACHE_MISSES.inc(table=table)
            if cache is None:
                cache = tables[(schema, table)] = {}
            return cache

    def invalidate(self, connector, schema: str, table: str) -> None:
        """Drop one table's pages (after DML through any executor)."""
        with self._lock:
            tables = self._by_connector.get(connector)
            if tables is not None:
                tables.pop((schema, table), None)

    def resident_tables(self, connector) -> list[tuple[str, str]]:
        """(schema, table) pairs currently device-resident for one
        connector (observability/tests)."""
        with self._lock:
            tables = self._by_connector.get(connector) or {}
            return [k for k, v in tables.items() if "" in v]

    def clear(self) -> None:
        with self._lock:
            self._by_connector.clear()


#: the process-wide cache every LocalExecutor scans through
SHARED = ScanPageCache()
