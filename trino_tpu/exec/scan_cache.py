"""Process-wide device-resident scan-page cache.

The serving layer runs many concurrent queries in one process, and the
hot TPC tables they scan are identical. Historically each executor
owned its own ``_scan_cache`` dict, so every executor paid its own
host->HBM transfer for the same table — fine when one long-lived
executor served every statement, wasteful the moment several engines
coexist (a coordinator's embedded runner, per-group fleet planners,
test fixtures). This module hoists that storage to a single
process-wide cache, the analog of the reference's worker-shared memory
connector pages: scanned device pages are keyed by the *connector
instance* that produced them, so any executor scanning the same
connector reuses the resident pages.

Identity keying is the isolation contract: two TpchConnector instances
with independently mutated tables (different test fixtures, different
catalogs) never share entries because the connector object itself is
the key. A ``WeakKeyDictionary`` makes the connector's lifetime the
cache's lifetime — dropping the last metadata reference frees its
device pages without an explicit close hook.

DML invalidation routes here too: a write through ANY executor drops
the shared entry, so a concurrent reader re-scans instead of serving
pages observed before the write.

Hit/miss traffic surfaces as ``trino_scan_cache_{hits,misses}_total``.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict

__all__ = ["ScanPageCache", "SplitBatchCache", "SHARED", "SHARED_SPLITS"]


class ScanPageCache:
    """connector instance -> (schema, table) -> per-table page dict.

    The per-table dict is the same shape executors always used:
    column-cache-key -> device Column, ``""`` -> validity mask,
    ``"#rows"`` -> row count. Callers mutate it in place under the
    engine's execution serialization; this class only guards the
    *map* structure with its own lock so concurrent executors can
    resolve tables without racing the weak map.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._by_connector: "weakref.WeakKeyDictionary" = (
            weakref.WeakKeyDictionary()
        )

    def table(self, connector, schema: str, table: str) -> dict:
        """The live page dict for one table (created empty on first
        use). Records a hit when the table is already device-resident
        (mask present — columns may still be added lazily), a miss
        when this call created the entry."""
        from trino_tpu import telemetry

        with self._lock:
            tables = self._by_connector.get(connector)
            if tables is None:
                tables = {}
                self._by_connector[connector] = tables
            cache = tables.get((schema, table))
            if cache is not None and "" in cache:
                telemetry.SCAN_CACHE_HITS.inc(table=table)
            else:
                telemetry.SCAN_CACHE_MISSES.inc(table=table)
            if cache is None:
                cache = tables[(schema, table)] = {}
            return cache

    def invalidate(self, connector, schema: str, table: str) -> None:
        """Drop one table's pages (after DML through any executor)."""
        with self._lock:
            tables = self._by_connector.get(connector)
            if tables is not None:
                tables.pop((schema, table), None)

    def resident_tables(self, connector) -> list[tuple[str, str]]:
        """(schema, table) pairs currently device-resident for one
        connector (observability/tests)."""
        with self._lock:
            tables = self._by_connector.get(connector) or {}
            return [k for k, v in tables.items() if "" in v]

    def clear(self) -> None:
        with self._lock:
            self._by_connector.clear()


class SplitBatchCache:
    """Byte-bounded LRU for streamed-split host batches.

    Whole-table identity caching (above) is exactly wrong for
    out-of-core scans: pinning every page of an SF100 table would
    recreate the memory problem streaming exists to avoid. Streamed
    reads instead cache per-(connector, schema, table, row-range,
    columns) host batches with an LRU bounded by total bytes, so a hot
    working set (dimension tables, re-scanned probe splits) stays warm
    while a single pass over a huge fact table churns through without
    accumulating. Connector identity is part of the key via ``id()``
    plus a weak finalizer that drops the connector's entries when it is
    collected — same isolation contract as ScanPageCache without
    pinning the connector alive."""

    def __init__(self, max_bytes: int = 256 << 20):
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()
        self._bytes = 0
        self._watched: set[int] = set()

    @staticmethod
    def _size(batch: dict) -> int:
        total = 0
        for v in batch.values():
            if isinstance(v, tuple):
                for a in v:
                    total += getattr(a, "nbytes", 0) or 0
            else:
                total += getattr(v, "nbytes", 0) or 0
        return total

    def _key(self, connector, schema, table, start, count, columns):
        return (id(connector), schema, table, start, count, tuple(columns))

    def get(self, connector, schema, table, start, count, columns):
        from trino_tpu import telemetry

        k = self._key(connector, schema, table, start, count, columns)
        with self._lock:
            batch = self._entries.get(k)
            if batch is not None:
                self._entries.move_to_end(k)
                telemetry.SCAN_CACHE_HITS.inc(table=table)
                return batch
        telemetry.SCAN_CACHE_MISSES.inc(table=table)
        return None

    def put(self, connector, schema, table, start, count, columns, batch):
        size = self._size(batch)
        if size > self.max_bytes:
            return  # a batch bigger than the cache would evict everything
        k = self._key(connector, schema, table, start, count, columns)
        with self._lock:
            if id(connector) not in self._watched:
                self._watched.add(id(connector))
                weakref.finalize(
                    connector, self._drop_connector, id(connector)
                )
            old = self._entries.pop(k, None)
            if old is not None:
                self._bytes -= self._size(old)
            self._entries[k] = batch
            self._bytes += size
            while self._bytes > self.max_bytes and self._entries:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= self._size(evicted)

    def _drop_connector(self, cid: int) -> None:
        with self._lock:
            self._watched.discard(cid)
            for k in [k for k in self._entries if k[0] == cid]:
                self._bytes -= self._size(self._entries.pop(k))

    def invalidate(self, connector, schema: str, table: str) -> None:
        with self._lock:
            dead = [
                k for k in self._entries
                if k[0] == id(connector) and k[1:3] == (schema, table)
            ]
            for k in dead:
                self._bytes -= self._size(self._entries.pop(k))

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0


#: the process-wide cache every LocalExecutor scans through
SHARED = ScanPageCache()

#: the process-wide streamed-split host-batch cache
SHARED_SPLITS = SplitBatchCache()
