"""Execution layer: device kernels + plan executors."""

from trino_tpu.exec.local import LocalExecutor

__all__ = ["LocalExecutor"]
