"""Canonical shape buckets + chain canonicalization: the one policy
module deciding which XLA programs can exist.

Per-shape compilation is this engine's analog of the reference's
runtime bytecode generation (MAIN/sql/gen/) — and its compile tax.
Two mechanisms keep the program population small enough that jit-cache
hits are the common case across queries AND scale factors:

1. **Capacity buckets.** Every operator input dimension (row
   capacity, aggregate slot tables, exchange buckets, chunked-agg
   chunk widths, TopN caps) quantizes onto ONE family — the
   power-of-two / 1.5x-power-of-two ladder of ``page.pad_capacity`` —
   through the helpers here, which also account padding overhead into
   the ``trino_shape_bucket_pad_waste_ratio`` gauge so the cost of
   bucketing stays observable.

2. **Chain canonicalization.** The executors key their jit caches on
   (plan structure, layout) tuples whose ``repr``s embed *symbol
   names*: ``sum(l_quantity)`` and ``sum(l_extendedprice)`` built
   byte-identical programs that compiled twice. ``canonicalize_chain``
   rewrites a fused operator chain into a nameless normal form —
   input columns pruned to the referenced set and renamed positionally
   in first-use order, intermediate symbols renamed scope-aware — so
   distinct queries sharing an operator mix (and the same query at
   data sizes landing in the same buckets) resolve to the SAME cached
   program. Dictionary identity stays in the layout signature:
   compiled programs bake dictionary codes and content-hash tables, so
   only columns sharing the dictionary object may share a program.

The ``shape_bucketing`` session property (ON|OFF, default ON) is the
escape hatch: OFF keeps the pre-canonicalization per-name cache keys.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, replace as dc_replace

from trino_tpu.expr.ir import AggCall, Call, Cast, InputRef, Literal, RowExpression
from trino_tpu.page import pad_capacity
from trino_tpu.plan import nodes as P

__all__ = [
    "bucket", "table_bucket", "exchange_bucket", "record_waste",
    "enabled", "canonicalize_chain", "CanonicalChain", "prewarm",
]

#: canonical-name prefix; U+00A7 cannot appear in parsed SQL symbols
_CANON = "§"

#: the minimum aggregate-table bucket: estimates below it all collapse
#: onto one rung, so group-count jitter across scale factors does not
#: mint new programs for small group-bys
TABLE_FLOOR = 1024


# ---------------------------------------------------------------------------
# bucket family + waste accounting
# ---------------------------------------------------------------------------

_waste_lock = threading.Lock()
#: site -> [requested_rows_sum, bucketed_rows_sum]
_waste: dict[str, list[int]] = {}


def record_waste(site: str, requested: int, bucketed: int) -> None:
    """Account one padding decision into the waste gauge (cumulative
    per site: gauge value = 1 - sum(requested)/sum(bucketed))."""
    if bucketed <= 0:
        return
    from trino_tpu import telemetry

    with _waste_lock:
        acc = _waste.setdefault(site, [0, 0])
        acc[0] += max(int(requested), 0)
        acc[1] += int(bucketed)
        ratio = 1.0 - (acc[0] / acc[1]) if acc[1] else 0.0
    telemetry.SHAPE_PAD_WASTE.set(round(ratio, 6), site=site)


def bucket(n: int, minimum: int = 8, site: str | None = None) -> int:
    """Canonical row-capacity bucket (the pad_capacity family: power of
    two or 1.5x a power of two, >= 96, divisible by 8; worst-case
    padding 33%). ``site`` feeds the waste gauge."""
    b = pad_capacity(n, minimum)
    if site is not None:
        record_waste(site, n, b)
    return b


def table_bucket(est: float, max_cap: int, site: str = "agg-table") -> int:
    """Canonical aggregate slot-table capacity for an estimated group
    count: 1.25x margin + floor, quantized onto the bucket family.
    The floor collapses small-group estimates across scale factors and
    queries onto one rung."""
    want = max(int(est * 1.25) + TABLE_FLOOR, TABLE_FLOOR)
    b = min(bucket(want), max_cap) if max_cap else bucket(want)
    record_waste(site, min(want, b), b)
    return b


def exchange_bucket(shard_cap: int, n_shards: int, site: str = "exchange") -> int:
    """Canonical per-destination bucket capacity for the all_to_all
    exchange (2x mean occupancy margin, >= 128, capped at the shard
    capacity so escalation can always terminate)."""
    want = max(2 * shard_cap // max(n_shards, 1), 128)
    b = min(bucket(want), shard_cap)
    record_waste(site, min(want, b), b)
    return b


def enabled(session) -> bool:
    """shape_bucketing session property (ON unless explicitly OFF)."""
    try:
        from trino_tpu import session_properties as SP

        return str(SP.get(session, "shape_bucketing")).upper() != "OFF"
    except Exception:
        return True


# ---------------------------------------------------------------------------
# chain canonicalization
# ---------------------------------------------------------------------------


@dataclass
class CanonicalChain:
    """A fused chain rewritten into nameless normal form."""

    #: the renamed chain (original nodes are never mutated)
    chain: list
    #: original input column name -> canonical name, in first-use order
    in_map: dict[str, str]
    #: canonical output symbol -> original output symbol
    out_map: dict[str, str]


class _Bail(Exception):
    """A construct canonicalization does not cover — callers fall back
    to the per-name cache key (correct, just not shared)."""


def _rename_expr(e: RowExpression, scope: dict[str, str], use) -> RowExpression:
    if isinstance(e, InputRef):
        return InputRef(e.type, use(e.name))
    if isinstance(e, Literal):
        return e
    if isinstance(e, Call):
        return Call(e.type, e.name, tuple(_rename_expr(a, scope, use) for a in e.args))
    if isinstance(e, Cast):
        return Cast(e.type, _rename_expr(e.arg, scope, use))
    raise _Bail(type(e).__name__)


def canonicalize_chain(chain: list, in_names: list[str]) -> CanonicalChain | None:
    """Rewrite a fused operator chain so its cache key is independent
    of symbol names: input columns prune to the referenced set (when a
    Project/Aggregate rebuilds the environment downstream) and rename
    positionally in first-use order; symbols introduced mid-chain
    rename scope-aware. Returns None when the chain contains a
    construct the rewriter does not cover (caller keeps the legacy
    key)."""
    in_set = set(in_names)
    counter = [0]
    scope: dict[str, str] = {}
    in_map: dict[str, str] = {}
    # a chain with no environment-rebuilding node passes every input
    # column through to its output: pruning would change the result,
    # so all inputs bind up front (still nameless — positional)
    rebuilds = any(isinstance(n, (P.Project, P.Aggregate)) for n in chain)

    def fresh() -> str:
        nm = f"{_CANON}{counter[0]}"
        counter[0] += 1
        return nm

    def use(name: str) -> str:
        hit = scope.get(name)
        if hit is not None:
            return hit
        if name in in_set and not any(
            isinstance(n, (P.Project, P.Aggregate))
            for n in canon_chain  # a rebuild already replaced the scope
        ):
            nm = fresh()
            scope[name] = nm
            in_map[name] = nm
            return nm
        raise _Bail(f"unbound symbol {name!r}")

    canon_chain: list = []
    if not rebuilds:
        for name in in_names:
            nm = fresh()
            scope[name] = nm
            in_map[name] = nm
    try:
        for nd in chain:
            if isinstance(nd, P.Filter):
                canon_chain.append(dc_replace(
                    nd, source=None,
                    predicate=_rename_expr(nd.predicate, scope, use),
                ))
            elif isinstance(nd, P.Project):
                assigns = {}
                new_scope = {}
                for sym, e in nd.assignments.items():
                    ce = _rename_expr(e, scope, use)
                    nm = fresh()
                    new_scope[sym] = nm
                    assigns[nm] = ce
                canon_chain.append(
                    dc_replace(nd, source=None, assignments=assigns)
                )
                scope = new_scope
            elif isinstance(nd, P.Aggregate):
                gk = [use(s) for s in nd.group_keys]
                aggs = {}
                new_scope = {s: scope[s] for s in nd.group_keys}
                for sym, call in nd.aggregates.items():
                    c2 = AggCall(
                        name=call.name,
                        args=tuple(
                            _rename_expr(a, scope, use) for a in call.args
                        ),
                        type=call.type,
                        distinct=call.distinct,
                        filter=(
                            None if call.filter is None
                            else _rename_expr(call.filter, scope, use)
                        ),
                    )
                    nm = fresh()
                    new_scope[sym] = nm
                    aggs[nm] = c2
                kr = (
                    None if nd.key_ranges is None
                    else {use(s): r for s, r in nd.key_ranges.items()}
                )
                outs = {
                    new_scope[s]: t
                    for s, t in nd.outputs.items() if s in new_scope
                }
                canon_chain.append(dc_replace(
                    nd, source=None, group_keys=gk, aggregates=aggs,
                    key_ranges=kr, outputs=outs,
                ))
                scope = new_scope
            elif isinstance(nd, (P.Sort, P.TopN)):
                keys = [
                    P.SortKey(use(k.symbol), k.ascending, k.nulls_first)
                    for k in nd.keys
                ]
                canon_chain.append(dc_replace(nd, source=None, keys=keys))
            elif isinstance(nd, P.Limit):
                canon_chain.append(dc_replace(nd, source=None))
            else:
                raise _Bail(type(nd).__name__)
    except _Bail:
        return None
    out_map = {canon: orig for orig, canon in scope.items()}
    return CanonicalChain(chain=canon_chain, in_map=in_map, out_map=out_map)


# ---------------------------------------------------------------------------
# pre-warm
# ---------------------------------------------------------------------------

#: default bucket set traced at server start: the canonical capacities
#: tiny/test scans and typical aggregate tables land on. Small on
#: purpose — with a warm persistent cache the whole set deserializes
#: in well under a second; cold it costs a few seconds once per
#: machine.
PREWARM_BUCKETS = (8192, 65536)


def prewarm(buckets=None, include_joins: bool = True) -> dict:
    """Trace-compile the hot kernel entry points (exec.kernels jitted
    functions) at the canonical bucket set, so the first query of a
    given operator mix pays dispatch, not compilation. Idempotent and
    persistent-cache-backed: on a machine with a warm ``.jax_cache``
    this deserializes instead of compiling. Returns a summary dict
    (buckets, seconds, compile count delta)."""
    import time

    import jax.numpy as jnp

    from trino_tpu import telemetry
    from trino_tpu.exec import kernels as K

    telemetry.install_jax_compile_hook()
    if buckets is None:
        raw = os.environ.get("TRINO_TPU_PREWARM_BUCKETS", "")
        buckets = (
            tuple(int(x) for x in raw.split(",") if x.strip())
            if raw.strip() else PREWARM_BUCKETS
        )
    t0 = time.perf_counter()
    c0 = telemetry.compile_snapshot()
    for cap in buckets:
        cap = bucket(cap)
        bits = jnp.zeros((cap,), dtype=jnp.uint64)
        mask = jnp.zeros((cap,), dtype=jnp.bool_)
        # grouping: single- and two-key sort_group at full key width
        # (sort_group/join_ranges/expand_matches are the standalone
        # jitted entry points; the fused chain programs inline their
        # bodies and warm through the persistent cache instead)
        K.sort_group((bits,), (None,), mask, TABLE_FLOOR, widths=(64,))
        K.sort_group(
            (bits, bits), (None, None), mask, TABLE_FLOOR, widths=(64, 64)
        )
        if include_joins:
            # hash-join probe: searchsorted ranges + match expansion
            order, lo, cnt = K.join_ranges(bits, mask, bits, mask)
            K.expand_matches(order, lo, cnt, out_capacity=cap)
    c1 = telemetry.compile_snapshot()
    return {
        "buckets": [int(b) for b in buckets],
        "seconds": round(time.perf_counter() - t0, 3),
        "compiles": int(c1["compiles"] - c0["compiles"]),
        "compile_seconds": round(
            c1["compile_seconds"] - c0["compile_seconds"], 3
        ),
    }
