"""Spooled stage outputs: the durable exchange tier of fleet mode.

The analog of the reference's external-exchange SPI + filesystem
exchange plugin (SPI/exchange/ExchangeManager.java,
plugin/trino-exchange-filesystem/.../FileSystemExchangeManager.java:38):
every stage's tasks write their output as hash-partitioned columnar
files on the host filesystem, committed atomically, so a downstream
stage — or a RETRY of a crashed task — reads identical bytes no matter
which worker produced or consumes them. This is the durability unit of
the whole fault-tolerant tier (SURVEY.md §5.4: stage/task outputs are
the checkpoint, there is no mid-operator state).

Layout (all under one per-query spool root, shared across workers on
one host; a multi-host deployment mounts shared storage the same way
the reference points the filesystem exchange at S3/GCS):

    {root}/stage-{sid}/t{task}-a{attempt}-p{part}.npz    partition data
    {root}/stage-{sid}/t{task}-a{attempt}-p{part}.done   partition marker
    {root}/stage-{sid}/t{task}-a{attempt}.done           commit marker

Commit protocol: partition files are written to ``*.tmp`` and renamed
(atomic on POSIX); after each partition file lands, a per-partition
``-p{part}.done`` marker (file name + whole-file CRC32) is committed
the same way, and the attempt-level ``.done`` marker is written last.
Attempt-level readers only consume attempts with the final marker; a
kill -9 mid-write leaves ignorable garbage. The per-partition markers
are the incremental-commit feed of the pipelined stage scheduler
(trino_tpu/scheduler.py): a consumer task pinned to a specific
attempt may read a partition as soon as its marker exists, before the
producing task finishes its remaining partitions. Duplicate attempts
of a task (speculative or post-crash retries) are deduplicated by
picking the smallest committed attempt — tasks are deterministic, so
any committed attempt carries identical data (the reference dedupes
replayed FTE output the same way,
MAIN/operator/DeduplicatingDirectExchangeBuffer.java).

Partition files are a real columnar page serde: per column a storage-
form numpy array (ints/doubles/bools/two-limb decimals as-is, VARCHAR
decoded to strings so no dictionary crosses the wire) + optional
validity array, with a JSON schema header — the PagesSerdeFactory
analog (MAIN/execution/buffer/PagesSerdeFactory.java:35).

Integrity: every partition file carries an 8-byte header (magic +
CRC32 of the npz body), and the ``.done`` marker records a manifest
of the attempt's files with whole-file checksums. ``read_partition``
verifies both and raises :class:`SpoolCorruptionError` on any
mismatch, truncation, or missing file — corrupted durable state is a
detected FAULT, never silently read as data (the reference checksums
spooled exchange pages the same way,
plugin/trino-exchange-filesystem/.../FileSystemExchangeManager.java).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib

import numpy as np

from trino_tpu import telemetry, types as T
from trino_tpu.page import Column, Page, pad_capacity

__all__ = [
    "write_task_output", "read_partition", "partition_ids",
    "page_to_host", "host_to_page", "committed_attempt",
    "SpoolCorruptionError", "quarantine_attempt", "next_attempt",
    "partition_marker", "committed_partitions",
    "encode_partition", "payload_from_bytes",
]


#: partition-file header: magic + CRC32-of-body, little-endian
_MAGIC = b"SPL1"
_HEADER = struct.Struct("<4sI")


class SpoolCorruptionError(RuntimeError):
    """A spooled partition file failed integrity verification.

    The message carries machine-parseable ``stage=`` / ``task=`` /
    ``attempt=`` tokens (see :func:`read_partition`) so a scheduler
    seeing the error — possibly serialized through a worker's FAILED
    state — can map the corrupt bytes back to the PRODUCING task and
    re-run it: FTE exchange-data-loss recovery, not just task retry.
    """

    def __init__(
        self, detail: str, *, stage_id: str | None = None,
        task_id: str | None = None, attempt: int | None = None,
        path: str | None = None,
    ):
        self.stage_id = stage_id
        self.task_id = task_id
        self.attempt = attempt
        self.path = path
        msg = detail
        if stage_id is not None:
            msg = (
                f"corrupt spool partition stage={stage_id} "
                f"task={task_id} attempt={attempt} "
                f"file={os.path.basename(path or '?')}: {detail}"
            )
        super().__init__(msg)


# ---- deterministic row hashing --------------------------------------------

_MIX_A = np.uint64(0xBF58476D1CE4E5B9)
_MIX_B = np.uint64(0x94D049BB133111EB)
_FNV = np.uint64(0x100000001B3)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Deterministic 64-bit mixer (splitmix64 finalizer): the fleet
    partition function must agree across PROCESSES, so python's
    randomized str hash is unusable."""
    with np.errstate(over="ignore"):
        x = x.astype(np.uint64, copy=True)
        x ^= x >> np.uint64(30)
        x *= _MIX_A
        x ^= x >> np.uint64(27)
        x *= _MIX_B
        x ^= x >> np.uint64(31)
    return x


def _key_lanes(values: np.ndarray, valid: np.ndarray | None) -> np.ndarray:
    """uint64 hash lane per row for one key column (storage-agnostic:
    equal SQL values produce equal lanes on both sides of a join)."""
    if values.dtype == object or values.dtype.kind in ("U", "S"):
        from trino_tpu.page import content_hash64

        out = content_hash64(values)
    elif values.ndim == 2:
        # two-limb decimal storage: combine limbs into the unscaled value
        with np.errstate(over="ignore"):
            out = (
                values[:, 0].astype(np.uint64) << np.uint64(32)
            ) + values[:, 1].astype(np.uint64)
    elif values.dtype.kind == "f":
        # widen to float64 BEFORE the bit view (a float32 view as
        # uint64 is a shape error; equal values must hash equally)
        f = np.where(values == 0.0, 0.0, values).astype(np.float64)
        out = f.view(np.uint64).copy()
    else:
        out = values.astype(np.int64).view(np.uint64).copy()
    out = _splitmix64(out)
    if valid is not None:
        out = np.where(valid, out, np.uint64(0))
    return out


def partition_ids(
    key_cols: list[tuple[np.ndarray, np.ndarray | None]], n_rows: int,
    n_parts: int,
) -> np.ndarray:
    """Partition id per row from the key columns' host arrays."""
    if not key_cols:
        return np.zeros(n_rows, dtype=np.int64)
    h = np.zeros(n_rows, dtype=np.uint64)
    with np.errstate(over="ignore"):
        for values, valid in key_cols:
            h = h * _FNV + _key_lanes(values, valid)
    return (h % np.uint64(n_parts)).astype(np.int64)


# ---- page <-> host columnar payload ---------------------------------------

def page_to_host(page: Page) -> dict:
    """Materialize a device page to host storage-form columns.

    Returns {names, types, cols: [(values, valid|None)]} with live rows
    compacted; VARCHAR decoded to plain string arrays."""
    import jax

    arrays = [page.mask]
    for c in page.columns:
        arrays.append(c.data)
        if c.valid is not None:
            arrays.append(c.valid)
    host = jax.device_get(arrays)
    sel = np.nonzero(host[0])[0]
    i = 1
    cols = []
    for c in page.columns:
        data = host[i][sel]
        i += 1
        valid = None
        if c.valid is not None:
            valid = host[i][sel]
            i += 1
        if c.dictionary is not None:
            data = c.dictionary.decode(data).astype(str)
        elif c.hash_pool is not None:
            data = c.hash_pool.values[data[:, 1]].astype(str)
        elif c.array_pool is not None:
            data = c.array_pool.decode(data)
        if valid is not None and data.dtype.kind == "U":
            data = np.where(valid, data, "")
        cols.append((data, valid))
    return {
        "names": list(page.names),
        "types": [c.type for c in page.columns],
        "cols": cols,
    }


def host_to_page(payload: dict) -> Page:
    """Rebuild a device Page from host columnar payload(s). VARCHAR
    columns re-encode with a fresh sorted dictionary."""
    names = payload["names"]
    types = payload["types"]
    cols = payload["cols"]
    n = len(cols[0][0]) if cols else 0
    cap = pad_capacity(n)
    columns = []
    for t, (values, valid) in zip(types, cols):
        columns.append(Column.from_numpy(t, values, valid=valid, capacity=cap))
    import jax.numpy as jnp

    mask = np.zeros(cap, dtype=np.bool_)
    mask[:n] = True
    return Page(
        list(names), columns, jnp.asarray(mask), known_rows=n, packed=True,
    )


def _concat_payloads(payloads: list[dict]) -> dict:
    first = payloads[0]
    if len(payloads) == 1:
        return first
    cols = []
    for i in range(len(first["names"])):
        datas = [p["cols"][i][0] for p in payloads]
        valids = [p["cols"][i][1] for p in payloads]
        if first["cols"][i][0].dtype.kind == "U" or any(
            d.dtype.kind in ("U", "O") for d in datas
        ):
            data = np.concatenate([d.astype(object) for d in datas])
        else:
            data = np.concatenate(datas)
        if any(v is not None for v in valids):
            valid = np.concatenate([
                v if v is not None else np.ones(len(d), dtype=np.bool_)
                for v, d in zip(valids, datas)
            ])
        else:
            valid = None
        cols.append((data, valid))
    return {"names": first["names"], "types": first["types"], "cols": cols}


def salt_filter(payload: dict, salt: int, factor: int) -> dict:
    """Keep one deterministic 1/``factor`` row slice of a host payload:
    rows whose index ``% factor == salt``. The SALTED exchange's
    fan-out half — K salt tasks each apply a different ``salt`` to the
    SAME hot-partition payload, producing a disjoint exact cover of its
    rows.

    Determinism across task attempts rests on the payload row order
    being a pure function of the pinned read: ``read_partition`` (and
    the worker's byte-identical direct-read mirror) concatenates
    producer tasks in pinned order with ascending partitions inside
    each, and each partition file's row order is fixed at producer
    commit. Key-hash salting would be useless here — every row of ONE
    hot key shares a hash — so the slice is positional, which balances
    even a single-key hot partition."""
    cols = payload.get("cols")
    if not cols:
        return payload
    n = len(cols[0][0])
    sel = (np.arange(n) % int(factor)) == int(salt)
    return {
        "names": payload["names"],
        "types": payload["types"],
        "cols": [
            (v[sel], None if valid is None else valid[sel])
            for v, valid in cols
        ],
    }


# ---- file format -----------------------------------------------------------

def encode_partition(payload: dict, sel: np.ndarray) -> tuple[bytes, int]:
    """Serialize the selected rows of a host payload into the
    checksummed partition wire format (header + npz body). Returns
    ``(raw_bytes, whole_file_crc)`` — the SAME bytes land on the spool
    and in the producer's direct-exchange buffer, so both paths share
    one serde and one integrity check."""
    arrays = {}
    schema = []
    for i, (t, (values, valid)) in enumerate(
        zip(payload["types"], payload["cols"])
    ):
        v = values[sel]
        entry = {
            "name": payload["names"][i], "type": str(t),
            "valid": valid is not None,
        }
        if isinstance(t, T.ArrayType):
            # list column: int64 offsets + flattened element values
            # (the Arrow ListArray / ArrayBlock layout) — npz cannot
            # hold object rows without pickle, and pickle never
            # crosses the exchange
            offs = np.zeros(len(v) + 1, dtype=np.int64)
            flat: list = []
            for j, row in enumerate(v):
                if row is None:
                    offs[j + 1] = offs[j]
                    continue
                flat.extend(row)
                offs[j + 1] = offs[j] + len(row)
            arrays[f"o{i}"] = offs
            if isinstance(t.element, T.VarcharType):
                arrays[f"d{i}"] = np.asarray(
                    [str(x) for x in flat], dtype=str
                )
            else:
                arrays[f"d{i}"] = np.asarray(
                    flat if flat else [], dtype=t.element.np_dtype
                )
            entry["array"] = True
        else:
            if v.dtype == object:
                v = v.astype(str)
            arrays[f"d{i}"] = v
        if valid is not None:
            arrays[f"v{i}"] = valid[sel]
        schema.append(entry)
    arrays["schema"] = np.frombuffer(
        json.dumps(schema).encode(), dtype=np.uint8
    )
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    body = buf.getvalue()
    header = _HEADER.pack(_MAGIC, zlib.crc32(body))
    raw = header + body
    return raw, zlib.crc32(raw)


def _write_partition_file(path: str, raw: bytes) -> None:
    """Durably land pre-encoded partition bytes (tmp + atomic rename)."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(raw)
    os.replace(tmp, path)
    telemetry.SPOOL_BYTES_WRITTEN.inc(len(raw))


def _save_npz(path: str, payload: dict, sel: np.ndarray) -> int:
    """Write one checksummed partition file; returns the CRC32 of the
    complete on-disk file (header + body) for the commit manifest."""
    raw, crc = encode_partition(payload, sel)
    _write_partition_file(path, raw)
    return crc


def _load_npz(
    path: str, expect_crc: int | None = None, on_bytes=None,
) -> dict:
    """Load + verify one partition file (counts bytes read and CRC
    failures into the metrics registry)."""
    try:
        out = _load_npz_verified(path, expect_crc, on_bytes)
    except SpoolCorruptionError:
        telemetry.SPOOL_CRC_FAILURES.inc()
        raise
    return out


def _load_npz_verified(
    path: str, expect_crc: int | None = None, on_bytes=None,
) -> dict:
    """Load + verify one partition file. ``expect_crc`` is the
    whole-file checksum from the commit manifest (when available);
    the embedded header CRC is always checked. Any mismatch,
    truncation, or unparseable payload raises SpoolCorruptionError
    (bare — read_partition re-raises with producer coordinates)."""
    try:
        with open(path, "rb") as f:
            raw = f.read()
    except FileNotFoundError:
        raise SpoolCorruptionError(f"partition file missing: {path}")
    out = payload_from_bytes(raw, expect_crc)
    telemetry.SPOOL_BYTES_READ.inc(len(raw))
    if on_bytes is not None:
        on_bytes(len(raw))
    return out


def payload_from_bytes(raw: bytes, expect_crc: int | None = None) -> dict:
    """Verify + decode partition wire bytes (shared by the spool read
    path and the direct producer-memory fetch path; no spool byte
    accounting happens here). ``expect_crc`` is the whole-file CRC32
    from the commit manifest / partition marker when available."""
    if expect_crc is not None and zlib.crc32(raw) != expect_crc:
        raise SpoolCorruptionError(
            "file checksum does not match commit manifest"
        )
    if len(raw) < _HEADER.size or raw[:4] != _MAGIC:
        raise SpoolCorruptionError("bad partition-file header")
    (_, crc) = _HEADER.unpack_from(raw)
    body = raw[_HEADER.size:]
    if zlib.crc32(body) != crc:
        raise SpoolCorruptionError("partition body fails CRC32")
    try:
        with np.load(io.BytesIO(body), allow_pickle=False) as z:
            schema = json.loads(bytes(z["schema"].tobytes()).decode())
            names, types, cols = [], [], []
            for i, col in enumerate(schema):
                names.append(col["name"])
                types.append(T.type_from_name(col["type"]))
                if col.get("array"):
                    # offsets + flat values back into object rows of
                    # python lists (what Column.from_numpy expects for
                    # ArrayType)
                    offs = z[f"o{i}"]
                    flat = z[f"d{i}"]
                    data = np.empty(len(offs) - 1, dtype=object)
                    for j in range(len(offs) - 1):
                        data[j] = flat[offs[j]:offs[j + 1]].tolist()
                else:
                    data = z[f"d{i}"]
                valid = z[f"v{i}"] if col["valid"] else None
                cols.append((data, valid))
    except SpoolCorruptionError:
        raise
    except Exception as e:
        # CRC passed but the npz container is unreadable — still a
        # durable-state fault, not an engine bug to propagate raw
        raise SpoolCorruptionError(f"unreadable npz payload: {e}")
    return {"names": names, "types": types, "cols": cols}


# ---- task output write / partition read ------------------------------------

def _stage_dir(root: str, stage_id: str) -> str:
    return os.path.join(root, f"stage-{stage_id}")


def partition_marker(
    root: str, stage_id: str, task_id: str, attempt: int, part: int
) -> str:
    """Path of the per-partition commit marker (exists once that
    partition's file is durably on disk, before the attempt-level
    ``.done``)."""
    return os.path.join(
        _stage_dir(root, stage_id), f"t{task_id}-a{attempt}-p{part}.done"
    )


def _commit_partition_marker(
    d: str, task_id: str, attempt: int, part: int, name: str, crc: int
) -> None:
    marker = os.path.join(d, f"t{task_id}-a{attempt}-p{part}.done")
    tmp = marker + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"file": name, "crc": crc}, f)
    os.replace(tmp, marker)


def committed_partitions(
    root: str, stage_id: str, task_id: str, attempt: int
) -> list[int]:
    """Partition ids of ``attempt`` whose per-partition markers are on
    disk (quarantined markers excluded). Sorted ascending."""
    d = _stage_dir(root, stage_id)
    if not os.path.isdir(d):
        return []
    prefix = f"t{task_id}-a{attempt}-p"
    out = []
    for f in os.listdir(d):
        if f.startswith(prefix) and f.endswith(".done"):
            body = f[len(prefix):-len(".done")]
            if body.isdigit():
                out.append(int(body))
    return sorted(out)


def write_task_output(
    root: str, stage_id: str, task_id: str, attempt: int, page: Page,
    partitioning: str, key_names: list[str], n_parts: int,
    partition_delay_ms: float = 0.0, on_partition=None,
    on_partition_bytes=None,
) -> dict:
    """Partition a task's output page and commit it to the spool.

    Each partition file is followed by its own ``-p{part}.done``
    marker (name + whole-file CRC32) the moment it lands —
    ``on_partition(part)`` fires after each such commit so the worker
    can report incremental progress to the pipelined scheduler.
    ``partition_delay_ms`` sleeps after each partition commit (test
    hook: widens the producer write tail so pipelined-admission
    overlap is observable on tiny data).

    ``on_partition_bytes(part, raw, crc)`` (optional) receives each
    partition's encoded wire bytes right after its marker commits —
    the worker's direct-exchange buffer pool hooks in here so buffered
    bytes are exactly the committed on-disk bytes.

    Returns ``{"rows": n, "bytes": total_file_bytes,
    "partition_rows": {part: rows}, "partition_bytes": {part:
    encoded_bytes}}`` for per-task output stats — the per-partition
    maps are the skew histograms the fleet folds into per-edge
    ``stage_stats`` (ROADMAP skew item, deliverable (a))."""
    import queue as _queue
    import threading as _threading
    import time as _time

    from trino_tpu import fault

    d = _stage_dir(root, stage_id)
    os.makedirs(d, exist_ok=True)
    payload = page_to_host(page)
    n = len(payload["cols"][0][0]) if payload["cols"] else 0
    if partitioning == "hash" and key_names:
        idx = [payload["names"].index(k) for k in key_names]
        parts = partition_ids(
            [payload["cols"][i] for i in idx], n, n_parts
        )
    elif partitioning == "round_robin":
        # scaled-writer fan-out: even row spread, no key
        parts = np.arange(n, dtype=np.int64) % max(int(n_parts), 1)
    else:
        parts = np.zeros(n, dtype=np.int64)
    written = []
    manifest: dict[str, int] = {}
    partition_rows: dict[int, int] = {}
    partition_bytes: dict[int, int] = {}

    # async background commit: encoding (the CPU-bound half) stays on
    # the caller's thread while a writer thread lands files + markers
    # in submission order. Observable ordering is unchanged — every
    # partition still commits file-then-marker-then-callback, the
    # spool-write chaos seam and the attempt-level ``.done`` still run
    # strictly after ALL partitions are durable (the join below) — so
    # admission gating, attempt pinning, and quarantine semantics are
    # byte-identical to the synchronous writer.
    work: _queue.Queue = _queue.Queue()
    failure: list[BaseException] = []

    def _writer():
        while True:
            item = work.get()
            if item is None:
                return
            part, name, raw, crc = item
            if failure:
                continue  # drain; first error wins at the join
            try:
                _write_partition_file(os.path.join(d, name), raw)
                _commit_partition_marker(
                    d, task_id, attempt, part, name, crc
                )
                if on_partition_bytes is not None:
                    on_partition_bytes(part, raw, crc)
                if on_partition is not None:
                    on_partition(part)
                if partition_delay_ms:
                    _time.sleep(partition_delay_ms / 1e3)
            except BaseException as e:  # surfaced at the join
                failure.append(e)

    writer = _threading.Thread(target=_writer, daemon=True)
    writer.start()
    try:
        for p in np.unique(parts):
            sel = np.nonzero(parts == p)[0]
            name = f"t{task_id}-a{attempt}-p{int(p)}.npz"
            raw, crc = encode_partition(payload, sel)
            manifest[name] = crc
            written.append(int(p))
            partition_rows[int(p)] = int(len(sel))
            partition_bytes[int(p)] = int(len(raw))
            work.put((int(p), name, raw, crc))
        if not written:
            # empty output still ships its schema (consumers need a
            # typed zero-row page, the empty-serialized-page analog)
            name = f"t{task_id}-a{attempt}-p0.npz"
            raw, crc = encode_partition(
                payload, np.zeros(0, dtype=np.int64)
            )
            manifest[name] = crc
            written.append(0)
            partition_rows[0] = 0
            partition_bytes[0] = int(len(raw))
            work.put((0, name, raw, crc))
    finally:
        work.put(None)
        writer.join()
    if failure:
        raise failure[0]
    # chaos seam: a spool-write fault fails the producing task AFTER
    # its partition files (and their per-partition markers) landed but
    # BEFORE the attempt-level commit marker — the genuinely dangerous
    # FTE window: a pipelined consumer may already be admitted on the
    # orphaned markers while attempt-level dedup never sees this
    # attempt. The partition files themselves are complete and
    # CRC-valid, so a consumer pinned to the orphan reads correct
    # bytes; the retry commits a fresh attempt for everyone else.
    fault.check("spool-write", tag=f"{stage_id}:{task_id}", attempt=attempt)
    # commit marker last: readers ignore attempts without one. The
    # marker doubles as the attempt's integrity manifest — file list
    # plus whole-file CRC32s — so a reader detects a swapped,
    # truncated, or vanished partition file, not just flipped bytes
    marker = os.path.join(d, f"t{task_id}-a{attempt}.done")
    tmp = marker + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"partitions": written, "files": manifest}, f)
    os.replace(tmp, marker)
    total = sum(
        os.path.getsize(os.path.join(d, name)) for name in manifest
    )
    # the spool IS the fleet's exchange tier: rows committed here are
    # rows moved between stages
    telemetry.EXCHANGE_ROWS.inc(int(n))
    for p in written:
        telemetry.EXCHANGE_PARTITION_ROWS.inc(
            partition_rows.get(p, 0),
            edge=str(stage_id), partition=str(p),
        )
        telemetry.EXCHANGE_PARTITION_BYTES.inc(
            partition_bytes.get(p, 0),
            edge=str(stage_id), partition=str(p),
        )
    return {
        "rows": int(n), "bytes": int(total),
        "partition_rows": partition_rows,
        "partition_bytes": partition_bytes,
    }


def committed_attempt(root: str, stage_id: str, task_id: str) -> int | None:
    """Smallest committed attempt of a task, or None. Quarantined
    attempts (marker renamed ``.done.bad``) are not committed."""
    d = _stage_dir(root, stage_id)
    if not os.path.isdir(d):
        return None
    best = None
    prefix = f"t{task_id}-a"
    for f in os.listdir(d):
        if f.startswith(prefix) and f.endswith(".done"):
            body = f[len(prefix):-len(".done")]
            if not body.isdigit():
                continue  # per-partition marker (tN-aA-pP.done)
            a = int(body)
            best = a if best is None else min(best, a)
    return best


def next_attempt(root: str, stage_id: str, task_id: str) -> int:
    """1 + the highest attempt number with any on-disk trace
    (committed, quarantined, or partial) — the attempt number a
    corruption-recovery re-run must use to avoid colliding with
    existing files."""
    d = _stage_dir(root, stage_id)
    if not os.path.isdir(d):
        return 0
    top = -1
    prefix = f"t{task_id}-a"
    for f in os.listdir(d):
        if not f.startswith(prefix):
            continue
        rest = f[len(prefix):]
        digits = ""
        for ch in rest:
            if not ch.isdigit():
                break
            digits += ch
        if digits:
            top = max(top, int(digits))
    return top + 1


def quarantine_attempt(
    root: str, stage_id: str, task_id: str, attempt: int
) -> bool:
    """Withdraw a corrupt attempt from the committed set by renaming
    its ``.done`` marker — AND every per-partition ``-p{part}.done``
    marker of the same attempt — to ``.done.bad`` (readers dedupe on
    the ``.done`` suffix, so the attempt stops existing for them; the
    data files stay for forensics). Retracting the partition markers
    matters under pipelined admission: a consumer pinned to this
    attempt must hit a hard SpoolCorruptionError on its next read
    instead of silently consuming quarantined bytes, and the scheduler
    rescinds admissions that depended on them. Idempotent: returns
    False when no marker of the attempt was left to withdraw."""
    d = _stage_dir(root, stage_id)
    withdrew = False
    marker = os.path.join(d, f"t{task_id}-a{attempt}.done")
    try:
        os.replace(marker, marker + ".bad")
        withdrew = True
    except FileNotFoundError:
        pass
    for p in committed_partitions(root, stage_id, task_id, attempt):
        pm = os.path.join(d, f"t{task_id}-a{attempt}-p{p}.done")
        try:
            os.replace(pm, pm + ".bad")
            withdrew = True
        except FileNotFoundError:
            pass
    return withdrew


def read_partition(
    root: str, stage_id: str, task_ids: list[str],
    partition: int | None, attempts: dict | None = None,
    on_bytes=None,
) -> dict:
    """Read one partition (or, when ``partition`` is None, everything)
    written by the given tasks, deduplicated to one committed attempt
    per task. Raises if any task has no committed attempt.

    ``attempts`` (``{task_id: attempt}``) pins specific producer
    attempts — the pipelined scheduler's admission contract: a
    consumer admitted on per-partition markers reads exactly the
    attempt the coordinator observed, never mixing attempts when a
    speculative or retried producer commits a different attempt later.
    A pinned attempt without its attempt-level ``.done`` is read
    through its per-partition markers; a pin whose markers were
    retracted (quarantine) raises :class:`SpoolCorruptionError` with
    producer coordinates so the scheduler re-runs the producer."""
    from trino_tpu import fault

    d = _stage_dir(root, stage_id)
    payloads = []
    empty = None
    empty_crc = None
    for tid in task_ids:
        # chaos seam: a spool-read fault surfaces as a task failure in
        # a worker (retried there) or escalates from the coordinator's
        # root read into the QUERY retry tier. The attempt defaults to
        # the active injector's (the CONSUMER's retry level), so
        # times-schedules let a retried read eventually succeed.
        fault.check("spool-read", tag=f"{stage_id}:{tid}")
        pinned = attempts.get(tid) if attempts else None
        if pinned is None:
            a = committed_attempt(root, stage_id, tid)
            if a is None:
                raise FileNotFoundError(
                    f"stage {stage_id} task {tid}: no committed attempt "
                    "in spool"
                )
        else:
            a = int(pinned)
        marker = os.path.join(d, f"t{tid}-a{a}.done")
        if pinned is not None and not os.path.exists(marker):
            # pinned attempt not (yet) fully committed: read through
            # its per-partition markers
            written = committed_partitions(root, stage_id, tid, a)
            if not written:
                raise SpoolCorruptionError(
                    "pinned attempt has no committed partitions "
                    "(markers retracted by quarantine?)",
                    stage_id=stage_id, task_id=tid, attempt=a,
                    path=marker,
                )
            crcs = {}
            for p in written:
                pm = os.path.join(d, f"t{tid}-a{a}-p{p}.done")
                try:
                    with open(pm) as f:
                        pmeta = json.load(f)
                    crcs[pmeta["file"]] = pmeta["crc"]
                except (OSError, ValueError, KeyError):
                    raise SpoolCorruptionError(
                        "unreadable per-partition marker",
                        stage_id=stage_id, task_id=tid, attempt=a,
                        path=pm,
                    ) from None
            if partition is not None and partition not in written:
                # the admission basis vanished between admit and read
                raise SpoolCorruptionError(
                    f"pinned partition {partition} has no marker "
                    "(retracted by quarantine?)",
                    stage_id=stage_id, task_id=tid, attempt=a,
                    path=marker,
                )
        else:
            with open(marker) as f:
                meta = json.load(f)
            written = meta["partitions"]
            crcs = meta.get("files", {})
        wanted = written if partition is None else (
            [partition] if partition in written else []
        )
        for p in wanted:
            name = f"t{tid}-a{a}-p{p}.npz"
            try:
                payloads.append(
                    _load_npz(
                        os.path.join(d, name), crcs.get(name), on_bytes
                    )
                )
            except SpoolCorruptionError as e:
                raise SpoolCorruptionError(
                    str(e), stage_id=stage_id, task_id=tid, attempt=a,
                    path=os.path.join(d, name),
                ) from None
        if empty is None and written:
            # remember any payload's schema for the empty-result case
            name = f"t{tid}-a{a}-p{written[0]}.npz"
            empty = os.path.join(d, name)
            empty_crc = (crcs.get(name), stage_id, tid, a)
    if not payloads:
        if empty is not None:
            try:
                p = _load_npz(empty, empty_crc[0], on_bytes)
            except SpoolCorruptionError as e:
                raise SpoolCorruptionError(
                    str(e), stage_id=empty_crc[1], task_id=empty_crc[2],
                    attempt=empty_crc[3], path=empty,
                ) from None
            return {
                "names": p["names"], "types": p["types"],
                "cols": [
                    (v[:0], None if valid is None else valid[:0])
                    for v, valid in p["cols"]
                ],
            }
        raise FileNotFoundError(
            f"stage {stage_id}: no data for partition {partition}"
        )
    return _concat_payloads(payloads)
