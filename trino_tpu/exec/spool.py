"""Spooled stage outputs: the durable exchange tier of fleet mode.

The analog of the reference's external-exchange SPI + filesystem
exchange plugin (SPI/exchange/ExchangeManager.java,
plugin/trino-exchange-filesystem/.../FileSystemExchangeManager.java:38):
every stage's tasks write their output as hash-partitioned columnar
files on the host filesystem, committed atomically, so a downstream
stage — or a RETRY of a crashed task — reads identical bytes no matter
which worker produced or consumes them. This is the durability unit of
the whole fault-tolerant tier (SURVEY.md §5.4: stage/task outputs are
the checkpoint, there is no mid-operator state).

Layout (all under one per-query spool root, shared across workers on
one host; a multi-host deployment mounts shared storage the same way
the reference points the filesystem exchange at S3/GCS):

    {root}/stage-{sid}/t{task}-a{attempt}-p{part}.npz   partition data
    {root}/stage-{sid}/t{task}-a{attempt}.done          commit marker

Commit protocol: partition files are written to ``*.tmp`` and renamed
(atomic on POSIX), then the ``.done`` marker is written last. Readers
only consume attempts with a marker; a kill -9 mid-write leaves
ignorable garbage. Duplicate attempts of a task (speculative or
post-crash retries) are deduplicated by picking the smallest committed
attempt — tasks are deterministic, so any committed attempt carries
identical data (the reference dedupes replayed FTE output the same
way, MAIN/operator/DeduplicatingDirectExchangeBuffer.java).

Partition files are a real columnar page serde: per column a storage-
form numpy array (ints/doubles/bools/two-limb decimals as-is, VARCHAR
decoded to strings so no dictionary crosses the wire) + optional
validity array, with a JSON schema header — the PagesSerdeFactory
analog (MAIN/execution/buffer/PagesSerdeFactory.java:35).
"""

from __future__ import annotations

import io
import json
import os

import numpy as np

from trino_tpu import types as T
from trino_tpu.page import Column, Page, pad_capacity

__all__ = [
    "write_task_output", "read_partition", "partition_ids",
    "page_to_host", "host_to_page", "committed_attempt",
]


# ---- deterministic row hashing --------------------------------------------

_MIX_A = np.uint64(0xBF58476D1CE4E5B9)
_MIX_B = np.uint64(0x94D049BB133111EB)
_FNV = np.uint64(0x100000001B3)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Deterministic 64-bit mixer (splitmix64 finalizer): the fleet
    partition function must agree across PROCESSES, so python's
    randomized str hash is unusable."""
    with np.errstate(over="ignore"):
        x = x.astype(np.uint64, copy=True)
        x ^= x >> np.uint64(30)
        x *= _MIX_A
        x ^= x >> np.uint64(27)
        x *= _MIX_B
        x ^= x >> np.uint64(31)
    return x


def _key_lanes(values: np.ndarray, valid: np.ndarray | None) -> np.ndarray:
    """uint64 hash lane per row for one key column (storage-agnostic:
    equal SQL values produce equal lanes on both sides of a join)."""
    if values.dtype == object or values.dtype.kind in ("U", "S"):
        from trino_tpu.page import content_hash64

        out = content_hash64(values)
    elif values.ndim == 2:
        # two-limb decimal storage: combine limbs into the unscaled value
        with np.errstate(over="ignore"):
            out = (
                values[:, 0].astype(np.uint64) << np.uint64(32)
            ) + values[:, 1].astype(np.uint64)
    elif values.dtype.kind == "f":
        # widen to float64 BEFORE the bit view (a float32 view as
        # uint64 is a shape error; equal values must hash equally)
        f = np.where(values == 0.0, 0.0, values).astype(np.float64)
        out = f.view(np.uint64).copy()
    else:
        out = values.astype(np.int64).view(np.uint64).copy()
    out = _splitmix64(out)
    if valid is not None:
        out = np.where(valid, out, np.uint64(0))
    return out


def partition_ids(
    key_cols: list[tuple[np.ndarray, np.ndarray | None]], n_rows: int,
    n_parts: int,
) -> np.ndarray:
    """Partition id per row from the key columns' host arrays."""
    if not key_cols:
        return np.zeros(n_rows, dtype=np.int64)
    h = np.zeros(n_rows, dtype=np.uint64)
    with np.errstate(over="ignore"):
        for values, valid in key_cols:
            h = h * _FNV + _key_lanes(values, valid)
    return (h % np.uint64(n_parts)).astype(np.int64)


# ---- page <-> host columnar payload ---------------------------------------

def page_to_host(page: Page) -> dict:
    """Materialize a device page to host storage-form columns.

    Returns {names, types, cols: [(values, valid|None)]} with live rows
    compacted; VARCHAR decoded to plain string arrays."""
    import jax

    arrays = [page.mask]
    for c in page.columns:
        arrays.append(c.data)
        if c.valid is not None:
            arrays.append(c.valid)
    host = jax.device_get(arrays)
    sel = np.nonzero(host[0])[0]
    i = 1
    cols = []
    for c in page.columns:
        data = host[i][sel]
        i += 1
        valid = None
        if c.valid is not None:
            valid = host[i][sel]
            i += 1
        if c.dictionary is not None:
            data = c.dictionary.decode(data).astype(str)
        elif c.hash_pool is not None:
            data = c.hash_pool.values[data[:, 1]].astype(str)
        elif c.array_pool is not None:
            data = c.array_pool.decode(data)
        if valid is not None and data.dtype.kind == "U":
            data = np.where(valid, data, "")
        cols.append((data, valid))
    return {
        "names": list(page.names),
        "types": [c.type for c in page.columns],
        "cols": cols,
    }


def host_to_page(payload: dict) -> Page:
    """Rebuild a device Page from host columnar payload(s). VARCHAR
    columns re-encode with a fresh sorted dictionary."""
    names = payload["names"]
    types = payload["types"]
    cols = payload["cols"]
    n = len(cols[0][0]) if cols else 0
    cap = pad_capacity(n)
    columns = []
    for t, (values, valid) in zip(types, cols):
        columns.append(Column.from_numpy(t, values, valid=valid, capacity=cap))
    import jax.numpy as jnp

    mask = np.zeros(cap, dtype=np.bool_)
    mask[:n] = True
    return Page(
        list(names), columns, jnp.asarray(mask), known_rows=n, packed=True,
    )


def _concat_payloads(payloads: list[dict]) -> dict:
    first = payloads[0]
    if len(payloads) == 1:
        return first
    cols = []
    for i in range(len(first["names"])):
        datas = [p["cols"][i][0] for p in payloads]
        valids = [p["cols"][i][1] for p in payloads]
        if first["cols"][i][0].dtype.kind == "U" or any(
            d.dtype.kind in ("U", "O") for d in datas
        ):
            data = np.concatenate([d.astype(object) for d in datas])
        else:
            data = np.concatenate(datas)
        if any(v is not None for v in valids):
            valid = np.concatenate([
                v if v is not None else np.ones(len(d), dtype=np.bool_)
                for v, d in zip(valids, datas)
            ])
        else:
            valid = None
        cols.append((data, valid))
    return {"names": first["names"], "types": first["types"], "cols": cols}


# ---- file format -----------------------------------------------------------

def _save_npz(path: str, payload: dict, sel: np.ndarray) -> None:
    arrays = {}
    schema = []
    for i, (t, (values, valid)) in enumerate(
        zip(payload["types"], payload["cols"])
    ):
        if isinstance(t, T.ArrayType):
            raise NotImplementedError(
                "ARRAY columns cannot cross the spooled exchange yet"
            )
        v = values[sel]
        if v.dtype == object:
            v = v.astype(str)
        arrays[f"d{i}"] = v
        if valid is not None:
            arrays[f"v{i}"] = valid[sel]
        schema.append({
            "name": payload["names"][i], "type": str(t),
            "valid": valid is not None,
        })
    arrays["schema"] = np.frombuffer(
        json.dumps(schema).encode(), dtype=np.uint8
    )
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)


def _load_npz(path: str) -> dict:
    with np.load(path, allow_pickle=False) as z:
        schema = json.loads(bytes(z["schema"].tobytes()).decode())
        names, types, cols = [], [], []
        for i, col in enumerate(schema):
            names.append(col["name"])
            types.append(T.type_from_name(col["type"]))
            data = z[f"d{i}"]
            valid = z[f"v{i}"] if col["valid"] else None
            cols.append((data, valid))
    return {"names": names, "types": types, "cols": cols}


# ---- task output write / partition read ------------------------------------

def _stage_dir(root: str, stage_id: str) -> str:
    return os.path.join(root, f"stage-{stage_id}")


def write_task_output(
    root: str, stage_id: str, task_id: str, attempt: int, page: Page,
    partitioning: str, key_names: list[str], n_parts: int,
) -> None:
    """Partition a task's output page and commit it to the spool."""
    d = _stage_dir(root, stage_id)
    os.makedirs(d, exist_ok=True)
    payload = page_to_host(page)
    n = len(payload["cols"][0][0]) if payload["cols"] else 0
    if partitioning == "hash" and key_names:
        idx = [payload["names"].index(k) for k in key_names]
        parts = partition_ids(
            [payload["cols"][i] for i in idx], n, n_parts
        )
    else:
        parts = np.zeros(n, dtype=np.int64)
    written = []
    for p in np.unique(parts):
        sel = np.nonzero(parts == p)[0]
        path = os.path.join(d, f"t{task_id}-a{attempt}-p{int(p)}.npz")
        _save_npz(path, payload, sel)
        written.append(int(p))
    if not written:
        # empty output still ships its schema (consumers need a typed
        # zero-row page, the empty-serialized-page analog)
        path = os.path.join(d, f"t{task_id}-a{attempt}-p0.npz")
        _save_npz(path, payload, np.zeros(0, dtype=np.int64))
        written.append(0)
    # commit marker last: readers ignore attempts without one
    marker = os.path.join(d, f"t{task_id}-a{attempt}.done")
    tmp = marker + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"partitions": written}, f)
    os.replace(tmp, marker)


def committed_attempt(root: str, stage_id: str, task_id: str) -> int | None:
    """Smallest committed attempt of a task, or None."""
    d = _stage_dir(root, stage_id)
    if not os.path.isdir(d):
        return None
    best = None
    prefix = f"t{task_id}-a"
    for f in os.listdir(d):
        if f.startswith(prefix) and f.endswith(".done"):
            a = int(f[len(prefix):-len(".done")])
            best = a if best is None else min(best, a)
    return best


def read_partition(
    root: str, stage_id: str, task_ids: list[str],
    partition: int | None,
) -> dict:
    """Read one partition (or, when ``partition`` is None, everything)
    written by the given tasks, deduplicated to one committed attempt
    per task. Raises if any task has no committed attempt."""
    d = _stage_dir(root, stage_id)
    payloads = []
    empty = None
    for tid in task_ids:
        a = committed_attempt(root, stage_id, tid)
        if a is None:
            raise FileNotFoundError(
                f"stage {stage_id} task {tid}: no committed attempt in spool"
            )
        marker = os.path.join(d, f"t{tid}-a{a}.done")
        with open(marker) as f:
            written = json.load(f)["partitions"]
        wanted = written if partition is None else (
            [partition] if partition in written else []
        )
        for p in wanted:
            payloads.append(
                _load_npz(os.path.join(d, f"t{tid}-a{a}-p{p}.npz"))
            )
        if empty is None and written:
            # remember any payload's schema for the empty-result case
            empty = os.path.join(d, f"t{tid}-a{a}-p{written[0]}.npz")
    if not payloads:
        if empty is not None:
            p = _load_npz(empty)
            return {
                "names": p["names"], "types": p["types"],
                "cols": [
                    (v[:0], None if valid is None else valid[:0])
                    for v, valid in p["cols"]
                ],
            }
        raise FileNotFoundError(
            f"stage {stage_id}: no data for partition {partition}"
        )
    return _concat_payloads(payloads)
