"""Single-fragment plan execution over device pages.

The analog of the reference's LocalExecutionPlanner + Driver
(MAIN/sql/planner/LocalExecutionPlanner.java:527,
MAIN/operator/Driver.java:66) collapsed into a batch-synchronous tree
walk: each plan node consumes whole device Pages and produces one —
there is no page-at-a-time pull loop because a TPU wants one large
batched computation per operator, not 4KB batches. Host syncs happen
only at capacity decisions (join fan-out, group counts), mirroring the
reference's build-side barriers.
"""

from __future__ import annotations

import os
import time
from dataclasses import replace as dc_replace

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import fault, jit_cache, memory, program_catalog, telemetry
from trino_tpu import types as T
from trino_tpu.exec import kernels as K
from trino_tpu.exec import shapes, stage
from trino_tpu.exec.aggregates import compute_aggregate
from trino_tpu.expr.compiler import ColumnLayout, compile_expr
from trino_tpu.expr.ir import AggCall, Call, Cast, InputRef, RowExpression
from trino_tpu.metadata import Metadata, Session
from trino_tpu.page import Column, Page, pad_capacity, unify_dictionaries
from trino_tpu.plan import nodes as P

__all__ = ["LocalExecutor", "QueryCancelled"]


def _hash_varchar_column(t, values, valid, capacity) -> Column:
    """Build a hash-coded varchar column: [hash64, source_row_id]
    lanes + a host string pool (one hash pass + a one-time injectivity
    proof — no sorted-dictionary build). On the astronomically rare
    hash collision, fall back to dictionary coding."""
    from trino_tpu.page import HashCollision, HashStringPool

    pool = HashStringPool(values)
    try:
        pool.verify_injective()
    except HashCollision:
        return Column.from_numpy(t, values, valid=valid, capacity=capacity)
    n = len(values)
    data = np.zeros((capacity, 2), dtype=np.int64)
    data[:n, 0] = pool.hashes()
    data[:n, 1] = np.arange(n)
    col_valid = None
    if valid is not None:
        v = np.zeros(capacity, dtype=np.bool_)
        v[:n] = valid
        col_valid = jnp.asarray(v)
    return Column(t, jnp.asarray(data), col_valid, None, pool)


def _scan_column(t, raw, capacity, hashed: bool = False) -> Column:
    """One scanned connector value -> device Column (shared by the
    cached full-table scan and fleet split scans). ``raw`` is either a
    host array or a (values, valid) tuple."""
    valid = None
    if isinstance(raw, tuple):
        raw, valid = raw
    if hashed:
        return _hash_varchar_column(
            t, np.asarray(raw, dtype=object), valid, capacity
        )
    return Column.from_numpy(t, raw, valid=valid, capacity=capacity)


#: seconds a fired ``compile-delay`` fault stalls one dispatch
COMPILE_DELAY_ENV = "TRINO_TPU_COMPILE_DELAY_S"
DEFAULT_COMPILE_DELAY_S = 0.25


def _maybe_compile_delay() -> None:
    """``compile-delay`` fault hook on the chain-dispatch path.

    Unlike every other site, a fired fault here fails NOTHING: it
    sleeps inside a compile-kind child of the thread's trace anchor,
    so the stall lands in the flight recorder's xla_compile bucket —
    a deterministic stand-in for an XLA recompile storm that the
    performance sentry must detect and attribute on a WARMED statement
    (whose real programs are cached and never recompile)."""
    try:
        fault.check("compile-delay")
        return
    except fault.InjectedFault:
        pass
    delay = float(
        os.environ.get(COMPILE_DELAY_ENV, "") or DEFAULT_COMPILE_DELAY_S
    )
    parent = jit_cache.active_span()
    sp = (
        parent.child("injected-compile-delay", "compile")
        if parent is not None else None
    )
    try:
        time.sleep(delay)
    finally:
        if sp is not None:
            sp.finish()


class QueryCancelled(RuntimeError):
    """Raised inside the executor when the query's cancel event fires
    (cooperative cancellation: in-flight device dispatches finish, the
    next operator boundary aborts — the reference cancels at its
    driver-quantum boundaries the same way, MAIN/operator/Driver.java)."""


class LocalExecutor:
    """Executes a logical plan tree on the local devices.

    Per-node device computations are traced into jitted programs cached
    by (plan structure, input layout, capacity) — the analog of the
    reference's per-query bytecode generation with
    PageFunctionCompiler's cache (MAIN/sql/gen/PageFunctionCompiler.java:102).
    Scanned tables are cached device-resident (a worker's memory
    connector analog), so repeated queries pay no host->HBM transfer.
    """

    def __init__(self, metadata: Metadata, session: Session):
        self.metadata = metadata
        self.session = session
        # feed trino_xla_compile_total/_seconds_total from jax's own
        # compile events (idempotent process-wide hook)
        telemetry.install_jax_compile_hook()
        #: structural key -> (jitted fn, host metadata); hit/miss rates
        #: surface as trino_jit_cache_{hits,misses}_total{cache="local"}
        self._jit_cache: dict = telemetry.CountingCache("local")
        #: dynamic-filter effectiveness log (tests + EXPLAIN ANALYZE):
        #: [{rows_in, rows_kept, pairs}] per join probe this executor ran
        self.df_log: list[dict] = []
        #: storage-scan pruning/streaming log (tests + EXPLAIN ANALYZE):
        #: [{table, rowgroups_total, rowgroups_pruned, partitions_pruned,
        #:   batches?, streamed?}] per pruned or streamed scan
        self.scan_log: list[dict] = []
        #: worker-local memory pool: every device allocation path
        #: reserves through a MemoryContext rooted here, and the
        #: per-node cap (query_max_memory_per_node) is enforced at
        #: reservation time
        self.memory_pool = memory.MemoryPool(
            limit_provider=self._per_node_cap
        )
        #: active query context — swapped per query by QueryRunner /
        #: the worker task loop; a default exists so direct executor
        #: use (tests, EXPLAIN) never needs getattr guards
        self.memory_ctx = self.memory_pool.query_context("adhoc")
        #: joins revoked into the spill tier by memory pressure
        #: (count of MemoryRevokingScheme-analog conversions)
        self.memory_revocations = 0
        #: revocation budget in force while a revoked subtree runs
        #: (makes hbm_budget() nonzero so spill paths chunk under it)
        self._revoked_budget = 0
        #: grace-join observability counters (exec.spill writes these;
        #: real fields so call sites never need getattr defaults)
        self.grace_recursion_hwm = 0
        self.grace_hot_pairs = 0
        #: cooperative cancellation: set by the coordinator, checked at
        #: operator boundaries
        self.cancel_event = None
        #: absolute monotonic deadline (query_max_execution_time): set
        #: by the engine per statement, checked at the same boundaries
        self.deadline = None
        #: batched chain prefetch results: id(chain top node) ->
        #: (node, Page) — populated by _prefetch_join_chains, consumed
        #: by execute(); holding the node object pins its id
        self._prefetched: dict = {}
        #: the Output node's source: the FINAL chain defers its
        #: flags/count sync into the result transfer (one fewer host
        #: round trip per query — material on a remote-device tunnel)
        self._defer_sync_for: P.PlanNode | None = None
        #: per-operator profiler (trino_tpu.profiler.OperatorProfiler)
        #: set for the duration of one query/task; None = no profiling
        self.profiler = None
        #: jit-cache key -> abstract (env, mask) avals captured at
        #: dispatch time, feeding lazy XLA cost analysis
        self._chain_avals: dict = {}
        #: jit-cache key -> {"flops", "bytes_accessed"} | None (the
        #: lazy cost cache; None records an analysis that failed so it
        #: is never retried)
        self._chain_costs: dict = {}
        #: per-query cache.CacheStats sink (set by the engine around
        #: each statement; None = device-tier traffic not attributed)
        self.cache_stats = None

    def hbm_budget(self) -> int:
        """Device-memory budget in bytes (session ``hbm_budget_bytes``;
        0 = resident mode). Tables/joins whose working sets exceed it
        stream through exec.spill instead of materializing. While a
        memory-revoked subtree runs, the per-node cap stands in as the
        budget so the whole subtree degrades into the spill tier."""
        from trino_tpu import session_properties as SP

        budget = int(SP.get(self.session, "hbm_budget_bytes"))
        return budget or self._revoked_budget

    def _per_node_cap(self) -> int:
        """query_max_memory_per_node in bytes (0 = unlimited)."""
        from trino_tpu import session_properties as SP

        return SP.parse_data_size(
            SP.get(self.session, "query_max_memory_per_node")
        )

    @property
    def tracked_bytes_hwm(self) -> int:
        """Largest tracked device working set this executor ever
        reserved (lifetime pool high-water mark; the budget-tier tests
        assert it stays within hbm_budget_bytes). Pre-governance this
        was an ad-hoc field; it is now derived from the memory pool."""
        return self.memory_pool.peak_bytes

    def invalidate_scan(self, catalog: str, schema: str, table: str):
        """Drop cached device pages for a table (called after writes —
        the reference's memory connector versions table handles the
        same way). Pages live in the process-wide shared cache, so a
        write through this executor also invalidates every concurrent
        reader of the same connector. Learned statistics (filter
        selectivities, group-by capacities) are dropped with it: they
        were observed against the pre-write data and would otherwise
        persist stale forever."""
        from trino_tpu.exec import scan_cache

        try:
            connector = self.metadata.connector(catalog)
        except KeyError:
            connector = None
        if connector is not None:
            scan_cache.SHARED.invalidate(connector, schema, table)
            scan_cache.SHARED_SPLITS.invalidate(connector, schema, table)
            if hasattr(connector, "invalidate"):
                connector.invalidate(schema, table)
            # cross-query cache tiers: bump the generation counter so
            # device pages and semantic results built over the old data
            # revalidate stale on their next probe, and drop this
            # process's pinned device entries eagerly
            from trino_tpu import cache as xcache

            ident, _content = xcache.connector_fingerprint(connector)
            xcache.GENERATIONS.bump(ident, schema, table)
            xcache.DEVICE.invalidate(ident, schema, table)
        for k in [
            k for k in self._jit_cache
            if isinstance(k, tuple) and k and k[0] in ("selectivity", "caps")
        ]:
            del self._jit_cache[k]

    def _device_cache_on(self) -> bool:
        """Session gate for the cross-query HBM tier (cache.DEVICE)."""
        from trino_tpu import session_properties as SP

        try:
            return bool(SP.get(self.session, "device_cache_enabled"))
        except Exception:
            return False

    def _cache_tokens(self, connector, schema: str, table: str) -> tuple:
        """Single-table staleness validators for a device-cache entry."""
        from trino_tpu import cache as xcache

        ident, _content = xcache.connector_fingerprint(connector)
        try:
            version = connector.table_version(schema, table)
        except Exception:
            version = 0
        return ((
            ident, schema, table,
            xcache.GENERATIONS.get(ident, schema, table), version,
        ),)

    def _check_cancel(self):
        if self.cancel_event is not None and self.cancel_event.is_set():
            raise QueryCancelled("Query was canceled")
        if self.deadline is not None and time.monotonic() > self.deadline:
            from trino_tpu.tracker import QueryDeadlineExceededError

            raise QueryDeadlineExceededError(
                "Query exceeded maximum execution time limit "
                "[query_max_execution_time]"
            )

    def execute(self, node: P.PlanNode) -> Page:
        prof = self.profiler
        if prof is None:
            return self._execute_impl(node)
        rec = prof.open(self._op_label(node), type(node).__name__, id(node))
        try:
            out = self._execute_impl(node)
        except BaseException:
            prof.close(rec, None)
            raise
        prof.close(rec, out)
        return out

    @staticmethod
    def _op_label(node: P.PlanNode) -> str:
        """Display label for one profiled operator. A fused chain
        executes as one XLA program, so its head labels the whole
        chain; everything else is its node type."""
        if isinstance(node, stage.FUSABLE):
            names = []
            cur = node
            while isinstance(cur, stage.FUSABLE):
                names.append(type(cur).__name__)
                cur = cur.sources[0]
            if len(names) > 1:
                return "→".join(reversed(names))
        return type(node).__name__

    def _execute_impl(self, node: P.PlanNode) -> Page:
        self._check_cancel()
        if isinstance(node, P.Output):
            # top of a query: drop any prefetch leftovers of a prior
            # (failed) query so node ids never alias across plans
            self._prefetched.clear()
        hit = self._prefetched.pop(id(node), None)
        if hit is not None and hit[0] is node:
            return hit[1]
        if isinstance(node, stage.FUSABLE):
            chain: list[P.PlanNode] = []
            cur = node
            while isinstance(cur, stage.FUSABLE):
                chain.append(cur)
                cur = cur.sources[0]
            if isinstance(cur, P.TableScan):
                from trino_tpu.exec import stream_scan

                if stream_scan.eligible(self, cur):
                    return stream_scan.run_chain_streamed(
                        self, list(reversed(chain)), cur
                    )
                budget = self.hbm_budget()
                if budget:
                    from trino_tpu.exec import spill

                    if spill.scan_bytes(self.metadata, cur) > budget // 4:
                        return spill.run_chain_streamed(
                            self, list(reversed(chain)), cur
                        )
                # not streaming (disabled or ineligible): the resident
                # materialization must still fit the per-node cap
                stream_scan.enforce_resident_fits(self, cur)
            base = self.execute(cur)
            return self._run_chain(list(reversed(chain)), base)
        m = getattr(self, f"_{type(node).__name__}", None)
        if m is None:
            raise NotImplementedError(f"no executor for {type(node).__name__}")
        return m(node)

    # ---- fused pipelines -------------------------------------------------

    @staticmethod
    def _node_key(n: P.PlanNode):
        if isinstance(n, P.Filter):
            return ("F", repr(n.predicate))
        if isinstance(n, P.Project):
            return ("P", tuple((s, repr(e)) for s, e in n.assignments.items()))
        if isinstance(n, P.Aggregate):
            return (
                "A", tuple(n.group_keys),
                tuple(
                    (s, a.name, a.distinct, repr(a.args), repr(a.filter))
                    for s, a in n.aggregates.items()
                ),
                n.step,
                # compiled programs bake key shift offsets/widths, so
                # different ranges must compile separately
                None if n.key_ranges is None else tuple(
                    sorted(n.key_ranges.items())
                ),
            )
        if isinstance(n, (P.Sort, P.TopN)):
            return (
                "S",
                tuple((k.symbol, k.ascending, k.nulls_first) for k in n.keys),
                getattr(n, "count", None),
            )
        if isinstance(n, P.Limit):
            return ("L", n.count, n.offset)
        raise NotImplementedError(type(n).__name__)

    def _run_chain(self, chain: list[P.PlanNode], page: Page) -> Page:
        self._check_cancel()  # also covers streamed per-chunk calls
        return self._run_chain_inner(chain, page)

    def _run_chain_inner(self, chain: list[P.PlanNode], page: Page) -> Page:
        """Run a fused operator chain: one jitted program, one dispatch.

        Grouped aggregations retry with 8x larger slot tables when the
        returned overflow flag trips; the learned capacity persists per
        chain shape so repeated queries never re-overflow (capacity
        hysteresis — the FlatHash table survives across pages in the
        reference, MAIN/operator/FlatHash.java:316).

        With session property ``max_chunk_rows`` set, aggregations over
        wider inputs run memory-bounded: partial aggregation per row
        chunk, then a FINAL combine over the concatenated partials —
        the working-set analog of the reference's spillable aggregation
        (MAIN/operator/aggregation/builder/SpillableHashAggregationBuilder.java:46);
        the chunk partials play the role of spilled sorted runs."""
        agg_arr = next(
            (
                i for i, n in enumerate(chain)
                if isinstance(n, P.Aggregate)
                and any(
                    c.name in ("array_agg", "map_agg")
                    for c in n.aggregates.values()
                )
            ),
            None,
        )
        if agg_arr is not None:
            # array construction is host-resident by design (pools);
            # the aggregation runs as a host group-by
            return self._host_array_agg(chain, agg_arr, page)
        # adaptive filter split: a selective leading Filter shrinks the
        # working capacity for the whole rest of the chain (dead-row
        # sorts/gathers dominate otherwise). Selectivity is learned per
        # chain shape; non-selective filters stay fused (the split
        # costs one extra sync + compaction).
        if (
            len(chain) > 1
            and isinstance(chain[0], P.Filter)
            and page.capacity >= (1 << 18)
            and any(
                isinstance(n, (P.Aggregate, P.Sort, P.TopN))
                for n in chain[1:]
            )
        ):
            skey = (
                "selectivity", self._node_key(chain[0]), page.capacity,
            )
            sel = self._jit_cache.get(skey)
            if sel is None or sel <= 0.5:
                filtered = self._run_chain(chain[:1], page)
                self._jit_cache[skey] = (
                    filtered.num_rows() / page.capacity
                )
                return self._run_chain(chain[1:], filtered)

        from trino_tpu import session_properties as SP

        chunk_rows = int(SP.get(self.session, "max_chunk_rows"))
        if chunk_rows > 0 and page.capacity > chunk_rows:
            # only SINGLE-step aggregations chunk: the FINAL combine
            # over the partial states materializes O(distinct keys),
            # like the reference's spill merge pass
            agg_i = next(
                (
                    i for i, n in enumerate(chain)
                    if isinstance(n, P.Aggregate)
                    and n.group_keys
                    and n.step == "SINGLE"
                ),
                None,
            )
            if (
                agg_i is not None
                and not any(
                    c.distinct
                    for c in chain[agg_i].aggregates.values()
                )
                # every pre node must be row-local: a Limit/Sort/TopN
                # before the aggregate is global, not per-chunk
                and all(
                    isinstance(n, (P.Filter, P.Project))
                    for n in chain[:agg_i]
                )
                and _splittable(chain[agg_i])
            ):
                return self._run_chain_chunked(
                    chain, page, agg_i, chunk_rows
                )
        caps_key = (
            "caps", tuple(self._node_key(n) for n in chain), page.capacity
        )
        learned = self._jit_cache.get(caps_key)
        if learned is not None:
            caps = {i: list(v) for i, v in learned.items()}
        else:
            caps = stage.plan_capacities(chain, page.capacity)
        while True:
            env, mask, flags, n_live_dev, out_layout = self._dispatch_chain(
                chain, page, caps
            )
            if (
                chain and chain[-1] is self._defer_sync_for
                and getattr(self, "_defer_ok", False)
            ):
                # final chain of the query: skip the sync — the result
                # transfer fetches the overflow flags alongside the
                # data, and the engine retries the query if one
                # tripped (learned capacities make that the rare path)
                out = Page(
                    list(out_layout.names),
                    [
                        Column(
                            out_layout.types[s], env[s][0], env[s][1],
                            out_layout.dicts.get(s),
                            out_layout.pools.get(s),
                            out_layout.arrays.get(s),
                        )
                        for s in out_layout.names
                    ],
                    mask,
                )
                out.pending_flags = (flags, caps_key, caps)
                return out
            # one host sync fetches overflow flags AND the live count,
            # so downstream consumers (compact, joins, result fetch)
            # never re-sync
            vals, n_live = jax.device_get((flags, n_live_dev))
            if vals:
                overflowed = [i for i, v in vals.items() if v]
                if overflowed:
                    for i in overflowed:
                        cap, mx = caps[i]
                        if cap >= mx:
                            raise RuntimeError(
                                "aggregation table overflow at max capacity"
                            )
                        caps[i][0] = min(cap * 8, mx)
                    self._jit_cache[caps_key] = {
                        i: list(v) for i, v in caps.items()
                    }
                    continue
            return self._finalize_chain(
                chain, env, mask, int(n_live), out_layout
            )

    def _host_array_agg(self, chain, agg_i: int, page: Page) -> Page:
        """array_agg as a host group-by building ArrayPools (the
        reference's ArrayAggregationFunction materializes per-group
        BlockBuilders the same way; pools are host-side here by
        design). NULL inputs are skipped. Other aggregates cannot mix
        with array_agg in one GROUP BY yet."""
        from trino_tpu.exec.spool import page_to_host

        nd = chain[agg_i]
        for call in nd.aggregates.values():
            if call.name not in ("array_agg", "map_agg"):
                raise NotImplementedError(
                    "array_agg/map_agg cannot combine with other "
                    "aggregates in one GROUP BY yet"
                )
            if len(call.args) != (2 if call.name == "map_agg" else 1):
                raise NotImplementedError(
                    f"{call.name} argument count"
                )
        pre = list(chain[:agg_i])
        # computed arguments materialize through an inserted Project so
        # the host group-by below reads plain columns
        if any(
            not isinstance(a, InputRef)
            for call in nd.aggregates.values() for a in call.args
        ):
            src_outputs = (
                pre[-1].outputs if pre
                else {
                    nm: c.type for nm, c in zip(page.names, page.columns)
                }
            )
            assigns = {
                s: InputRef(t, s) for s, t in src_outputs.items()
            }
            new_aggs = {}
            for sym, call in nd.aggregates.items():
                new_args = []
                for j, a in enumerate(call.args):
                    if isinstance(a, InputRef):
                        new_args.append(a)
                    else:
                        tmp = f"{sym}__arg{j}"
                        assigns[tmp] = a
                        new_args.append(InputRef(a.type, tmp))
                new_aggs[sym] = dc_replace(call, args=tuple(new_args))
            proj = P.Project(
                {s: e.type for s, e in assigns.items()},
                source=nd.sources[0] if nd.sources else None,
                assignments=assigns,
            )
            pre.append(proj)
            nd = dc_replace(nd, aggregates=new_aggs)
        if pre:
            page = self._run_chain(pre, page)
        payload = page_to_host(self._compact(page))
        col_of = dict(zip(payload["names"], payload["cols"]))
        type_of = dict(zip(payload["names"], payload["types"]))
        n = len(payload["cols"][0][0]) if payload["cols"] else 0
        keys = list(nd.group_keys)
        if keys:
            lanes = []
            for k in reversed(keys):
                v, valid = col_of[k]
                if v.dtype == object or v.dtype.kind == "U":
                    _u, codes = np.unique(v.astype(str), return_inverse=True)
                    lanes.append(codes)
                else:
                    lanes.append(v)
                if valid is not None:
                    lanes.append((~valid).astype(np.int8))
            order = np.lexsort(lanes)
        else:
            order = np.arange(n)
        # group boundaries over the sorted rows
        def key_tuple(i):
            out = []
            for k in keys:
                v, valid = col_of[k]
                out.append(
                    None if (valid is not None and not valid[i]) else v[i]
                )
            return tuple(out)

        groups: list[list[int]] = []
        last = object()
        for i in order:
            kt = key_tuple(i) if keys else ()
            if kt != last:
                groups.append([])
                last = kt
            groups[-1].append(i)
        if not keys and not groups:
            groups = [[]]
        out_named: dict[str, tuple] = {}
        for k in keys:
            v, valid = col_of[k]
            firsts = [g[0] for g in groups]
            kv = v[firsts] if len(firsts) else v[:0]
            kval = None if valid is None else valid[firsts]
            out_named[k] = (type_of[k], kv, kval)
        for sym, call in nd.aggregates.items():
            if call.name == "map_agg":
                # one (key, value) entry per row with a non-NULL key
                # (MapAggAggregationFunction semantics)
                kv, kvalid = col_of[call.args[0].name]
                vv, vvalid = col_of[call.args[1].name]
                maps = np.empty(len(groups), dtype=object)
                for gi, g in enumerate(groups):
                    maps[gi] = [
                        (
                            kv[i],
                            None if (vvalid is not None and not vvalid[i])
                            else vv[i],
                        )
                        for i in g
                        if kvalid is None or kvalid[i]
                    ]
                out_named[sym] = (nd.outputs[sym], maps, None)
                continue
            src = call.args[0].name
            v, valid = col_of[src]
            lists = np.empty(len(groups), dtype=object)
            for gi, g in enumerate(groups):
                lists[gi] = [
                    v[i] for i in g
                    if valid is None or valid[i]
                ]
            out_named[sym] = (nd.outputs[sym], lists, None)
        cap = shapes.bucket(max(len(groups), 1), site="agg-host")
        names, cols = [], []
        for s, (t, vals, valid) in out_named.items():
            names.append(s)
            cols.append(Column.from_numpy(t, vals, valid=valid, capacity=cap))
        m = np.zeros(cap, dtype=np.bool_)
        m[: len(groups)] = True
        out = Page(
            names, cols, jnp.asarray(m),
            known_rows=len(groups), packed=True,
        )
        if chain[agg_i + 1:]:
            return self._run_chain(chain[agg_i + 1:], out)
        return out

    def _canon_view(self, chain, page: Page):
        """(chain', page', out_map) with the chain rewritten to
        nameless normal form and the page pruned/renamed to match —
        the cache-key normalization that lets distinct queries sharing
        an operator mix resolve to one compiled program. out_map is
        None when bucketing is OFF or the chain has a construct the
        rewriter does not cover (callers then key on original names)."""
        if not shapes.enabled(self.session):
            return chain, page, None
        canon = shapes.canonicalize_chain(chain, list(page.names))
        if canon is None:
            return chain, page, None
        cols = dict(zip(page.names, page.columns))
        view = Page(
            list(canon.in_map.values()),
            [cols[o] for o in canon.in_map],
            page.mask,
            known_rows=page.known_rows, packed=page.packed,
        )
        return canon.chain, view, canon.out_map

    def _dispatch_chain(self, chain, page: Page, caps):
        """Compile (cached) + dispatch one fused chain program without
        waiting for the result — callers sync when they need the flags
        and live count (batched across independent chains where
        possible)."""
        chain, page, out_map = self._canon_view(chain, page)
        key = (
            "chain",
            tuple(self._node_key(n) for n in chain),
            tuple((i, c[0]) for i, c in sorted(caps.items())),
            self._layout_sig(page),
        )
        hit = self._jit_cache.get(key)
        was_miss = hit is None
        if was_miss:
            in_layout = stage.ChainLayout(
                names=list(page.names),
                types={
                    n: c.type for n, c in zip(page.names, page.columns)
                },
                dicts={
                    n: c.dictionary
                    for n, c in zip(page.names, page.columns)
                },
                capacity=page.capacity,
                pools={
                    n: c.hash_pool
                    for n, c in zip(page.names, page.columns)
                    if c.hash_pool is not None
                },
                arrays={
                    n: c.array_pool
                    for n, c in zip(page.names, page.columns)
                    if c.array_pool is not None
                },
            )
            fn, out_layout = stage.build_chain(chain, in_layout, caps)

            def counted(env, mask, _fn=fn):
                env2, mask2, flags = _fn(env, mask)
                return env2, mask2, flags, K.count_true(mask2)

            hit = (jax.jit(counted), out_layout)
            self._jit_cache[key] = hit
        fn, out_layout = hit
        env_in = self._env(page)
        if key not in self._chain_avals:
            # shape metadata only — feeds lazy cost analysis without
            # touching device data or the dispatch hot path
            abstract = jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                (env_in, page.mask),
            )
            self._chain_avals[key] = abstract
        if was_miss:
            # catalog the freshly built program; the resolver lowers at
            # the recorded avals on first cost/memory/HLO read
            program_catalog.CATALOG.register(
                key, source="local",
                label="→".join(type(n).__name__ for n in chain),
                resolver=program_catalog.aot_resolver(
                    fn, self._chain_avals[key]
                ),
            )
        else:
            program_catalog.CATALOG.note_hit(key)
        if self.profiler is not None:
            self.profiler.note_dispatch(key)
        _maybe_compile_delay()
        if was_miss:
            # the first call pays jit trace + backend compile (or a
            # persistent-cache deserialize) before the async dispatch
            # returns — that wall IS the program's compile cost
            t0 = time.perf_counter()
            env, mask, flags, n_live_dev = fn(env_in, page.mask)
            program_catalog.CATALOG.note_compile_seconds(
                key, time.perf_counter() - t0
            )
        else:
            env, mask, flags, n_live_dev = fn(env_in, page.mask)
        if out_map is not None:
            # the cached program speaks canonical names; translate its
            # outputs back for this call (the cached out_layout is
            # shared — never mutate it)
            out_layout, env = _rename_out(out_layout, env, out_map)
        return env, mask, flags, n_live_dev, out_layout

    def chain_cost(self, key) -> dict | None:
        """XLA cost model ({'flops', 'bytes_accessed'}) for one cached
        chain program, read through the process-wide program catalog —
        one lazy ``lower().compile()`` per program (a persistent-cache
        deserialize of what the dispatch path already built), shared
        with every other catalog consumer instead of recomputed per
        lookup. A failed analysis memoizes as None per executor so it
        is not retried per query."""
        if key in self._chain_costs:
            return self._chain_costs[key]
        if program_catalog.CATALOG.entry_for(key) is None:
            # executor restored from a snapshot / catalog evicted: the
            # program still lives in the jit cache, so re-catalog it.
            # plain dict.get: a cost lookup is not a cache hit/miss
            # event (CountingCache feeds trino_jit_cache_* counters)
            hit = dict.get(self._jit_cache, key)
            abstract = self._chain_avals.get(key)
            if hit is None or abstract is None:
                self._chain_costs[key] = None
                return None
            codes = {"F": "Filter", "P": "Project", "A": "Aggregate",
                     "S": "Sort", "T": "TopN", "L": "Limit"}
            label = "→".join(
                codes.get(k[0], str(k[0])) for k in key[1]
            ) or "chain"
            program_catalog.CATALOG.register(
                key, source="local", label=label,
                resolver=program_catalog.aot_resolver(hit[0], abstract),
            )
        cost = program_catalog.CATALOG.cost(key)
        self._chain_costs[key] = cost
        return cost

    def _finalize_chain(self, chain, env, mask, n_live: int, out_layout):
        cols = [
            Column(
                out_layout.types[s],
                env[s][0],
                env[s][1],
                out_layout.dicts.get(s),
                out_layout.pools.get(s),
                out_layout.arrays.get(s),
            )
            for s in out_layout.names
        ]
        out = Page(list(out_layout.names), cols, mask)
        out.known_rows = n_live
        # chains ending in a sort emit live rows first (sort_perm
        # pushes dead rows last)
        out.packed = isinstance(chain[-1], (P.Sort, P.TopN))
        if pad_capacity(out.known_rows) < out.capacity:
            out = self._compact(out)
        return out

    def _run_chain_chunked(
        self, chain: list[P.PlanNode], page: Page, agg_i: int, chunk_rows: int
    ) -> Page:
        """Partial-aggregate fixed-size row chunks, concatenate the
        partial states, FINAL-combine (grace aggregation)."""
        from trino_tpu.plan.distribute import _split_aggregate

        partial, final = _split_aggregate(chain[agg_i])
        pre = chain[:agg_i]
        post = chain[agg_i + 1:]
        uniform = (
            shapes.enabled(self.session) and page.capacity > chunk_rows
        )
        partials = []
        for lo in range(0, page.capacity, chunk_rows):
            hi = min(lo + chunk_rows, page.capacity)
            if uniform and hi - lo < chunk_rows:
                # back the final window up to full width so every chunk
                # shares one shape (and one compiled partial program);
                # mask off the rows the previous chunk already covered
                sl = _slice_page(
                    page, page.capacity - chunk_rows, page.capacity
                )
                overlap = chunk_rows - (hi - lo)
                sl.mask = sl.mask.at[:overlap].set(False)
                sl.known_rows = None
                shapes.record_waste("agg-chunk", hi - lo, chunk_rows)
            else:
                sl = _slice_page(page, lo, hi)
            partials.append(self._run_chain(pre + [partial], sl))
        combined = _concat_pages(partials)
        return self._run_chain([final] + post, combined)

    # ---- expression evaluation ------------------------------------------

    def _layout(self, page: Page) -> ColumnLayout:
        return ColumnLayout(
            types={n: c.type for n, c in zip(page.names, page.columns)},
            dictionaries={
                n: c.dictionary for n, c in zip(page.names, page.columns)
            },
            array_pools={
                n: c.array_pool for n, c in zip(page.names, page.columns)
                if c.array_pool is not None
            },
        )

    def _layout_sig(self, page: Page) -> tuple:
        # dictionary identity is its CONTENT fingerprint, not id():
        # spool-read pages rebuild equal dictionaries per statement,
        # and id-keyed programs would never be shared across them
        return tuple(
            (
                n, repr(c.type),
                None if c.dictionary is None else c.dictionary.fingerprint,
                None if c.hash_pool is None else c.hash_pool.token,
                None if c.array_pool is None else c.array_pool.token,
                c.valid is not None,
            )
            for n, c in zip(page.names, page.columns)
        ) + (page.capacity,)

    def _env(self, page: Page) -> dict:
        return {n: (c.data, c.valid) for n, c in zip(page.names, page.columns)}

    def _eval(self, page: Page, expr: RowExpression):
        """Evaluate one expression over a page (eager path for join
        residuals etc.). Returns (data, valid, dictionary), data
        broadcast to the page capacity."""
        compiled = compile_expr(expr, self._layout(page))
        data, valid = compiled.fn(self._env(page))
        cap = page.capacity
        if jnp.ndim(data) == 0:
            data = jnp.broadcast_to(data, (cap,))
        if valid is not None and jnp.ndim(valid) == 0:
            valid = jnp.broadcast_to(valid, (cap,))
        return data, valid, compiled.dictionary

    # ---- leaf nodes ------------------------------------------------------

    def _RemoteSource(self, node: P.RemoteSource) -> Page:
        """Pages for remote sources arrive out-of-band (fleet tasks
        resolve spool partitions before execution — the analog of the
        ExchangeOperator's pulled pages, MAIN/operator/ExchangeOperator.java:43)."""
        pages = getattr(self, "remote_pages", None) or {}
        if node.source_id not in pages:
            raise RuntimeError(f"no pages bound for remote source {node.source_id!r}")
        return pages[node.source_id]

    def _TableScan(self, node: P.TableScan) -> Page:
        if node.split is not None:
            return self._scan_split(node)
        connector = self.metadata.connector(node.catalog)
        if node.domains and node.assignments and getattr(
            connector, "supports_domains", False
        ):
            # domain-pruned scans bypass the device cache (the pruned
            # row set is filter-specific, not the table)
            return self._scan_pruned(node, connector)
        if not connector.cacheable:
            cache = {}  # live views (system tables) re-scan per query
        else:
            # process-wide shared pages: concurrent queries (and other
            # executors over the same connector) reuse one resident copy
            from trino_tpu.exec import scan_cache

            cache = scan_cache.SHARED.table(
                connector, node.schema, node.table
            )
        hashed_syms = set(node.hash_varchar or [])
        # hash-coded and dictionary-coded variants of a column cache
        # under distinct keys (a symbol's encoding is plan-dependent)
        def ckey(sym, cname):
            return f"#hash:{cname}" if sym in hashed_syms else cname

        missing = [
            (s, c) for s, c in node.assignments.items()
            if ckey(s, c) not in cache
        ]
        if missing or "" not in cache:
            connector = self.metadata.connector(node.catalog)
            cols = connector.scan(
                node.schema, node.table, [c for _, c in missing]
            )
            if missing:
                # row count from the scanned arrays themselves: a
                # second row_count() call could see a DIFFERENT
                # snapshot on live views (system tables)
                first = cols[missing[0][1]]
                n = len(first[0] if isinstance(first, tuple) else first)
            else:
                n = connector.row_count(node.schema, node.table)
            cap = shapes.bucket(n, site="scan")
            if "" not in cache:
                mask = np.zeros(cap, dtype=np.bool_)
                mask[:n] = True
                cache[""] = jnp.asarray(mask)
            for sym, cname in missing:
                cache[ckey(sym, cname)] = _scan_column(
                    node.outputs[sym], cols[cname], cap,
                    hashed=sym in hashed_syms,
                )
            cache["#rows"] = n
        names = list(node.assignments)
        columns = [
            cache[ckey(s, c)] for s, c in node.assignments.items()
        ]
        return Page(
            names, columns, cache[""],
            known_rows=cache["#rows"], packed=True,
        )

    def _scan_pruned(self, node: P.TableScan, connector) -> Page:
        """Scan with TupleDomain pushdown: the connector prunes storage
        units (parquet rowgroups) by footer stats; the filter above
        re-applies, so results stay exact (PushPredicateIntoTableScan +
        rowgroup pruning, lib/trino-parquet/.../reader/ParquetReader.java:85).

        With ``device_cache_enabled``, the pruned device page is pinned
        in the cross-query HBM tier keyed by connector fingerprint +
        assignments + the pushed domains (a pruned row set is
        filter-specific, so the domains ARE the key — this closes the
        historical cache bypass for domain-pushdown scans)."""
        from trino_tpu.connectors.base import ColumnDomain

        dkey = tokens = None
        if self._device_cache_on():
            from trino_tpu import cache as xcache

            hashed = set(node.hash_varchar or [])
            dkey = xcache.DEVICE.scan_key(
                connector, node.schema, node.table,
                tuple(
                    (s, c, s in hashed)
                    for s, c in node.assignments.items()
                ),
                domains=node.domains,
            )
            if dkey is not None:
                hit = xcache.DEVICE.get(dkey, self.cache_stats)
                if hit is not None:
                    return hit
                tokens = self._cache_tokens(
                    connector, node.schema, node.table
                )
        domains = {
            c: ColumnDomain(*dom) for c, dom in node.domains.items()
        }
        cols = connector.scan(
            node.schema, node.table, list(node.assignments.values()),
            domains=domains,
        )
        metrics = getattr(connector, "scan_metrics", None)
        if metrics:
            self.scan_log.append({
                "table": f"{node.schema}.{node.table}",
                "streamed": False,
                "rowgroups_total": int(metrics.get("rowgroups_total", 0)),
                "rowgroups_pruned": int(
                    metrics.get(
                        "rowgroups_pruned",
                        metrics.get("rowgroups_total", 0)
                        - metrics.get("rowgroups_read", 0),
                    )
                ),
                "partitions_pruned": int(
                    metrics.get("partitions_pruned", 0)
                ),
            })
            del self.scan_log[:-100]  # bounded: executors outlive queries
        first = cols[next(iter(node.assignments.values()))]
        n = len(first[0] if isinstance(first, tuple) else first)
        cap = shapes.bucket(n, site="scan")
        hashed_syms = set(node.hash_varchar or [])
        names, columns = [], []
        for sym, cname in node.assignments.items():
            names.append(sym)
            columns.append(_scan_column(
                node.outputs[sym], cols[cname], cap,
                hashed=sym in hashed_syms,
            ))
        mask = np.zeros(cap, dtype=np.bool_)
        mask[:n] = True
        page = Page(
            names, columns, jnp.asarray(mask), known_rows=n, packed=True,
        )
        if dkey is not None and tokens is not None:
            from trino_tpu import cache as xcache

            xcache.DEVICE.put(dkey, page, tokens, pool=self.memory_pool)
        return page

    def _scan_split(self, node: P.TableScan) -> Page:
        """Scan one row-range split of a table (fleet-mode source
        parallelism). With ``device_cache_enabled`` the split page is
        pinned in the cross-query HBM tier keyed by connector
        fingerprint + split range + assignments + pushed domains, so a
        serving worker re-assigned the same split on a repeat statement
        pays no host->device transfer; otherwise split scans stay
        uncached (a worker sees a different split per task, and fleet
        tables are read once per stage wave)."""
        from trino_tpu.connectors.base import ColumnDomain, Split

        start, count = node.split
        connector = self.metadata.connector(node.catalog)
        dkey = tokens = None
        if self._device_cache_on():
            from trino_tpu import cache as xcache

            hashed0 = set(node.hash_varchar or [])
            dkey = xcache.DEVICE.scan_key(
                connector, node.schema, node.table,
                tuple(
                    (s, c, s in hashed0)
                    for s, c in node.assignments.items()
                ),
                domains=node.domains,
                split=Split(node.table, start, count),
            )
            if dkey is not None:
                hit = xcache.DEVICE.get(dkey, self.cache_stats)
                if hit is not None:
                    return hit
                tokens = self._cache_tokens(
                    connector, node.schema, node.table
                )
        split = Split(node.table, start, count)
        kw = {}
        if node.domains and getattr(connector, "supports_domains", False):
            # pushed-down domains (static filters + coordinator-fed
            # dynamic filters) prune row groups WITHIN this split; the
            # filter above re-applies, so dropped rows stay exact
            kw["domains"] = {
                c: ColumnDomain(*dom) for c, dom in node.domains.items()
            }
        cols = connector.scan(
            node.schema, node.table, list(node.assignments.values()),
            split=split, **kw,
        )
        if node.assignments:
            first = cols[next(iter(node.assignments.values()))]
            n = len(first[0] if isinstance(first, tuple) else first)
        else:
            n = count
        cap = shapes.bucket(count, site="scan-split")
        hashed_syms = set(node.hash_varchar or [])
        names, columns = [], []
        for sym, cname in node.assignments.items():
            names.append(sym)
            columns.append(_scan_column(
                node.outputs[sym], cols[cname], cap,
                hashed=sym in hashed_syms,
            ))
        mask = np.zeros(cap, dtype=np.bool_)
        mask[:n] = True
        page = Page(
            names, columns, jnp.asarray(mask),
            known_rows=n, packed=True,
        )
        if dkey is not None and tokens is not None:
            from trino_tpu import cache as xcache

            xcache.DEVICE.put(dkey, page, tokens, pool=self.memory_pool)
        return page

    def _Exchange(self, node: P.Exchange) -> Page:
        # single-device execution: every exchange is the identity (the
        # mesh executor overrides this with collectives/gathers)
        return self.execute(node.source)

    def _Values(self, node: P.Values) -> Page:
        from trino_tpu.page import StringDictionary

        n = len(node.rows)
        cap = shapes.bucket(max(n, 8), site="values")
        mask = np.zeros(cap, dtype=np.bool_)
        mask[:n] = True
        names, cols = [], []
        for i, (sym, t) in enumerate(node.outputs.items()):
            vals = [r[i] for r in node.rows]
            if isinstance(t, (T.ArrayType, T.MapType, T.RowType)):
                # pool-backed literals: from_numpy builds the pool and
                # the NULL mask from the None entries
                names.append(sym)
                cols.append(Column.from_numpy(t, vals, capacity=cap))
                continue
            nulls = np.asarray([v is None for v in vals], dtype=np.bool_)
            filled = [0 if v is None else v for v in vals]
            dictionary = None
            if isinstance(t, T.VarcharType):
                dictionary, codes = StringDictionary.from_strings(
                    np.asarray(
                        ["" if nulls[j] else str(v)
                         for j, v in enumerate(filled)] or [""],
                        dtype=object,
                    )
                )
                data = np.zeros(cap, dtype=np.int32)
                data[:n] = codes[:n]
            elif isinstance(t, T.DecimalType) and t.is_long:
                data = np.zeros((cap, 2), dtype=np.int64)
                iv = np.asarray(filled, dtype=np.int64)
                data[:n, 0] = iv >> 32
                data[:n, 1] = iv & 0xFFFFFFFF
            else:
                data = np.zeros(cap, dtype=t.np_dtype)
                data[:n] = np.asarray(filled, dtype=t.np_dtype)
            valid = None
            if nulls.any():
                v = np.ones(cap, dtype=np.bool_)
                v[:n] = ~nulls
                valid = jnp.asarray(v)
            names.append(sym)
            cols.append(Column(t, jnp.asarray(data), valid, dictionary))
        return Page(
            names, cols, jnp.asarray(mask),
            known_rows=n, packed=True,
        )

    # ---- write path ------------------------------------------------------

    def _TableWriter(self, node: "P.TableWriter") -> Page:
        """Drain the upstream subtree into a connector WriteSink
        (MAIN/operator/TableWriterOperator.java analog). Emits one row
        per sealed fragment — ($rows, $bytes, $fragment) — with the
        task totals on the first row; an empty input emits zero rows
        (TableFinish still commits, so an empty CTAS creates the
        table)."""
        from trino_tpu.exec import write as W
        from trino_tpu.exec.spool import page_to_host

        page = self.execute(node.source)
        handle = node.handle
        conn = self.metadata.connector(handle["catalog"])
        sink = conn.write_sink(handle, getattr(self, "write_ctx", None))
        mctx = self.memory_ctx.child("table-writer")
        try:
            W.write_through_sink(
                sink, handle, page_to_host(page), node.columns, mctx,
            )
            res = W.finish_sink(sink, mctx)
        except BaseException:
            sink.abort()
            raise
        #: harvested into task stats by the worker / EXPLAIN ANALYZE
        self.last_write_stats = res
        rows = [
            (
                res["rows_written"] if i == 0 else 0,
                res["bytes_written"] if i == 0 else 0,
                f,
            )
            for i, f in enumerate(res["fragments"])
        ]
        return self._Values(P.Values(dict(node.outputs), rows=rows))

    def _TableFinish(self, node: "P.TableFinish") -> Page:
        """Single-task atomic commit (TableFinishOperator analog):
        collect the gathered fragment rows and hand them to
        Connector.finish_write exactly once."""
        from trino_tpu.exec import write as W
        from trino_tpu.exec.spool import page_to_host

        page = self.execute(node.source)
        frags = W.fragment_rows(page_to_host(page))
        token = str(
            (getattr(self, "write_ctx", None) or {}).get("epoch", "")
        )
        rows, secs = W.commit_write(
            self.metadata, node.handle, frags, token=token,
        )
        h = node.handle
        self.invalidate_scan(h["catalog"], h["schema"], h["table"])
        summary = W.fragments_summary(frags)
        self.last_commit_stats = {
            "rows": rows,
            "bytes": summary["bytes"],
            "files": summary["files"],
            "commit_seconds": secs,
        }
        return self._Values(P.Values(dict(node.outputs), rows=[(rows,)]))

    # ---- row-level nodes -------------------------------------------------

    def _Output(self, node: P.Output) -> Page:
        self._defer_sync_for = node.source
        try:
            page = self.execute(node.source)
        finally:
            self._defer_sync_for = None
        cols = [page.column(s) for s in node.symbols]
        out = Page(
            list(node.names), cols, page.mask,
            known_rows=page.known_rows, packed=page.packed,
        )
        pend = getattr(page, "pending_flags", None)
        if pend is not None:
            out.pending_flags = pend
        return out

    def note_deferred_overflow(self, pending) -> bool:
        """Check a deferred final-chain overflow flag set (already
        fetched to host). Returns True when a capacity was bumped and
        the query must re-run."""
        vals, caps_key, caps = pending
        overflowed = [i for i, v in vals.items() if v]
        if not overflowed:
            return False
        for i in overflowed:
            cap, mx = caps[i]
            if cap >= mx:
                raise RuntimeError(
                    "aggregation table overflow at max capacity"
                )
            caps[i][0] = min(cap * 8, mx)
        self._jit_cache[caps_key] = {i: list(v) for i, v in caps.items()}
        return True

    def _compact(self, page: Page, extra_capacity: int = 0) -> Page:
        """Gather live rows to the front and shrink capacity
        (Page.compact analog, SPI/Page.java:180) — one jitted program
        per (layout, capacity) so the device sees a single dispatch.
        Syncs the count only when the producer did not record it."""
        n_live = page.num_rows()
        cap = shapes.bucket(n_live + extra_capacity, site="compact")
        if page.packed and cap >= page.capacity:
            return page
        limit = cap if cap < page.capacity else page.capacity
        if shapes.enabled(self.session):
            # positional key: any two pages with the same dtype/lane/
            # nullability vector share one gather program, whatever
            # their column names
            sig = tuple(
                (str(c.data.dtype), c.data.shape[1:], c.valid is not None)
                for c in page.columns
            ) + (page.capacity,)
            keys = [str(i) for i in range(len(page.columns))]
        else:
            sig = self._layout_sig(page)
            keys = list(page.names)
        key = ("compact", sig, limit)
        fn = self._jit_cache.get(key)
        if fn is None:
            def compact_fn(env, mask):
                # stable argsort on the dead flag: isolated scatter- and
                # searchsorted-based compactions microbenchmark faster,
                # but in full query programs the sort variant measures
                # best on v5e (XLA fuses the gather consumers)
                perm = jnp.argsort(
                    (~mask).astype(jnp.int8), stable=True
                )[:limit]
                env2 = {
                    s: (
                        d[perm],
                        None if v is None else v[perm],
                    )
                    for s, (d, v) in env.items()
                }
                return env2, mask[perm]

            fn = jax.jit(compact_fn)
            self._jit_cache[key] = fn
        env_in = {k: (c.data, c.valid) for k, c in zip(keys, page.columns)}
        env2, mask2 = fn(env_in, page.mask)
        cols = [
            Column(c.type, *env2[k], c.dictionary, c.hash_pool, c.array_pool)
            for k, c in zip(keys, page.columns)
        ]
        out = Page(list(page.names), cols, mask2)
        out.known_rows = n_live
        out.packed = True
        return out

    # ---- aggregation -----------------------------------------------------

    def _Join(self, node: P.Join) -> Page:
        if node.kind == "right":
            node = P.Join(
                node.outputs, kind="left", left=node.right, right=node.left,
                criteria=[(r, l) for l, r in node.criteria],
                filter=node.filter,
                df_range_keep=None, df_keep_frac=None,
            )
        budget = self.hbm_budget()
        if budget and node.kind in ("inner", "left") and node.criteria:
            plan = self._plan_budget_join(node, budget)
            if plan is not None:
                return plan
        if not budget and node.kind in ("inner", "left") and node.criteria:
            plan = self._maybe_revoke_join(node)
            if plan is not None:
                return plan
        frag = self._build_cache_probe(node.right)
        right_hit = frag[2] if frag is not None else None
        if not budget and right_hit is None:
            # prefetch trades device memory for round trips — never
            # under an HBM budget, where spill paths may stream the
            # same subtrees chunk-wise instead (and never when the
            # build side is already HBM-resident: prefetching its scan
            # chains would pin pages the hit makes redundant)
            self._prefetch_join_chains(node)
        left = self._compact(self.execute(node.left))
        if right_hit is not None:
            right = right_hit
        else:
            right = self._compact(self.execute(node.right))
            if frag is not None:
                from trino_tpu import cache as xcache

                xcache.DEVICE.put(
                    frag[0], right, frag[1], pool=self.memory_pool
                )
        if node.kind == "cross":
            return self._cross_join(node, left, right)
        try:
            return self._equi_join(node, left, right)
        except memory.ExceededMemoryLimitError:
            # reactive revocation: the resident working set (padded
            # device capacities) breached the per-node cap even though
            # the live-row estimate fit. The failed reserve recorded
            # nothing, so re-plan the join through the spill tier.
            if budget or node.kind not in ("inner", "left") or not node.criteria:
                raise
            plan = self._maybe_revoke_join(node, force=True)
            if plan is None:
                raise
            return plan

    def _build_cache_probe(self, sub: P.PlanNode):
        """``(key, tokens, hit_page|None)`` when a join build-side
        subtree is fragment-cacheable in the HBM tier (keyed by the
        canonical subtree hash — the *built* pages, dictionaries
        unified and compacted, are what gets pinned); None otherwise."""
        if not self._device_cache_on():
            return None
        from trino_tpu import cache as xcache

        digest = xcache.plan_digest(sub, self.session)
        if digest is None:
            return None
        tokens = xcache.table_tokens(sub, self.metadata)
        if tokens is None or not tokens:
            # no table scans under the subtree (Values/RemoteSource):
            # nothing content-addresses its data, so never cache it
            return None
        key = xcache.DEVICE.frag_key(digest)
        return key, tokens, xcache.DEVICE.get(key, self.cache_stats)

    def _prefetch_join_chains(self, node: P.PlanNode) -> None:
        """Dispatch every aggregate-free Filter/Project chain over a
        table scan found under a join tree in one async burst, then
        fetch ALL their live counts in a single host round trip.

        The per-chain sync exists to learn the live count (capacity
        decisions); issuing them serially pays one device round trip
        per chain — through a remote-device tunnel that latency
        dominates the query (Q3: three scan chains = three ~80 ms
        syncs). Independent chains have no data dependencies, so their
        programs queue back-to-back and one transfer collects every
        count (the reference overlaps the same work with concurrent
        split drivers, MAIN/execution/executor/)."""
        cands = []

        def chain_of(n):
            chain = []
            cur = n
            while isinstance(cur, stage.FUSABLE):
                chain.append(cur)
                cur = cur.sources[0]
            return list(reversed(chain)), cur

        def collect(n):
            if isinstance(n, stage.FUSABLE):
                chain, base = chain_of(n)
                if (
                    isinstance(base, P.TableScan)
                    and base.split is None
                    and all(
                        isinstance(x, (P.Filter, P.Project))
                        for x in chain
                    )
                ):
                    cands.append((n, chain, base))
                return
            if isinstance(n, (P.Join, P.SemiJoin)):
                for s in n.sources:
                    collect(s)

        for s in node.sources:
            collect(s)
        cands = [c for c in cands if id(c[0]) not in self._prefetched]
        if len(cands) < 2:
            return  # batching needs at least two chains to pay off
        pending = []
        for top, chain, scan in cands:
            base = self._TableScan(scan)
            env, mask, flags, n_live_dev, out_layout = self._dispatch_chain(
                chain, base, {}
            )
            pending.append((top, chain, env, mask, n_live_dev, out_layout))
        counts = jax.device_get([p[4] for p in pending])
        for (top, chain, env, mask, _d, out_layout), n_live in zip(
            pending, counts
        ):
            page = self._finalize_chain(
                chain, env, mask, int(n_live), out_layout
            )
            self._prefetched[id(top)] = (top, page)

    def _plan_budget_join(self, node: P.Join, budget: int) -> Page | None:
        """Memory-scaled join strategies (SURVEY §5.7): streamed probe
        against a resident build when only the probe exceeds budget,
        grace-hash partitioning when both sides do. Returns None when
        the resident path fits."""
        from trino_tpu.exec import spill

        l_bytes = spill.est_output_bytes(self, node.left)
        r_bytes = spill.est_output_bytes(self, node.right)
        slab = budget // 4
        if l_bytes <= slab and r_bytes <= slab:
            return None
        probe_chain, probe_scan = self._streamable(node.left)
        if l_bytes > slab and r_bytes <= slab and probe_scan is not None:
            build = self._compact(self.execute(node.right))
            return spill.streamed_probe_join(
                self, node, probe_chain, probe_scan, build
            )
        if l_bytes > slab or r_bytes > slab:
            # grace-hash handles inner AND left joins (each partition
            # pair covers its key range exclusively, so unmatched
            # probe rows emit exactly once)
            return spill.grace_join(self, node)
        return None

    def _maybe_revoke_join(self, node: P.Join, force: bool = False) -> Page | None:
        """Memory revocation (MemoryRevokingScheme analog): with no
        session hbm budget, a hash join whose estimated resident
        working set would breach query_max_memory_per_node is switched
        into the spill tier — the cap stands in as the budget for the
        whole subtree — instead of failing at reservation time.
        ``force`` skips the estimate check: the reactive path in
        ``_Join`` uses it after a resident reserve already raised
        (padded device capacities can exceed the live-row estimate).
        Only when even the revoked path cannot fit does the pool raise
        ExceededMemoryLimitError."""
        cap = self.memory_pool.limit_bytes()
        if not cap:
            return None
        from trino_tpu.exec import spill

        est = (
            spill.est_output_bytes(self, node.left)
            + spill.est_output_bytes(self, node.right)
            + spill.est_output_bytes(self, node)
        )
        if not force and est <= max(
            cap - self.memory_pool.reserved_bytes, 0
        ):
            return None
        prev = self._revoked_budget
        self._revoked_budget = cap
        try:
            plan = self._plan_budget_join(node, cap)
            if plan is not None:
                self.memory_revocations += 1
            return plan
        finally:
            self._revoked_budget = prev

    @staticmethod
    def _streamable(node: P.PlanNode):
        """(chain, scan) when the subtree is a fusable chain over a
        TableScan — the shape the chunked scan path can stream."""
        chain: list[P.PlanNode] = []
        cur = node
        while isinstance(cur, stage.FUSABLE):
            chain.append(cur)
            cur = cur.sources[0]
        if isinstance(cur, P.TableScan):
            # only row-local operators chunk safely here: aggregates
            # reduce cardinality (and stream independently via
            # execute()); Limit/TopN/Sort are global — a per-chunk
            # limit concatenated across chunks would drop the
            # truncation (run_chain_streamed handles those shapes via
            # its partial/final split instead)
            if any(
                isinstance(n, (P.Aggregate, P.Limit, P.TopN, P.Sort))
                for n in chain
            ):
                return None, None
            return list(reversed(chain)), cur
        return None, None

    #: cross joins materialize chunk-wise beyond this many output rows
    #: (previously a moderately sized cross join OOMed in one shot)
    CROSS_CHUNK_ROWS = 1 << 22

    def _cross_join(self, node: P.Join, left: Page, right: Page) -> Page:
        # callers (_Join) hand in already-compacted pages
        n_l, n_r = left.num_rows(), right.num_rows()
        from trino_tpu import session_properties as SP

        limit = int(SP.get(self.session, "cross_join_chunk_rows"))
        budget = self.hbm_budget()
        if budget:
            from trino_tpu.exec import spill

            limit = min(
                limit,
                max(
                    (budget // spill.CHUNK_BUDGET_FRACTION)
                    // spill.row_bytes(node.outputs),
                    1 << 16,
                ),
            )
        if n_l * n_r > limit and max(n_l, n_r) > 1:
            from trino_tpu.exec import spill

            # chunk the LARGER side: chunking the left against a
            # right that alone exceeds the limit would recurse with a
            # 1-row chunk forever
            chunk_left = n_l >= n_r
            n_big = n_l if chunk_left else n_r
            n_other = n_r if chunk_left else n_l
            rows_per = max(limit // max(n_other, 1), 1)
            runs = []
            for lo in range(0, n_big, rows_per):
                hi = min(lo + rows_per, n_big)
                if chunk_left:
                    out = self._cross_join(
                        node, self._compact(_slice_page(left, lo, hi)),
                        right,
                    )
                else:
                    out = self._cross_join(
                        node, left,
                        self._compact(_slice_page(right, lo, hi)),
                    )
                run = spill.page_to_host(self._compact(out))
                if run.n_rows:
                    runs.append(run)
            if not runs:
                runs = [spill._empty_run(node.outputs)]
            return spill.host_concat_to_page(self, runs)
        cap = shapes.bucket(max(n_l * n_r, 1), site="cross-join")
        key = (
            "cross", n_l, n_r,
            self._layout_sig(left), self._layout_sig(right),
        )
        fn = self._jit_cache.get(key)
        if fn is None:
            l_cap, r_cap = left.capacity, right.capacity
            lnames, rnames = list(left.names), list(right.names)

            def fx(lenv, renv):
                j = jnp.arange(cap)
                li = jnp.clip(j // max(n_r, 1), 0, max(l_cap - 1, 0))
                ri = jnp.clip(j % max(n_r, 1), 0, max(r_cap - 1, 0))
                out_live = j < n_l * n_r
                env2 = {}
                for names, env, idx in (
                    (lnames, lenv, li), (rnames, renv, ri)
                ):
                    for nm in names:
                        d, v = env[nm]
                        env2[nm] = (d[idx], None if v is None else v[idx])
                return env2, out_live

            fn = jax.jit(fx)
            self._jit_cache[key] = fn
        env2, mask = fn(self._env(left), self._env(right))
        names, cols = [], []
        for page in (left, right):
            for nm, c in zip(page.names, page.columns):
                names.append(nm)
                cols.append(Column(c.type, *env2[nm], c.dictionary, c.hash_pool, c.array_pool))
        out = Page(names, cols, mask)
        out.known_rows = n_l * n_r
        out.packed = True
        return out

    def _nested_loop_join(self, node: P.Join, probe: Page, build: Page) -> Page:
        """Joins WITHOUT equi criteria (`a JOIN b ON a.x < b.y`): the
        NestedLoopJoinOperator + join-filter shape
        (MAIN/operator/join/NestedLoopJoinOperator.java:43). Cross-
        expand chunk-wise (bounded by CROSS_CHUNK_ROWS), evaluate the
        filter over the pair page, and for outer kinds append the
        unmatched rows with a NULL far side. _Join already flipped
        RIGHT to LEFT, so kinds here are inner/left/full."""
        from trino_tpu.exec import spill

        # row-id lanes ride through the cross expansion so unmatched
        # probe/build rows are identifiable afterwards
        def with_ids(page: Page, idname: str) -> Page:
            ids = Column(
                T.BIGINT,
                jnp.arange(page.capacity, dtype=jnp.int64),
            )
            return Page(
                list(page.names) + [idname], list(page.columns) + [ids],
                page.mask, known_rows=page.known_rows, packed=page.packed,
            )

        p2 = with_ids(probe, "__nl_pid")
        b2 = with_ids(build, "__nl_bid")
        cross_outputs = {
            **{n: c.type for n, c in zip(p2.names, p2.columns)},
            **{n: c.type for n, c in zip(b2.names, b2.columns)},
        }
        cross_node = P.Join(
            cross_outputs, kind="cross", left=node.left, right=node.right
        )
        pairs = self._cross_join(cross_node, p2, b2)
        if node.filter is not None:
            ce = compile_expr(node.filter, self._layout(pairs))
            data, valid = ce.fn(self._env(pairs))
            keep = data if valid is None else (data & valid)
            pairs = self._compact(
                Page(list(pairs.names), list(pairs.columns),
                     pairs.mask & keep)
            )
        out_syms = list(node.outputs)
        matched = Page(
            out_syms, [pairs.column(s) for s in out_syms], pairs.mask,
            known_rows=pairs.known_rows, packed=pairs.packed,
        )
        if node.kind == "inner":
            return matched
        runs = [spill.page_to_host(matched)]
        pid_run = spill.page_to_host(
            Page(["__nl_pid"], [pairs.column("__nl_pid")], pairs.mask,
                 known_rows=pairs.known_rows, packed=pairs.packed)
        )
        matched_pids = set(pid_run.columns[0][0].tolist())
        runs.append(self._nl_unmatched(
            node, probe, build, matched_pids, out_syms, probe_side=True
        ))
        if node.kind == "full":
            bid_run = spill.page_to_host(
                Page(["__nl_bid"], [pairs.column("__nl_bid")], pairs.mask,
                     known_rows=pairs.known_rows, packed=pairs.packed)
            )
            matched_bids = set(bid_run.columns[0][0].tolist())
            runs.append(self._nl_unmatched(
                node, probe, build, matched_bids, out_syms,
                probe_side=False,
            ))
        runs = [r for r in runs if r.n_rows] or [
            spill._empty_run(dict(node.outputs))
        ]
        return spill.host_concat_to_page(self, runs)

    def _nl_unmatched(
        self, node: P.Join, probe: Page, build: Page, matched: set,
        out_syms: list, probe_side: bool,
    ):
        """HostRun of one side's unmatched rows, far side all-NULL."""
        from trino_tpu.exec import spill

        page = probe if probe_side else build
        run = spill.page_to_host(page)
        keep = [
            i for i in range(run.n_rows) if i not in matched
        ]
        near = set(page.names)
        cols = []
        types = []
        for s in out_syms:
            t = node.outputs[s]
            types.append(t)
            if s in near:
                v, valid = run.columns[run.names.index(s)]
                cols.append((
                    v[keep],
                    None if valid is None else valid[keep],
                ))
            else:
                # far side: typed zeros, all invalid
                src = (build if probe_side else probe).column(s)
                shape = (len(keep), 2) if jnp.ndim(src.data) == 2 else (
                    len(keep),
                )
                filler = (
                    np.zeros(len(keep), dtype=object)
                    if src.dictionary is not None or src.hash_pool is not None
                    else np.zeros(shape, dtype=t.np_dtype)
                )
                if filler.dtype == object:
                    filler[:] = ""
                cols.append((filler, np.zeros(len(keep), dtype=bool)))
        return spill.HostRun(out_syms, types, cols, len(keep))

    def _unify_join_dicts(self, probe: Page, build: Page, criteria):
        """Remap VARCHAR key pairs onto shared dictionaries (host-side
        dictionary union + one device gather per remapped column).
        Hash-coded pairs skip remapping entirely — hash codes are
        globally consistent — but must pass the cross-pool injectivity
        proof so hash equality implies string equality."""
        for lsym, rsym in criteria:
            pc, bc = probe.column(lsym), build.column(rsym)
            if pc.hash_pool is not None and bc.hash_pool is not None:
                pc.hash_pool.verify_joinable(bc.hash_pool)
                continue
            if pc.dictionary is not None or bc.dictionary is not None:
                pc2, bc2 = unify_dictionaries(pc, bc)
                probe.columns[probe.names.index(lsym)] = pc2
                build.columns[build.names.index(rsym)] = bc2

    @staticmethod
    def _join_key_kinds(probe, build, criteria):
        """Static per-criterion key kind from the page columns:
        'hash' = hash-coded varchar (key is the hash lane alone; the id
        lane is row identity), 'auto' = plain/limb columns."""
        kinds = []
        for l, r in criteria:
            pc = probe.column(l)
            bc = build.column(r)
            if pc.hash_pool is not None or bc.hash_pool is not None:
                if pc.hash_pool is None or bc.hash_pool is None:
                    raise NotImplementedError(
                        "join between hash-coded and dictionary-coded "
                        "varchar (the planner co-encodes join pairs)"
                    )
                kinds.append("hash")
            else:
                kinds.append("auto")
        return kinds

    @staticmethod
    def _traced_join_keys(penv, benv, criteria, kinds=None):
        """Combined uint64 keys for probe/build sides from traced envs.

        Single fixed-width key -> exact; multi-column (including
        two-limb decimal keys, which expand into hi/lo parts) ->
        hash-combined and ``verify`` is True (matches re-checked after
        expansion). Hash-coded varchar keys ('hash' kind) contribute
        their hash lane only — the id lane is row identity, not value
        identity. The returned ``pairs`` are 1D (probe, build) part
        arrays for the verification loop.
        """
        pv = bv = None
        p_parts: list = []
        b_parts: list = []
        for i, (l, r) in enumerate(criteria):
            pd, pvd = penv[l]
            bd, bvd = benv[r]
            pv = _and_mask(pv, pvd)
            bv = _and_mask(bv, bvd)
            if kinds is not None and kinds[i] == "hash":
                p_parts.append(pd[:, 0])
                b_parts.append(bd[:, 0])
            else:
                p_parts.extend(K.limb_parts(pd))
                b_parts.extend(K.limb_parts(bd))
        if len(p_parts) == 1:
            pk, _ = K.normalize_key(p_parts[0], None)
            bk, _ = K.normalize_key(b_parts[0], None)
            verify = False
        else:
            pk = K.hash_columns([(d, None) for d in p_parts])
            bk = K.hash_columns([(d, None) for d in b_parts])
            verify = True
        pairs = list(zip(p_parts, b_parts))
        return pk, bk, pv, bv, pairs, verify

    def _join_count(self, criteria, probe: Page, build: Page):
        """Join phase A: sorted build order + per-probe match ranges +
        total match count — ONE jitted program, one host sync (the
        output-capacity decision, the reference's build-side barrier).
        """
        key = (
            "joinA", tuple(criteria),
            self._layout_sig(probe), self._layout_sig(build),
        )
        fn = self._jit_cache.get(key)
        if fn is None:
            crit = list(criteria)
            kinds = self._join_key_kinds(probe, build, crit)

            def fa(penv, pmask, benv, bmask):
                pk, bk, pv, bv, _, _ = self._traced_join_keys(
                    penv, benv, crit, kinds
                )
                probe_live = pmask if pv is None else (pmask & pv)
                build_live = bmask if bv is None else (bmask & bv)
                order, lo, cnt = K.join_ranges(
                    bk, build_live, pk, probe_live
                )
                return order, lo, cnt, K.blocked_sum(cnt)

            fn = jax.jit(fa)
            self._jit_cache[key] = fn
        order, lo, cnt, total_dev = fn(
            self._env(probe), probe.mask, self._env(build), build.mask
        )
        return order, lo, cnt, int(jax.device_get(total_dev))

    # ---- dynamic filtering (DynamicFilterService analog,
    # MAIN/server/DynamicFilterService.java:106: collect build-side key
    # bounds, prune the probe before the expensive join work) ----------

    #: probes below this skip dynamic filtering (the two extra syncs
    #: cost more than the saved sort time)
    DF_MIN_PROBE = 1 << 17
    #: apply the filter only when it drops at least this fraction
    DF_MIN_DROP = 0.3

    def _df_pairs(self, criteria, probe: Page, build: Page):
        """Criteria usable for min/max dynamic filtering: plain integer
        domains (ints, dates, decimals, dictionary codes are excluded —
        code spaces already unified but bounds are meaningless across
        remaps)."""
        pairs = []
        for ls, rs in criteria:
            pc, bc = probe.column(ls), build.column(rs)
            if pc.dictionary is not None or bc.dictionary is not None:
                continue
            if pc.hash_pool is not None or bc.hash_pool is not None:
                continue  # hashes carry no order; min/max cannot prune
            if jnp.ndim(pc.data) != 1:
                continue  # two-limb columns have no 1D order domain
            if np.dtype(pc.data.dtype).kind != "i":
                continue
            pairs.append((ls, rs))
        return pairs

    def _dynamic_filter(self, node: P.Join, probe: Page, build: Page) -> Page:
        """Prune probe rows whose key cannot match any build row.

        Inner joins only: outer probes must keep unmatched rows. Cost:
        one tiny reduction program + one filtered compaction — two host
        syncs, the price the reference pays for its DF barrier. NULL
        probe keys are dropped too (they never match an inner join).

        Gated by the planner's df_range_keep hint: a min/max filter
        only prunes when the build's key RANGE is narrower than the
        probe's — uniform dense builds keep ~100% and the two syncs
        are pure cost (the measured Q3 regression)."""
        from trino_tpu import session_properties as SP

        if not SP.get(self.session, "dynamic_filtering_enabled"):
            return probe
        if node.kind != "inner" or probe.capacity < self.DF_MIN_PROBE:
            return probe
        if node.df_range_keep is None or node.df_range_keep > 0.7:
            return probe
        pairs = self._df_pairs(node.criteria, probe, build)
        if not pairs:
            return probe
        key_a = ("dfA", tuple(r for _, r in pairs), self._layout_sig(build))
        fn_a = self._jit_cache.get(key_a)
        if fn_a is None:
            rsyms = [r for _, r in pairs]

            def fa(benv, bmask):
                outs = []
                for r in rsyms:
                    d, v = benv[r]
                    live = bmask if v is None else (bmask & v)
                    big = jnp.iinfo(d.dtype).max
                    small = jnp.iinfo(d.dtype).min
                    outs.append(jnp.min(jnp.where(live, d, big)))
                    outs.append(jnp.max(jnp.where(live, d, small)))
                return jnp.stack([o.astype(jnp.int64) for o in outs])

            fn_a = jax.jit(fa)
            self._jit_cache[key_a] = fn_a
        bounds = fn_a(self._env(build), build.mask)
        key_b = ("dfB", tuple(l for l, _ in pairs), self._layout_sig(probe))
        fn_b = self._jit_cache.get(key_b)
        if fn_b is None:
            lsyms = [l for l, _ in pairs]

            def fb(penv, pmask, bnds):
                keep = pmask
                for i, l in enumerate(lsyms):
                    d, v = penv[l]
                    lo = bnds[2 * i].astype(d.dtype)
                    hi = bnds[2 * i + 1].astype(d.dtype)
                    keep = keep & (d >= lo) & (d <= hi)
                    if v is not None:
                        keep = keep & v
                return keep, K.count_true(keep)

            fn_b = jax.jit(fb)
            self._jit_cache[key_b] = fn_b
        keep, kept_dev = fn_b(self._env(probe), probe.mask, bounds)
        in_rows = probe.num_rows()
        kept = int(jax.device_get(kept_dev))
        self.df_log.append(
            {"rows_in": in_rows, "rows_kept": kept, "pairs": pairs}
        )
        del self.df_log[:-100]  # bounded: executors outlive queries
        if kept > (1.0 - self.DF_MIN_DROP) * in_rows:
            return probe
        filtered = Page(
            list(probe.names), list(probe.columns), keep,
            known_rows=kept,
        )
        return self._compact(filtered)

    def _equi_join(self, node: P.Join, probe: Page, build: Page) -> Page:
        if not node.criteria:
            return self._nested_loop_join(node, probe, build)
        self._unify_join_dicts(probe, build, node.criteria)
        probe = self._dynamic_filter(node, probe, build)
        order, lo, cnt, total = self._join_count(node.criteria, probe, build)
        out_cap = shapes.bucket(max(total, 1), site="join")
        # reserve the join's whole device working set (probe + build +
        # expansion output + index arrays) against the memory pool —
        # the budget tier's tests rely on this being honest, and the
        # per-node cap is enforced here (ExceededMemoryLimitError when
        # even the revoked/spill path cannot fit)
        out_row = sum(
            (2 if jnp.ndim((probe if s in probe.names else build)
                           .column(s).data) == 2 else 1) * 8
            for s in node.outputs
        )
        working_set = (
            _page_dev_bytes(probe) + _page_dev_bytes(build)
            + out_cap * (out_row + 8)
        )
        ctx = self.memory_ctx.child("join")
        ctx.reserve(working_set)
        ctx.free(working_set)
        key = (
            "joinB", node.kind, tuple(node.criteria), tuple(node.outputs),
            repr(node.filter), out_cap,
            self._layout_sig(probe), self._layout_sig(build),
        )
        hit = self._jit_cache.get(key)
        if hit is None:
            hit = self._build_join_expand(node, probe, build, out_cap)
            self._jit_cache[key] = hit
        fn, out_meta = hit
        env2, mask2 = fn(
            self._env(probe), probe.mask, self._env(build), build.mask,
            order, lo, cnt,
        )
        cols = [
            Column(t, *env2[s], d, hp, ap) for s, _fp, t, d, hp, ap in out_meta
        ]
        out = Page([s for s, *_ in out_meta], cols, mask2)
        if (
            node.kind == "inner"
            and node.filter is None
            and len(node.criteria) == 1
        ):
            # exact single-key expansion emits matches as a dense
            # prefix of length ``total`` — downstream never re-syncs
            out.known_rows = total
            out.packed = True
        return out

    def _build_join_expand(self, node: P.Join, probe: Page, build: Page, out_cap: int):
        """Join phase B: expansion, verification, output gathers,
        residual filter, and outer-row sections — ONE jitted program.
        Outer (left/full) unmatched rows are emitted as extra full-size
        sections with NULLs for the far side, exactly like the mesh
        executor — no data-dependent capacity, no extra sync."""
        criteria = list(node.criteria)
        kind = node.kind
        p_cap, b_cap = probe.capacity, build.capacity
        out_meta = []  # (sym, from_probe, type, dict, hash_pool, array_pool)
        for sym in node.outputs:
            from_probe = sym in probe.names
            c = (probe if from_probe else build).column(sym)
            out_meta.append(
                (sym, from_probe, c.type, c.dictionary, c.hash_pool,
                 c.array_pool)
            )
        filter_c = None
        fsyms: list[str] = []
        if node.filter is not None:
            filter_c = compile_expr(node.filter, _pair_layout(probe, build))
            fsyms = sorted(_expr_symbols(node.filter))
        probe_names = set(probe.names)

        kinds = self._join_key_kinds(probe, build, criteria)

        def fb(penv, pmask, benv, bmask, order, lo, cnt):
            pk, bk, pv, bv, pairs, verify = self._traced_join_keys(
                penv, benv, criteria, kinds
            )
            probe_idx, build_idx, out_live = K.expand_matches(
                order, lo, cnt, out_cap
            )
            if verify:
                for pd, bd in pairs:
                    pb, _ = K.normalize_key(pd, None)
                    bb, _ = K.normalize_key(bd, None)
                    out_live = out_live & (pb[probe_idx] == bb[build_idx])
            inner = {}
            for sym, from_probe, _t, _d, _hp, _ap in out_meta:
                d, v = (penv if from_probe else benv)[sym]
                idx = probe_idx if from_probe else build_idx
                inner[sym] = (d[idx], None if v is None else v[idx])
            if filter_c is not None:
                fenv = _gather_pair_env(
                    penv, benv, probe_names, fsyms,
                    probe_idx, build_idx, base=inner,
                )
                fd, fv = filter_c.fn(fenv)
                out_live = out_live & (fd if fv is None else (fd & fv))
            sections = {sym: [inner[sym]] for sym, *_ in out_meta}
            masks = [out_live]
            if kind in ("left", "full"):
                matched = K.range_any(cnt, out_live)
                unmatched = pmask & ~matched
                for sym, from_probe, _t, _d, _hp, _ap in out_meta:
                    if from_probe:
                        sections[sym].append(penv[sym])
                    else:
                        d0, _ = benv[sym]
                        # preserve trailing lanes (two-limb decimals,
                        # sketch states) in the NULL section
                        sections[sym].append((
                            jnp.zeros(
                                (p_cap,) + d0.shape[1:], dtype=d0.dtype
                            ),
                            jnp.zeros((p_cap,), dtype=jnp.bool_),
                        ))
                masks.append(unmatched)
            if kind == "full":
                bmatched = K.scatter_any(build_idx, out_live, b_cap)
                bunmatched = bmask & ~bmatched
                for sym, from_probe, _t, _d, _hp, _ap in out_meta:
                    if from_probe:
                        d0, _ = penv[sym]
                        sections[sym].append((
                            jnp.zeros(
                                (b_cap,) + d0.shape[1:], dtype=d0.dtype
                            ),
                            jnp.zeros((b_cap,), dtype=jnp.bool_),
                        ))
                    else:
                        sections[sym].append(benv[sym])
                masks.append(bunmatched)
            env2 = {}
            for sym, *_ in out_meta:
                env2[sym] = _concat_sections(sections[sym])
            mask2 = masks[0] if len(masks) == 1 else jnp.concatenate(masks)
            return env2, mask2

        return jax.jit(fb), out_meta

    def _build_semi_expand(self, node: P.SemiJoin, source: Page, filt: Page, out_cap: int):
        """Semi-join expansion phase: verify hash-combined matches and
        apply the correlated residual filter, then reduce per-probe —
        ONE jitted program returning the match vector."""
        criteria = list(node.keys)
        filter_c = None
        fsyms: list[str] = []
        if node.filter is not None:
            filter_c = compile_expr(node.filter, _pair_layout(source, filt))
            fsyms = sorted(_expr_symbols(node.filter))
        probe_names = set(source.names)

        kinds = self._join_key_kinds(source, filt, criteria)

        def fb(penv, benv, order, lo, cnt):
            pk, bk, pv, bv, pairs, _verify = self._traced_join_keys(
                penv, benv, criteria, kinds
            )
            probe_idx, build_idx, out_live = K.expand_matches(
                order, lo, cnt, out_cap
            )
            for pd, bd in pairs:
                pb, _ = K.normalize_key(pd, None)
                bb, _ = K.normalize_key(bd, None)
                out_live = out_live & (pb[probe_idx] == bb[build_idx])
            if filter_c is not None:
                fenv = _gather_pair_env(
                    penv, benv, probe_names, fsyms, probe_idx, build_idx
                )
                fd, fv = filter_c.fn(fenv)
                out_live = out_live & (fd if fv is None else (fd & fv))
            return K.range_any(cnt, out_live)

        return jax.jit(fb)

    # ---- window / set operations -----------------------------------------

    def _GroupId(self, node: P.GroupId) -> Page:
        """Replicate the input once per grouping set with NULLed
        non-member keys + a set-id column (GroupIdOperator analog,
        MAIN/operator/GroupIdOperator.java) — one device concat of k
        masked copies; the aggregation above fuses over the result."""
        src = self.execute(node.source)
        k = len(node.grouping_sets)
        in_cap = src.capacity
        out_cap = shapes.bucket(k * in_cap, site="group-id")
        keyed = set(s for st in node.grouping_sets for s in st)
        pad = out_cap - k * in_cap

        def tile(pieces, fill):
            if pad:
                pieces = pieces + [
                    jnp.full((pad,) + pieces[0].shape[1:], fill,
                             dtype=pieces[0].dtype)
                ]
            return jnp.concatenate(pieces)

        names, cols = [], []
        for name, col in zip(src.names, src.columns):
            if name in keyed:
                valid_full = (
                    col.valid if col.valid is not None
                    else jnp.ones((in_cap,), dtype=jnp.bool_)
                )
                none = jnp.zeros((in_cap,), dtype=jnp.bool_)
                valid = tile(
                    [
                        valid_full if name in st else none
                        for st in node.grouping_sets
                    ],
                    False,
                )
            else:
                valid = (
                    None if col.valid is None
                    else tile([col.valid] * k, False)
                )
            data = tile([col.data] * k, 0)
            names.append(name)
            cols.append(
                Column(col.type, data, valid, col.dictionary, col.hash_pool)
            )
        names.append(node.id_symbol)
        cols.append(Column(
            T.BIGINT,
            tile(
                [
                    jnp.full((in_cap,), i, dtype=jnp.int64)
                    for i in range(k)
                ],
                0,
            ),
        ))
        mask = tile([src.mask] * k, False)
        rows = src.num_rows()
        return Page(names, cols, mask, known_rows=rows * k, packed=False)

    def _Unnest(self, node: P.Unnest) -> Page:
        """Static-fanout UNNEST (UnnestOperator analog,
        MAIN/operator/unnest/UnnestOperator.java): output position
        t = i * k + j holds element j of source row i — one reshape,
        no data-dependent shapes. Shorter zipped arrays NULL-pad.

        UNNEST over real ARRAY columns takes the pool-expansion path
        (_unnest_columns) — lengths are data-dependent there."""
        page = self.execute(node.source)
        if any(not isinstance(a, tuple) for a in node.arrays):
            return self._unnest_columns(node, page)
        k = max(len(a) for a in node.arrays)
        cap = page.capacity
        out_cap = cap * k
        key = (
            "unnest",
            tuple(tuple(repr(e) for e in a) for a in node.arrays),
            tuple(node.element_symbols),
            self._layout_sig(page),
        )
        hit = self._jit_cache.get(key)
        if hit is None:
            from trino_tpu.page import StringDictionary

            layout = self._layout(page)
            producers = []  # per arg: list of ('expr', c) | ('code', int)
            elem_dicts = []
            for a, sym in zip(node.arrays, node.element_symbols):
                cs = [compile_expr(e, layout) for e in a]
                t = node.outputs[sym]
                if isinstance(t, T.VarcharType):
                    if all(
                        c.is_literal and c.dictionary is not None
                        for c in cs
                    ):
                        # one merged dictionary over the literal pool;
                        # each element becomes a constant code
                        merged = StringDictionary(np.unique(
                            np.concatenate(
                                [c.dictionary.values for c in cs]
                            )
                        ))
                        producers.append([
                            (
                                "code",
                                int(np.searchsorted(
                                    merged.values,
                                    c.dictionary.values[0],
                                )),
                            )
                            for c in cs
                        ])
                        elem_dicts.append(merged)
                        continue
                    dict_ids = {id(c.dictionary) for c in cs}
                    if len(dict_ids) != 1 or None in {
                        c.dictionary for c in cs
                    }:
                        raise NotImplementedError(
                            "UNNEST varchar elements must share one "
                            "dictionary or all be literals"
                        )
                    elem_dicts.append(cs[0].dictionary)
                else:
                    elem_dicts.append(None)
                producers.append([("expr", c) for c in cs])

            def fx(env, mask):
                idx = jnp.arange(out_cap, dtype=jnp.int32) // k
                env2 = {}
                for s, (d, v) in env.items():
                    env2[s] = (
                        d[idx], None if v is None else v[idx]
                    )
                for sym, prods in zip(node.element_symbols, producers):
                    t = node.outputs[sym]
                    cols = []
                    vals = []
                    for kind_, c in prods:
                        if kind_ == "code":
                            d = jnp.full((cap,), c, dtype=jnp.int32)
                            v = None
                        else:
                            d, v = stage._bcast(*c.fn(env), cap)
                        cols.append(d)
                        vals.append(
                            jnp.ones((cap,), dtype=jnp.bool_)
                            if v is None else v
                        )
                    stacked = jnp.stack(cols, axis=1)  # [cap, k_m]
                    svalid = jnp.stack(vals, axis=1)
                    k_m = stacked.shape[1]
                    if k_m < k:  # NULL-pad shorter zipped arrays
                        pad = jnp.zeros((cap, k - k_m), dtype=stacked.dtype)
                        stacked = jnp.concatenate([stacked, pad], axis=1)
                        svalid = jnp.concatenate(
                            [
                                svalid,
                                jnp.zeros(
                                    (cap, k - k_m), dtype=jnp.bool_
                                ),
                            ],
                            axis=1,
                        )
                    env2[sym] = (
                        stacked.reshape(out_cap),
                        svalid.reshape(out_cap),
                    )
                return env2, mask[idx]

            hit = (jax.jit(fx), elem_dicts)
            self._jit_cache[key] = hit
        fn, elem_dicts = hit
        env2, mask2 = fn(self._env(page), page.mask)
        names, cols = [], []
        for nm, c in zip(page.names, page.columns):
            names.append(nm)
            cols.append(Column(c.type, *env2[nm], c.dictionary, c.hash_pool, c.array_pool))
        for sym, d in zip(node.element_symbols, elem_dicts):
            names.append(sym)
            cols.append(Column(node.outputs[sym], *env2[sym], d))
        return Page(names, cols, mask2)

    def _unnest_columns(self, node: P.Unnest, page: Page) -> Page:
        """UNNEST over ARRAY-typed columns: row lengths come from the
        host pool (offsets+values layout), the expansion index builds
        host-side (np.repeat over live rows), source columns gather
        device-side by the uploaded index, and element columns build
        from pool slices (UnnestOperator over ArrayBlock,
        MAIN/operator/unnest/UnnestOperator.java:44). Multiple arrays
        zip; shorter ones NULL-pad (Trino semantics)."""
        mask = np.asarray(page.mask)
        sel = np.nonzero(mask)[0]
        args = []
        for a in node.arrays:
            if isinstance(a, tuple):
                raise NotImplementedError(
                    "mixing ARRAY literals and ARRAY columns in one "
                    "UNNEST is not supported"
                )
            if not isinstance(a, InputRef):
                raise NotImplementedError(
                    "UNNEST argument must be an ARRAY column reference"
                )
            c = page.column(a.name)
            if c.array_pool is None:
                raise NotImplementedError(
                    f"UNNEST: {a.name} carries no array pool"
                )
            handles = np.asarray(c.data)[sel]
            valid = (
                None if c.valid is None else np.asarray(c.valid)[sel]
            )
            lens = c.array_pool.lengths()[handles]
            if valid is not None:
                lens = np.where(valid, lens, 0)
            args.append((c.array_pool, handles, lens))
        row_len = args[0][2]
        for _, _, ln in args[1:]:
            row_len = np.maximum(row_len, ln)
        total = int(row_len.sum())
        if total == 0:
            # empty expansion (no live rows / all arrays empty-or-NULL)
            cap0 = pad_capacity(1)
            names0 = list(page.names) + list(node.element_symbols)
            cols0 = [
                Column(c.type, c.data[:cap0],
                       None if c.valid is None else c.valid[:cap0],
                       c.dictionary, c.hash_pool, c.array_pool)
                for c in page.columns
            ] + [
                Column.from_numpy(
                    node.outputs[s],
                    np.zeros(
                        0,
                        dtype=object if isinstance(
                            node.outputs[s], T.VarcharType
                        ) else node.outputs[s].np_dtype,
                    ),
                    capacity=cap0,
                )
                for s in node.element_symbols
            ]
            return Page(
                names0, cols0,
                jnp.zeros((cap0,), dtype=jnp.bool_),
                known_rows=0, packed=True,
            )
        out_cap = shapes.bucket(max(total, 1), site="unnest")
        # source-row index per output row + within-array position
        src = np.repeat(sel, row_len)
        starts = np.concatenate([[0], np.cumsum(row_len)[:-1]])
        within = np.arange(total, dtype=np.int64) - np.repeat(starts, row_len)
        # gather the source columns device-side by the uploaded index
        idx_pad = np.zeros(out_cap, dtype=np.int32)
        idx_pad[:total] = src
        idx_dev = jnp.asarray(idx_pad)
        names, cols = [], []
        for n2, c in zip(page.names, page.columns):
            cols.append(Column(
                c.type, c.data[idx_dev],
                None if c.valid is None else c.valid[idx_dev],
                c.dictionary, c.hash_pool, c.array_pool,
            ))
            names.append(n2)
        # element columns from pool slices (host gather — the values
        # buffer is host-resident by design)
        for sym, (pool, handles, lens) in zip(node.element_symbols, args):
            ln_rep = np.repeat(lens, row_len)
            offs = np.repeat(pool.offsets[:-1][handles], row_len)
            ok = within < ln_rep
            at = np.where(ok, offs + within, 0)
            if len(pool.values):
                vals = pool.values[np.clip(at, 0, len(pool.values) - 1)]
            else:
                vals = np.zeros(
                    total,
                    dtype=object if isinstance(
                        node.outputs[sym], T.VarcharType
                    ) else node.outputs[sym].np_dtype,
                )
            if isinstance(node.outputs[sym], T.VarcharType):
                vals = np.where(ok, vals, "")
            cols.append(Column.from_numpy(
                node.outputs[sym], vals, valid=ok, capacity=out_cap,
            ))
            names.append(sym)
        out_mask = np.zeros(out_cap, dtype=np.bool_)
        out_mask[:total] = True
        return Page(
            names, cols, jnp.asarray(out_mask),
            known_rows=total, packed=True,
        )

    def _Window(self, node: P.Window) -> Page:
        from trino_tpu.exec.window import build_window_program

        page = self.execute(node.source)
        key = (
            "window", tuple(node.partition_by),
            tuple(
                (k.symbol, k.ascending, k.nulls_first)
                for k in node.order_keys
            ),
            tuple(
                (s, c.name, repr(c.args), repr(c.frame))
                for s, c in node.functions.items()
            ),
            self._layout_sig(page),
        )
        hit = self._jit_cache.get(key)
        if hit is None:
            types = {n: c.type for n, c in zip(page.names, page.columns)}
            dicts = {
                n: c.dictionary for n, c in zip(page.names, page.columns)
            }
            fn, out_meta = build_window_program(
                node, types, dicts, page.capacity
            )
            hit = (jax.jit(fn), out_meta)
            self._jit_cache[key] = hit
        fn, out_meta = hit
        env2 = fn(self._env(page), page.mask)
        names = list(page.names)
        cols = list(page.columns)
        for sym, t, d in out_meta:
            names.append(sym)
            cols.append(Column(t, *env2[sym], d))
        return Page(
            names, cols, page.mask,
            known_rows=page.known_rows, packed=page.packed,
        )

    def _Union(self, node: P.Union) -> Page:
        from trino_tpu.page import StringDictionary, _remap

        pages = [self.execute(s) for s in node.all_sources]
        # unify dictionaries per output column across branches: one
        # merged sorted dictionary, each branch remapped by gather
        for sym, src_syms in node.symbol_map.items():
            cols = [p.column(s) for p, s in zip(pages, src_syms)]
            if any(c.hash_pool is not None for c in cols):
                raise NotImplementedError(
                    "hash-coded varchar columns cannot merge across "
                    "UNION branches (no shared dictionary)"
                )
            if any(c.dictionary is not None for c in cols):
                # a branch may carry a dictionary-less varchar column
                # (typed NULL literals, e.g. a global grouping-set
                # branch's NULLed keys): treat it as an empty dictionary
                empty = np.asarray([], dtype=object)
                merged = StringDictionary(np.unique(np.concatenate(
                    [
                        c.dictionary.values if c.dictionary is not None
                        else empty
                        for c in cols
                    ]
                )))
                for p, s, c in zip(pages, src_syms, cols):
                    vals = (
                        c.dictionary.values if c.dictionary is not None
                        else empty
                    )
                    remap = np.searchsorted(
                        merged.values, vals
                    ).astype(np.int32)
                    p.columns[p.names.index(s)] = _remap(c, remap, merged)
        names, cols = [], []
        for sym, src_syms in node.symbol_map.items():
            parts = [
                (p.column(s).data, p.column(s).valid)
                for p, s in zip(pages, src_syms)
            ]
            out_t = node.outputs[sym]
            if isinstance(out_t, T.DecimalType) and out_t.is_long:
                # a branch may carry the column 1-D (typed NULL
                # literals / short-encoded values); widen to limbs so
                # sections concatenate shape-consistently
                from trino_tpu.exec.aggregates import _limb_encode

                parts = [
                    (
                        d if jnp.ndim(d) == 2
                        else _limb_encode(d.astype(jnp.int64)),
                        v,
                    )
                    for d, v in parts
                ]
            data, valid = _concat_sections(parts)
            ref = pages[0].column(src_syms[0])
            names.append(sym)
            cols.append(Column(node.outputs[sym], data, valid, ref.dictionary))
        mask = jnp.concatenate([p.mask for p in pages])
        out = Page(names, cols, mask)
        if all(p.known_rows is not None for p in pages):
            out.known_rows = sum(p.known_rows for p in pages)
        return out

    # ---- semi join -------------------------------------------------------

    def _SemiJoin(self, node: P.SemiJoin) -> Page:
        budget = self.hbm_budget()
        if not budget:
            self._prefetch_join_chains(node)
        if budget:
            from trino_tpu.exec import spill

            src_chain, src_scan = self._streamable(node.source)
            if (
                src_scan is not None
                and spill.est_output_bytes(self, node.source) > budget // 4
                and spill.est_output_bytes(self, node.filter_source)
                <= budget // 4
            ):
                filt = self._compact(self.execute(node.filter_source))
                return spill.streamed_semi_join(
                    self, node, src_chain, src_scan, filt
                )
        source = self.execute(node.source)
        filt = self._compact(self.execute(node.filter_source))
        return self._semi_join_pages(node, source, filt)

    def _semi_join_pages(self, node: P.SemiJoin, source: Page, filt: Page) -> Page:
        self._unify_join_dicts(source, filt, node.keys)
        pv = bv = None
        for lsym, rsym in node.keys:
            pv = _and_mask(pv, source.column(lsym).valid)
            bv = _and_mask(bv, filt.column(rsym).valid)
        needs_expand = len(node.keys) > 1 or node.filter is not None
        if needs_expand:
            order, lo, cnt, total = self._join_count(
                node.keys, source, filt
            )
            out_cap = shapes.bucket(max(total, 1), site="semi-join")
            key = (
                "semiB", tuple(node.keys), repr(node.filter), out_cap,
                self._layout_sig(source), self._layout_sig(filt),
            )
            fn = self._jit_cache.get(key)
            if fn is None:
                fn = self._build_semi_expand(node, source, filt, out_cap)
                self._jit_cache[key] = fn
            matched = fn(
                self._env(source), self._env(filt), order, lo, cnt
            )
        else:
            key = (
                "semiA", tuple(node.keys),
                self._layout_sig(source), self._layout_sig(filt),
            )
            fn = self._jit_cache.get(key)
            if fn is None:
                crit = list(node.keys)
                kinds = self._join_key_kinds(source, filt, crit)

                def fa(penv, pmask, benv, bmask):
                    pk, bk, pv2, bv2, _, _ = self._traced_join_keys(
                        penv, benv, crit, kinds
                    )
                    probe_live = pmask if pv2 is None else (pmask & pv2)
                    build_live = bmask if bv2 is None else (bmask & bv2)
                    _, _, cnt = K.join_ranges(
                        bk, build_live, pk, probe_live
                    )
                    return cnt > 0

                fn = jax.jit(fa)
                self._jit_cache[key] = fn
            matched = fn(
                self._env(source), source.mask, self._env(filt), filt.mask
            )
        valid = None
        if node.null_aware and filt.num_rows() == 0:
            # x IN (empty) is FALSE — even for NULL x (and NOT IN TRUE);
            # the 3VL valid mask must not apply over an empty build side.
            pass
        elif node.null_aware:
            # IN 3VL: NULL probe key with a nonempty (per-probe) set,
            # or no match while the set has NULLs -> NULL (reference
            # SemiJoinNode semantics). EXISTS is 2-valued.
            build_null_for = self._in_build_nulls(node, source, filt, bv)
            if pv is not None or build_null_for is not None:
                valid = jnp.ones_like(matched)
                if build_null_for is not None:
                    valid = valid & (matched | ~build_null_for)
                if pv is not None:
                    if node.filter is None:
                        # the set is the whole (nonempty) build side
                        valid = valid & pv
                    else:
                        # NULL probe key is FALSE, not NULL, when its
                        # correlated set filters down to empty
                        nonempty = self._correlated_nonempty(
                            node, source, filt, pv
                        )
                        valid = valid & (pv | ~nonempty)
        names = list(source.names) + [node.match_symbol]
        cols = list(source.columns) + [
            Column(T.BOOLEAN, matched, valid, None)
        ]
        return Page(
            names, cols, source.mask,
            known_rows=source.known_rows, packed=source.packed,
        )

    def _in_build_nulls(self, node: P.SemiJoin, source: Page, filt: Page, bv):
        """Per-probe 'the build side contributed a NULL key' vector for
        IN 3VL, or None when no NULL keys exist.

        With a correlated residual filter, only NULL-key build rows
        that pass the filter against that probe row count (the review
        case: x NOT IN (select y from t where t.z <> outer.w))."""
        if bv is None:
            return None
        null_rows = np.nonzero(np.asarray(filt.mask & ~bv))[0]
        if len(null_rows) == 0:
            return None
        if node.filter is None:
            return jnp.ones((source.capacity,), dtype=jnp.bool_)
        any_null = jnp.zeros((source.capacity,), dtype=jnp.bool_)
        probe_idx = jnp.arange(source.capacity, dtype=jnp.int32)
        for r in null_rows.tolist():
            build_idx = jnp.full((source.capacity,), r, dtype=jnp.int32)
            pair = self._gather_pair_page(
                source, filt, probe_idx, build_idx, source.mask
            )
            fd, fv, _ = self._eval(pair, node.filter)
            passes = fd if fv is None else (fd & fv)
            any_null = any_null | passes
        return any_null

    def _correlated_nonempty(self, node: P.SemiJoin, source: Page, filt: Page, pv):
        """Per-probe 'some live build row passes the residual filter'
        vector — the per-probe set of a correlated IN is empty when no
        build row passes against that probe row. Only NULL-key probe
        rows need it, so loop over those (usually few), evaluating the
        filter against the whole build page per row."""
        nonempty = np.zeros((source.capacity,), dtype=np.bool_)
        need = np.nonzero(np.asarray(source.mask & ~pv))[0]
        if len(need) == 0:
            return jnp.asarray(nonempty)
        build_idx = jnp.arange(filt.capacity, dtype=jnp.int32)
        for i in need.tolist():
            probe_idx = jnp.full((filt.capacity,), i, dtype=jnp.int32)
            pair = self._gather_pair_page(
                source, filt, probe_idx, build_idx, filt.mask
            )
            fd, fv, _ = self._eval(pair, node.filter)
            passes = fd if fv is None else (fd & fv)
            nonempty[i] = bool(np.asarray(jnp.any(passes & filt.mask)))
        return jnp.asarray(nonempty)

    @staticmethod
    def _gather_pair_page(probe: Page, build: Page, probe_idx, build_idx, live) -> Page:
        names, cols = [], []
        for page, idx in ((probe, probe_idx), (build, build_idx)):
            for n, c in zip(page.names, page.columns):
                names.append(n)
                cols.append(
                    Column(
                        c.type,
                        c.data[idx],
                        None if c.valid is None else c.valid[idx],
                        c.dictionary,
                    )
                )
        return Page(names, cols, live)


def _splittable(agg: P.Aggregate) -> bool:
    """Probe partial/final decomposability BEFORE running chunks, so a
    non-splittable aggregate never discards computed partials."""
    from trino_tpu.plan.distribute import _split_aggregate

    try:
        _split_aggregate(agg)
        return True
    except NotImplementedError:
        return False


def _page_dev_bytes(page: Page) -> int:
    """Actual device bytes of a page's arrays (mask + data + valids)."""
    total = page.mask.shape[0]  # bool
    for c in page.columns:
        n = 1
        for d in c.data.shape:
            n *= int(d)
        total += n * c.data.dtype.itemsize
        if c.valid is not None:
            total += c.valid.shape[0]
    return total


def _rename_out(out_layout, env: dict, out_map: dict):
    """Translate a canonical chain program's output layout + env back
    to the caller's original symbol names (see shapes.canonicalize_chain).
    Builds fresh structures — the cached layout is shared across calls."""
    m = out_map
    layout = stage.ChainLayout(
        names=[m[n] for n in out_layout.names],
        types={m[n]: out_layout.types[n] for n in out_layout.names},
        dicts={m[n]: out_layout.dicts.get(n) for n in out_layout.names},
        capacity=out_layout.capacity,
        pools={m[n]: p for n, p in out_layout.pools.items() if n in m},
        arrays={m[n]: a for n, a in out_layout.arrays.items() if n in m},
    )
    env2 = {m[n]: env[n] for n in out_layout.names}
    return layout, env2


def _slice_page(page: Page, lo: int, hi: int) -> Page:
    """Row-range view of a page (device slices are cheap)."""
    cols = [
        Column(
            c.type, c.data[lo:hi],
            None if c.valid is None else c.valid[lo:hi],
            c.dictionary,
            c.hash_pool,
            c.array_pool,
        )
        for c in page.columns
    ]
    return Page(list(page.names), cols, page.mask[lo:hi])


def _concat_pages(pages: list[Page]) -> Page:
    """Concatenate same-layout pages (chunk partials share column
    types and dictionaries by construction)."""
    if len(pages) == 1:
        return pages[0]
    first = pages[0]
    cols = []
    for i, c in enumerate(first.columns):
        data = jnp.concatenate([p.columns[i].data for p in pages])
        if any(p.columns[i].valid is not None for p in pages):
            valid = jnp.concatenate([
                (
                    # [:1]: valids are per-ROW even for multi-lane data
                    # (two-limb decimals, sketch states)
                    jnp.ones(p.columns[i].data.shape[:1], dtype=jnp.bool_)
                    if p.columns[i].valid is None else p.columns[i].valid
                )
                for p in pages
            ])
        else:
            valid = None
        cols.append(Column(c.type, data, valid, c.dictionary, c.hash_pool, c.array_pool))
    mask = jnp.concatenate([p.mask for p in pages])
    return Page(list(first.names), cols, mask)


def _pair_layout(a: Page, b: Page) -> ColumnLayout:
    """Expression layout over the concatenated columns of two pages
    (for residual join filters evaluated on matched pairs)."""
    return ColumnLayout(
        types={
            **{n: c.type for n, c in zip(a.names, a.columns)},
            **{n: c.type for n, c in zip(b.names, b.columns)},
        },
        dictionaries={
            **{n: c.dictionary for n, c in zip(a.names, a.columns)},
            **{n: c.dictionary for n, c in zip(b.names, b.columns)},
        },
    )


def _gather_pair_env(penv, benv, probe_names, syms, probe_idx, build_idx, base=None):
    """Expanded-pair environment for the given symbols (traced)."""
    fenv = dict(base or {})
    for sym in syms:
        if sym not in fenv:
            from_probe = sym in probe_names
            d, v = (penv if from_probe else benv)[sym]
            idx = probe_idx if from_probe else build_idx
            fenv[sym] = (d[idx], None if v is None else v[idx])
    return fenv


def _concat_sections(parts):
    """Concatenate (data, valid|None) sections; None = all-valid."""
    if len(parts) == 1:
        return parts[0]
    data = jnp.concatenate([d for d, _ in parts])
    if all(v is None for _, v in parts):
        return data, None
    valids = [
        jnp.ones(d.shape[:1], dtype=jnp.bool_) if v is None else v
        for d, v in parts
    ]
    return data, jnp.concatenate(valids)


def _expr_symbols(e: RowExpression) -> set[str]:
    """Free input symbols of an expression tree."""
    out: set[str] = set()
    stack = [e]
    while stack:
        x = stack.pop()
        if isinstance(x, InputRef):
            out.add(x.name)
        elif isinstance(x, Call):
            stack.extend(x.args)
        elif isinstance(x, Cast):
            stack.append(x.arg)
    return out


def _and_mask(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b
