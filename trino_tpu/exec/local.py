"""Single-fragment plan execution over device pages.

The analog of the reference's LocalExecutionPlanner + Driver
(MAIN/sql/planner/LocalExecutionPlanner.java:527,
MAIN/operator/Driver.java:66) collapsed into a batch-synchronous tree
walk: each plan node consumes whole device Pages and produces one —
there is no page-at-a-time pull loop because a TPU wants one large
batched computation per operator, not 4KB batches. Host syncs happen
only at capacity decisions (join fan-out, group counts), mirroring the
reference's build-side barriers.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from trino_tpu import types as T
from trino_tpu.exec import kernels as K
from trino_tpu.exec.aggregates import compute_aggregate
from trino_tpu.expr.compiler import ColumnLayout, compile_expr
from trino_tpu.expr.ir import AggCall, RowExpression
from trino_tpu.metadata import Metadata, Session
from trino_tpu.page import Column, Page, pad_capacity, unify_dictionaries
from trino_tpu.plan import nodes as P

__all__ = ["LocalExecutor"]


class LocalExecutor:
    """Executes a logical plan tree on the local devices."""

    def __init__(self, metadata: Metadata, session: Session):
        self.metadata = metadata
        self.session = session

    def execute(self, node: P.PlanNode) -> Page:
        m = getattr(self, f"_{type(node).__name__}", None)
        if m is None:
            raise NotImplementedError(f"no executor for {type(node).__name__}")
        return m(node)

    # ---- expression evaluation ------------------------------------------

    def _layout(self, page: Page) -> ColumnLayout:
        return ColumnLayout(
            types={n: c.type for n, c in zip(page.names, page.columns)},
            dictionaries={
                n: c.dictionary for n, c in zip(page.names, page.columns)
            },
        )

    def _eval(self, page: Page, expr: RowExpression):
        """Evaluate an expression over a page.

        Returns (data, valid, dictionary) with data broadcast to the
        page capacity.
        """
        compiled = compile_expr(expr, self._layout(page))
        env = {
            n: (c.data, c.valid) for n, c in zip(page.names, page.columns)
        }
        data, valid = compiled.fn(env)
        cap = page.capacity
        if jnp.ndim(data) == 0:
            data = jnp.broadcast_to(data, (cap,))
        if valid is not None and jnp.ndim(valid) == 0:
            valid = jnp.broadcast_to(valid, (cap,))
        return data, valid, compiled.dictionary

    # ---- leaf nodes ------------------------------------------------------

    def _TableScan(self, node: P.TableScan) -> Page:
        connector = self.metadata.connector(node.catalog)
        cols = connector.scan(
            node.schema, node.table, list(node.assignments.values())
        )
        named = {
            sym: (node.outputs[sym], cols[col])
            for sym, col in node.assignments.items()
        }
        return Page.from_arrays(named)

    def _Values(self, node: P.Values) -> Page:
        # only the zero-column single-row form (SELECT without FROM)
        if node.outputs:
            raise NotImplementedError("general VALUES is not supported yet")
        mask = np.zeros(8, dtype=np.bool_)
        mask[: len(node.rows)] = True
        return Page([], [], jnp.asarray(mask))

    # ---- row-level nodes -------------------------------------------------

    def _Filter(self, node: P.Filter) -> Page:
        page = self.execute(node.source)
        data, valid, _ = self._eval(page, node.predicate)
        keep = data if valid is None else (data & valid)
        return Page(page.names, page.columns, page.mask & keep)

    def _Project(self, node: P.Project) -> Page:
        page = self.execute(node.source)
        names, cols = [], []
        for sym, expr in node.assignments.items():
            data, valid, dictionary = self._eval(page, expr)
            names.append(sym)
            cols.append(Column(expr.type, data, valid, dictionary))
        return Page(names, cols, page.mask)

    def _Limit(self, node: P.Limit) -> Page:
        page = self.execute(node.source)
        rank = jnp.cumsum(page.mask.astype(jnp.int64))
        keep = page.mask & (rank > node.offset)
        if node.count >= 0:
            keep = keep & (rank <= node.offset + node.count)
        return Page(page.names, page.columns, keep)

    def _Output(self, node: P.Output) -> Page:
        page = self.execute(node.source)
        cols = [page.column(s) for s in node.symbols]
        return Page(list(node.names), cols, page.mask)

    def _Exchange(self, node: P.Exchange) -> Page:
        # single-fragment local execution: exchanges are pass-through;
        # the distributed executor lowers REMOTE ones to collectives
        return self.execute(node.source)

    # ---- sorting ---------------------------------------------------------

    def _sort_keys(self, page: Page, keys: list[P.SortKey]):
        out = []
        for k in keys:
            col = page.column(k.symbol)
            nulls_first = k.nulls_first
            if nulls_first is None:
                # reference default: nulls are largest (ASC last, DESC first)
                nulls_first = not k.ascending
            out.append((col.data, col.valid, k.ascending, nulls_first))
        return out

    def _apply_perm(self, page: Page, perm: jnp.ndarray, limit: int | None = None) -> Page:
        cols = []
        for c in page.columns:
            data = c.data[perm]
            valid = None if c.valid is None else c.valid[perm]
            if limit is not None:
                data = data[:limit]
                valid = None if valid is None else valid[:limit]
            cols.append(Column(c.type, data, valid, c.dictionary))
        mask = page.mask[perm]
        if limit is not None:
            mask = mask[:limit]
        return Page(page.names, cols, mask)

    def _Sort(self, node: P.Sort) -> Page:
        page = self.execute(node.source)
        perm = K.sort_perm(self._sort_keys(page, node.keys), page.mask)
        return self._apply_perm(page, perm)

    def _TopN(self, node: P.TopN) -> Page:
        page = self.execute(node.source)
        perm = K.sort_perm(self._sort_keys(page, node.keys), page.mask)
        out = self._apply_perm(page, perm, limit=None)
        pos = jnp.arange(out.capacity)
        mask = out.mask & (pos < node.count)
        cap = pad_capacity(min(node.count, out.capacity))
        return self._slice(Page(out.names, out.columns, mask), cap)

    @staticmethod
    def _slice(page: Page, capacity: int) -> Page:
        if capacity >= page.capacity:
            return page
        cols = [
            Column(
                c.type,
                c.data[:capacity],
                None if c.valid is None else c.valid[:capacity],
                c.dictionary,
            )
            for c in page.columns
        ]
        return Page(page.names, cols, page.mask[:capacity])

    def _compact(self, page: Page, extra_capacity: int = 0) -> Page:
        """Gather live rows to the front and shrink capacity
        (Page.compact analog, SPI/Page.java:180). Host-syncs the count."""
        n_live = page.num_rows()
        cap = pad_capacity(n_live + extra_capacity)
        perm = jnp.argsort((~page.mask).astype(jnp.int8), stable=True)
        if cap >= page.capacity:
            return self._apply_perm(page, perm)
        return self._apply_perm(page, perm, limit=cap)

    # ---- aggregation -----------------------------------------------------

    def _Aggregate(self, node: P.Aggregate) -> Page:
        page = self.execute(node.source)
        live = page.mask
        if not node.group_keys:
            return self._global_aggregate(node, page)

        key_cols = [page.column(s) for s in node.group_keys]
        n_live = page.num_rows()
        capacity = pad_capacity(max(2 * n_live, 8))
        norm = [K.normalize_key(c.data, c.valid) for c in key_cols]
        group, owner = K.assign_groups(
            tuple(b for b, _ in norm), tuple(f for _, f in norm), live, capacity
        )
        occupied = owner < page.capacity

        names, cols = [], []
        own_idx = jnp.clip(owner, 0, page.capacity - 1)
        for sym, col in zip(node.group_keys, key_cols):
            data = col.data[own_idx]
            valid = None if col.valid is None else (col.valid[own_idx] & occupied)
            names.append(sym)
            cols.append(Column(col.type, data, valid, col.dictionary))

        for sym, call in node.aggregates.items():
            data, valid = self._run_agg(page, call, group, capacity, live, key_cols)
            names.append(sym)
            cols.append(
                Column(
                    call.type, data, _and_mask(valid, None),
                    self._agg_dictionary(page, call),
                )
            )
        out = Page(names, cols, occupied)
        return self._compact(out)

    def _global_aggregate(self, node: P.Aggregate, page: Page) -> Page:
        # one output row, even over empty input (reference semantics)
        live = page.mask
        group = jnp.where(live, 0, 1).astype(jnp.int32)
        names, cols = [], []
        cap = 8
        for sym, call in node.aggregates.items():
            data, valid = self._run_agg(page, call, group, 1, live, [])
            data = _pad_to(data, cap)
            valid = None if valid is None else _pad_to(valid, cap)
            names.append(sym)
            cols.append(
                Column(call.type, data, valid, self._agg_dictionary(page, call))
            )
        mask = np.zeros(cap, dtype=np.bool_)
        mask[0] = True
        return Page(names, cols, jnp.asarray(mask))

    def _agg_dictionary(self, page: Page, call: AggCall):
        if not isinstance(call.type, T.VarcharType):
            return None
        # min/max/any_value over varchar keep the argument's dictionary
        compiled = compile_expr(call.args[0], self._layout(page))
        return compiled.dictionary

    def _run_agg(
        self, page: Page, call: AggCall, group, capacity, live, key_cols
    ):
        arg = None
        if call.args:
            data, valid, _ = self._eval(page, call.args[0])
            arg = (data, valid)
        contrib_live = live
        if call.filter is not None:
            fd, fv, _ = self._eval(page, call.filter)
            contrib_live = contrib_live & (fd if fv is None else (fd & fv))
        g = group
        if call.distinct:
            g, contrib_live = self._dedupe(
                key_cols, arg, group, contrib_live, page.capacity
            )
        # rows that don't contribute use the drop segment
        g = jnp.where(contrib_live, g, capacity)
        return compute_aggregate(
            call.name, call.type, arg, g, capacity, contrib_live
        )

    def _dedupe(self, key_cols, arg, group, live, page_capacity):
        """DISTINCT: keep one representative row per (group, value)."""
        data, valid = arg
        live_d = live if valid is None else (live & valid)
        norm = [K.normalize_key(c.data, c.valid) for c in key_cols]
        norm.append(K.normalize_key(data, valid))
        cap2 = pad_capacity(max(2 * page_capacity, 8))
        g2, owner2 = K.assign_groups(
            tuple(b for b, _ in norm), tuple(f for _, f in norm), live_d, cap2
        )
        row_idx = jnp.arange(page_capacity, dtype=jnp.int32)
        rep = live_d & (owner2[jnp.clip(g2, 0, cap2 - 1)] == row_idx)
        return group, rep

    # ---- joins -----------------------------------------------------------

    def _Join(self, node: P.Join) -> Page:
        left = self._compact(self.execute(node.left))
        right = self._compact(self.execute(node.right))
        if node.kind == "cross":
            return self._cross_join(node, left, right)
        if node.kind == "right":
            flipped = P.Join(
                node.outputs, kind="left", left=node.right, right=node.left,
                criteria=[(r, l) for l, r in node.criteria],
                filter=node.filter,
            )
            # re-execute would recompute sources; join directly instead
            return self._equi_join(flipped, right, left)
        return self._equi_join(node, left, right)

    def _cross_join(self, node: P.Join, left: Page, right: Page) -> Page:
        # callers (_Join) hand in already-compacted pages
        n_l, n_r = left.num_rows(), right.num_rows()
        cap = pad_capacity(max(n_l * n_r, 1))
        j = jnp.arange(cap)
        li = jnp.clip(j // max(n_r, 1), 0, max(left.capacity - 1, 0))
        ri = jnp.clip(j % max(n_r, 1), 0, max(right.capacity - 1, 0))
        out_live = j < n_l * n_r
        names, cols = [], []
        for page, idx in ((left, li), (right, ri)):
            for n, c in zip(page.names, page.columns):
                names.append(n)
                cols.append(
                    Column(
                        c.type,
                        c.data[idx],
                        None if c.valid is None else c.valid[idx],
                        c.dictionary,
                    )
                )
        return Page(names, cols, out_live)

    def _join_key(self, probe: Page, build: Page, criteria):
        """Combined uint64 keys for probe/build sides.

        Single fixed-width key -> exact; multi-column -> hash-combined
        and ``verify`` is True (matches re-checked after expansion).
        """
        pairs = []
        for lsym, rsym in criteria:
            pc, bc = probe.column(lsym), build.column(rsym)
            if pc.dictionary is not None or bc.dictionary is not None:
                pc2, bc2 = unify_dictionaries(pc, bc)
                probe.columns[probe.names.index(lsym)] = pc2
                build.columns[build.names.index(rsym)] = bc2
                pc, bc = pc2, bc2
            pairs.append((pc, bc))
        probe_valid = None
        build_valid = None
        for pc, bc in pairs:
            probe_valid = _and_mask(probe_valid, pc.valid)
            build_valid = _and_mask(build_valid, bc.valid)
        if len(pairs) == 1:
            pk, _ = K.normalize_key(pairs[0][0].data, None)
            bk, _ = K.normalize_key(pairs[0][1].data, None)
            verify = False
        else:
            pk = K.hash_columns([(c.data, None) for c, _ in pairs])
            bk = K.hash_columns([(c.data, None) for _, c in pairs])
            verify = True
        return pk, bk, probe_valid, build_valid, pairs, verify

    def _equi_join(self, node: P.Join, probe: Page, build: Page) -> Page:
        if not node.criteria:
            raise NotImplementedError(f"{node.kind} join without equi criteria")
        pk, bk, pv, bv, pairs, verify = self._join_key(
            probe, build, node.criteria
        )
        probe_live = probe.mask if pv is None else (probe.mask & pv)
        build_live = build.mask if bv is None else (build.mask & bv)
        order, lo, cnt = K.join_ranges(bk, build_live, pk, probe_live)
        total = int(jnp.sum(cnt))
        out_cap = pad_capacity(max(total, 1))
        probe_idx, build_idx, out_live = K.expand_matches(
            order, lo, cnt, out_cap
        )
        if verify:
            for pc, bc in pairs:
                out_live = out_live & (pc.data[probe_idx] == bc.data[build_idx])

        inner = self._gather_join_columns(
            node, probe, build, probe_idx, build_idx, out_live
        )
        if node.filter is not None:
            fd, fv, _ = self._eval(inner, node.filter)
            out_live = inner.mask & (fd if fv is None else (fd & fv))
            inner = Page(inner.names, inner.columns, out_live)
        if node.kind == "inner":
            return inner
        if node.kind in ("left", "full"):
            matched = K.seg_sum(
                inner.mask.astype(jnp.int32), probe_idx, probe.capacity
            ) > 0
            unmatched = probe.mask & ~matched
            out = self._append_outer_rows(node, inner, probe, unmatched, side="probe")
            if node.kind == "full":
                bmatched = K.seg_sum(
                    inner.mask.astype(jnp.int32),
                    jnp.where(inner.mask, build_idx, build.capacity),
                    build.capacity,
                ) > 0
                bunmatched = build.mask & ~bmatched
                out = self._append_outer_rows(node, out, build, bunmatched, side="build")
            return out
        raise NotImplementedError(f"join kind {node.kind}")

    def _gather_join_columns(
        self, node: P.Join, probe: Page, build: Page, probe_idx, build_idx, out_live
    ) -> Page:
        names, cols = [], []
        for sym in node.outputs:
            if sym in probe.names:
                c, idx = probe.column(sym), probe_idx
            else:
                c, idx = build.column(sym), build_idx
            names.append(sym)
            cols.append(
                Column(
                    c.type,
                    c.data[idx],
                    None if c.valid is None else c.valid[idx],
                    c.dictionary,
                )
            )
        return Page(names, cols, out_live)

    def _append_outer_rows(
        self, node: P.Join, inner: Page, side_page: Page, unmatched, side: str
    ) -> Page:
        """Append unmatched outer rows with NULLs for the other side."""
        n_un = int(jnp.sum(unmatched))
        if n_un == 0:
            return inner
        perm = jnp.argsort(~unmatched, stable=True)
        cap2 = pad_capacity(n_un)
        idx = perm[:cap2]
        sec_live = jnp.arange(cap2) < n_un
        names, cols = [], []
        for sym, c_in in zip(inner.names, inner.columns):
            if sym in side_page.names:
                c = side_page.column(sym)
                data2 = c.data[idx]
                valid2 = sec_live if c.valid is None else (c.valid[idx] & sec_live)
            else:
                data2 = jnp.zeros((cap2,), dtype=c_in.type.np_dtype)
                valid2 = jnp.zeros((cap2,), dtype=jnp.bool_)
            data = jnp.concatenate([c_in.data, data2])
            v1 = c_in.valid
            if v1 is None and valid2 is not None:
                v1 = jnp.ones((inner.capacity,), dtype=jnp.bool_)
            valid = None if v1 is None else jnp.concatenate([v1, valid2])
            names.append(sym)
            cols.append(Column(c_in.type, data, valid, c_in.dictionary))
        mask = jnp.concatenate([inner.mask, sec_live])
        return Page(names, cols, mask)

    # ---- semi join -------------------------------------------------------

    def _SemiJoin(self, node: P.SemiJoin) -> Page:
        source = self.execute(node.source)
        filt = self._compact(self.execute(node.filter_source))
        pk, bk, pv, bv, pairs, verify = self._join_key(
            source, filt, node.keys
        )
        probe_live = source.mask if pv is None else (source.mask & pv)
        build_live = filt.mask if bv is None else (filt.mask & bv)
        order, lo, cnt = K.join_ranges(bk, build_live, pk, probe_live)
        if verify or node.filter is not None:
            total = int(jnp.sum(cnt))
            out_cap = pad_capacity(max(total, 1))
            probe_idx, build_idx, out_live = K.expand_matches(
                order, lo, cnt, out_cap
            )
            for pc, bc in pairs:
                out_live = out_live & (pc.data[probe_idx] == bc.data[build_idx])
            if node.filter is not None:
                # residual correlated predicate over (source, filter) pairs
                pair_page = self._gather_pair_page(
                    source, filt, probe_idx, build_idx, out_live
                )
                fd, fv, _ = self._eval(pair_page, node.filter)
                out_live = out_live & (fd if fv is None else (fd & fv))
            matched = K.seg_sum(
                out_live.astype(jnp.int32), probe_idx, source.capacity
            ) > 0
        else:
            matched = cnt > 0
        valid = None
        if node.null_aware:
            # IN 3VL: NULL probe key, or no match while the build side
            # has NULLs -> NULL (reference SemiJoinNode semantics).
            # EXISTS is 2-valued: match is plain TRUE/FALSE.
            build_null_for = self._in_build_nulls(node, source, filt, bv)
            if pv is not None or build_null_for is not None:
                valid = pv if pv is not None else jnp.ones_like(matched)
                if build_null_for is not None:
                    valid = valid & (matched | ~build_null_for)
        names = list(source.names) + [node.match_symbol]
        cols = list(source.columns) + [
            Column(T.BOOLEAN, matched, valid, None)
        ]
        return Page(names, cols, source.mask)

    def _in_build_nulls(self, node: P.SemiJoin, source: Page, filt: Page, bv):
        """Per-probe 'the build side contributed a NULL key' vector for
        IN 3VL, or None when no NULL keys exist.

        With a correlated residual filter, only NULL-key build rows
        that pass the filter against that probe row count (the review
        case: x NOT IN (select y from t where t.z <> outer.w))."""
        if bv is None:
            return None
        null_rows = np.nonzero(np.asarray(filt.mask & ~bv))[0]
        if len(null_rows) == 0:
            return None
        if node.filter is None:
            return jnp.ones((source.capacity,), dtype=jnp.bool_)
        any_null = jnp.zeros((source.capacity,), dtype=jnp.bool_)
        probe_idx = jnp.arange(source.capacity, dtype=jnp.int32)
        for r in null_rows.tolist():
            build_idx = jnp.full((source.capacity,), r, dtype=jnp.int32)
            pair = self._gather_pair_page(
                source, filt, probe_idx, build_idx, source.mask
            )
            fd, fv, _ = self._eval(pair, node.filter)
            passes = fd if fv is None else (fd & fv)
            any_null = any_null | passes
        return any_null

    @staticmethod
    def _gather_pair_page(probe: Page, build: Page, probe_idx, build_idx, live) -> Page:
        names, cols = [], []
        for page, idx in ((probe, probe_idx), (build, build_idx)):
            for n, c in zip(page.names, page.columns):
                names.append(n)
                cols.append(
                    Column(
                        c.type,
                        c.data[idx],
                        None if c.valid is None else c.valid[idx],
                        c.dictionary,
                    )
                )
        return Page(names, cols, live)


def _and_mask(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b


def _pad_to(arr: jnp.ndarray, capacity: int) -> jnp.ndarray:
    n = arr.shape[0]
    if n >= capacity:
        return arr[:capacity]
    return jnp.concatenate(
        [arr, jnp.zeros((capacity - n,), dtype=arr.dtype)]
    )
