"""Single-fragment plan execution over device pages.

The analog of the reference's LocalExecutionPlanner + Driver
(MAIN/sql/planner/LocalExecutionPlanner.java:527,
MAIN/operator/Driver.java:66) collapsed into a batch-synchronous tree
walk: each plan node consumes whole device Pages and produces one —
there is no page-at-a-time pull loop because a TPU wants one large
batched computation per operator, not 4KB batches. Host syncs happen
only at capacity decisions (join fan-out, group counts), mirroring the
reference's build-side barriers.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.exec import kernels as K
from trino_tpu.exec import stage
from trino_tpu.exec.aggregates import compute_aggregate
from trino_tpu.expr.compiler import ColumnLayout, compile_expr
from trino_tpu.expr.ir import AggCall, RowExpression
from trino_tpu.metadata import Metadata, Session
from trino_tpu.page import Column, Page, pad_capacity, unify_dictionaries
from trino_tpu.plan import nodes as P

__all__ = ["LocalExecutor"]


class LocalExecutor:
    """Executes a logical plan tree on the local devices.

    Per-node device computations are traced into jitted programs cached
    by (plan structure, input layout, capacity) — the analog of the
    reference's per-query bytecode generation with
    PageFunctionCompiler's cache (MAIN/sql/gen/PageFunctionCompiler.java:102).
    Scanned tables are cached device-resident (a worker's memory
    connector analog), so repeated queries pay no host->HBM transfer.
    """

    def __init__(self, metadata: Metadata, session: Session):
        self.metadata = metadata
        self.session = session
        #: structural key -> (jitted fn, host metadata)
        self._jit_cache: dict = {}
        #: (catalog, schema, table) -> {column name: Column}; "" -> mask
        self._scan_cache: dict = {}

    def execute(self, node: P.PlanNode) -> Page:
        if isinstance(node, stage.FUSABLE):
            chain: list[P.PlanNode] = []
            cur = node
            while isinstance(cur, stage.FUSABLE):
                chain.append(cur)
                cur = cur.sources[0]
            base = self.execute(cur)
            return self._run_chain(list(reversed(chain)), base)
        m = getattr(self, f"_{type(node).__name__}", None)
        if m is None:
            raise NotImplementedError(f"no executor for {type(node).__name__}")
        return m(node)

    # ---- fused pipelines -------------------------------------------------

    @staticmethod
    def _node_key(n: P.PlanNode):
        if isinstance(n, P.Filter):
            return ("F", repr(n.predicate))
        if isinstance(n, P.Project):
            return ("P", tuple((s, repr(e)) for s, e in n.assignments.items()))
        if isinstance(n, P.Aggregate):
            return (
                "A", tuple(n.group_keys),
                tuple(
                    (s, a.name, a.distinct, repr(a.args), repr(a.filter))
                    for s, a in n.aggregates.items()
                ),
                n.step,
            )
        if isinstance(n, (P.Sort, P.TopN)):
            return (
                "S",
                tuple((k.symbol, k.ascending, k.nulls_first) for k in n.keys),
                getattr(n, "count", None),
            )
        if isinstance(n, P.Limit):
            return ("L", n.count, n.offset)
        raise NotImplementedError(type(n).__name__)

    def _run_chain(self, chain: list[P.PlanNode], page: Page) -> Page:
        """Run a fused operator chain: one jitted program, one dispatch.

        Grouped aggregations retry with 8x larger slot tables when the
        returned overflow flag trips (rare: only when the group count
        exceeds capacity/2 of the initial guess)."""
        caps = stage.plan_capacities(chain, page.capacity)
        while True:
            key = (
                "chain",
                tuple(self._node_key(n) for n in chain),
                tuple((i, c[0]) for i, c in sorted(caps.items())),
                self._layout_sig(page),
            )
            hit = self._jit_cache.get(key)
            if hit is None:
                in_layout = stage.ChainLayout(
                    names=list(page.names),
                    types={
                        n: c.type for n, c in zip(page.names, page.columns)
                    },
                    dicts={
                        n: c.dictionary
                        for n, c in zip(page.names, page.columns)
                    },
                    capacity=page.capacity,
                )
                fn, out_layout = stage.build_chain(chain, in_layout, caps)

                def counted(env, mask, _fn=fn):
                    env2, mask2, flags = _fn(env, mask)
                    return env2, mask2, flags, K.count_true(mask2)

                hit = (jax.jit(counted), out_layout)
                self._jit_cache[key] = hit
            fn, out_layout = hit
            env, mask, flags, n_live_dev = fn(self._env(page), page.mask)
            # one host sync fetches overflow flags AND the live count,
            # so downstream consumers (compact, joins, result fetch)
            # never re-sync
            vals, n_live = jax.device_get((flags, n_live_dev))
            if vals:
                overflowed = [i for i, v in vals.items() if v]
                if overflowed:
                    for i in overflowed:
                        cap, mx = caps[i]
                        if cap >= mx:
                            raise RuntimeError(
                                "aggregation table overflow at max capacity"
                            )
                        caps[i][0] = min(cap * 8, mx)
                    continue
            cols = [
                Column(
                    out_layout.types[s],
                    env[s][0],
                    env[s][1],
                    out_layout.dicts.get(s),
                )
                for s in out_layout.names
            ]
            out = Page(list(out_layout.names), cols, mask)
            out.known_rows = int(n_live)
            # chains ending in a sort emit live rows first (sort_perm
            # pushes dead rows last)
            out.packed = isinstance(chain[-1], (P.Sort, P.TopN))
            if pad_capacity(out.known_rows) < out.capacity:
                out = self._compact(out)
            return out

    # ---- expression evaluation ------------------------------------------

    def _layout(self, page: Page) -> ColumnLayout:
        return ColumnLayout(
            types={n: c.type for n, c in zip(page.names, page.columns)},
            dictionaries={
                n: c.dictionary for n, c in zip(page.names, page.columns)
            },
        )

    def _layout_sig(self, page: Page) -> tuple:
        return tuple(
            (n, repr(c.type), id(c.dictionary), c.valid is not None)
            for n, c in zip(page.names, page.columns)
        ) + (page.capacity,)

    def _env(self, page: Page) -> dict:
        return {n: (c.data, c.valid) for n, c in zip(page.names, page.columns)}

    def _eval(self, page: Page, expr: RowExpression):
        """Evaluate one expression over a page (eager path for join
        residuals etc.). Returns (data, valid, dictionary), data
        broadcast to the page capacity."""
        compiled = compile_expr(expr, self._layout(page))
        data, valid = compiled.fn(self._env(page))
        cap = page.capacity
        if jnp.ndim(data) == 0:
            data = jnp.broadcast_to(data, (cap,))
        if valid is not None and jnp.ndim(valid) == 0:
            valid = jnp.broadcast_to(valid, (cap,))
        return data, valid, compiled.dictionary

    # ---- leaf nodes ------------------------------------------------------

    def _TableScan(self, node: P.TableScan) -> Page:
        key = (node.catalog, node.schema, node.table)
        cache = self._scan_cache.setdefault(key, {})
        missing = [c for c in node.assignments.values() if c not in cache]
        if missing or "" not in cache:
            connector = self.metadata.connector(node.catalog)
            cols = connector.scan(node.schema, node.table, missing)
            n = connector.row_count(node.schema, node.table)
            cap = pad_capacity(n)
            if "" not in cache:
                mask = np.zeros(cap, dtype=np.bool_)
                mask[:n] = True
                cache[""] = jnp.asarray(mask)
            by_col = {c: s for s, c in node.assignments.items()}
            for cname in missing:
                cache[cname] = Column.from_numpy(
                    node.outputs[by_col[cname]], cols[cname], capacity=cap
                )
            cache["#rows"] = n
        names = list(node.assignments)
        columns = [cache[c] for c in node.assignments.values()]
        return Page(
            names, columns, cache[""],
            known_rows=cache["#rows"], packed=True,
        )

    def _Exchange(self, node: P.Exchange) -> Page:
        # single-device execution: every exchange is the identity (the
        # mesh executor overrides this with collectives/gathers)
        return self.execute(node.source)

    def _Values(self, node: P.Values) -> Page:
        # only the zero-column single-row form (SELECT without FROM)
        if node.outputs:
            raise NotImplementedError("general VALUES is not supported yet")
        mask = np.zeros(8, dtype=np.bool_)
        mask[: len(node.rows)] = True
        return Page(
            [], [], jnp.asarray(mask),
            known_rows=len(node.rows), packed=True,
        )

    # ---- row-level nodes -------------------------------------------------

    def _Output(self, node: P.Output) -> Page:
        page = self.execute(node.source)
        cols = [page.column(s) for s in node.symbols]
        return Page(
            list(node.names), cols, page.mask,
            known_rows=page.known_rows, packed=page.packed,
        )

    def _compact(self, page: Page, extra_capacity: int = 0) -> Page:
        """Gather live rows to the front and shrink capacity
        (Page.compact analog, SPI/Page.java:180) — one jitted program
        per (layout, capacity) so the device sees a single dispatch.
        Syncs the count only when the producer did not record it."""
        n_live = page.num_rows()
        cap = pad_capacity(n_live + extra_capacity)
        if page.packed and cap >= page.capacity:
            return page
        limit = cap if cap < page.capacity else page.capacity
        key = ("compact", self._layout_sig(page), limit)
        fn = self._jit_cache.get(key)
        if fn is None:
            def compact_fn(env, mask):
                perm = jnp.argsort(
                    (~mask).astype(jnp.int8), stable=True
                )[:limit]
                env2 = {
                    s: (
                        d[perm],
                        None if v is None else v[perm],
                    )
                    for s, (d, v) in env.items()
                }
                return env2, mask[perm]

            fn = jax.jit(compact_fn)
            self._jit_cache[key] = fn
        env2, mask2 = fn(self._env(page), page.mask)
        cols = [
            Column(c.type, *env2[s], c.dictionary)
            for s, c in zip(page.names, page.columns)
        ]
        out = Page(list(page.names), cols, mask2)
        out.known_rows = n_live
        out.packed = True
        return out

    # ---- aggregation -----------------------------------------------------

    def _Join(self, node: P.Join) -> Page:
        left = self._compact(self.execute(node.left))
        right = self._compact(self.execute(node.right))
        if node.kind == "cross":
            return self._cross_join(node, left, right)
        if node.kind == "right":
            flipped = P.Join(
                node.outputs, kind="left", left=node.right, right=node.left,
                criteria=[(r, l) for l, r in node.criteria],
                filter=node.filter,
            )
            # re-execute would recompute sources; join directly instead
            return self._equi_join(flipped, right, left)
        return self._equi_join(node, left, right)

    def _cross_join(self, node: P.Join, left: Page, right: Page) -> Page:
        # callers (_Join) hand in already-compacted pages
        n_l, n_r = left.num_rows(), right.num_rows()
        cap = pad_capacity(max(n_l * n_r, 1))
        j = jnp.arange(cap)
        li = jnp.clip(j // max(n_r, 1), 0, max(left.capacity - 1, 0))
        ri = jnp.clip(j % max(n_r, 1), 0, max(right.capacity - 1, 0))
        out_live = j < n_l * n_r
        names, cols = [], []
        for page, idx in ((left, li), (right, ri)):
            for n, c in zip(page.names, page.columns):
                names.append(n)
                cols.append(
                    Column(
                        c.type,
                        c.data[idx],
                        None if c.valid is None else c.valid[idx],
                        c.dictionary,
                    )
                )
        return Page(names, cols, out_live)

    def _join_key(self, probe: Page, build: Page, criteria):
        """Combined uint64 keys for probe/build sides.

        Single fixed-width key -> exact; multi-column -> hash-combined
        and ``verify`` is True (matches re-checked after expansion).
        """
        pairs = []
        for lsym, rsym in criteria:
            pc, bc = probe.column(lsym), build.column(rsym)
            if pc.dictionary is not None or bc.dictionary is not None:
                pc2, bc2 = unify_dictionaries(pc, bc)
                probe.columns[probe.names.index(lsym)] = pc2
                build.columns[build.names.index(rsym)] = bc2
                pc, bc = pc2, bc2
            pairs.append((pc, bc))
        probe_valid = None
        build_valid = None
        for pc, bc in pairs:
            probe_valid = _and_mask(probe_valid, pc.valid)
            build_valid = _and_mask(build_valid, bc.valid)
        if len(pairs) == 1:
            pk, _ = K.normalize_key(pairs[0][0].data, None)
            bk, _ = K.normalize_key(pairs[0][1].data, None)
            verify = False
        else:
            pk = K.hash_columns([(c.data, None) for c, _ in pairs])
            bk = K.hash_columns([(c.data, None) for _, c in pairs])
            verify = True
        return pk, bk, probe_valid, build_valid, pairs, verify

    def _equi_join(self, node: P.Join, probe: Page, build: Page) -> Page:
        if not node.criteria:
            raise NotImplementedError(f"{node.kind} join without equi criteria")
        pk, bk, pv, bv, pairs, verify = self._join_key(
            probe, build, node.criteria
        )
        probe_live = probe.mask if pv is None else (probe.mask & pv)
        build_live = build.mask if bv is None else (build.mask & bv)
        order, lo, cnt = K.join_ranges(bk, build_live, pk, probe_live)
        total = int(jnp.sum(cnt))
        out_cap = pad_capacity(max(total, 1))
        probe_idx, build_idx, out_live = K.expand_matches(
            order, lo, cnt, out_cap
        )
        exact = not verify
        if verify:
            out_live = _verify_matches(pairs, probe_idx, build_idx, out_live)

        inner = self._gather_join_columns(
            node, probe, build, probe_idx, build_idx, out_live
        )
        if exact and node.filter is None:
            # the expansion emits matches as a dense prefix of length
            # ``total`` — record it so downstream never re-syncs
            inner.known_rows = total
            inner.packed = True
        if node.filter is not None:
            fd, fv, _ = self._eval(inner, node.filter)
            out_live = inner.mask & (fd if fv is None else (fd & fv))
            inner = Page(inner.names, inner.columns, out_live)
        if node.kind == "inner":
            return inner
        if node.kind in ("left", "full"):
            matched = K.range_any(cnt, inner.mask)
            unmatched = probe.mask & ~matched
            out = self._append_outer_rows(node, inner, probe, unmatched, side="probe")
            if node.kind == "full":
                bmatched = K.scatter_any(
                    build_idx, inner.mask, build.capacity
                )
                bunmatched = build.mask & ~bmatched
                out = self._append_outer_rows(node, out, build, bunmatched, side="build")
            return out
        raise NotImplementedError(f"join kind {node.kind}")

    def _gather_join_columns(
        self, node: P.Join, probe: Page, build: Page, probe_idx, build_idx, out_live
    ) -> Page:
        names, cols = [], []
        for sym in node.outputs:
            if sym in probe.names:
                c, idx = probe.column(sym), probe_idx
            else:
                c, idx = build.column(sym), build_idx
            names.append(sym)
            cols.append(
                Column(
                    c.type,
                    c.data[idx],
                    None if c.valid is None else c.valid[idx],
                    c.dictionary,
                )
            )
        return Page(names, cols, out_live)

    def _append_outer_rows(
        self, node: P.Join, inner: Page, side_page: Page, unmatched, side: str
    ) -> Page:
        """Append unmatched outer rows with NULLs for the other side."""
        n_un = int(jnp.sum(unmatched))
        if n_un == 0:
            return inner
        perm = jnp.argsort(~unmatched, stable=True)
        cap2 = pad_capacity(n_un)
        idx = perm[:cap2]
        sec_live = jnp.arange(cap2) < n_un
        names, cols = [], []
        for sym, c_in in zip(inner.names, inner.columns):
            if sym in side_page.names:
                c = side_page.column(sym)
                data2 = c.data[idx]
                valid2 = sec_live if c.valid is None else (c.valid[idx] & sec_live)
            else:
                data2 = jnp.zeros((cap2,), dtype=c_in.type.np_dtype)
                valid2 = jnp.zeros((cap2,), dtype=jnp.bool_)
            data = jnp.concatenate([c_in.data, data2])
            v1 = c_in.valid
            if v1 is None and valid2 is not None:
                v1 = jnp.ones((inner.capacity,), dtype=jnp.bool_)
            valid = None if v1 is None else jnp.concatenate([v1, valid2])
            names.append(sym)
            cols.append(Column(c_in.type, data, valid, c_in.dictionary))
        mask = jnp.concatenate([inner.mask, sec_live])
        return Page(names, cols, mask)

    # ---- semi join -------------------------------------------------------

    def _SemiJoin(self, node: P.SemiJoin) -> Page:
        source = self.execute(node.source)
        filt = self._compact(self.execute(node.filter_source))
        return self._semi_join_pages(node, source, filt)

    def _semi_join_pages(self, node: P.SemiJoin, source: Page, filt: Page) -> Page:
        pk, bk, pv, bv, pairs, verify = self._join_key(
            source, filt, node.keys
        )
        probe_live = source.mask if pv is None else (source.mask & pv)
        build_live = filt.mask if bv is None else (filt.mask & bv)
        order, lo, cnt = K.join_ranges(bk, build_live, pk, probe_live)
        if verify or node.filter is not None:
            total = int(jnp.sum(cnt))
            out_cap = pad_capacity(max(total, 1))
            probe_idx, build_idx, out_live = K.expand_matches(
                order, lo, cnt, out_cap
            )
            out_live = _verify_matches(pairs, probe_idx, build_idx, out_live)
            if node.filter is not None:
                # residual correlated predicate over (source, filter) pairs
                pair_page = self._gather_pair_page(
                    source, filt, probe_idx, build_idx, out_live
                )
                fd, fv, _ = self._eval(pair_page, node.filter)
                out_live = out_live & (fd if fv is None else (fd & fv))
            matched = K.range_any(cnt, out_live)
        else:
            matched = cnt > 0
        valid = None
        if node.null_aware and filt.num_rows() == 0:
            # x IN (empty) is FALSE — even for NULL x (and NOT IN TRUE);
            # the 3VL valid mask must not apply over an empty build side.
            pass
        elif node.null_aware:
            # IN 3VL: NULL probe key with a nonempty (per-probe) set,
            # or no match while the set has NULLs -> NULL (reference
            # SemiJoinNode semantics). EXISTS is 2-valued.
            build_null_for = self._in_build_nulls(node, source, filt, bv)
            if pv is not None or build_null_for is not None:
                valid = jnp.ones_like(matched)
                if build_null_for is not None:
                    valid = valid & (matched | ~build_null_for)
                if pv is not None:
                    if node.filter is None:
                        # the set is the whole (nonempty) build side
                        valid = valid & pv
                    else:
                        # NULL probe key is FALSE, not NULL, when its
                        # correlated set filters down to empty
                        nonempty = self._correlated_nonempty(
                            node, source, filt, pv
                        )
                        valid = valid & (pv | ~nonempty)
        names = list(source.names) + [node.match_symbol]
        cols = list(source.columns) + [
            Column(T.BOOLEAN, matched, valid, None)
        ]
        return Page(
            names, cols, source.mask,
            known_rows=source.known_rows, packed=source.packed,
        )

    def _in_build_nulls(self, node: P.SemiJoin, source: Page, filt: Page, bv):
        """Per-probe 'the build side contributed a NULL key' vector for
        IN 3VL, or None when no NULL keys exist.

        With a correlated residual filter, only NULL-key build rows
        that pass the filter against that probe row count (the review
        case: x NOT IN (select y from t where t.z <> outer.w))."""
        if bv is None:
            return None
        null_rows = np.nonzero(np.asarray(filt.mask & ~bv))[0]
        if len(null_rows) == 0:
            return None
        if node.filter is None:
            return jnp.ones((source.capacity,), dtype=jnp.bool_)
        any_null = jnp.zeros((source.capacity,), dtype=jnp.bool_)
        probe_idx = jnp.arange(source.capacity, dtype=jnp.int32)
        for r in null_rows.tolist():
            build_idx = jnp.full((source.capacity,), r, dtype=jnp.int32)
            pair = self._gather_pair_page(
                source, filt, probe_idx, build_idx, source.mask
            )
            fd, fv, _ = self._eval(pair, node.filter)
            passes = fd if fv is None else (fd & fv)
            any_null = any_null | passes
        return any_null

    def _correlated_nonempty(self, node: P.SemiJoin, source: Page, filt: Page, pv):
        """Per-probe 'some live build row passes the residual filter'
        vector — the per-probe set of a correlated IN is empty when no
        build row passes against that probe row. Only NULL-key probe
        rows need it, so loop over those (usually few), evaluating the
        filter against the whole build page per row."""
        nonempty = np.zeros((source.capacity,), dtype=np.bool_)
        need = np.nonzero(np.asarray(source.mask & ~pv))[0]
        if len(need) == 0:
            return jnp.asarray(nonempty)
        build_idx = jnp.arange(filt.capacity, dtype=jnp.int32)
        for i in need.tolist():
            probe_idx = jnp.full((filt.capacity,), i, dtype=jnp.int32)
            pair = self._gather_pair_page(
                source, filt, probe_idx, build_idx, filt.mask
            )
            fd, fv, _ = self._eval(pair, node.filter)
            passes = fd if fv is None else (fd & fv)
            nonempty[i] = bool(np.asarray(jnp.any(passes & filt.mask)))
        return jnp.asarray(nonempty)

    @staticmethod
    def _gather_pair_page(probe: Page, build: Page, probe_idx, build_idx, live) -> Page:
        names, cols = [], []
        for page, idx in ((probe, probe_idx), (build, build_idx)):
            for n, c in zip(page.names, page.columns):
                names.append(n)
                cols.append(
                    Column(
                        c.type,
                        c.data[idx],
                        None if c.valid is None else c.valid[idx],
                        c.dictionary,
                    )
                )
        return Page(names, cols, live)


def _verify_matches(pairs, probe_idx, build_idx, out_live):
    """Re-check hash-combined multi-column matches by exact key bits.

    Compares normalized bits rather than raw values so float keys keep
    canonical semantics (-0.0 == +0.0, NaN == NaN) consistently with
    the single-column bit-key path."""
    for pc, bc in pairs:
        pb, _ = K.normalize_key(pc.data, None)
        bb, _ = K.normalize_key(bc.data, None)
        out_live = out_live & (pb[probe_idx] == bb[build_idx])
    return out_live


def _and_mask(a, b):
    if a is None:
        return b
    if b is None:
        return a
    return a & b
