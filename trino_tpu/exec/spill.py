"""Bigger-than-HBM execution: streamed scans, chunked chains,
streamed-probe joins, and grace-hash partitioned joins.

The analog of the reference's memory-scaling tier (SURVEY.md §5.7):
`ConnectorPageSource` streaming (SPI/connector/ConnectorPageSource.java:24),
spillable aggregation
(MAIN/operator/aggregation/builder/SpillableHashAggregationBuilder.java:46),
and the grace-hash spilled join
(MAIN/operator/join/HashBuilderOperator.java:162-182,
PartitionedLookupSourceFactory) — re-shaped for a TPU:

- the "disk" tier is host RAM: device HBM is the scarce resource, host
  memory stands in for the reference's spill files (a later host->GCS
  tier slots in behind the same HostChunk seam);
- the streaming unit is a fixed-size row chunk: the connector yields
  row ranges (`Split`s), each chunk runs the SAME compiled chain
  program (uniform capacity -> one XLA program for every chunk);
- partial/final decomposition reuses the distributed planner's
  aggregate split (plan.distribute._split_aggregate) — a chunk is to
  the budget what a shard is to the mesh;
- the grace-hash join partitions both sides by key hash on host and
  joins partition pairs device-side, exactly the reference's
  build-side spill states collapsed into a batch loop.

Activated by the ``hbm_budget_bytes`` session property (0/absent =
resident mode). The budget is a planning target, not an allocator: the
executor sizes chunks and partitions so no single device working set
exceeds it, and every working set is reserved through the query's
memory context (``trino_tpu.memory``) — which enforces the per-node
cap and maintains the high-water mark (``ex.tracked_bytes_hwm``)
that tests assert against. The memory governor also engages this tier
through revocation: a resident join that would breach
``query_max_memory_per_node`` re-enters here with the cap as budget.
"""

from __future__ import annotations

import numpy as np

from trino_tpu import types as T
from trino_tpu.connectors.base import Split
from trino_tpu.page import Column, Page, pad_capacity
from trino_tpu.plan import nodes as P

__all__ = [
    "scan_bytes", "row_bytes", "est_output_bytes", "run_chain_streamed",
    "streamed_probe_join", "grace_join", "streamed_semi_join",
]

#: minimum chunk size — tiny chunks drown in per-dispatch latency
MIN_CHUNK_ROWS = 1 << 16

#: a single streamed working set targets this fraction of the budget
#: (several arrays are alive at once inside a chain program)
CHUNK_BUDGET_FRACTION = 4


def _col_bytes(t: T.DataType) -> int:
    """Device bytes per row of a column (varchar = int32 codes)."""
    if isinstance(t, T.VarcharType):
        return 4
    return int(np.dtype(t.np_dtype).itemsize)


def row_bytes(outputs: dict[str, T.DataType]) -> int:
    return max(sum(_col_bytes(t) for t in outputs.values()), 1)


def scan_bytes(metadata, node: P.TableScan) -> int:
    """Estimated device-resident bytes of a table scan."""
    try:
        n = metadata.connector(node.catalog).row_count(node.schema, node.table)
    except Exception:
        return 0
    return n * row_bytes(node.outputs)


def est_output_bytes(ex, node: P.PlanNode) -> int:
    """Estimated bytes of a node's output (stats-based rows x width)."""
    from trino_tpu.plan.stats import estimate

    rows = estimate(node, ex.metadata).rows
    return int(rows) * row_bytes(node.outputs)


def chunk_rows_for(budget: int, per_row: int) -> int:
    target = max(budget // CHUNK_BUDGET_FRACTION, 1)
    return max(pad_capacity(target // max(per_row, 1)), MIN_CHUNK_ROWS)


def _note(ex, nbytes: int) -> None:
    """Account one transient streamed working set against the query's
    memory context: the reserve enforces query_max_memory_per_node and
    records the high-water mark; the immediate free mirrors the
    batch-synchronous lifetime (the arrays are dead once the chain
    program returns)."""
    ctx = ex.memory_ctx.child("spill")
    ctx.reserve(nbytes)
    ctx.free(nbytes)


def _page_bytes(page: Page) -> int:
    return page.capacity * row_bytes(
        {n: c.type for n, c in zip(page.names, page.columns)}
    )


# ---- scan chunk source (ConnectorPageSource analog) ------------------------

def scan_chunk_pages(ex, node: P.TableScan, chunk_rows: int):
    """Yield device Pages of ``chunk_rows`` rows each — the streamed
    scan path. Never touches the executor's resident scan cache; every
    chunk has the SAME capacity so one compiled program serves all.

    Double-buffered: chunk k+1's host->device upload (jax.device_put
    is asynchronous) is issued BEFORE chunk k is yielded, so the
    transfer of the next chunk overlaps the consumer's compute on the
    current one — the chunk-upload/compute overlap the round-3 VERDICT
    called out as missing (weak #2); the reference overlaps page reads
    with operator work through its async ConnectorPageSource the same
    way (SPI/connector/ConnectorPageSource.java:24)."""
    connector = ex.metadata.connector(node.catalog)
    n = connector.row_count(node.schema, node.table)
    names = list(node.assignments)

    def build(start: int) -> Page:
        count = min(chunk_rows, n - start) if n else 0
        split = Split(node.table, start, max(count, 0))
        cols_raw = connector.scan(
            node.schema, node.table, list(node.assignments.values()), split
        )
        cols = []
        for sym, cname in node.assignments.items():
            v = cols_raw[cname]
            valid = None
            if isinstance(v, tuple):
                v, valid = v
            cols.append(
                Column.from_numpy(
                    node.outputs[sym], v, valid=valid, capacity=chunk_rows
                )
            )
        mask = np.zeros(chunk_rows, dtype=np.bool_)
        mask[:count] = True
        import jax.numpy as jnp

        page = Page(
            names, cols, jnp.asarray(mask), known_rows=count, packed=True,
        )
        # the in-flight chunk plus the one being consumed are both
        # device-resident while overlapped
        _note(ex, 2 * _page_bytes(page))
        return page

    starts = list(range(0, max(n, 1), chunk_rows))
    if n == 0:
        yield build(0)
        return
    # producer thread: generates + uploads one chunk ahead of the
    # consumer (bounded queue = the double buffer)
    import queue as _queue
    import threading as _threading

    q: _queue.Queue = _queue.Queue(maxsize=1)

    def produce():
        try:
            for start in starts:
                q.put(build(start))
            q.put(None)
        except BaseException as e:  # surface in the consumer
            q.put(e)

    t = _threading.Thread(target=produce, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is None:
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        # unblock the producer if the consumer stops early (Limit)
        while t.is_alive():
            try:
                q.get_nowait()
            except _queue.Empty:
                pass
            t.join(timeout=0.05)


# ---- host accumulation (the spill-file analog) -----------------------------

class HostRun:
    """A spilled batch: packed host columns (varchar decoded to
    objects so chunk-local dictionaries never leak across chunks)."""

    __slots__ = ("names", "types", "columns", "n_rows")

    def __init__(self, names, types, columns, n_rows):
        self.names = names
        self.types = types
        self.columns = columns  # [(values[np], valid[np]|None)]
        self.n_rows = n_rows


def page_to_host(page: Page) -> HostRun:
    n = page.num_rows()
    sel = np.nonzero(np.asarray(page.mask))[0]
    cols = []
    for c in page.columns:
        data = np.asarray(c.data)[sel]
        valid = None if c.valid is None else np.asarray(c.valid)[sel]
        if c.dictionary is not None:
            data = c.dictionary.values[data].astype(object)
        elif c.hash_pool is not None:
            data = c.hash_pool.values[data[:, 1]].astype(object)
        cols.append((data, valid))
    return HostRun(
        list(page.names),
        [c.type for c in page.columns],
        cols,
        len(sel) if n is None else n,
    )


def host_concat_to_page(ex, runs: list[HostRun]) -> Page:
    """Concatenate host runs into ONE device page (the unspill /
    merge-read step). Varchar columns re-encode into one fresh
    dictionary over the full result."""
    import jax.numpy as jnp

    first = runs[0]
    total = sum(r.n_rows for r in runs)
    cap = pad_capacity(total)
    cols = []
    for i, t in enumerate(first.types):
        vals = np.concatenate([r.columns[i][0] for r in runs])
        if any(r.columns[i][1] is not None for r in runs):
            valid = np.concatenate([
                (
                    np.ones(r.n_rows, dtype=bool)
                    if r.columns[i][1] is None else r.columns[i][1]
                )
                for r in runs
            ])
        else:
            valid = None
        cols.append(Column.from_numpy(t, vals, valid=valid, capacity=cap))
    mask = np.zeros(cap, dtype=np.bool_)
    mask[:total] = True
    page = Page(
        list(first.names), cols, jnp.asarray(mask),
        known_rows=total, packed=True,
    )
    _note(ex, _page_bytes(page))
    return page


def _empty_run(outputs: dict[str, T.DataType]) -> HostRun:
    return HostRun(
        list(outputs),
        list(outputs.values()),
        [
            (
                np.empty(
                    0,
                    dtype=object if isinstance(t, T.VarcharType)
                    else t.np_dtype,
                ),
                None,
            )
            for t in outputs.values()
        ],
        0,
    )


# ---- streamed chains -------------------------------------------------------

def _split_chain(chain: list[P.PlanNode]):
    """(per_chunk_nodes, final_nodes, merge_keys): the per-chunk part
    is row-local (or a PARTIAL aggregate / partial TopN/Limit/Sort);
    the final part runs once over the combined chunk outputs — the
    same decomposition the distributed planner applies per shard.
    ``merge_keys`` is set for a full Sort: each chunk sorts
    device-side, runs spill sorted, and the combine step MERGES runs
    on host instead of concatenating (the spilled OrderByOperator's
    sorted-run merge, MAIN/operator/OrderByOperator.java +
    MergeHashSort analog)."""
    from trino_tpu.exec.local import _splittable
    from trino_tpu.plan.distribute import _split_aggregate

    for i, nd in enumerate(chain):
        if isinstance(nd, P.Aggregate):
            if (
                nd.step == "SINGLE"
                and not any(c.distinct for c in nd.aggregates.values())
                and _splittable(nd)
            ):
                partial, final = _split_aggregate(nd)
                partial.est_groups = nd.est_groups
                partial.key_ranges = nd.key_ranges
                final.est_groups = nd.est_groups
                final.key_ranges = nd.key_ranges
                return chain[:i] + [partial], [final] + chain[i + 1:], None
            return chain[:i], chain[i:], None
        if isinstance(nd, P.TopN):
            # a chunk-local TopN bounds each chunk's contribution; the
            # final TopN re-ranks the concatenation
            return chain[: i + 1], chain[i:], None
        if isinstance(nd, P.Sort):
            # chunk-local device sorts -> host-merged sorted runs; the
            # Sort itself never sees the whole input on device
            return chain[: i + 1], chain[i + 1:], list(nd.keys)
        if isinstance(nd, P.Limit):
            per = P.Limit(
                dict(nd.outputs), source=None,
                count=nd.count + nd.offset if nd.count >= 0 else -1,
                offset=0,
            )
            return chain[:i] + [per], chain[i:], None
    return list(chain), [], None


def _order_bits_np(values: np.ndarray) -> np.ndarray | None:
    """Monotone u64 encoding on host (kernels.order_bits analog);
    None for types without a single-lane encoding."""
    if values.dtype == object or values.ndim != 1:
        return None
    if values.dtype.kind == "f":
        b = values.astype(np.float64).view(np.uint64)
        sign = (b >> np.uint64(63)).astype(bool)
        with np.errstate(over="ignore"):
            return np.where(
                sign, ~b, b | np.uint64(0x8000000000000000)
            )
    if values.dtype == np.bool_:
        return values.astype(np.uint64)
    with np.errstate(over="ignore"):
        return values.astype(np.int64).view(np.uint64) ^ np.uint64(
            0x8000000000000000
        )


def merge_sorted_runs(runs: list, keys) -> "HostRun":
    """Merge device-sorted host runs into one globally-ordered run.

    Single non-nullable numeric key: true k-way merge — iterated
    pairwise vectorized merges on u64 rank arrays (log2(k) passes of
    searchsorted + gather), the host analog of the reference's
    MergeSortedPages. Everything else (multi-key, nullable, varchar,
    two-limb): one host lexsort over the concatenation — the device
    budget is unaffected either way; only host CPU differs."""
    runs = [r for r in runs if r.n_rows] or runs[:1]
    if len(runs) == 1:
        return runs[0]
    first = runs[0]
    idx_of = {n: i for i, n in enumerate(first.names)}

    def take(run, order):
        return HostRun(
            list(run.names), list(run.types),
            [
                (v[order], None if valid is None else valid[order])
                for v, valid in run.columns
            ],
            len(order),
        )

    def concat(a, b):
        cols = []
        for (va, xa), (vb, xb) in zip(a.columns, b.columns):
            if va.dtype == object or vb.dtype == object:
                v = np.concatenate([va.astype(object), vb.astype(object)])
            else:
                v = np.concatenate([va, vb])
            if xa is not None or xb is not None:
                x = np.concatenate([
                    xa if xa is not None else np.ones(len(va), dtype=bool),
                    xb if xb is not None else np.ones(len(vb), dtype=bool),
                ])
            else:
                x = None
            cols.append((v, x))
        return HostRun(
            list(a.names), list(a.types), cols, a.n_rows + b.n_rows
        )

    k0 = keys[0]
    ci = idx_of[k0.symbol]
    single = (
        len(keys) == 1
        and first.columns[ci][1] is None
        and _order_bits_np(first.columns[ci][0]) is not None
    )
    if single:
        items = []
        for r in runs:
            bits = _order_bits_np(r.columns[ci][0])
            if not k0.ascending:
                bits = ~bits
            items.append((bits, r))
        while len(items) > 1:
            nxt = []
            for j in range(0, len(items) - 1, 2):
                ba, ra = items[j]
                bb, rb = items[j + 1]
                # stable pairwise merge: run a's rows precede equal
                # rows of run b
                pos_a = np.arange(len(ba)) + np.searchsorted(bb, ba, "left")
                pos_b = np.arange(len(bb)) + np.searchsorted(ba, bb, "right")
                m = len(ba) + len(bb)
                order = np.empty(m, dtype=np.int64)
                order[pos_a] = np.arange(len(ba))
                order[pos_b] = len(ba) + np.arange(len(bb))
                merged = take(concat(ra, rb), order)
                bits = np.empty(m, dtype=np.uint64)
                bits[pos_a] = ba
                bits[pos_b] = bb
                nxt.append((bits, merged))
            if len(items) % 2:
                nxt.append(items[-1])
            items = nxt
        return items[0][1]

    # general path: host lexsort over the concatenation
    combined = runs[0]
    for r in runs[1:]:
        combined = concat(combined, r)
    lanes = []
    for k in reversed(keys):
        i = idx_of[k.symbol]
        values, valid = combined.columns[i]
        if values.dtype == object:
            _u, codes = np.unique(values.astype(str), return_inverse=True)
            lane_list = [codes if k.ascending else -codes]
        elif values.ndim == 2:
            hi, lo = values[:, 0], values[:, 1]
            if k.ascending:
                lane_list = [lo, hi]
            else:
                with np.errstate(over="ignore"):
                    lane_list = [-lo, -hi]
        else:
            bits = _order_bits_np(values)
            lane_list = [bits if k.ascending else ~bits]
        for lane in lane_list:
            lanes.append(lane)
        if valid is not None:
            nf = (
                k.nulls_first if k.nulls_first is not None
                else not k.ascending
            )
            nulls = (~valid).astype(np.int8)
            lanes.append(-nulls if nf else nulls)
    order = np.lexsort(lanes)
    return take(combined, order)


def run_chain_streamed(ex, chain: list[P.PlanNode], scan: P.TableScan) -> Page:
    """Execute chain-over-scan without ever materializing the table:
    stream chunks, run the per-chunk part, spill outputs to host, then
    run the final part over the merged result."""
    budget = ex.hbm_budget()
    chunk_rows = chunk_rows_for(budget, row_bytes(scan.outputs))
    per_chunk, final, merge_keys = _split_chain(chain)
    limit_needed = None
    if per_chunk and isinstance(per_chunk[-1], P.Limit):
        c = per_chunk[-1].count
        limit_needed = c if c >= 0 else None
    runs: list[HostRun] = []
    collected = 0
    for page in scan_chunk_pages(ex, scan, chunk_rows):
        out = (
            ex._run_chain(list(per_chunk), page) if per_chunk else page
        )
        out = ex._compact(out)
        run = page_to_host(out)
        if run.n_rows:
            runs.append(run)
            collected += run.n_rows
        if limit_needed is not None and collected >= limit_needed:
            break
    if not runs:
        out_node = (per_chunk or [scan])[-1]
        runs = [_empty_run(out_node.outputs)]
    if merge_keys is not None and len(runs) > 1:
        runs = [merge_sorted_runs(runs, merge_keys)]
    combined = host_concat_to_page(ex, runs)
    if final:
        return ex._run_chain(list(final), combined)
    return combined


# ---- streamed-probe join ---------------------------------------------------

def streamed_probe_join(
    ex, node: P.Join, probe_chain: list[P.PlanNode],
    probe_scan: P.TableScan, build: Page,
) -> Page:
    """Join a bigger-than-budget probe against a resident build side,
    one chunk at a time (the reference's streamed LookupJoin over a
    finished LookupSource). Valid for inner/left joins: every probe
    row is judged independently against the full build."""
    budget = ex.hbm_budget()
    chunk_rows = chunk_rows_for(budget, row_bytes(probe_scan.outputs))
    runs: list[HostRun] = []
    for page in scan_chunk_pages(ex, probe_scan, chunk_rows):
        probe = (
            ex._run_chain(list(probe_chain), page) if probe_chain else page
        )
        probe = ex._compact(probe)
        joined = ex._equi_join(node, probe, build)
        _note(ex, _page_bytes(joined))
        run = page_to_host(ex._compact(joined))
        if run.n_rows:
            runs.append(run)
    if not runs:
        runs = [_empty_run(node.outputs)]
    return host_concat_to_page(ex, runs)


def streamed_semi_join(
    ex, node: P.SemiJoin, source_chain: list[P.PlanNode],
    source_scan: P.TableScan, filt: Page,
) -> Page:
    """Chunked semi-join: the match column is row-local given the
    (small, resident) filter side."""
    budget = ex.hbm_budget()
    chunk_rows = chunk_rows_for(budget, row_bytes(source_scan.outputs))
    runs: list[HostRun] = []
    for page in scan_chunk_pages(ex, source_scan, chunk_rows):
        src = (
            ex._run_chain(list(source_chain), page) if source_chain else page
        )
        src = ex._compact(src)
        out = ex._semi_join_pages(node, src, filt)
        run = page_to_host(ex._compact(out))
        if run.n_rows:
            runs.append(run)
    if not runs:
        runs = [_empty_run(node.outputs)]
    return host_concat_to_page(ex, runs)


# ---- grace-hash join -------------------------------------------------------

_MIX_1 = np.uint64(0xFF51AFD7ED558CCD)
_MIX_2 = np.uint64(0xC4CEB9FE1A85EC53)


def _host_mix64(h: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        h = h ^ (h >> np.uint64(33))
        h = h * _MIX_1
        h = h ^ (h >> np.uint64(33))
        h = h * _MIX_2
        h = h ^ (h >> np.uint64(33))
    return h


def _host_partition_ids(
    run: HostRun, key_syms: list[str], parts: int, salt: int = 0
):
    """Partition id per row from the combined key hash (numpy — this
    is the spill-write pass, host-bandwidth bound like the reference's
    spiller). ``salt`` decorrelates recursive sub-partitioning levels:
    a level-d split re-hashes so an over-full bucket spreads instead of
    collapsing into one sub-bucket again."""
    h = np.full(run.n_rows, np.uint64(salt * 0x9E3779B97F4A7C15 % (1 << 64)),
                dtype=np.uint64)
    for s in key_syms:
        i = run.names.index(s)
        vals, valid = run.columns[i]
        if vals.dtype == object or vals.dtype.kind in ("U", "S"):
            # hash the string VALUE itself: chunk-local unique codes
            # would shift between chunks/sides and split equal keys
            # across partitions (silently losing matches)
            import zlib

            bits = np.fromiter(
                (zlib.crc32(str(v).encode()) for v in vals),
                dtype=np.uint64, count=len(vals),
            )
        elif vals.dtype.kind == "f":
            v64 = vals.astype(np.float64)  # f32 widens exactly
            v64 = np.where(
                v64 == 0.0, 0.0, np.where(np.isnan(v64), np.nan, v64)
            )
            bits = v64.view(np.uint64)
        else:
            bits = vals.astype(np.int64).view(np.uint64)
        if valid is not None:
            bits = np.where(valid, bits, np.uint64(0))
        with np.errstate(over="ignore"):
            h = _host_mix64(h ^ _host_mix64(bits))
    return (h % np.uint64(parts)).astype(np.int64)


def _split_run(run: HostRun, part_ids: np.ndarray, parts: int):
    out = []
    for p in range(parts):
        sel = part_ids == p
        cols = [
            (
                vals[sel],
                None if valid is None else valid[sel],
            )
            for vals, valid in run.columns
        ]
        out.append(
            HostRun(run.names, run.types, cols, int(sel.sum()))
        )
    return out


def _host_chunks(ex, node: P.PlanNode, chunk_rows: int):
    """Stream ANY plan subtree to host chunks: chains over scans go
    chunk-by-chunk; everything else executes resident and spills
    once."""
    chain, scan = ex._streamable(node)
    if scan is not None:
        for page in scan_chunk_pages(ex, scan, chunk_rows):
            out = ex._run_chain(list(chain), page) if chain else page
            run = page_to_host(ex._compact(out))
            if run.n_rows:
                yield run
        return
    page = ex._compact(ex.execute(node))
    run = page_to_host(page)
    if run.n_rows:
        yield run


def grace_join(ex, node: P.Join) -> Page:
    """Both sides exceed the budget: hash-partition both to host RAM
    and join partition pairs device-side (HashBuilderOperator's
    SPILLING_INPUT -> INPUT_SPILLED states as a batch loop).

    Partition count is sized so one pair's working set fits the chunk
    budget; key-hash co-partitioning guarantees matching rows land in
    the same pair."""
    budget = ex.hbm_budget()
    l_bytes = est_output_bytes(ex, node.left)
    r_bytes = est_output_bytes(ex, node.right)
    pair_budget = max(budget // CHUNK_BUDGET_FRACTION, 1)
    parts = max(int(np.ceil((l_bytes + r_bytes) / pair_budget)), 2)
    # session override (tests + operators): an under-sized first pass
    # exercises the recursive sub-partitioning below
    forced = int(ex.session.properties.get("grace_partitions", 0) or 0)
    if forced:
        parts = forced
    chunk_rows = chunk_rows_for(
        budget, max(row_bytes(node.left.outputs), 1)
    )
    lkeys = [a for a, _ in node.criteria]
    rkeys = [b for _, b in node.criteria]
    l_parts: list[list[HostRun]] = [[] for _ in range(parts)]
    r_parts: list[list[HostRun]] = [[] for _ in range(parts)]
    for side, keys, acc in (
        (node.left, lkeys, l_parts), (node.right, rkeys, r_parts),
    ):
        for run in _host_chunks(ex, side, chunk_rows):
            ids = _host_partition_ids(run, keys, parts)
            for p, piece in enumerate(_split_run(run, ids, parts)):
                if piece.n_rows:
                    acc[p].append(piece)
    runs: list[HostRun] = []
    for p in range(parts):
        _grace_pair(
            ex, node, lkeys, rkeys, l_parts[p], r_parts[p],
            pair_budget, 1, runs,
        )
    if not runs:
        runs = [_empty_run(node.outputs)]
    return host_concat_to_page(ex, runs)


#: recursion bound for under-split grace partitions; beyond it the
#: hot-key fallback streams the pair in chunk pairs
GRACE_MAX_DEPTH = 5


def _run_bytes(run: HostRun) -> int:
    return sum(
        v.nbytes + (0 if x is None else x.nbytes)
        for v, x in run.columns
    ) if run.n_rows else 0


def _grace_pair(
    ex, node: P.Join, lkeys, rkeys, lp: list[HostRun], rp: list[HostRun],
    pair_budget: int, depth: int, out_runs: list[HostRun],
) -> None:
    """Join one co-partitioned pair, recursively sub-partitioning any
    pair whose MEASURED bytes exceed the pair budget — the estimate
    that sized the initial partition count gets no trust beyond round
    one (reference: recursive spilled-partition probing,
    MAIN/operator/join/PartitionedLookupSourceFactory.java,
    PartitionedConsumption.java). A pair that re-hashing cannot split
    (a single hot key) falls back to chunk-pair streaming for inner
    joins, or joins oversized with the overage tracked in the HWM."""
    if not lp and (node.kind != "full" or not rp):
        return
    if not rp and node.kind == "inner":
        return
    l_bytes = sum(_run_bytes(r) for r in lp)
    r_bytes = sum(_run_bytes(r) for r in rp)
    if l_bytes + r_bytes > pair_budget:
        if depth < GRACE_MAX_DEPTH:
            sub = 4
            lsub: list[list[HostRun]] = [[] for _ in range(sub)]
            rsub: list[list[HostRun]] = [[] for _ in range(sub)]
            for side_runs, keys, acc in (
                (lp, lkeys, lsub), (rp, rkeys, rsub),
            ):
                for run in side_runs:
                    ids = _host_partition_ids(run, keys, sub, salt=depth)
                    for q, piece in enumerate(_split_run(run, ids, sub)):
                        if piece.n_rows:
                            acc[q].append(piece)
            bucket_bytes = [
                sum(_run_bytes(r) for r in lsub[q])
                + sum(_run_bytes(r) for r in rsub[q])
                for q in range(sub)
            ]
            if max(bucket_bytes) < (l_bytes + r_bytes):
                # the split separated at least one key: recurse
                ex.grace_recursion_hwm = max(
                    ex.grace_recursion_hwm, depth + 1
                )
                for q in range(sub):
                    _grace_pair(
                        ex, node, lkeys, rkeys, lsub[q], rsub[q],
                        pair_budget, depth + 1, out_runs,
                    )
                return
        if node.kind == "inner" and node.filter is None:
            # single hot key (re-hash cannot split it, or the depth cap
            # was hit): stream the pair as chunk pairs — every probe
            # chunk joins every build chunk; co-partitioned on one key,
            # so the union is the exact join
            _grace_hot_pair(
                ex, node, lp, rp, pair_budget, out_runs
            )
            return
        # outer/filtered hot pair: join oversized; the HWM records the
        # overage honestly instead of silently under-counting
    lpr = lp or [_empty_run(node.left.outputs)]
    rpr = rp or [_empty_run(node.right.outputs)]
    probe = host_concat_to_page(ex, lpr)
    build = host_concat_to_page(ex, rpr)
    joined = ex._equi_join(node, probe, build)
    _note(ex, _page_bytes(joined))
    run = page_to_host(ex._compact(joined))
    if run.n_rows:
        out_runs.append(run)


def _grace_hot_pair(
    ex, node: P.Join, lp: list[HostRun], rp: list[HostRun],
    pair_budget: int, out_runs: list[HostRun],
) -> None:
    """Hot-key pair: both sides chunk to the pair budget and every
    (probe chunk, build chunk) combination joins device-side — a
    blocked nested-loop over the one key's rows, the only shape that
    respects the budget when re-partitioning cannot help."""
    ex.grace_hot_pairs += 1
    half = max(pair_budget // 2, 1)

    def chunks(runs, outputs):
        per = row_bytes(outputs)
        target = max(half // max(per, 1), 1024)
        acc: list[HostRun] = []
        acc_rows = 0
        for r in runs:
            acc.append(r)
            acc_rows += r.n_rows
            if acc_rows >= target:
                yield acc, acc_rows
                acc, acc_rows = [], 0
        if acc_rows:
            yield acc, acc_rows

    build_chunks = list(chunks(rp, node.right.outputs))
    for l_runs, _n in chunks(lp, node.left.outputs):
        probe = host_concat_to_page(ex, l_runs)
        for b_runs, _m in build_chunks:
            build = host_concat_to_page(ex, b_runs)
            joined = ex._equi_join(node, probe, build)
            _note(ex, _page_bytes(joined))
            run = page_to_host(ex._compact(joined))
            if run.n_rows:
                out_runs.append(run)
