"""Out-of-core split-granular streamed storage scans.

The storage half of the spill tier (exec/spill.py): where
``spill.scan_chunk_pages`` streams *generator-backed* connectors by
slicing arbitrary row ranges, real columnar storage wants the chunk
boundary to follow the file's own structure — row groups — so each
batch is one (coalesced) footer-pruned unit, read through the
connector's split path with partition/min-max pruning applied BEFORE
any data page is decoded (the ConnectorPageSource + ParquetReader
pairing, SPI/connector/ConnectorPageSource.java:24 over
lib/trino-parquet/.../reader/ParquetReader.java:85).

Shape discipline: every batch pads to ONE canonical capacity
(exec/shapes bucket of the budget-derived chunk rows), so the whole
stream — and any other query sharing the operator mix — executes one
compiled XLA program (PR 6's bucketing contract; streaming must not
re-open the compile tax).

Memory discipline: each batch's device working set is reserved through
the query's MemoryContext for the duration of the chain program, so
``query_max_memory_per_node`` governs the stream honestly and the
pool's high-water mark reflects real residency. The per-chunk /
final decomposition (partial aggregation, chunk-local TopN/Limit,
device-sorted runs host-merged) is spill's ``_split_chain`` — early
aggregation is what lets an SF100 scan-aggregate finish inside a
2 GiB budget.

Fault discipline: every split read passes a ``scan-read`` chaos gate
and retries AT SPLIT GRANULARITY — a mid-stream read failure re-reads
one split, never the table.

Read batches are cached in ``scan_cache.SHARED_SPLITS`` (byte-bounded
LRU) keyed by (connector, table, range, columns, domains), so hot
working sets stay warm without pinning an SF100 table in host memory.
"""

from __future__ import annotations

import numpy as np

from trino_tpu import fault, telemetry
from trino_tpu.connectors.base import ColumnDomain, Split
from trino_tpu.exec import scan_cache, shapes, spill
from trino_tpu.page import Column, Page, pad_capacity
from trino_tpu.plan import nodes as P

__all__ = ["eligible", "run_chain_streamed", "SCAN_READ_ATTEMPTS"]

#: split-read retry bound (transient I/O + injected scan-read faults)
SCAN_READ_ATTEMPTS = 3


def _connector(ex, node: P.TableScan):
    try:
        return ex.metadata.connector(node.catalog)
    except KeyError:
        return None


def budget_bytes(ex) -> int:
    """The governing budget: the explicit streaming budget when set,
    else the per-node memory cap (streaming is how a big scan FITS the
    cap, so the cap is the budget)."""
    return ex.hbm_budget() or ex._per_node_cap()


def eligible(ex, node: P.TableScan) -> bool:
    """Stream when the connector can iterate splits and the estimated
    scan would occupy more than a quarter of the budget resident."""
    from trino_tpu import session_properties as SP

    conn = _connector(ex, node)
    if conn is None or not getattr(conn, "streamable", False):
        return False
    if not SP.get(ex.session, "streaming_scan_enabled"):
        return False
    budget = budget_bytes(ex)
    if not budget:
        return False
    if node.split is not None:
        rows = int(node.split[1])
    else:
        try:
            rows = conn.row_count(node.schema, node.table)
        except Exception:
            return False
    return rows * spill.row_bytes(node.outputs) > budget // 4


def enforce_resident_fits(ex, node: P.TableScan) -> None:
    """A streamable scan that will NOT stream must fit the per-node cap
    resident: probe-reserve the materialized page bytes through the
    query's memory context so an over-budget table fails loudly with
    ``ExceededMemoryLimitError`` (naming the cap and the query) instead
    of silently blowing host/device memory. The probe frees
    immediately — the real pages reserve as they materialize."""
    conn = _connector(ex, node)
    if conn is None or not getattr(conn, "streamable", False):
        return
    cap = ex._per_node_cap()
    if not cap:
        return
    if node.split is not None:
        rows = int(node.split[1])
    else:
        try:
            rows = conn.row_count(node.schema, node.table)
        except Exception:
            return
    est = rows * spill.row_bytes(node.outputs)
    if est <= cap:
        return
    ctx = ex.memory_ctx.child("scan-resident")
    ctx.reserve(est)  # raises ExceededMemoryLimitError over the cap
    ctx.free(est)


def _domains_of(node: P.TableScan) -> dict | None:
    if not node.domains:
        return None
    return {c: ColumnDomain(*dom) for c, dom in node.domains.items()}


def _domain_key(domains: dict | None) -> tuple:
    """Stable fingerprint of the pushed-down domains — part of the
    batch-cache key, since domains change the rows a read returns."""
    if not domains:
        return ()
    return tuple(
        (c, d.lo, d.hi, d.lo_strict, d.hi_strict)
        for c, d in sorted(domains.items())
    )


def _read_ranges(ex, conn, node: P.TableScan, chunk_rows: int):
    """Enumerate the read ranges: connector splits (partition +
    row-group pruned from the scan's domains), clipped to the bound
    split if this is one fleet task's share, sub-chunked to the
    budget-derived row bound. Returns (ranges, domains, prune metrics).
    """
    domains = _domains_of(node)
    n = conn.row_count(node.schema, node.table)
    lo, hi = 0, n
    if node.split is not None:
        lo = int(node.split[0])
        hi = min(n, lo + int(node.split[1]))
    target = max(1, -(-max(hi - lo, 1) // chunk_rows))
    splits = conn.splits(node.schema, node.table, target, domains=domains)
    metrics = dict(getattr(conn, "scan_metrics", None) or {})
    ranges: list[tuple[int, int]] = []
    for s in splits:
        a, b = max(s.start, lo), min(s.start + s.count, hi)
        while a < b:
            c = min(chunk_rows, b - a)
            ranges.append((a, c))
            a += c
    return ranges, domains, metrics


def _read_batch(ex, conn, node: P.TableScan, start: int, count: int,
                domains, dom_key: tuple):
    """One split-range read: LRU batch cache in front, scan-read chaos
    gate + split-granular retry behind."""
    cols = list(node.assignments.values())
    key_cols = tuple(cols) + dom_key
    cacheable = getattr(conn, "cacheable", False)
    if cacheable:
        batch = scan_cache.SHARED_SPLITS.get(
            conn, node.schema, node.table, start, count, key_cols
        )
        if batch is not None:
            return batch
    tag = f"{node.schema}.{node.table}:{start}"
    last: BaseException | None = None
    for attempt in range(SCAN_READ_ATTEMPTS):
        try:
            fault.check("scan-read", tag=tag, attempt=attempt)
            batch = conn.scan(
                node.schema, node.table, cols,
                Split(node.table, start, count), domains=domains,
            )
            break
        except (fault.InjectedFault, OSError) as e:
            last = e
    else:
        raise last  # type: ignore[misc]
    if cacheable:
        scan_cache.SHARED_SPLITS.put(
            conn, node.schema, node.table, start, count, key_cols, batch
        )
    return batch


def _batch_page(node: P.TableScan, batch: dict, count: int,
                capacity: int) -> Page:
    """Host batch -> device page at the canonical stream capacity."""
    import jax.numpy as jnp

    names = list(node.assignments)
    cols = []
    m = None
    for sym, cname in node.assignments.items():
        v = batch[cname]
        valid = None
        if isinstance(v, tuple):
            v, valid = v
        if m is None:
            m = len(v)
        cols.append(Column.from_numpy(
            node.outputs[sym], v, valid=valid, capacity=capacity
        ))
    if m is None:
        m = count
    mask = np.zeros(capacity, dtype=np.bool_)
    mask[:m] = True
    return Page(names, cols, jnp.asarray(mask), known_rows=m, packed=True)


def run_chain_streamed(
    ex, chain: list[P.PlanNode], scan: P.TableScan
) -> Page:
    """Execute chain-over-scan without materializing the table: iterate
    pruned split ranges, run the per-chunk part of the chain on each
    batch, spill outputs to host, then run the final part over the
    merged result (spill.run_chain_streamed with storage-aware
    chunking, pushdown, caching, retry, and honest accounting)."""
    from trino_tpu import session_properties as SP

    conn = _connector(ex, scan)
    budget = budget_bytes(ex)
    per_row = spill.row_bytes(scan.outputs)
    chunk_rows = spill.chunk_rows_for(budget, per_row)
    override = int(SP.get(ex.session, "max_chunk_rows") or 0)
    if override:
        chunk_rows = max(pad_capacity(min(chunk_rows, override)), 8)
    capacity = shapes.bucket(chunk_rows, site="stream-scan")
    per_chunk, final, merge_keys = spill._split_chain(chain)
    limit_needed = None
    if per_chunk and isinstance(per_chunk[-1], P.Limit):
        c = per_chunk[-1].count
        limit_needed = c if c >= 0 else None
    ranges, domains, metrics = _read_ranges(ex, conn, scan, chunk_rows)
    dom_key = _domain_key(domains)
    page_budget = 2 * capacity * per_row  # upload + chain working set
    ctx = ex.memory_ctx.child("stream-scan")
    runs: list[spill.HostRun] = []
    collected = 0
    batches = 0
    for start, count in ranges:
        ex._check_cancel()
        batch = _read_batch(ex, conn, scan, start, count, domains, dom_key)
        with ctx.reserving(page_budget):
            page = _batch_page(scan, batch, count, capacity)
            out = (
                ex._run_chain(list(per_chunk), page) if per_chunk else page
            )
            out = ex._compact(out)
            run = spill.page_to_host(out)
        batches += 1
        telemetry.SCAN_BATCHES.inc(table=scan.table)
        if run.n_rows:
            runs.append(run)
            collected += run.n_rows
        if limit_needed is not None and collected >= limit_needed:
            break
    if not runs:
        runs = [spill._empty_run((per_chunk or [scan])[-1].outputs)]
    if merge_keys is not None and len(runs) > 1:
        runs = [spill.merge_sorted_runs(runs, merge_keys)]
    ex.scan_log.append({
        "table": f"{scan.schema}.{scan.table}",
        "streamed": True,
        "batches": batches,
        "rowgroups_total": int(metrics.get("rowgroups_total", 0)),
        "rowgroups_pruned": int(metrics.get("rowgroups_pruned", 0)),
        "partitions_pruned": int(metrics.get("partitions_pruned", 0)),
        "splits": int(metrics.get("splits", 0)),
    })
    combined = spill.host_concat_to_page(ex, runs)
    if final:
        return ex._run_chain(list(final), combined)
    return combined
