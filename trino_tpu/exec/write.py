"""TableWriter/TableFinish execution helpers.

The host-side half of the write path (MAIN/operator/
TableWriterOperator.java + TableFinishOperator.java analog): device
pages materialize to host storage columns, stream through a connector
``WriteSink``, and the sealed fragments ride the exchange fabric to a
single TableFinish task whose ``commit_write`` is the one atomic
mutation of the whole statement.

Deliberately free of engine imports — the fleet worker and the local
executor both drive it.
"""

from __future__ import annotations

import json
import time

import numpy as np

from trino_tpu import telemetry
from trino_tpu import types as T

__all__ = [
    "sink_columns_from_payload",
    "fragment_rows",
    "write_through_sink",
    "finish_sink",
    "commit_write",
    "fragments_summary",
]


def sink_columns_from_payload(
    handle: dict, payload: dict, writer_columns: list[str]
) -> tuple[dict, int]:
    """Map a ``page_to_host`` payload onto sink storage columns.

    ``writer_columns`` is the TableWriter's symbol list, positionally
    aligned with ``handle["columns"]`` (the target column order).
    Long decimals collapse from the device two-limb ``[n, 2]`` layout
    to unscaled int64 — the storage form every sink expects."""
    idx = {n: i for i, n in enumerate(payload["names"])}
    cols: dict = {}
    n_rows = len(payload["cols"][0][0]) if payload["cols"] else 0
    for (cname, _tstr), sym in zip(handle["columns"], writer_columns):
        vals, valid = payload["cols"][idx[sym]]
        t = payload["types"][idx[sym]]
        if (
            isinstance(t, T.DecimalType)
            and getattr(vals, "ndim", 1) == 2
        ):
            vals = (
                vals[:, 0].astype(np.int64) << np.int64(32)
            ) + vals[:, 1].astype(np.int64)
        cols[cname] = (vals, valid)
    return cols, n_rows


def fragment_rows(payload: dict) -> list[str]:
    """Extract the fragment strings from a TableWriter output payload
    (the ``$fragment`` column; NULL rows — writer tasks that produced
    no fragments — are dropped)."""
    idx = {n: i for i, n in enumerate(payload["names"])}
    vals, valid = payload["cols"][idx["$fragment"]]
    out = []
    for i, v in enumerate(vals):
        if valid is not None and not valid[i]:
            continue
        s = str(v)
        if s:
            out.append(s)
    return out


def write_through_sink(sink, handle, payload, writer_columns, memory_ctx=None):
    """Append one host payload to ``sink``, accounting the sink's
    buffered-bytes delta against ``memory_ctx`` (task MemoryContext)
    so buffered writes obey query_max_memory_per_node."""
    cols, n = sink_columns_from_payload(handle, payload, writer_columns)
    if n == 0:
        return
    before = sink.buffered_bytes
    sink.append(cols, n)
    if memory_ctx is not None:
        delta = sink.buffered_bytes - before
        if delta > 0:
            memory_ctx.reserve(delta)
        elif delta < 0:
            memory_ctx.free(-delta)


def finish_sink(sink, memory_ctx=None) -> dict:
    """Seal the sink; release any remaining buffered-byte reservation;
    emit write telemetry. Returns the per-task writer result:
    ``{"fragments", "rows_written", "bytes_written", "files"}``."""
    held = sink.buffered_bytes
    try:
        frags = sink.finish()
    finally:
        if memory_ctx is not None and held > 0:
            memory_ctx.free(held)
    telemetry.WRITE_ROWS.inc(sink.rows_written)
    telemetry.WRITE_BYTES.inc(sink.bytes_written)
    telemetry.WRITE_FILES.inc(sink.files_written)
    return {
        "fragments": list(frags),
        "rows_written": int(sink.rows_written),
        "bytes_written": int(sink.bytes_written),
        "files": int(sink.files_written),
    }


def commit_write(
    metadata, handle: dict, fragments: list[str], token: str = "",
) -> tuple[int, float]:
    """TableFinish commit: hand the winning attempts' fragment set to
    the connector's atomic ``finish_write``. Returns
    ``(rows_committed, commit_seconds)``."""
    conn = metadata.connector(handle["catalog"])
    t0 = time.monotonic()
    rows = conn.finish_write(handle, list(fragments), token=token)
    dt = time.monotonic() - t0
    telemetry.WRITE_COMMIT_SECONDS.observe(dt)
    return int(rows), dt


def fragments_summary(fragments: list[str]) -> dict:
    """Fold a fragment set into display stats for EXPLAIN ANALYZE:
    total rows / bytes / file count (memory fragments count as one
    file each)."""
    rows = files = bytes_ = 0
    for f in fragments:
        try:
            d = json.loads(f)
        except (ValueError, TypeError):
            continue
        rows += int(d.get("rows", 0))
        bytes_ += int(d.get("bytes", 0))
        files += 1
    return {"rows": rows, "bytes": bytes_, "files": files}
