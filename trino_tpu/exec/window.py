"""Window function evaluation — sort-based, scatter-free.

The analog of the reference's WindowOperator + window function suite
(MAIN/operator/WindowOperator.java, MAIN/operator/window/): instead of
per-partition pagination and per-row framing loops, one jitted program
computes every window function of a node:

1. rows are permuted to (partition, order-keys) order — the ORDER BY
   keys are sorted first (kernels.sort_perm), then a stable partition
   grouping (kernels.sort_group with pre_perm) leaves each partition
   as one contiguous, ordered run;
2. ranks and frames become position arithmetic + segmented scans over
   that run structure (cumsum differences for ROWS frames, peer-group
   ends for RANGE frames, associative min/max scans for running
   min/max);
3. results gather back to original row order through the inverse
   permutation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from trino_tpu import types as T
from trino_tpu.exec import kernels as K
from trino_tpu.exec.stage import _key_width, _norm_opt
from trino_tpu.expr.compiler import _div_round_half_up
from trino_tpu.plan import nodes as P

__all__ = ["build_window_program"]

#: SQL default frame: RANGE BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW
_DEFAULT_FRAME = ("range", ("unbounded_preceding", None), ("current", None))


def build_window_program(node: P.Window, layout_types, layout_dicts, capacity):
    """(fn, out_meta): ``fn(env, mask) -> env2`` adds one column per
    window function; ``out_meta`` is [(sym, type, dictionary)] for the
    new columns. Pure and jittable."""
    part_syms = list(node.partition_by)
    order_keys = [
        (k.symbol, k.ascending, k.nulls_first) for k in node.order_keys
    ]
    widths = tuple(
        _key_width(layout_types[s], layout_dicts.get(s)) for s in part_syms
    )
    fns = dict(node.functions)
    out_meta = []
    for sym, call in fns.items():
        d = None
        if (
            isinstance(call.type, T.VarcharType)
            and call.args
            and hasattr(call.args[0], "name")
        ):
            d = layout_dicts.get(call.args[0].name)
        out_meta.append((sym, call.type, d))

    def fn(env, mask):
        n = mask.shape[0]
        # 1. order-by sort first, stable partition grouping on top
        if order_keys:
            sk = []
            for s, asc, nf in order_keys:
                if nf is None:
                    nf = not asc  # reference default: nulls largest
                data, valid = env[s]
                if jnp.ndim(data) == 2:
                    # two-limb decimal key: (hi, lo) lexicographic ==
                    # numeric order (hi signed, lo canonical)
                    sk.append((data[:, 0], valid, asc, nf))
                    sk.append((data[:, 1], None, asc, False))
                else:
                    sk.append((data, valid, asc, nf))
            pre = K.sort_perm(sk, mask)
        else:
            pre = None
        norm = [_norm_opt(*env[s]) for s in part_syms]
        info = K.sort_group(
            tuple(b for b, _ in norm),
            tuple(f for _, f in norm),
            mask, n, widths=widths, pre_perm=pre,
        )
        perm = info.perm
        inv = jnp.argsort(perm, stable=True)
        pos = jnp.arange(n, dtype=jnp.int32)
        live_s = mask[perm]
        # partition start/end per sorted row
        gid_c = jnp.clip(info.gid_sorted, 0, n - 1)
        pstart = info.starts[gid_c]
        pend = info.ends[gid_c]  # exclusive
        # peer groups: order-key ties within a partition
        pboundary = (pos == 0) | (
            info.gid_sorted != jnp.roll(info.gid_sorted, 1)
        )
        same_order = jnp.ones((n,), dtype=jnp.bool_)
        for s, _asc, _nf in order_keys:
            data, valid = env[s]
            parts = (
                [data[:, 0], data[:, 1]] if jnp.ndim(data) == 2
                else [data]
            )
            for i, p in enumerate(parts):
                bits, flag = K.normalize_key(p, valid if i == 0 else None)
                bs = bits[perm]
                same_order = same_order & (bs == jnp.roll(bs, 1))
                if valid is not None and i == 0:
                    fl = flag[perm]
                    same_order = same_order & (fl == jnp.roll(fl, 1))
        peer_b = pboundary | ~same_order
        # peer start: running max of boundary positions
        peer_start = jax.lax.associative_scan(
            jnp.maximum, jnp.where(peer_b, pos, -1)
        )
        # peer end (exclusive): next boundary position, from the right
        nxt = jnp.concatenate(
            [peer_b[1:], jnp.ones((1,), dtype=jnp.bool_)]
        )
        rev = jnp.flip(jnp.where(nxt, pos + 1, n + 1))
        peer_end = jnp.flip(jax.lax.associative_scan(jnp.minimum, rev))
        peer_end = jnp.minimum(peer_end, pend)

        row_number = (pos - pstart + 1).astype(jnp.int64)

        # RANGE-offset support: with exactly one order key, expose the
        # key in sorted, direction-normalized form so offset bounds
        # become segmented binary searches (the reference's
        # RangeValueWindowFrame, MAIN/operator/window/ — here O(log n)
        # vectorized probes instead of per-row cursors)
        range_ctx = None
        if len(order_keys) == 1:
            s, asc, nf = order_keys[0]
            data, valid = env[s]
            t = layout_types.get(s)
            if jnp.ndim(data) != 2 and jnp.issubdtype(
                jnp.asarray(data).dtype, jnp.number
            ):
                scale = (
                    10 ** t.scale if isinstance(t, T.DecimalType) else 1
                )
                w = data[perm]
                if not asc:
                    w = -w
                vp = None
                if valid is not None:
                    vp = valid[perm]
                    nulls_first = nf if nf is not None else (not asc)
                    sent = _fill_for(w.dtype, not nulls_first)
                    w = jnp.where(vp, w, sent)
                range_ctx = (w, vp, scale)

        env2 = dict(env)
        for sym, call in fns.items():
            data_s, valid_s = _eval_call(
                call, env, mask, perm, info, pos, live_s,
                pstart, pend, peer_start, peer_end, peer_b, row_number, n,
                range_ctx,
            )
            # back to original row order
            data = data_s[inv]
            valid = None if valid_s is None else valid_s[inv]
            env2[sym] = (data, valid)
        return env2

    return fn, out_meta


def _eval_call(
    call, env, mask, perm, info, pos, live_s,
    pstart, pend, peer_start, peer_end, peer_b, row_number, n,
    range_ctx=None,
):
    """One window function in sorted space."""
    name = call.name
    if name == "row_number":
        return row_number, None
    if name == "rank":
        return (peer_start - pstart + 1).astype(jnp.int64), None
    if name == "dense_rank":
        c = jnp.cumsum(peer_b.astype(jnp.int64))
        return c - c[jnp.clip(pstart, 0, n - 1)] + 1, None
    if name == "percent_rank":
        # (rank - 1) / (partition rows - 1); 0 for single-row partitions
        size = (pend - pstart).astype(jnp.float64)
        rank = (peer_start - pstart + 1).astype(jnp.float64)
        return (
            jnp.where(size > 1, (rank - 1) / jnp.maximum(size - 1, 1), 0.0),
            None,
        )
    if name == "cume_dist":
        # rows preceding or peer with current / partition rows
        size = (pend - pstart).astype(jnp.float64)
        return (
            (peer_end - pstart).astype(jnp.float64) / jnp.maximum(size, 1),
            None,
        )
    if name == "ntile":
        k = _const_arg(call.args[0])
        size = (pend - pstart).astype(jnp.int64)
        i = row_number - 1
        return (i * k) // jnp.maximum(size, 1) + 1, None
    if name in ("lead", "lag"):
        off = _const_arg(call.args[1]) if len(call.args) > 1 else 1
        step = off if name == "lead" else -off
        src = pos + step
        ok = (src >= pstart) & (src < pend)
        data, valid = _sorted_arg(env, call.args[0], perm)
        at = jnp.clip(src, 0, n - 1)
        out = data[at]
        out_valid = ok if valid is None else (ok & valid[at])
        if len(call.args) > 2:
            dd, dv = _sorted_arg(env, call.args[2], perm)
            out = jnp.where(ok, out, dd)
            dval = (
                jnp.ones((n,), dtype=jnp.bool_) if dv is None else dv
            )
            base = (
                jnp.ones((n,), dtype=jnp.bool_)
                if valid is None else valid[at]
            )
            out_valid = jnp.where(ok, base, dval)
        return out, out_valid
    frame = call.frame or _DEFAULT_FRAME
    mode, start, end = frame
    if name in ("min", "max") and start[0] != "unbounded_preceding":
        # running scans cover prefix frames only (sliding-window
        # min/max needs a deque structure the reference also
        # special-cases, MAIN/operator/window/)
        raise NotImplementedError(
            "min/max window frames must start UNBOUNDED PRECEDING"
        )
    # frame bounds as sorted positions [lo, hi) per row
    if mode == "range" and (
        start[0] in ("preceding", "following")
        or end[0] in ("preceding", "following")
    ):
        if range_ctx is None:
            raise NotImplementedError(
                "RANGE offset frames require exactly one numeric "
                "ORDER BY key"
            )
        w, vp, scale = range_ctx

        def rbound(b, is_lo):
            kind, off = b
            if kind == "unbounded_preceding":
                return pstart
            if kind == "unbounded_following":
                return pend
            if kind == "current":
                return peer_start if is_lo else peer_end
            delta = off * scale * (1 if kind == "following" else -1)
            target = w + jnp.asarray(delta).astype(w.dtype)
            return _seg_searchsorted(w, target, pstart, pend, is_lo, n)

        lo = rbound(start, True)
        hi = rbound(end, False)
        if vp is not None:
            # null-key rows: the frame is the null peer group
            lo = jnp.where(vp, lo, peer_start)
            hi = jnp.where(vp, hi, peer_end)
    else:
        lo = _bound_pos(start, pos, pstart, pend, peer_start, peer_end, mode, True)
        hi = _bound_pos(end, pos, pstart, pend, peer_start, peer_end, mode, False)
    lo = jnp.clip(lo, pstart, pend)
    hi = jnp.clip(hi, pstart, pend)

    if name in ("first_value", "last_value"):
        data, valid = _sorted_arg(env, call.args[0], perm)
        at = jnp.clip(jnp.where(name == "first_value", lo, hi - 1), 0, n - 1)
        ok = hi > lo
        out_valid = ok if valid is None else (ok & valid[at])
        return data[at], out_valid
    if name == "nth_value":
        k = _const_arg(call.args[1])
        data, valid = _sorted_arg(env, call.args[0], perm)
        at = jnp.clip(lo + k - 1, 0, n - 1)
        ok = (k >= 1) & (lo + k - 1 < hi)
        out_valid = ok if valid is None else (ok & valid[at])
        return data[at], out_valid
    # aggregates over the frame
    if name == "count_all":
        contrib = live_s
        data = None
    else:
        data, valid = _sorted_arg(env, call.args[0] if call.args else None, perm)
        contrib = live_s if valid is None else (live_s & valid)
    cnt = _range_sum(contrib.astype(jnp.int64), lo, hi, n)
    if name in ("count", "count_all"):
        return cnt, None
    if name == "sum":
        if isinstance(call.type, T.DecimalType) and call.type.is_long:
            # exact limb window sums (frames bounded by the page, so
            # both 32-bit limb prefix sums stay within int64); the
            # argument may itself already be a two-limb column (e.g.
            # sum(sum(decimal)) OVER in windows-over-aggregates)
            if jnp.ndim(data) == 2:
                hi_in = jnp.where(contrib, data[:, 0], 0)
                lo_in = jnp.where(contrib, data[:, 1], 0)
            else:
                masked = jnp.where(
                    contrib, data, jnp.zeros((), dtype=data.dtype)
                )
                hi_in = masked >> jnp.int64(32)
                lo_in = masked & jnp.int64(0xFFFFFFFF)
            s_hi = _range_sum(hi_in, lo, hi, n)
            s_lo = _range_sum(lo_in, lo, hi, n)
            carry = s_lo >> jnp.int64(32)
            return (
                jnp.stack(
                    [s_hi + carry, s_lo & jnp.int64(0xFFFFFFFF)], axis=-1
                ),
                cnt > 0,
            )
        z = jnp.zeros((), dtype=data.dtype)
        s = _range_sum(
            jnp.where(contrib, data, z), lo, hi, n, gid=info.gid_sorted
        )
        return s.astype(data.dtype), cnt > 0
    if name == "avg":
        if isinstance(call.type, T.DecimalType) and call.type.is_long:
            # limb window sums + exact 96/64 divide (mirrors the sum
            # branch; the argument may be a two-limb column when
            # averaging an exact decimal(38) aggregate in a window)
            from trino_tpu.exec.aggregates import (
                _limb_div_round,
                _limb_encode,
                _limb_norm,
            )

            if jnp.ndim(data) == 2:
                hi_in = jnp.where(contrib, data[:, 0], 0)
                lo_in = jnp.where(contrib, data[:, 1], 0)
            else:
                masked = jnp.where(
                    contrib, data, jnp.zeros((), dtype=data.dtype)
                )
                hi_in = masked >> jnp.int64(32)
                lo_in = masked & jnp.int64(0xFFFFFFFF)
            s_hi = _range_sum(hi_in, lo, hi, n)
            s_lo = _range_sum(lo_in, lo, hi, n)
            h2, l2 = _limb_norm(s_hi, s_lo)
            q = _limb_div_round(h2, l2, jnp.maximum(cnt, 1))
            return _limb_encode(q), cnt > 0
        if isinstance(call.type, T.DecimalType):
            s = _range_sum(jnp.where(contrib, data, 0), lo, hi, n)
            return _div_round_half_up(s, jnp.maximum(cnt, 1)), cnt > 0
        s = _range_sum(
            jnp.where(contrib, data.astype(jnp.float64), 0.0), lo, hi, n,
            gid=info.gid_sorted,
        )
        return s / jnp.maximum(cnt, 1), cnt > 0
    if name in ("min", "max"):
        is_min = name == "min"
        if jnp.ndim(data) == 2:
            return _range_minmax_limbs(
                data, contrib, lo, hi, info, is_min, n
            ), cnt > 0
        return _range_minmax(
            data, contrib, lo, hi, pos, pstart, info, is_min, n
        ), cnt > 0
    raise NotImplementedError(f"window function {name}")


def _const_arg(ref) -> int:
    from trino_tpu.expr.ir import Literal

    if isinstance(ref, Literal):
        return int(ref.value)
    raise NotImplementedError("window offset must be a literal")


def _sorted_arg(env, ref, perm):
    if ref is None:
        return None, None
    from trino_tpu.expr.ir import Literal

    if isinstance(ref, Literal):
        n = perm.shape[0]
        if ref.value is None:
            return (
                jnp.zeros((n,), dtype=ref.type.np_dtype),
                jnp.zeros((n,), dtype=jnp.bool_),
            )
        if not isinstance(ref.value, (int, float, bool)):
            raise NotImplementedError(
                f"literal window argument {ref.value!r}"
            )
        return jnp.full((n,), ref.value, dtype=ref.type.np_dtype), None
    data, valid = env[ref.name]
    return data[perm], None if valid is None else valid[perm]


def _bound_pos(bound, pos, pstart, pend, peer_start, peer_end, mode, is_lo):
    kind, off = bound
    if kind == "unbounded_preceding":
        return pstart
    if kind == "unbounded_following":
        return pend
    if kind == "current":
        if mode == "range":
            # RANGE CURRENT ROW includes the whole peer group
            return peer_start if is_lo else peer_end
        return pos if is_lo else pos + 1
    if kind == "preceding":
        return pos - off if is_lo else pos - off + 1
    # following
    return pos + off if is_lo else pos + off + 1


def _seg_searchsorted(w, target, lo0, hi0, left, n):
    """Per-row binary search of ``target[i]`` within the row's own
    sorted segment ``w[lo0[i]:hi0[i])``. ``left`` gives the first
    position with w >= target, else first with w > target. Fixed
    log-depth unrolled loop — jittable, O(n log n) gathers total."""
    lo = lo0.astype(jnp.int64)
    hi = hi0.astype(jnp.int64)
    for _ in range(max(n.bit_length(), 1)):
        cont = lo < hi
        mid = (lo + hi) // 2
        vm = w[jnp.clip(mid, 0, n - 1)]
        go = (vm < target) if left else (vm <= target)
        lo = jnp.where(cont & go, mid + 1, lo)
        hi = jnp.where(cont & ~go, mid, hi)
    return lo.astype(lo0.dtype)


def _range_sum(vals, lo, hi, n, gid=None):
    """Per-row sum of vals over sorted positions [lo, hi).

    Integer sums use a global cumsum difference (exact in int64).
    Float sums with ``gid`` use a per-partition segmented scan in
    float64: a global-cumsum difference would quantize every frame at
    ulp(global running prefix), so a small partition next to a huge one
    loses all its precision (the same cross-group cancellation
    aggregates.seg_sum_ranges avoids)."""
    if gid is not None and jnp.issubdtype(vals.dtype, jnp.floating):
        acc = vals.astype(jnp.float64)

        def op(a, b):
            ga, va = a
            gb, vb = b
            return gb, jnp.where(ga == gb, va + vb, vb)

        _, cs = jax.lax.associative_scan(op, (gid, acc))
        zero = jnp.zeros((), dtype=jnp.float64)
        hi_at = jnp.clip(hi - 1, 0, n - 1)
        lo_at = jnp.clip(lo - 1, 0, n - 1)
        top = jnp.where(hi > 0, cs[hi_at], zero)
        # lo-1 belongs to the same partition iff the frame doesn't start
        # at the partition boundary (lo is clipped to pstart upstream)
        bot = jnp.where((lo > 0) & (gid[lo_at] == gid), cs[lo_at], zero)
        return jnp.where(hi > lo, top - bot, zero)
    cs = jnp.cumsum(vals)
    zero = jnp.zeros((), dtype=vals.dtype)
    hi_at = jnp.clip(hi - 1, 0, n - 1)
    lo_at = jnp.clip(lo - 1, 0, n - 1)
    top = jnp.where(hi > 0, cs[hi_at], zero)
    bot = jnp.where(lo > 0, cs[lo_at], zero)
    return jnp.where(hi > lo, top - bot, zero)


def _range_minmax(data, contrib, lo, hi, pos, pstart, info, is_min, n):
    """Running min/max for prefix frames (lo == partition start):
    a segmented scan; the value at hi-1 is the frame's reduction.
    General sliding frames would need a different structure and are
    rejected at plan time by the frame checks above."""
    if is_min:
        fill = _fill_for(data.dtype, True)
    else:
        fill = _fill_for(data.dtype, False)
    masked = jnp.where(contrib, data, fill)
    red = jnp.minimum if is_min else jnp.maximum

    def op(a, b):
        ga, va = a
        gb, vb = b
        return gb, jnp.where(ga == gb, red(va, vb), vb)

    _, scan = jax.lax.associative_scan(op, (info.gid_sorted, masked))
    at = jnp.clip(hi - 1, 0, n - 1)
    return jnp.where(hi > lo, scan[at], fill)


def _range_minmax_limbs(data, contrib, lo, hi, info, is_min, n):
    """Running min/max over a two-limb decimal column: numeric order is
    lexicographic (hi signed, lo canonical non-negative), so the
    segmented scan carries both limbs and picks per comparison."""
    i64 = jnp.iinfo(jnp.int64)
    fill_hi = jnp.int64(i64.max if is_min else i64.min)
    fill_lo = jnp.int64(0xFFFFFFFF if is_min else 0)
    hi_l = jnp.where(contrib, data[:, 0], fill_hi)
    lo_l = jnp.where(contrib, data[:, 1], fill_lo)

    def op(a, b):
        ga, ha, la = a
        gb, hb, lb = b
        if is_min:
            a_better = (ha < hb) | ((ha == hb) & (la < lb))
        else:
            a_better = (ha > hb) | ((ha == hb) & (la > lb))
        use_a = (ga == gb) & a_better
        return gb, jnp.where(use_a, ha, hb), jnp.where(use_a, la, lb)

    _, sh, sl = jax.lax.associative_scan(
        op, (info.gid_sorted, hi_l, lo_l)
    )
    at = jnp.clip(hi - 1, 0, n - 1)
    ok = hi > lo
    return jnp.stack(
        [jnp.where(ok, sh[at], fill_hi), jnp.where(ok, sl[at], fill_lo)],
        axis=-1,
    )


def _fill_for(dtype, is_min):
    import numpy as np

    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.array(np.inf if is_min else -np.inf, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.array(is_min, dtype=jnp.bool_)
    iinfo = jnp.iinfo(dtype)
    return jnp.array(iinfo.max if is_min else iinfo.min, dtype=dtype)
