"""Aggregate function evaluation as sorted segment reductions.

The analog of the reference's accumulator layer
(MAIN/operator/aggregation/, AccumulatorCompiler) — but scatter-free:
``kernels.sort_group`` leaves each group as one contiguous run of the
sorted row order, so every aggregate is a gather (into sorted order) +
cumsum + boundary-difference, or a segmented associative scan for
min/max. On TPU this replaces the serializing scatter that
``segment_sum`` lowers to (~100 ms/op at 1M rows on v5e) with sorts,
gathers and scans that each cost single-digit milliseconds.

Work shared between the aggregates of one GROUP BY (the gather of a
column into group order, the per-group row count, contribution masks)
is deduplicated through a per-step ``share`` cache, the analog of the
reference's shared GroupByHash + per-aggregate accumulators split.

Global (ungrouped) aggregates skip the grouping entirely and lower to
dense masked reductions.

Distinct aggregates dedupe first: a second ``sort_group`` over
(group keys + argument) keeps one representative row per distinct
value, then the plain path aggregates the representatives
(the reference routes this through MarkDistinct / DistinctAccumulator).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.exec import kernels as K
from trino_tpu.expr.compiler import _div_round_half_up

__all__ = ["compute_aggregate", "VARIANCE_FNS"]

VARIANCE_FNS = {
    "stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop",
}

#: HLL register counts (relative standard error = 1.04/sqrt(m)):
#: global approx_distinct gets 4096 registers (~1.6%); grouped gets 512
#: (~4.6%) to bound per-group state at 512 bytes. The reference's
#: default HLL standard error is 2.3% (ApproximateCountDistinctAggregations).
HLL_GLOBAL_BUCKETS = 4096
HLL_GROUPED_BUCKETS = 512

#: quantile-summary sizes (rank error per shard <= count/points)
QUANT_GLOBAL_POINTS = 1024
QUANT_GROUPED_POINTS = 256


class _Reducer:
    """Segment reductions for one GROUP BY (``info`` set) or one global
    aggregate (``info`` None -> [1]-shaped dense reductions).

    ``share`` caches device intermediates across the aggregates of one
    step, keyed by object identity (keys hold a reference to the keyed
    array so ids stay valid for the cache's lifetime).
    """

    def __init__(self, info: K.GroupInfo | None, capacity: int, contrib,
                 share: dict | None = None):
        self.info = info
        self.capacity = capacity
        self.contrib = contrib
        self.share = share if share is not None else {}
        self.contrib_s = (
            None if info is None else self._sorted(contrib)
        )

    def _sorted(self, x):
        key = ("sorted", id(x))
        hit = self.share.get(key)
        if hit is None or hit[0] is not x:
            hit = (x, x[self.info.perm])
            self.share[key] = hit
        return hit[1]

    def with_valid(self, valid):
        """Reducer whose contribution also requires ``valid`` (cached)."""
        if valid is None:
            return self
        key = ("and", id(self.contrib), id(valid))
        hit = self.share.get(key)
        if hit is None or hit[0] is not self.contrib or hit[1] is not valid:
            hit = (self.contrib, valid, self.contrib & valid)
            self.share[key] = hit
        return _Reducer(self.info, self.capacity, hit[2], self.share)

    def sum(self, data, dtype=None):
        """Masked per-group sum; ``dtype`` casts AFTER the gather so the
        gathered column is shared with other aggregates of the step."""
        if self.info is None:
            x = data if dtype is None else data.astype(dtype)
            zero = jnp.zeros((), dtype=x.dtype)
            return jnp.sum(jnp.where(self.contrib, x, zero))[None]
        xs = self._sorted(data)
        if dtype is not None:
            xs = xs.astype(dtype)
        zero = jnp.zeros((), dtype=xs.dtype)
        masked = jnp.where(self.contrib_s, xs, zero)
        return K.seg_sum_ranges(masked, self.info, zero)

    def sum_limbs(self, data):
        """Exact (hi, lo) limb sums of an int64 column (or an
        already-two-limb [n, 2] column, for re-aggregating decimal(38)
        results) — the limb split happens AFTER the shared sorted
        gather (splitting first would double the dominant [n]-gather
        per decimal aggregate)."""
        if jnp.ndim(data) == 2:
            hi_in, lo_in = data[:, 0], data[:, 1]
        else:
            hi_in = lo_in = None
        if self.info is None:
            if hi_in is not None:
                z = jnp.int64(0)
                hi = jnp.sum(jnp.where(self.contrib, hi_in, z))[None]
                lo = jnp.sum(jnp.where(self.contrib, lo_in, z))[None]
                return _limb_norm(hi, lo)
            masked = jnp.where(self.contrib, data, jnp.int64(0))
            hi = jnp.sum(masked >> jnp.int64(32))[None]
            lo = jnp.sum(masked & jnp.int64(0xFFFFFFFF))[None]
            return _limb_norm(hi, lo)
        zero = jnp.int64(0)
        if hi_in is not None:
            hs = jnp.where(self.contrib_s, self._sorted(hi_in), zero)
            ls = jnp.where(self.contrib_s, self._sorted(lo_in), zero)
            return _limb_norm(
                K.seg_sum_ranges(hs, self.info, zero),
                K.seg_sum_ranges(ls, self.info, zero),
            )
        xs = self._sorted(data)
        masked = jnp.where(self.contrib_s, xs, jnp.int64(0))
        hi = K.seg_sum_ranges(masked >> jnp.int64(32), self.info, zero)
        lo = K.seg_sum_ranges(
            masked & jnp.int64(0xFFFFFFFF), self.info, zero
        )
        return _limb_norm(hi, lo)

    def count(self):
        key = ("count", id(self.contrib))
        hit = self.share.get(key)
        if hit is None or hit[0] is not self.contrib:
            if self.info is None:
                cnt = jnp.sum(self.contrib.astype(jnp.int64))[None]
            else:
                cnt = K.seg_sum_ranges(
                    self.contrib_s.astype(jnp.int64), self.info,
                    jnp.int64(0),
                )
            hit = (self.contrib, cnt)
            self.share[key] = hit
        return hit[1]

    def minmax(self, data, fill, is_min: bool):
        if self.info is None:
            masked = jnp.where(self.contrib, data, fill)
            red = jnp.min if is_min else jnp.max
            return red(masked)[None]
        masked = jnp.where(self.contrib_s, self._sorted(data), fill)
        return K.seg_minmax_scan(masked, self.info, fill, is_min)

    def first_value(self, data):
        """Value of the first contributing row per group."""
        n = data.shape[0]
        if self.info is None:
            idx = jnp.arange(n, dtype=jnp.int32)
            first = jnp.min(jnp.where(self.contrib, idx, n))[None]
            return data[jnp.clip(first, 0, max(n - 1, 0))]
        rows, _ = K.seg_first_index(self.contrib_s, self.info)
        return data[jnp.clip(rows, 0, max(n - 1, 0))]


def compute_aggregate(
    name: str,
    out_type: T.DataType,
    arg,
    info: K.GroupInfo | None,
    capacity: int,
    contrib: jnp.ndarray,
    share: dict | None = None,
):
    """Evaluate one aggregate.

    ``info`` carries the sorted-group context (None for a global
    aggregate — output shape [1]); ``contrib`` masks the rows that
    feed this aggregate (liveness & FILTER & DISTINCT dedupe), in
    original row order, as are the ``arg`` (data, valid) pairs.
    Returns (data[capacity|1], valid[...] | None).
    """
    red = _Reducer(info, capacity, contrib, share)

    if name in _FINAL_COMBINES:
        return _FINAL_COMBINES[name](out_type, arg, red)
    if isinstance(name, str) and name.startswith("var_final:"):
        return _var_final(name[10:], arg, red)
    if isinstance(arg, list) and len(arg) == 1:
        arg = arg[0]
    if name == "count_all":
        return red.count(), None

    if name == "count_if":
        data, valid = arg
        eff = contrib & data
        if valid is not None:
            eff = eff & valid
        return _Reducer(info, capacity, eff, share).count(), None

    if name in ("approx_distinct", "approx_distinct_partial"):
        # HLL over prepared 64-bit hash lanes (exec.stage builds the
        # lane: content hashes for varchar, splitmix64 for numerics).
        # Constant-size register state -> splittable partial/final with
        # bounded bytes through every exchange (reference:
        # MAIN/operator/aggregation/ApproximateCountDistinctAggregations.java).
        data, valid = arg
        eff = contrib if valid is None else (contrib & valid)
        if isinstance(out_type, T.SketchType):
            m = out_type.lanes
        else:
            m = HLL_GLOBAL_BUCKETS if info is None else HLL_GROUPED_BUCKETS
        reg = _hll_registers(data, eff, m, info, capacity)
        if name == "approx_distinct_partial":
            return reg, None
        return _hll_estimate(reg), None

    if name == "approx_distinct_final":
        data, valid = arg if not isinstance(arg, list) else arg[0]
        eff = contrib if valid is None else (contrib & valid)
        reg = _hll_merge(data, eff, info, capacity)
        return _hll_estimate(reg), None

    if name == "approx_percentile_partial":
        (vd, vv), _q = arg
        if jnp.ndim(vd) == 2:
            # two-limb decimal values flatten to float64 for the
            # summary (the sketch is approximate by contract; float64
            # carries ~15-16 significant digits)
            vd = (
                vd[:, 0].astype(jnp.float64) * 4294967296.0
                + vd[:, 1].astype(jnp.float64)
            )
        eff = contrib if vv is None else (contrib & vv)
        k = out_type.lanes - 1
        state, _nonempty = _quant_summary(vd, eff, info, capacity, k, red)
        return state, None

    if name == "approx_percentile_final":
        (sd, sv), (qd, _qv) = arg
        return _quant_merge(
            sd, qd, contrib, sv, info, capacity, out_type
        )

    if name == "approx_percentile":
        # EXACT sorted-rank percentile (the reference's qdigest sketch
        # approximates, MAIN/operator/aggregation/ApproximateLongPercentileAggregations;
        # a sort-based engine gets the exact answer for the same cost
        # class): rows re-sort (group, contributing-first, value) and
        # each group reads index round(q * (cnt-1)) of its run.
        (vd, vv), (qd, _qv) = arg
        eff = contrib if vv is None else (contrib & vv)
        q = qd.reshape(-1)[0].astype(jnp.float64)
        n = vd.shape[0]
        if jnp.ndim(vd) == 2:
            # two-limb decimal: numeric order is lexicographic
            # (hi signed, lo canonical) — stable two-pass sort; the
            # rank gather below then returns the exact limb row
            p = jnp.argsort(K.order_bits(vd[:, 1]), stable=True)
            p = p[jnp.argsort(K.order_bits(vd[p, 0]), stable=True)]
            p = p.astype(jnp.int32)
        else:
            p = jnp.argsort(K.order_bits(vd), stable=True).astype(jnp.int32)
        p = p[jnp.argsort((~eff)[p], stable=True)]
        er = _Reducer(info, capacity, eff, share)
        cnt2 = er.count()
        if info is None:
            starts = jnp.zeros((1,), dtype=jnp.int64)
        else:
            # group runs occupy the same [start, end) ranges as info's
            # ordering (identical per-group populations, dead rows last)
            p = p[jnp.argsort(info.group[p], stable=True)]
            starts = info.starts.astype(jnp.int64)
        offs = jnp.clip(
            jnp.round(q * (cnt2.astype(jnp.float64) - 1.0)).astype(jnp.int64),
            0, jnp.maximum(cnt2 - 1, 0),
        )
        at = jnp.clip(starts + offs, 0, max(n - 1, 0))
        out = vd[p[at.astype(jnp.int32)]]
        return out, cnt2 > 0

    if name in ("max_by", "min_by"):
        (vd, vv), (kd, kv) = arg
        is_min = name == "min_by"
        eff = contrib if kv is None else (contrib & kv)
        kbits = K.order_bits(kd)
        n = kd.shape[0]
        if info is None:
            worst = (
                jnp.uint64(0xFFFFFFFFFFFFFFFF) if is_min else jnp.uint64(0)
            )
            masked = jnp.where(eff, kbits, worst)
            m = (jnp.min if is_min else jnp.max)(masked)
            # first CONTRIBUTING row at the extreme — a sentinel-valued
            # real key must not lose to an excluded row
            idx = jnp.argmax(eff & (masked == m))[None]
            has = jnp.any(eff)[None]
            out = vd[jnp.clip(idx, 0, max(n - 1, 0))]
            ov = has if vv is None else (has & vv[idx])
            return out, ov
        er = _Reducer(info, capacity, eff, share)
        rows = K.seg_arg_extreme(
            er._sorted(kbits), er.contrib_s, info, is_min
        )
        has = er.count() > 0
        at = jnp.clip(rows, 0, max(n - 1, 0))
        out = vd[at]
        ov = has if vv is None else (has & vv[at])
        return out, ov

    data, valid = arg
    red = red.with_valid(valid)

    if name == "count":
        return red.count(), None

    cnt = red.count()
    nonempty = cnt > 0

    if name == "sum":
        if isinstance(out_type, T.DecimalType) and out_type.is_long:
            # decimal(38) sum: EXACT two-limb int64 accumulation (the
            # Int128 DecimalSumAggregation analog). Each int64 input
            # splits into hi = x >> 32 (sign-extended) and lo 32 bits;
            # both limb sums fit int64 for any page (|hi| <= 2^31,
            # lo < 2^32, rows < 2^31), so no achievable sum overflows.
            hi, lo = red.sum_limbs(data)
            return jnp.stack([hi, lo], axis=-1), nonempty
        cast = (
            out_type.np_dtype
            if isinstance(out_type, (T.DoubleType, T.RealType))
            else None
        )
        return red.sum(data, dtype=cast), nonempty

    if name == "avg":
        if isinstance(out_type, T.DecimalType):
            # exact limb sum, then exact 96/64 long division with
            # round-half-away (reference: DecimalAverageAggregation);
            # the quotient always fits int64 (an average is bounded by
            # the inputs). Long-decimal outputs re-encode as limbs.
            hi, lo = red.sum_limbs(data)
            q = _limb_div_round(hi, lo, jnp.maximum(cnt, 1))
            if out_type.is_long:
                q = _limb_encode(q)
            return q, nonempty
        s = red.sum(data, dtype=jnp.float64)
        return s / jnp.maximum(cnt, 1), nonempty


    if name in ("min", "max"):
        is_min = name == "min"
        if jnp.ndim(data) == 2:
            # two-limb decimal: extreme of hi, then extreme of lo among
            # rows at that hi (lexicographic == numeric order since lo
            # is canonical non-negative)
            hi, lo = data[:, 0], data[:, 1]
            iinfo = jnp.iinfo(jnp.int64)
            fill_hi = jnp.int64(iinfo.max if is_min else iinfo.min)
            m_hi = red.minmax(hi, fill_hi, is_min)
            if info is None:
                at_ext = hi == m_hi[0]
            else:
                at_ext = hi == m_hi[
                    jnp.clip(info.group, 0, capacity - 1)
                ]
            red2 = _Reducer(info, capacity, red.contrib & at_ext, share)
            fill_lo = jnp.int64((1 << 32) if is_min else -1)
            m_lo = red2.minmax(lo, fill_lo, is_min)
            return jnp.stack([m_hi, m_lo], axis=-1), nonempty
        if data.dtype == jnp.bool_:
            fill = jnp.int8(1 if is_min else 0)
            out = red.minmax(data.astype(jnp.int8), fill, is_min)
            return out.astype(jnp.bool_), nonempty
        if jnp.issubdtype(data.dtype, jnp.floating):
            fill = jnp.array(
                np.inf if is_min else -np.inf, dtype=data.dtype
            )
        else:
            iinfo = jnp.iinfo(data.dtype)
            fill = jnp.array(
                iinfo.max if is_min else iinfo.min, dtype=data.dtype
            )
        return red.minmax(data, fill, is_min), nonempty

    if name in ("any_value", "arbitrary"):
        return red.first_value(data), nonempty

    if name in ("bool_and", "bool_or"):
        is_min = name == "bool_and"
        fill = jnp.int8(1 if is_min else 0)
        out = red.minmax(data.astype(jnp.int8), fill, is_min)
        return out.astype(jnp.bool_), nonempty

    if name in VARIANCE_FNS:
        s1 = red.sum(data, dtype=jnp.float64)
        x = data.astype(jnp.float64)
        s2 = red.sum(x * x)
        n = cnt.astype(jnp.float64)
        m2 = s2 - (s1 * s1) / jnp.maximum(n, 1.0)
        m2 = jnp.maximum(m2, 0.0)  # clamp fp cancellation
        pop = name.endswith("_pop")
        denom = n if pop else n - 1.0
        ok = cnt >= (1 if pop else 2)
        var = m2 / jnp.maximum(denom, 1.0)
        if name.startswith("stddev"):
            var = jnp.sqrt(var)
        return var, ok

    raise NotImplementedError(f"aggregate {name}")


# ---- sketches (HLL / quantile summaries) -----------------------------------

def _clz64(x: jnp.ndarray) -> jnp.ndarray:
    """Vectorized count-of-leading-zeros over uint64 (binary descent —
    XLA has no clz primitive)."""
    zero = x == 0
    n = jnp.zeros(x.shape, dtype=jnp.int32)
    for s in (32, 16, 8, 4, 2, 1):
        no_high = (x >> np.uint64(64 - s)) == 0
        n = n + jnp.where(no_high, s, 0)
        x = jnp.where(no_high, x << np.uint64(s), x)
    return jnp.where(zero, 64, n)


def dev_hash64(data: jnp.ndarray) -> jnp.ndarray:
    """Device-side 64-bit value hash (splitmix64 finalizer) for HLL
    lanes over numeric/date/decimal columns. Two-limb decimals combine
    limbs into the unscaled value first; floats canonicalize -0.0."""
    import jax

    if data.ndim == 2:
        with np.errstate(over="ignore"):
            v = (
                data[:, 0].astype(jnp.uint64) << np.uint64(32)
            ) + data[:, 1].astype(jnp.uint64)
    elif jnp.issubdtype(data.dtype, jnp.floating):
        f = jnp.where(data == 0.0, 0.0, data).astype(jnp.float64)
        v = jax.lax.bitcast_convert_type(f, jnp.uint64)
    else:
        v = data.astype(jnp.int64).astype(jnp.uint64)
    v ^= v >> np.uint64(30)
    v *= np.uint64(0xBF58476D1CE4E5B9)
    v ^= v >> np.uint64(27)
    v *= np.uint64(0x94D049BB133111EB)
    v ^= v >> np.uint64(31)
    return v


def _hll_registers(h, contrib, m, info, capacity):
    """HLL register arrays from 64-bit hash lanes, scatter-free: rows
    sort by (group, bucket, -rho) and each register reads the first
    element of its run via searchsorted (one sort + one dense gather —
    the engine's sort-based analog of the reference's per-row register
    update loop, MAIN/.../aggregation/state/AbstractHyperLogLogState).
    Returns int8[capacity, m] (capacity 1 for global)."""
    b = int(m).bit_length() - 1
    bucket = (h >> np.uint64(64 - b)).astype(jnp.int64)
    rho = jnp.clip(_clz64(h << np.uint64(b)) + 1, 1, 64 - b + 1)
    caps = 1 if info is None else capacity
    if info is None:
        key = bucket
    else:
        key = info.group.astype(jnp.int64) * m + bucket
    dead = jnp.int64(caps * m)
    key = jnp.where(contrib, key, dead)
    combined = key * 64 + (63 - rho.astype(jnp.int64))
    sc = jnp.sort(combined)
    targets = jnp.arange(caps * m, dtype=jnp.int64) * 64
    pos = jnp.searchsorted(sc, targets)
    n = sc.shape[0]
    at = jnp.clip(pos, 0, n - 1)
    found = sc[at]
    hit = (pos < n) & ((found >> 6) == (targets >> 6))
    reg = jnp.where(hit, 63 - (found & 63), 0).astype(jnp.int8)
    return reg.reshape(caps, m)


def _hll_estimate(reg: jnp.ndarray) -> jnp.ndarray:
    """Registers -> cardinality estimate (standard HLL with the
    small-range linear-counting correction; 64-bit hashes make the
    large-range correction unnecessary)."""
    m = reg.shape[-1]
    alpha = 0.7213 / (1.0 + 1.079 / m)
    inv = jnp.sum(2.0 ** (-reg.astype(jnp.float64)), axis=-1)
    raw = alpha * m * m / inv
    v = jnp.sum((reg == 0), axis=-1).astype(jnp.float64)
    lin = m * jnp.log(jnp.where(v > 0, m / jnp.maximum(v, 1.0), 1.0))
    est = jnp.where((raw <= 2.5 * m) & (v > 0), lin, raw)
    return jnp.round(est).astype(jnp.int64)


def _hll_merge(states, contrib, info, capacity):
    """Element-wise max of member rows' register arrays (the FINAL
    combine; register max is the HLL merge). Inputs are partial-state
    rows — few per group — so a scatter-max is fine here."""
    live = jnp.where(contrib[:, None], states, jnp.zeros((), jnp.int8))
    if info is None:
        return jnp.max(live, axis=0, keepdims=True)
    gid = jnp.clip(info.group, 0, capacity - 1)
    out = jnp.zeros((capacity, states.shape[1]), dtype=jnp.int8)
    return out.at[gid].max(live)


def _quant_sorted_perm(vd, eff, info):
    """Permutation ordering rows (group asc, contributing-first,
    value asc) — shared by the exact percentile and the summary
    builder."""
    vbits = K.order_bits(vd)
    p = jnp.argsort(vbits, stable=True).astype(jnp.int32)
    p = p[jnp.argsort((~eff)[p], stable=True)]
    if info is not None:
        p = p[jnp.argsort(info.group[p], stable=True)]
    return p


def _quant_summary(vd, eff, info, capacity, k, red):
    """Mergeable quantile summary: k evenly-spaced order statistics
    per group + a count lane, as float64[cap, k+1] (the qdigest-state
    analog, MAIN/.../aggregation/ApproximateLongPercentileAggregations;
    rank error after merging S shards is <= total/k per shard).
    """
    p = _quant_sorted_perm(vd, eff, info)
    er = _Reducer(red.info, capacity, eff, red.share)
    cnt = er.count()
    caps = 1 if info is None else capacity
    if info is None:
        starts = jnp.zeros((1,), dtype=jnp.int64)
    else:
        starts = info.starts.astype(jnp.int64)
    i = jnp.arange(k, dtype=jnp.float64)
    offs = jnp.clip(
        jnp.round((i[None, :] + 0.5) / k * cnt[:, None].astype(jnp.float64) - 0.5),
        0, jnp.maximum(cnt[:, None] - 1, 0).astype(jnp.float64),
    ).astype(jnp.int64)
    n = vd.shape[0]
    at = jnp.clip(starts[:, None] + offs, 0, max(n - 1, 0))
    pts = vd[p[at.astype(jnp.int32)]].astype(jnp.float64)
    state = jnp.concatenate(
        [pts, cnt[:caps, None].astype(jnp.float64)], axis=1
    )
    return state, cnt > 0


def _quant_merge(states, q, contrib, valid, info, capacity, out_type):
    """FINAL combine of quantile summaries: member rows' points merge
    as a weighted quantile (each point carries weight count/k). Sort-
    and-searchsorted based; input rows are partial states (few per
    group)."""
    k = states.shape[1] - 1
    pts = states[:, :k]
    cnt = states[:, k]
    eff = contrib if valid is None else (contrib & valid)
    w = jnp.where(eff & (cnt > 0), cnt / k, 0.0)
    n = states.shape[0]
    caps = 1 if info is None else capacity
    # flatten to n*k weighted points
    vals = pts.reshape(-1)
    wts = jnp.repeat(w, k)
    if info is None:
        gid_f = jnp.zeros(n * k, dtype=jnp.int64)
    else:
        gid_f = jnp.repeat(
            jnp.clip(info.group, 0, capacity - 1).astype(jnp.int64), k
        )
    vbits = K.order_bits(vals)
    p = jnp.argsort(vbits, stable=True).astype(jnp.int32)
    p = p[jnp.argsort(gid_f[p], stable=True)]
    gs = gid_f[p]
    ws = wts[p]
    cum = jnp.cumsum(ws)
    starts = jnp.searchsorted(gs, jnp.arange(caps, dtype=jnp.int64))
    base = jnp.where(
        starts > 0, cum[jnp.clip(starts - 1, 0, n * k - 1)], 0.0
    )
    total = jnp.zeros((caps,), dtype=jnp.float64).at[gs].add(ws)
    # within-group cumulative weight; pick the first point reaching
    # q * total (approximate global rank selection)
    adj = cum - base[gs]
    target = q.reshape(-1)[0].astype(jnp.float64) * total
    reached = adj >= target[gs] - 1e-9
    idx = jnp.arange(n * k, dtype=jnp.int64)
    cand = jnp.where(reached & (ws > 0), idx, n * k)
    first = jnp.full((caps,), n * k, dtype=jnp.int64).at[gs].min(cand)
    # groups with no weight fall back to any index (masked by valid)
    at = jnp.clip(first, 0, max(n * k - 1, 0))
    out = vals[p[at.astype(jnp.int32)]]
    has = total > 0
    if isinstance(out_type, (T.DoubleType, T.RealType)):
        return out.astype(out_type.np_dtype.type), has
    if isinstance(out_type, T.DecimalType) and out_type.is_long:
        return _limb_encode(jnp.round(out).astype(jnp.int64)), has
    return jnp.round(out).astype(jnp.int64).astype(out_type.np_dtype.type), has


def _limb_encode(q: jnp.ndarray) -> jnp.ndarray:
    """Re-encode an int64 value as canonical two limbs (hi signed,
    lo in [0, 2^32)) — the storage form of long-decimal columns."""
    return jnp.stack(
        [q >> jnp.int64(32), q & jnp.int64(0xFFFFFFFF)], axis=-1
    )


def _limb_norm(s_hi, s_lo):
    """Canonicalize limb sums: lo into [0, 2^32), carry into hi."""
    carry = s_lo >> jnp.int64(32)
    lo = s_lo & jnp.int64(0xFFFFFFFF)
    return s_hi + carry, lo


def _limb_div_round(hi, lo, cnt):
    """(hi*2^32 + lo) / cnt exactly, rounded half away from zero.

    Schoolbook 96/64 long division in two int64 steps: q1 = hi // cnt
    leaves r1 < cnt <= 2^31, so (r1 << 32) | lo fits int64."""
    q1 = hi // cnt  # floor
    r1 = hi - q1 * cnt  # in [0, cnt)
    rem = (r1 << jnp.int64(32)) | lo
    q2 = rem // cnt
    r2 = rem - q2 * cnt
    q = (q1 << jnp.int64(32)) + q2  # floor((hi*2^32+lo)/cnt)
    # round half away from zero on the floor quotient: positive values
    # bump at >= .5, negative at > .5 (floor already moved them down)
    neg = (hi < 0) | ((hi == 0) & (lo < 0))
    bump = jnp.where(neg, 2 * r2 > cnt, 2 * r2 >= cnt)
    return q + jnp.where(bump, 1, 0)


# ---- FINAL-step combines ---------------------------------------------------
# The distributed split (plan.distribute._split_aggregate) produces
# shard-local PARTIAL states which these combine after the hash
# exchange — the reference's final Accumulator step over serialized
# intermediate state (MAIN/operator/aggregation/ state serializers).


def _state_sum(pair, red: _Reducer):
    data, valid = pair
    if valid is not None:
        key = ("nulled", id(data), id(valid))
        hit = red.share.get(key)
        if hit is None or hit[0] is not data or hit[1] is not valid:
            hit = (
                data, valid,
                jnp.where(valid, data, jnp.zeros((), dtype=data.dtype)),
            )
            red.share[key] = hit
        data = hit[2]
    return red.sum(data)


def _count_final(out_type, args, red: _Reducer):
    """Sum of partial counts; never NULL (COUNT semantics)."""
    pair = args[0] if isinstance(args, list) else args
    return _state_sum(pair, red), None


def _avg_final(out_type, args, red: _Reducer):
    s = _state_sum(args[0], red)
    c = _state_sum(args[1], red)
    nonempty = c > 0
    if isinstance(out_type, T.DecimalType):
        q = _div_round_half_up(s, jnp.maximum(c, 1))
        if out_type.is_long:
            q = _limb_encode(q)
        return q, nonempty
    return s.astype(jnp.float64) / jnp.maximum(c, 1), nonempty


def _var_final(kind, args, red: _Reducer):
    n = _state_sum(args[0], red).astype(jnp.float64)
    s1 = _state_sum(args[1], red)
    s2 = _state_sum(args[2], red)
    m2 = jnp.maximum(s2 - (s1 * s1) / jnp.maximum(n, 1.0), 0.0)
    pop = kind.endswith("_pop")
    denom = n if pop else n - 1.0
    ok = n >= (1 if pop else 2)
    var = m2 / jnp.maximum(denom, 1.0)
    if kind.startswith("stddev"):
        var = jnp.sqrt(var)
    return var, ok


def _decimal_sum_final(out_type, args, red: _Reducer):
    """FINAL combine of distributed long-decimal sums: partial states
    are two BIGINT limb-sum columns (hi32, lo)."""
    s_hi = _state_sum(args[0], red)
    s_lo = _state_sum(args[1], red)
    hi, lo = _limb_norm(s_hi, s_lo)
    _, hv = args[0]
    cred = red.with_valid(hv)
    return jnp.stack([hi, lo], axis=-1), cred.count() > 0


def _decimal_avg_final(out_type, args, red: _Reducer):
    """FINAL combine of distributed decimal averages: exact limb sum
    of partial limb states, divided by the combined count."""
    s_hi = _state_sum(args[0], red)
    s_lo = _state_sum(args[1], red)
    cnt = _state_sum(args[2], red)
    hi, lo = _limb_norm(s_hi, s_lo)
    nonempty = cnt > 0
    q = _limb_div_round(hi, lo, jnp.maximum(cnt, 1))
    if isinstance(out_type, T.DecimalType) and out_type.is_long:
        q = _limb_encode(q)
    return q, nonempty


def _limb_partial_sum(which: str):
    """PARTIAL limb sums over the raw decimal column: 'hi32' sums the
    sign-extended top 32 bits, 'lo32' the low 32 bits (both exact in
    int64 for any page)."""

    def fn(out_type, args, red: _Reducer):
        pair = args[0] if isinstance(args, list) else args
        data, valid = pair
        r = red.with_valid(valid)
        # both limb partials build the same scan graph; XLA CSE merges
        # them inside the fused step program
        hi, lo = r.sum_limbs(data)
        part = hi if which == "hi32" else lo
        # NULL when no row contributed, so the FINAL combine keeps SUM's
        # all-NULL-group semantics
        return part, r.count() > 0

    return fn


_FINAL_COMBINES = {
    "count_final": _count_final,
    "avg_final": _avg_final,
    "decimal_sum_final": _decimal_sum_final,
    "decimal_avg_final": _decimal_avg_final,
    "sum_hi32": _limb_partial_sum("hi32"),
    "sum_lo32": _limb_partial_sum("lo32"),
}
