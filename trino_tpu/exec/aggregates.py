"""Aggregate function evaluation as segment reductions.

The analog of the reference's accumulator layer
(MAIN/operator/aggregation/, AccumulatorCompiler): each aggregate is a
(masked) segment reduction over group ids produced by
``kernels.assign_groups``. Per-row accumulate loops become one
``segment_sum``/``segment_min``/``segment_max`` per aggregate, which
XLA lowers to sorted-scatter updates — the whole group-by runs as a
handful of fused device ops.

Distinct aggregates dedupe first: a second ``assign_groups`` over
(group keys + argument) keeps one representative row per distinct
value, then the plain path aggregates the representatives
(the reference routes this through MarkDistinct / DistinctAccumulator).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.exec import kernels as K
from trino_tpu.expr.compiler import _div_round_half_up

__all__ = ["compute_aggregate", "VARIANCE_FNS"]

VARIANCE_FNS = {
    "stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop",
}


def compute_aggregate(
    name: str,
    out_type: T.DataType,
    arg,
    group: jnp.ndarray,
    capacity: int,
    live: jnp.ndarray,
):
    """Evaluate one aggregate over group ids.

    ``group[i]`` in [0, capacity) for rows that aggregate, ``capacity``
    for rows that don't (dead rows / later: filtered rows). ``arg`` is
    one (data, valid) pair, or a list of pairs for the multi-state
    FINAL combines below. Returns (data[capacity], valid[capacity] | None).
    """
    if name in _FINAL_COMBINES:
        return _FINAL_COMBINES[name](out_type, arg, group, capacity, live)
    if isinstance(name, str) and name.startswith("var_final:"):
        return _var_final(name[10:], arg, group, capacity, live)
    if isinstance(arg, list) and len(arg) == 1:
        arg = arg[0]
    if name == "count_all":
        cnt = K.seg_sum(live.astype(jnp.int64), group, capacity)
        return cnt, None

    data, valid = arg
    contrib = live if valid is None else (live & valid)

    if name == "count":
        cnt = K.seg_sum(contrib.astype(jnp.int64), group, capacity)
        return cnt, None

    cnt = K.seg_sum(contrib.astype(jnp.int64), group, capacity)
    nonempty = cnt > 0

    if name == "sum":
        z = jnp.zeros((), dtype=data.dtype)
        s = K.seg_sum(jnp.where(contrib, data, z), group, capacity)
        if isinstance(out_type, (T.DoubleType, T.RealType)):
            s = s.astype(out_type.np_dtype)
        return s, nonempty

    if name == "avg":
        if isinstance(out_type, T.DecimalType):
            # unscaled int sum / count, rounded half away from zero
            # (reference: DecimalAverageAggregation)
            s = K.seg_sum(jnp.where(contrib, data, 0), group, capacity)
            d = _div_round_half_up(s, jnp.maximum(cnt, 1))
            return d, nonempty
        s = K.seg_sum(
            jnp.where(contrib, data.astype(jnp.float64), 0.0), group, capacity
        )
        return s / jnp.maximum(cnt, 1), nonempty

    if name in ("min", "max"):
        if data.dtype == jnp.bool_:
            d8 = data.astype(jnp.int8)
            fill = jnp.int8(1 if name == "min" else 0)
            masked = jnp.where(contrib, d8, fill)
            red = K.seg_min if name == "min" else K.seg_max
            return red(masked, group, capacity).astype(jnp.bool_), nonempty
        if jnp.issubdtype(data.dtype, jnp.floating):
            fill = jnp.array(
                np.inf if name == "min" else -np.inf, dtype=data.dtype
            )
        else:
            info = jnp.iinfo(data.dtype)
            fill = jnp.array(
                info.max if name == "min" else info.min, dtype=data.dtype
            )
        masked = jnp.where(contrib, data, fill)
        red = K.seg_min if name == "min" else K.seg_max
        return red(masked, group, capacity), nonempty

    if name in ("any_value", "arbitrary"):
        n = data.shape[0]
        idx = jnp.arange(n, dtype=jnp.int64)
        first = K.seg_min(jnp.where(contrib, idx, n), group, capacity)
        return data[jnp.clip(first, 0, n - 1)], nonempty

    if name in ("bool_and", "bool_or"):
        d8 = data.astype(jnp.int8)
        fill = jnp.int8(1 if name == "bool_and" else 0)
        masked = jnp.where(contrib, d8, fill)
        red = K.seg_min if name == "bool_and" else K.seg_max
        return red(masked, group, capacity).astype(jnp.bool_), nonempty

    if name in VARIANCE_FNS:
        x = jnp.where(contrib, data.astype(jnp.float64), 0.0)
        s1 = K.seg_sum(x, group, capacity)
        s2 = K.seg_sum(x * x, group, capacity)
        n = cnt.astype(jnp.float64)
        m2 = s2 - (s1 * s1) / jnp.maximum(n, 1.0)
        m2 = jnp.maximum(m2, 0.0)  # clamp fp cancellation
        pop = name.endswith("_pop")
        denom = n if pop else n - 1.0
        ok = cnt >= (1 if pop else 2)
        var = m2 / jnp.maximum(denom, 1.0)
        if name.startswith("stddev"):
            var = jnp.sqrt(var)
        return var, ok

    raise NotImplementedError(f"aggregate {name}")


# ---- FINAL-step combines ---------------------------------------------------
# The distributed split (plan.distribute._split_aggregate) produces
# shard-local PARTIAL states which these combine after the hash
# exchange — the reference's final Accumulator step over serialized
# intermediate state (MAIN/operator/aggregation/ state serializers).


def _state_sum(pair, group, capacity, live):
    data, valid = pair
    contrib = live if valid is None else (live & valid)
    z = jnp.zeros((), dtype=data.dtype)
    return K.seg_sum(jnp.where(contrib, data, z), group, capacity)


def _count_final(out_type, args, group, capacity, live):
    """Sum of partial counts; never NULL (COUNT semantics)."""
    pair = args[0] if isinstance(args, list) else args
    return _state_sum(pair, group, capacity, live), None


def _avg_final(out_type, args, group, capacity, live):
    s = _state_sum(args[0], group, capacity, live)
    c = _state_sum(args[1], group, capacity, live)
    nonempty = c > 0
    if isinstance(out_type, T.DecimalType):
        return _div_round_half_up(s, jnp.maximum(c, 1)), nonempty
    return s.astype(jnp.float64) / jnp.maximum(c, 1), nonempty


def _var_final(kind, args, group, capacity, live):
    n = _state_sum(args[0], group, capacity, live).astype(jnp.float64)
    s1 = _state_sum(args[1], group, capacity, live)
    s2 = _state_sum(args[2], group, capacity, live)
    m2 = jnp.maximum(s2 - (s1 * s1) / jnp.maximum(n, 1.0), 0.0)
    pop = kind.endswith("_pop")
    denom = n if pop else n - 1.0
    ok = n >= (1 if pop else 2)
    var = m2 / jnp.maximum(denom, 1.0)
    if kind.startswith("stddev"):
        var = jnp.sqrt(var)
    return var, ok


_FINAL_COMBINES = {
    "count_final": _count_final,
    "avg_final": _avg_final,
}
