"""Distributed plan execution over a device mesh.

The analog of the reference's worker tier — SqlTaskExecution running
fragment pipelines plus the shuffle subsystem
(MAIN/execution/SqlTaskExecution.java:83, PartitionedOutputOperator ->
HTTP exchange -> ExchangeOperator, SURVEY.md §3.4) — rebuilt SPMD:

- a ``ShardedPage`` is the distributed Page: every column is ONE jax
  array sharded over the mesh axis (global shape
  [n_shards * shard_capacity]); shard i owns its slice. No serde, no
  buffers — device arrays stay device arrays.
- fusable operator chains compile to one ``shard_map``-ped XLA program
  (each shard runs the same fused pipeline on its rows);
- the hash ``Exchange`` is one ``lax.all_to_all`` on ICI
  (parallel.exchange.partition_exchange) inside the same SPMD program
  style — the whole shuffle is a collective, not a protocol;
- joins co-partition or broadcast their build side and run shard-local
  sort-probe joins; data-dependent output capacities are resolved with
  one host sync (count phase, then expand phase) — mirroring the
  reference's build-side barrier;
- ``Exchange(single)`` gathers a ShardedPage into an ordinary Page;
  everything above it (final TopN, output formatting) runs on the
  inherited single-device executor — the coordinator's final stage.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as PS

from trino_tpu import program_catalog, telemetry, types as T
from trino_tpu.exec import kernels as K
from trino_tpu.exec import shapes as shape_policy
from trino_tpu.exec import stage
from trino_tpu.exec.failure import FailureInjector, InjectedFailure
from trino_tpu.exec.local import LocalExecutor, _rename_out
from trino_tpu.expr.compiler import compile_expr, ColumnLayout
from trino_tpu.metadata import Metadata, Session
from trino_tpu.page import Column, Page, pad_capacity, unify_dictionaries
from trino_tpu.parallel.core import WORKER_AXIS, make_mesh
from trino_tpu.parallel.exchange import partition_exchange
from trino_tpu.plan import nodes as P

__all__ = ["MeshExecutor", "ShardedPage", "SkewOverflow"]


class SkewOverflow(RuntimeError):
    """An exchange destination overflowed the per-shard capacity even
    at the maximum bucket size — hash partitioning alone cannot place
    this data (a hot key owns more rows than one shard holds). Join
    callers recover via the skew-split path; other callers surface it."""


@dataclass
class ShardedPage:
    """Columnar batch sharded along the mesh's worker axis.

    Column data has global shape [n_shards * shard_capacity] with a
    NamedSharding over the axis; row order carries no meaning across
    shards (a bag of rows, like the reference's distributed Pages)."""

    names: list[str]
    columns: list[Column]
    mask: jnp.ndarray
    n_shards: int

    @property
    def shard_capacity(self) -> int:
        return self.mask.shape[0] // self.n_shards

    def column(self, name: str) -> Column:
        return self.columns[self.names.index(name)]


def _exchange_key_pairs(cols):
    """(bits, valid) pairs for exchange-key hashing: hash-coded varchar
    contributes its hash lane only (the id lane is row identity and
    would split equal strings across destinations); two-limb decimals
    contribute both limbs."""
    pairs = []
    for c in cols:
        if c.hash_pool is not None:
            pairs.append((c.data[:, 0], c.valid))
            continue
        parts = K.limb_parts(c.data)
        pairs.extend(
            (p, c.valid if i == 0 else None)
            for i, p in enumerate(parts)
        )
    return pairs


def _page_leaves(page) -> tuple[list, list[tuple[str, bool]]]:
    """Flatten a (Sharded)Page into [data, valid?...] leaves + mask."""
    leaves, meta = [], []
    for n, c in zip(page.names, page.columns):
        leaves.append(c.data)
        if c.valid is not None:
            leaves.append(c.valid)
        meta.append((n, c.valid is not None))
    leaves.append(page.mask)
    return leaves, meta


def _env_from_leaves(leaves, meta):
    env, i = {}, 0
    for name, has_valid in meta:
        data = leaves[i]
        i += 1
        valid = None
        if has_valid:
            valid = leaves[i]
            i += 1
        env[name] = (data, valid)
    return env, leaves[i]


def _make_prelude(criteria, p_meta, b_meta, n_p, verify, kinds=None):
    """Shared shard-local join-key builder for equi and semi joins:
    splits the flat leaves back into probe/build envs and produces
    normalized key bits, combined keys, and 3VL-aware live masks.
    ``kinds[i] == 'hash'`` marks hash-coded varchar criteria (key =
    hash lane only)."""

    def prelude(ls):
        p_env, p_mask = _env_from_leaves(list(ls[:n_p]), p_meta)
        b_env, b_mask = _env_from_leaves(list(ls[n_p:]), b_meta)
        pv = bv = None
        p_bits, b_bits = [], []
        for i, (lsym, rsym) in enumerate(criteria):
            pd, pvx = p_env[lsym]
            bd, bvx = b_env[rsym]
            if pvx is not None:
                pv = pvx if pv is None else (pv & pvx)
            if bvx is not None:
                bv = bvx if bv is None else (bv & bvx)
            if kinds is not None and kinds[i] == "hash":
                p_bits.append(K.normalize_key(pd[:, 0], None)[0])
                b_bits.append(K.normalize_key(bd[:, 0], None)[0])
                continue
            # two-limb decimal keys expand into hi/lo parts
            for part in K.limb_parts(pd):
                p_bits.append(K.normalize_key(part, None)[0])
            for part in K.limb_parts(bd):
                b_bits.append(K.normalize_key(part, None)[0])
        if verify or len(p_bits) > len(criteria):
            pk = K.hash_columns([(b, None) for b in p_bits])
            bk = K.hash_columns([(b, None) for b in b_bits])
        else:
            pk, bk = p_bits[0], b_bits[0]
        probe_live = p_mask if pv is None else (p_mask & pv)
        build_live = b_mask if bv is None else (b_mask & bv)
        return (
            p_env, p_mask, b_env, b_mask,
            pk, bk, probe_live, build_live, p_bits, b_bits,
        )

    return prelude


class MeshExecutor(LocalExecutor):
    """Executes distribution-planned trees (plan.distribute) over a
    jax.sharding.Mesh; single-prop regions fall through to the
    inherited local executor."""

    def __init__(
        self,
        metadata: Metadata,
        session: Session,
        mesh: Mesh | None = None,
        axis: str = WORKER_AXIS,
    ):
        super().__init__(metadata, session)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.axis = axis
        self.n_shards = int(self.mesh.shape[axis])
        self._row_sharding = NamedSharding(self.mesh, PS(axis))
        self._dist_scan_cache: dict = {}
        self._mesh_jit_cache: dict = telemetry.CountingCache("mesh")
        #: test hook: arm per-stage failures; stage programs retry
        #: (FailureInjector analog, MAIN/execution/FailureInjector.java:39)
        self.failure_injector = FailureInjector()
        #: count of joins that took the skew-split path (tests/metrics)
        self.skew_joins = 0
        #: count of exchange bucket-capacity escalations (tests assert
        #: skew-proof plans never escalate)
        self.exchange_escalations = 0
        #: per-query exchange telemetry (EXPLAIN ANALYZE + tests):
        #: all_to_all count and device bytes moved through them
        self.exchange_stats = {"exchanges": 0, "bytes": 0}

    def _attempt(self, tag: str, call):
        """Run one stage-shard program with injected-failure retry.

        The retry unit of the fault-tolerant scheduler
        (EventDrivenFaultTolerantQueryScheduler analog): stage inputs
        are retained device arrays, so a failed invocation simply
        re-runs against them — the spooled-stage-output durability of
        the reference comes free from XLA buffer lifetimes."""
        from trino_tpu import fault

        attempt = 0
        while True:
            try:
                self.failure_injector.check(tag, attempt)
                # the process-global chaos injector (trino_tpu.fault)
                # addresses mesh stages through the same task-exec
                # site, so multi-site chaos runs compose with the
                # executor-local injector
                fault.check("task-exec", tag, attempt)
                return call()
            except fault.InjectedFault:
                attempt += 1
                if attempt >= self.failure_injector.max_attempts:
                    raise

    # ---- boundaries ------------------------------------------------------

    def _Exchange(self, node: P.Exchange) -> Page:
        if node.partitioning != "single":
            raise AssertionError(
                "non-single exchange reached the local executor"
            )
        return self.gather(self.execute_dist(node.source))

    def execute_dist(self, node: P.PlanNode) -> ShardedPage:
        self._check_cancel()
        if isinstance(node, stage.FUSABLE):
            chain: list[P.PlanNode] = []
            cur = node
            while isinstance(cur, stage.FUSABLE):
                chain.append(cur)
                cur = cur.sources[0]
            base = self.execute_dist(cur)
            return self._run_chain_sharded(list(reversed(chain)), base)
        if isinstance(node, P.RemoteSource):
            # fleet x mesh seam: a spooled stage input scatters over
            # THIS worker's device mesh; when the producing stage hash-
            # partitioned on keys, re-exchange locally so every key
            # owns one shard (FINAL aggregation / co-partitioned
            # consumers assume key-disjoint shards) — the DCN partition
            # re-partitions over ICI within the worker (SURVEY §5.8)
            page = self._RemoteSource(node)
            sp = self.scatter(page)
            keys = (getattr(self, "remote_hash_keys", None) or {}).get(
                node.source_id
            )
            if keys and all(k in sp.names for k in keys):
                sp = self.hash_exchange(sp, keys)
            return sp
        if isinstance(node, P.TableScan):
            if node.split is not None:
                # a fleet split-bound scan covers [start, start+count)
                # only: run the local split scan, then shard it (the
                # whole-table dist cache would double-count)
                return self.scatter(self._TableScan(node))
            return self._scan_dist(node)
        if isinstance(node, P.Exchange):
            if node.partitioning == "hash":
                sp = self.execute_dist(node.source)
                return self.hash_exchange(sp, node.hash_symbols)
            if node.partitioning == "range":
                sp = self.execute_dist(node.source)
                return self.range_exchange(sp, node.sort_keys)
            raise AssertionError(
                f"exchange {node.partitioning} cannot produce a sharded page"
            )
        if isinstance(node, P.Join):
            return self._dist_join(node)
        if isinstance(node, P.SemiJoin):
            return self._dist_semi(node)
        if isinstance(node, P.GroupId):
            return self._dist_groupid(node)
        raise NotImplementedError(
            f"no distributed executor for {type(node).__name__}"
        )

    # ---- scan / gather / scatter ----------------------------------------

    def invalidate_scan(self, catalog: str, schema: str, table: str):
        super().invalidate_scan(catalog, schema, table)
        self._dist_scan_cache.pop((catalog, schema, table), None)

    def _shard_layout(self, n: int) -> tuple[int, int]:
        """(rows per shard, padded per-shard capacity) for n rows."""
        per = -(-max(n, 1) // self.n_shards)  # ceil
        return per, pad_capacity(per)

    def _shard_split(self, host: np.ndarray, n: int, per: int, cap: int):
        """Lay n host rows contiguously into the [n_shards * cap]
        sharded layout and put it on the mesh."""
        out = np.zeros(
            (self.n_shards * cap,) + host.shape[1:], dtype=host.dtype
        )
        for s in range(self.n_shards):
            take = min(max(n - s * per, 0), per)
            out[s * cap: s * cap + take] = host[s * per: s * per + take]
        return jax.device_put(out, self._row_sharding)

    def _scan_dist(self, node: P.TableScan) -> ShardedPage:
        key = (node.catalog, node.schema, node.table)
        if not self.metadata.connector(node.catalog).cacheable:
            cache = {}  # live views re-scan per query
        else:
            cache = self._dist_scan_cache.setdefault(key, {})
        hashed_syms = set(node.hash_varchar or [])

        def ckey(sym, cname):
            return f"#hash:{cname}" if sym in hashed_syms else cname

        missing = [
            (sym, c) for sym, c in node.assignments.items()
            if ckey(sym, c) not in cache
        ]
        if missing or "" not in cache:
            connector = self.metadata.connector(node.catalog)
            cols = connector.scan(
                node.schema, node.table, [c for _, c in missing]
            )
            if missing:
                first = cols[missing[0][1]]
                n = len(first[0] if isinstance(first, tuple) else first)
            else:
                n = connector.row_count(node.schema, node.table)
            per, cap = self._shard_layout(n)
            if "" not in cache:
                cache[""] = self._shard_split(
                    np.ones(n, dtype=np.bool_), n, per, cap
                )
            for sym, cname in missing:
                v = cols[cname]
                valid = None
                if isinstance(v, tuple):
                    v, valid = v
                if sym in hashed_syms:
                    from trino_tpu.exec.local import _hash_varchar_column

                    # global pool + global row ids: the id lane stays
                    # meaningful on every shard (pools are host-side)
                    col = _hash_varchar_column(
                        node.outputs[sym], np.asarray(v, dtype=object),
                        valid, max(n, 1),
                    )
                else:
                    col = Column.from_numpy(
                        node.outputs[sym], v, valid=valid,
                        capacity=max(n, 1),
                    )
                cache[ckey(sym, cname)] = Column(
                    col.type,
                    self._shard_split(
                        np.asarray(col.data[:n]), n, per, cap
                    ),
                    None if col.valid is None else self._shard_split(
                        np.asarray(col.valid[:n]), n, per, cap
                    ),
                    col.dictionary,
                    col.hash_pool,
                )
        names = list(node.assignments)
        columns = [
            cache[ckey(s, c)] for s, c in node.assignments.items()
        ]
        return ShardedPage(names, columns, cache[""], self.n_shards)

    def gather(self, sp: ShardedPage) -> Page:
        """ShardedPage -> compacted single-device Page (the reference's
        root-stage output buffer drain)."""
        mask = np.asarray(sp.mask)
        idx = np.nonzero(mask)[0]
        cap = pad_capacity(len(idx))
        cols = []
        for c in sp.columns:
            src = np.asarray(c.data)
            data = np.zeros((cap,) + src.shape[1:], dtype=src.dtype)
            data[: len(idx)] = src[idx]
            valid = None
            if c.valid is not None:
                v = np.zeros(cap, dtype=np.bool_)
                v[: len(idx)] = np.asarray(c.valid)[idx]
                valid = jnp.asarray(v)
            cols.append(Column(c.type, jnp.asarray(data), valid, c.dictionary, c.hash_pool))
        out_mask = np.zeros(cap, dtype=np.bool_)
        out_mask[: len(idx)] = True
        return Page(
            list(sp.names), cols, jnp.asarray(out_mask),
            known_rows=len(idx), packed=True,
        )

    def scatter(self, page: Page) -> ShardedPage:
        """Split a local Page's live rows contiguously over the mesh."""
        idx = np.nonzero(np.asarray(page.mask))[0]
        n = len(idx)
        per, cap = self._shard_layout(n)
        cols = []
        for c in page.columns:
            valid = None
            if c.valid is not None:
                valid = self._shard_split(
                    np.asarray(c.valid)[idx], n, per, cap
                )
            cols.append(
                Column(
                    c.type,
                    self._shard_split(np.asarray(c.data)[idx], n, per, cap),
                    valid,
                    c.dictionary,
                    c.hash_pool,
                )
            )
        mask = self._shard_split(np.ones(n, dtype=np.bool_), n, per, cap)
        return ShardedPage(list(page.names), cols, mask, self.n_shards)

    def _broadcast_page(self, node) -> Page:
        """Resolve an Exchange(broadcast) — or, in a fleet fragment, a
        RemoteSource standing for a cut broadcast exchange — into one
        local Page (replicated into SPMD programs via a P() in_spec)."""
        if isinstance(node, P.RemoteSource):
            return self._compact(self._RemoteSource(node))
        if node.input_dist == "single":
            return self._compact(self.execute(node.source))
        return self.gather(self.execute_dist(node.source))

    # ---- sharded fused chains -------------------------------------------

    def _sharded_sig(self, sp: ShardedPage) -> tuple:
        return tuple(
            (
                n, repr(c.type), id(c.dictionary),
                None if c.hash_pool is None else c.hash_pool.token,
                c.valid is not None,
            )
            for n, c in zip(sp.names, sp.columns)
        ) + (sp.shard_capacity, self.n_shards)

    def _run_chain_sharded(
        self, chain: list[P.PlanNode], sp: ShardedPage
    ) -> ShardedPage:
        """Chain runner per shard: same fused-pipeline compiler as the
        local executor, wrapped in shard_map so every shard executes the
        one program on its rows (overflow flags pmax-reduced)."""
        shard_cap = sp.shard_capacity
        caps = stage.plan_capacities(chain, shard_cap, n_shards=self.n_shards)
        axis = self.axis
        out_map = None
        if shape_policy.enabled(self.session):
            canon = shape_policy.canonicalize_chain(chain, list(sp.names))
            if canon is not None:
                # nameless normal form: the shard program (and its
                # cache key) goes independent of this query's symbol
                # names — see exec.shapes
                by_name = dict(zip(sp.names, sp.columns))
                sp = ShardedPage(
                    list(canon.in_map.values()),
                    [by_name[o] for o in canon.in_map],
                    sp.mask, self.n_shards,
                )
                chain, out_map = canon.chain, canon.out_map
        while True:
            key = (
                "mesh-chain",
                tuple(self._node_key(n) for n in chain),
                tuple((i, c[0]) for i, c in sorted(caps.items())),
                self._sharded_sig(sp),
            )
            hit = self._mesh_jit_cache.get(key)
            if hit is None:
                in_layout = stage.ChainLayout(
                    names=list(sp.names),
                    types={n: c.type for n, c in zip(sp.names, sp.columns)},
                    dicts={
                        n: c.dictionary
                        for n, c in zip(sp.names, sp.columns)
                    },
                    capacity=shard_cap,
                    pools={
                        n: c.hash_pool
                        for n, c in zip(sp.names, sp.columns)
                        if c.hash_pool is not None
                    },
                )
                fn, out_layout = stage.build_chain(chain, in_layout, caps)
                leaves, meta = _page_leaves(sp)

                def flat_fn(*ls, _fn=fn, _meta=meta):
                    env, mask = _env_from_leaves(list(ls), _meta)
                    env2, mask2, flags = _fn(env, mask)
                    flags = {
                        k: jax.lax.pmax(v.astype(jnp.int32), axis)
                        for k, v in flags.items()
                    }
                    return env2, mask2, flags

                def flat_fn_shape(*ls, _fn=fn, _meta=meta):
                    # structure-only twin of flat_fn (pmax needs the
                    # mesh axis, which eval_shape doesn't provide)
                    env, mask = _env_from_leaves(list(ls), _meta)
                    return _fn(env, mask)

                leaf_shapes = [
                    jax.ShapeDtypeStruct(
                        (l.shape[0] // self.n_shards,) + l.shape[1:], l.dtype
                    )
                    for l in leaves
                ]
                out_shape = jax.eval_shape(flat_fn_shape, *leaf_shapes)
                out_specs = (
                    jax.tree.map(lambda _: PS(axis), out_shape[0]),
                    PS(axis),
                    jax.tree.map(lambda _: PS(), out_shape[2]),
                )
                prog = jax.jit(
                    jax.shard_map(
                        flat_fn,
                        mesh=self.mesh,
                        in_specs=(PS(axis),) * len(leaves),
                        out_specs=out_specs,
                        check_vma=False,
                    )
                )
                hit = (prog, out_layout, meta)
                self._mesh_jit_cache[key] = hit
                # catalog the sharded program at its full (unsharded)
                # leaf avals — lowering there reuses the same jit trace
                program_catalog.CATALOG.register(
                    key, source="mesh",
                    label="→".join(type(n).__name__ for n in chain),
                    resolver=program_catalog.aot_resolver(
                        prog,
                        tuple(
                            jax.ShapeDtypeStruct(l.shape, l.dtype)
                            for l in leaves
                        ),
                    ),
                )
                t_compile = time.perf_counter()
            else:
                program_catalog.CATALOG.note_hit(key)
                t_compile = None
            prog, out_layout, meta = hit
            leaves, _ = _page_leaves(sp)
            env, mask, flags = self._attempt(
                "chain", lambda: prog(*leaves)
            )
            if t_compile is not None:
                program_catalog.CATALOG.note_compile_seconds(
                    key, time.perf_counter() - t_compile
                )
            if flags:
                vals = jax.device_get(flags)
                overflowed = [i for i, v in vals.items() if v]
                if overflowed:
                    for i in overflowed:
                        cap, mx = caps[i]
                        if cap >= mx:
                            raise RuntimeError(
                                "aggregation table overflow at max capacity"
                            )
                        caps[i][0] = min(cap * 8, mx)
                    continue
            if out_map is not None:
                out_layout, env = _rename_out(out_layout, env, out_map)
            cols = [
                Column(
                    out_layout.types[s],
                    env[s][0],
                    env[s][1],
                    out_layout.dicts.get(s),
                    out_layout.pools.get(s),
                )
                for s in out_layout.names
            ]
            return ShardedPage(
                list(out_layout.names), cols, mask, self.n_shards
            )

    # ---- hash exchange ---------------------------------------------------

    def hash_exchange(
        self, sp: ShardedPage, key_symbols: list[str]
    ) -> ShardedPage:
        cols = [sp.column(k) for k in key_symbols]
        h = K.hash_columns(_exchange_key_pairs(cols))
        dest = (h % jnp.uint64(self.n_shards)).astype(jnp.int32)
        return self.exchange_by_dest(
            sp, dest, edge=f"mesh-hash({', '.join(key_symbols)})"
        )

    def range_exchange(
        self, sp: ShardedPage, sort_keys
    ) -> ShardedPage:
        """Distributed-sort shuffle: rows route to shards by sampled
        splitters of the first sort key, so shard-local sorts
        concatenate into global order (the range-partitioned analog of
        the reference's merge exchange, MAIN/operator/MergeOperator.java
        — instead of merging sorted streams on one node, the engine
        makes shard ranges disjoint up front; equal keys colocate, so
        ties never straddle a shard boundary)."""
        k = sort_keys[0]
        col = sp.column(k.symbol)
        if col.hash_pool is not None:
            raise AssertionError(
                "range exchange over a hash-coded varchar key (the "
                "stats gate must keep ORDER BY columns dictionary-coded)"
            )
        nulls_first = (
            k.nulls_first if k.nulls_first is not None else not k.ascending
        )
        key = ("mesh-range-bits", self._sharded_sig(sp), k.symbol,
               k.ascending, nulls_first)
        prog = self._mesh_jit_cache.get(key)
        if prog is None:
            def fb(data, valid):
                d = data[:, 0] if data.ndim == 2 else data
                bits = K.order_bits(d)
                if not k.ascending:
                    bits = ~bits
                if valid is not None:
                    sentinel = (
                        jnp.uint64(0) if nulls_first
                        else jnp.uint64(0xFFFFFFFFFFFFFFFF)
                    )
                    bits = jnp.where(valid, bits, sentinel)
                return bits

            prog = jax.jit(fb, static_argnames=())
            self._mesh_jit_cache[key] = prog
        bits = prog(col.data, col.valid)
        # splitters from a strided sample (the runtime analog of the
        # reference's DeterminePartitionCount + writer rebalancing:
        # quantiles of the observed key distribution)
        stride = max(int(bits.shape[0]) // 4096, 1)
        sample = np.asarray(bits[::stride])
        live = np.asarray(sp.mask[::stride])
        sample = sample[live]
        if len(sample) == 0:
            dest = jnp.zeros(bits.shape, dtype=jnp.int32)
        else:
            qs = np.quantile(
                np.sort(sample),
                [i / self.n_shards for i in range(1, self.n_shards)],
                method="nearest",
            ).astype(np.uint64)
            dest = jnp.searchsorted(
                jnp.asarray(qs), bits, side="right"
            ).astype(jnp.int32)
        return self.exchange_by_dest(
            sp, dest, edge=f"mesh-range({k.symbol})"
        )

    def exchange_by_dest(
        self, sp: ShardedPage, dest: jnp.ndarray,
        edge: str = "mesh-exchange",
    ) -> ShardedPage:
        """Route every live row to the shard named by ``dest`` — the
        engine's shuffle: one all_to_all over ICI, with bucket-overflow
        retry (the OutputBuffer backpressure analog). ``edge`` names
        the exchange for the ``check_exchange_coverage`` debug
        assertion (live rows must be conserved across the shuffle)."""
        shard_cap = sp.shard_capacity
        n = self.n_shards
        bucket_cap = shape_policy.exchange_bucket(shard_cap, n)
        leaves, meta = _page_leaves(sp)
        self.exchange_stats["exchanges"] += 1
        moved = sum(
            int(np.prod(l.shape)) * l.dtype.itemsize for l in leaves
        )
        self.exchange_stats["bytes"] += moved
        telemetry.EXCHANGE_BYTES.inc(moved)
        while True:
            key = (
                "mesh-exchange",
                tuple((l.dtype.str, l.shape) for l in leaves),
                bucket_cap,
            )
            prog = self._mesh_jit_cache.get(key)
            if prog is None:
                axis = self.axis

                def fn(dest_, *ls):
                    live = ls[-1]
                    payload = {str(i): a for i, a in enumerate(ls[:-1])}
                    recv, rlive, ovf = partition_exchange(
                        dest_, live, payload, n, bucket_cap, axis
                    )
                    ovf = jax.lax.pmax(ovf.astype(jnp.int32), axis)
                    out = [recv[str(i)] for i in range(len(ls) - 1)]
                    return out, rlive, ovf

                prog = jax.jit(
                    jax.shard_map(
                        fn,
                        mesh=self.mesh,
                        in_specs=(PS(axis),) * (len(leaves) + 1),
                        out_specs=(
                            [PS(axis)] * (len(leaves) - 1),
                            PS(axis),
                            PS(),
                        ),
                        check_vma=False,
                    )
                )
                self._mesh_jit_cache[key] = prog
            out, rlive, ovf = self._attempt(
                "exchange", lambda: prog(dest, *leaves)
            )
            if bool(jax.device_get(ovf)) and bucket_cap < shard_cap:
                self.exchange_escalations += 1
                bucket_cap = min(bucket_cap * 4, shard_cap)
                continue
            if bool(jax.device_get(ovf)):
                raise SkewOverflow(
                    "exchange bucket overflow at max capacity"
                )
            cols, i = [], 0
            for (name, has_valid), c in zip(meta, sp.columns):
                data = out[i]
                i += 1
                valid = None
                if has_valid:
                    valid = out[i]
                    i += 1
                cols.append(Column(c.type, data, valid, c.dictionary, c.hash_pool))
            from trino_tpu import session_properties as SP

            count_now = bool(
                SP.get(self.session, "exchange_partition_counters")
            )
            if not count_now:
                # sampled mode: count every Nth all_to_all instead of
                # every one. The host sync the counters force costs the
                # whole dispatch pipeline, so exact counting taxes
                # every exchange; 1/N sampling keeps skew observability
                # on by default at 1/N of that tax. Tradeoff: absolute
                # rows under-report by ~N (the metric is a sample, not
                # a census) but max/mean and cv are preserved in
                # expectation — hot-partition DETECTION survives
                # sampling, exact conservation accounting does not.
                n_sample = int(
                    SP.get(
                        self.session, "exchange_partition_counter_sample"
                    )
                )
                if n_sample > 0:
                    seq = getattr(self, "_exchange_count_seq", 0)
                    self._exchange_count_seq = seq + 1
                    count_now = (seq % n_sample) == 0
            if count_now:
                # skew observability (forces a host sync, so gated the
                # same way as the coverage check): per-destination live
                # row counts for this named edge, folded into
                # exchange_stats histograms and the
                # trino_exchange_partition_rows metric family
                d_host, live_host = jax.device_get((dest, sp.mask))
                counts = np.bincount(
                    np.asarray(d_host).ravel()[
                        np.asarray(live_host).ravel().astype(bool)
                    ],
                    minlength=n,
                )
                hist = self.exchange_stats.setdefault(
                    "partition_rows", {}
                ).setdefault(edge, {})
                for p, c in enumerate(counts):
                    if c:
                        hist[p] = hist.get(p, 0) + int(c)
                        telemetry.EXCHANGE_PARTITION_ROWS.inc(
                            int(c), edge=edge, partition=str(p)
                        )
            if SP.get(self.session, "check_exchange_coverage"):
                # debug assertion (forces a host sync): an all_to_all
                # must conserve live rows — any loss here is exactly
                # the mesh×fleet wrong-results class, attributed to
                # this named edge instead of surfacing as a silently
                # short result
                from trino_tpu.plan.validate import ExchangeCoverageError

                n_in, n_out = jax.device_get((
                    jnp.sum(sp.mask.astype(jnp.int32)),
                    jnp.sum(rlive.astype(jnp.int32)),
                ))
                if int(n_in) != int(n_out):
                    raise ExchangeCoverageError(
                        edge, int(n_in), int(n_out),
                        detail=(
                            f"{self.n_shards}-shard all_to_all, "
                            f"bucket_cap={bucket_cap}"
                        ),
                    )
            return ShardedPage(list(sp.names), cols, rlive, self.n_shards)

    # ---- distributed joins ----------------------------------------------

    def _unify_key_dicts(self, left, right, criteria) -> None:
        """Remap varchar join keys onto shared dictionaries BEFORE any
        hashing, so co-partitioning routes equal strings to the same
        shard regardless of which table they came from."""
        for ls, rs in criteria:
            lc, rc = left.column(ls), right.column(rs)
            if lc.hash_pool is not None and rc.hash_pool is not None:
                # hash codes are globally consistent; only the
                # cross-pool injectivity proof is needed
                lc.hash_pool.verify_joinable(rc.hash_pool)
                continue
            if lc.dictionary is not None or rc.dictionary is not None:
                lc2, rc2 = unify_dictionaries(lc, rc)
                left.columns[left.names.index(ls)] = lc2
                right.columns[right.names.index(rs)] = rc2

    def _dist_join(self, node: P.Join) -> ShardedPage:
        if node.kind == "cross":
            return self._dist_cross(node)
        if not node.criteria:
            # non-equi join: no key to co-partition on — gather both
            # sides and run the local nested-loop path, then re-shard
            # (the reference replicates the build side into
            # NestedLoopJoinOperator; at this shape the local tier is
            # the honest cost)
            if node.kind == "right":
                node = P.Join(
                    node.outputs, kind="left", left=node.right,
                    right=node.left, criteria=[], filter=node.filter,
                    df_range_keep=None, df_keep_frac=None,
                )

            def page_of(n: P.PlanNode) -> Page:
                if isinstance(n, P.RemoteSource):
                    return self._RemoteSource(n)
                if (
                    isinstance(n, P.Exchange)
                    and n.partitioning == "broadcast"
                ):
                    return self._broadcast_page(n)
                return self.gather(self.execute_dist(n))

            return self.scatter(
                self._nested_loop_join(
                    node,
                    self._compact(page_of(node.left)),
                    self._compact(page_of(node.right)),
                )
            )
        kind, criteria = node.kind, list(node.criteria)
        if node.distribution == "BROADCAST":
            probe = self.execute_dist(node.left)
            build = self._broadcast_page(node.right)
            self._unify_key_dicts(probe, build, criteria)
            if kind == "inner":
                probe = self._dynamic_filter_sharded(
                    node, probe, build, criteria
                )
            replicated = True
        else:
            left = self.execute_dist(node.left)
            right = self.execute_dist(node.right)
            if kind == "right":
                left, right = right, left
                criteria = [(b, a) for a, b in criteria]
                kind = "left"
            self._unify_key_dicts(left, right, criteria)
            if kind == "inner":
                # prune BEFORE the all_to_all: fewer exchanged rows and
                # smaller co-partitioned shard capacities
                left = self._dynamic_filter_sharded(
                    node, left, right, criteria
                )
            out = None
            if kind == "inner" and self._probe_is_skewed(left, criteria):
                out = self._skew_join(node, left, right, criteria)
            if out is not None:
                return out
            try:
                probe = self.hash_exchange(left, [a for a, _ in criteria])
                build = self.hash_exchange(right, [b for _, b in criteria])
            except SkewOverflow:
                if kind != "inner":
                    raise
                out = self._skew_join(node, left, right, criteria)
                if out is None:
                    raise
                return out
            replicated = False
        out_syms = list(node.outputs)
        if kind == "right":
            # BROADCAST right joins never occur (distribute forces
            # PARTITIONED), so kind is inner/left/full here
            raise AssertionError("unflipped right join in mesh executor")
        return self._equi_join_sharded(
            node, probe, build, replicated, kind, criteria, out_syms
        )

    def _dynamic_filter_sharded(
        self, node: P.Join, probe: ShardedPage, build, criteria
    ) -> ShardedPage:
        """Distributed dynamic filtering (DynamicFilterService analog,
        MAIN/server/DynamicFilterService.java:106): prune probe rows
        whose join key matches NO build row, BEFORE the all_to_all —
        fewer exchanged rows and smaller co-partitioned shard
        capacities. Inner joins only (callers enforce).

        Unlike the reference's min/max + bloom domains, the filter here
        is an exact membership probe (sort + searchsorted — join phase
        A reused as a filter): only the build KEY column crosses shards
        (all_gather of one column vs exchanging every probe column),
        and uniform dense keys — where min/max never prunes — still
        drop. Multi-key criteria use the hash-combined key, so false
        positives pass through harmlessly to the real join."""
        from trino_tpu import session_properties as SP

        if not SP.get(self.session, "dynamic_filtering_enabled"):
            return probe
        axis = self.axis
        if probe.shard_capacity * probe.n_shards < self.DF_MIN_PROBE:
            return probe
        # planner hint: expected keep fraction under membership — a
        # near-1.0 keep means the probe pass is pure cost
        if node.df_keep_frac is None or node.df_keep_frac > 0.7:
            return probe
        replicated = not isinstance(build, ShardedPage)
        p_leaves, p_meta = _page_leaves(probe)
        b_leaves, b_meta = _page_leaves(build)
        n_p = len(p_leaves)
        kinds = self._join_key_kinds(probe, build, criteria)
        prelude = _make_prelude(
            criteria, p_meta, b_meta, n_p, len(criteria) > 1, kinds
        )
        leaves = p_leaves + b_leaves
        key_b = (
            "mesh-df", tuple(criteria), self._sharded_sig(probe),
            self._join_sig(build, replicated),
        )
        prog_b = self._mesh_jit_cache.get(key_b)
        if prog_b is None:
            def fk(*ls):
                (_, p_mask, _, _, pk, bk, probe_live, build_live,
                 _, _) = prelude(ls)
                if not replicated:
                    bk = jax.lax.all_gather(bk, axis, tiled=True)
                    build_live = jax.lax.all_gather(
                        build_live, axis, tiled=True
                    )
                _, _, cnt = K.join_ranges(bk, build_live, pk, probe_live)
                keep = probe_live & (cnt > 0)
                n_in = jnp.sum(p_mask.astype(jnp.int32)).reshape(1)
                n_keep = jnp.sum(keep.astype(jnp.int32)).reshape(1)
                return keep, n_in, n_keep

            prog_b = jax.jit(
                jax.shard_map(
                    fk, mesh=self.mesh,
                    in_specs=(PS(axis),) * n_p + (
                        (PS(),) if replicated else (PS(axis),)
                    ) * len(b_leaves),
                    out_specs=(PS(axis), PS(axis), PS(axis)),
                    check_vma=False,
                )
            )
            self._mesh_jit_cache[key_b] = prog_b
        keep, n_in_dev, n_keep_dev = self._attempt(
            "dynamic-filter", lambda: prog_b(*leaves)
        )
        n_in, n_keep = jax.device_get((n_in_dev, n_keep_dev))
        in_rows, kept = int(n_in.sum()), int(n_keep.sum())
        self.df_log.append(
            {"rows_in": in_rows, "rows_kept": kept, "pairs": list(criteria)}
        )
        del self.df_log[:-100]  # bounded: executors outlive queries
        if kept > (1.0 - self.DF_MIN_DROP) * max(in_rows, 1):
            return probe
        new_cap = pad_capacity(int(max(n_keep.max(), 1)))
        if new_cap >= probe.shard_capacity:
            # no capacity win; still use the narrowed mask
            return ShardedPage(
                list(probe.names), list(probe.columns), keep, probe.n_shards
            )
        key_c = ("mesh-dfC", self._sharded_sig(probe), new_cap)
        prog_c = self._mesh_jit_cache.get(key_c)
        if prog_c is None:
            def fc(kp, *ls):
                perm = jnp.argsort(
                    (~kp).astype(jnp.int8), stable=True
                )[:new_cap]
                return [a[perm] for a in ls], kp[perm]

            prog_c = jax.jit(
                jax.shard_map(
                    fc, mesh=self.mesh,
                    in_specs=(PS(axis),) * (len(p_leaves) + 1),
                    out_specs=([PS(axis)] * len(p_leaves), PS(axis)),
                    check_vma=False,
                )
            )
            self._mesh_jit_cache[key_c] = prog_c
        out, new_mask = prog_c(keep, *p_leaves)
        cols, i = [], 0
        for (name, has_valid), c in zip(p_meta, probe.columns):
            data = out[i]
            i += 1
            valid = None
            if has_valid:
                valid = out[i]
                i += 1
            cols.append(Column(c.type, data, valid, c.dictionary, c.hash_pool))
        return ShardedPage(list(probe.names), cols, new_mask, probe.n_shards)

    # ---- skew-split join (SkewedPartitionRebalancer analog,
    # MAIN/operator/output/SkewedPartitionRebalancer.java — but for
    # joins, which the reference just lets eat the skew) --------------

    #: probes below this skip the skew histogram (one tiny dispatch)
    SKEW_MIN_PROBE = 1 << 16
    #: a destination this many times above the mean marks skew
    SKEW_FACTOR = 4.0

    def _probe_is_skewed(self, probe: ShardedPage, criteria) -> bool:
        """One cheap histogram dispatch: is any exchange destination
        loaded far beyond the mean? Without the split, a hot key
        inflates every shard's received capacity (n_shards x bucket)
        and serializes the whole mesh behind one shard's join. The
        (dest, counts) pair memoizes for _skew_join's immediate reuse."""
        if probe.shard_capacity * probe.n_shards < self.SKEW_MIN_PROBE:
            return False
        keys = tuple(a for a, _ in criteria)
        dest, counts = self._dest_counts(probe, list(keys))
        total = counts.sum()
        if total == 0:
            return False
        mean = total / self.n_shards
        skewed = bool(counts.max() > self.SKEW_FACTOR * mean)
        # memoize for _skew_join's immediate reuse only — holding the
        # page/dest arrays any longer would pin device memory
        self._dest_memo = (
            (id(probe), keys, probe, dest, counts) if skewed else None
        )
        return skewed

    def _dest_counts_memo(self, sp: ShardedPage, key_syms: list[str]):
        memo = getattr(self, "_dest_memo", None)
        self._dest_memo = None  # one-shot: never pin device arrays
        if (
            memo is not None
            and memo[0] == id(sp)
            and memo[1] == tuple(key_syms)
            and memo[2] is sp
        ):
            return memo[3], memo[4]
        return self._dest_counts(sp, key_syms)

    def _dest_counts(self, sp: ShardedPage, key_syms: list[str]):
        """(dest per row, global per-destination row counts)."""
        cols = [sp.column(k) for k in key_syms]
        h = K.hash_columns(_exchange_key_pairs(cols))
        dest = (h % jnp.uint64(self.n_shards)).astype(jnp.int32)
        prog = self._mesh_jit_cache.get("dest-hist")
        if prog is None:
            n = self.n_shards

            def hist(d, m):
                return jax.ops.segment_sum(
                    jnp.where(m, 1, 0), d, num_segments=n
                )

            prog = jax.jit(hist)
            self._mesh_jit_cache["dest-hist"] = prog
        counts = prog(dest, sp.mask)
        return dest, np.asarray(jax.device_get(counts))

    def _skew_join(
        self, node: P.Join, left: ShardedPage, right: ShardedPage,
        criteria,
    ) -> ShardedPage | None:
        """Inner join under destination skew: split the BUILD side into
        hot destinations (broadcast to every shard) and cold ones
        (hash-partitioned as usual); hot PROBE rows salt round-robin
        across the mesh. The two joins partition the key space
        disjointly, so their union is the exact join — a hot key's
        probe rows spread over all shards instead of escalating one
        bucket to shard capacity and failing."""
        lkeys = [a for a, _ in criteria]
        rkeys = [b for _, b in criteria]
        p_dest, p_counts = self._dest_counts_memo(left, lkeys)
        b_dest, b_counts = self._dest_counts(right, rkeys)
        shard_cap = left.shard_capacity
        # a destination is hot when either side's load cannot fit the
        # exchange's maximum bucket
        mean = max(p_counts.sum() / self.n_shards, 1.0)
        # hot = destinations overloaded on the PROBE side (what skew
        # salting fixes); a tightly-packed-but-balanced build must not
        # trip this — its per-dest load naturally sits near capacity
        hot = (p_counts > shard_cap // 2) | (
            p_counts > self.SKEW_FACTOR * mean
        )
        # the hot builds replicate to every shard — bail out to the
        # plain exchange when that replica would itself be oversized
        # (the memory blowup this path exists to avoid)
        if (
            not hot.any()
            or b_counts[hot].sum() > 4 * right.shard_capacity
        ):
            return None
        self.skew_joins += 1
        hot_dev = jnp.asarray(hot)

        # probe: hot rows round-robin by global position, cold rows keep
        # their hash destination
        rr = (
            jnp.arange(p_dest.shape[0], dtype=jnp.int32)
            % jnp.int32(self.n_shards)
        )
        salted = jnp.where(hot_dev[p_dest], rr, p_dest)
        probe = self.exchange_by_dest(left, salted)

        # build: cold rows exchange; hot rows gather into one local
        # replicated page (hot keys are few — their build rows fit)
        cold_mask = right.mask & ~hot_dev[b_dest]
        cold = ShardedPage(
            list(right.names), list(right.columns), cold_mask,
            right.n_shards,
        )
        build_cold = self.hash_exchange(cold, rkeys)
        hot_mask = right.mask & hot_dev[b_dest]
        hot_sp = ShardedPage(
            list(right.names), list(right.columns), hot_mask,
            right.n_shards,
        )
        build_hot = self.gather(hot_sp)

        out_syms = list(node.outputs)
        part1 = self._equi_join_sharded(
            node, probe, build_cold, False, "inner", criteria, out_syms
        )
        part2 = self._equi_join_sharded(
            node, probe, build_hot, True, "inner", criteria, out_syms
        )
        return self._concat_sharded(part1, part2)

    def _dist_groupid(self, node: P.GroupId) -> ShardedPage:
        """Shard-local GroupId replication: each shard concatenates k
        masked copies of its rows (no exchange — the aggregation above
        hash-exchanges on (id, keys)). GroupIdOperator analog
        (MAIN/operator/GroupIdOperator.java) in SPMD form."""
        src = self.execute_dist(node.source)
        k = len(node.grouping_sets)
        sets = [tuple(st) for st in node.grouping_sets]
        keyed = set(s for st in sets for s in st)
        leaves, meta = _page_leaves(src)
        axis = self.axis
        key = ("mesh-groupid", self._sharded_sig(src), tuple(sets))
        prog = self._mesh_jit_cache.get(key)
        if prog is None:
            names = list(src.names)

            def fg(*ls):
                env, mask = _env_from_leaves(ls, meta)
                outs = []
                for name in names:
                    data, valid = env[name]
                    outs.append(jnp.concatenate([data] * k))
                    n = data.shape[0]
                    if name in keyed:
                        vf = (
                            valid if valid is not None
                            else jnp.ones((n,), dtype=jnp.bool_)
                        )
                        none = jnp.zeros((n,), dtype=jnp.bool_)
                        outs.append(jnp.concatenate([
                            vf if name in st else none for st in sets
                        ]))
                    elif valid is not None:
                        outs.append(jnp.concatenate([valid] * k))
                n = mask.shape[0]
                outs.append(jnp.concatenate([
                    jnp.full((n,), i, dtype=jnp.int64) for i in range(k)
                ]))
                outs.append(jnp.concatenate([mask] * k))
                return outs

            n_out = sum(
                2 if (nm in keyed or hv) else 1 for nm, hv in meta
            ) + 2
            prog = jax.jit(
                jax.shard_map(
                    fg, mesh=self.mesh,
                    in_specs=(PS(axis),) * len(leaves),
                    out_specs=[PS(axis)] * n_out,
                    check_vma=False,
                )
            )
            self._mesh_jit_cache[key] = prog
        out = prog(*leaves)
        cols, i = [], 0
        names = []
        for (name, has_valid), c in zip(meta, src.columns):
            data = out[i]
            i += 1
            valid = None
            if name in keyed or has_valid:
                valid = out[i]
                i += 1
            names.append(name)
            cols.append(Column(c.type, data, valid, c.dictionary, c.hash_pool))
        names.append(node.id_symbol)
        cols.append(Column(T.BIGINT, out[i]))
        i += 1
        mask = out[i]
        return ShardedPage(names, cols, mask, src.n_shards)

    def _concat_sharded(self, a: ShardedPage, b: ShardedPage) -> ShardedPage:
        """Per-shard concatenation of two same-layout sharded pages."""
        axis = self.axis
        a_leaves, meta = _page_leaves(a)
        b_leaves, _ = _page_leaves(b)
        key = (
            "mesh-concat", self._sharded_sig(a), self._sharded_sig(b),
        )
        prog = self._mesh_jit_cache.get(key)
        if prog is None:
            n_a = len(a_leaves)

            def fc(*ls):
                xs, ys = ls[:n_a], ls[n_a:]
                return [
                    jnp.concatenate([x, y]) for x, y in zip(xs, ys)
                ]

            prog = jax.jit(
                jax.shard_map(
                    fc, mesh=self.mesh,
                    in_specs=(PS(axis),) * (len(a_leaves) + len(b_leaves)),
                    out_specs=[PS(axis)] * len(a_leaves),
                    check_vma=False,
                )
            )
            self._mesh_jit_cache[key] = prog
        out = prog(*a_leaves, *b_leaves)
        cols, i = [], 0
        for (name, has_valid), c in zip(meta, a.columns):
            data = out[i]
            i += 1
            valid = None
            if has_valid:
                valid = out[i]
                i += 1
            cols.append(Column(c.type, data, valid, c.dictionary, c.hash_pool))
        mask = out[i]
        return ShardedPage(list(a.names), cols, mask, a.n_shards)

    def _match_count_capacity(self, key, prelude, in_specs, leaves) -> int:
        """Phase A of a distributed join: per-shard match totals, one
        host sync, padded output capacity (the build-side barrier)."""
        prog = self._mesh_jit_cache.get(key)
        if prog is None:
            axis = self.axis

            def fa(*ls):
                (_, _, _, _, pk, bk, probe_live, build_live, _, _) = (
                    prelude(ls)
                )
                _, _, cnt = K.join_ranges(bk, build_live, pk, probe_live)
                return jnp.sum(cnt).reshape(1)

            prog = jax.jit(
                jax.shard_map(
                    fa, mesh=self.mesh, in_specs=in_specs,
                    out_specs=PS(axis), check_vma=False,
                )
            )
            self._mesh_jit_cache[key] = prog
        totals = jax.device_get(
            self._attempt("join-count", lambda: prog(*leaves))
        )
        return pad_capacity(int(max(totals.max(), 1)))

    def _join_sig(self, page, replicated: bool) -> tuple:
        cap = (
            page.shard_capacity
            if isinstance(page, ShardedPage) else page.capacity
        )
        return tuple(
            (
                n, repr(c.type), id(c.dictionary),
                None if c.hash_pool is None else c.hash_pool.token,
                c.valid is not None,
            )
            for n, c in zip(page.names, page.columns)
        ) + (cap, replicated)

    def _equi_join_sharded(
        self, node, probe, build, replicated, kind, criteria, out_syms
    ) -> ShardedPage:
        axis = self.axis
        p_cap = probe.shard_capacity
        b_cap = (
            build.capacity if replicated else build.shard_capacity
        )
        p_leaves, p_meta = _page_leaves(probe)
        b_leaves, b_meta = _page_leaves(build)
        n_p = len(p_leaves)
        p_cols0 = {n: c for n, c in zip(probe.names, probe.columns)}
        kinds = self._join_key_kinds(probe, build, criteria)
        verify = len(criteria) > 1 or any(
            k != "hash" and jnp.ndim(p_cols0[a].data) == 2
            for k, (a, _) in zip(kinds, criteria)
        )
        p_cols = {n: c for n, c in zip(probe.names, probe.columns)}
        b_cols = {n: c for n, c in zip(build.names, build.columns)}
        prelude = _make_prelude(
            criteria, p_meta, b_meta, n_p, verify, kinds
        )
        in_specs = (PS(axis),) * n_p + (
            (PS(),) if replicated else (PS(axis),)
        ) * len(b_leaves)

        # phase A: per-shard match counts -> one host sync for capacity
        key_a = (
            "mesh-joinA", tuple(criteria),
            self._join_sig(probe, False), self._join_sig(build, replicated),
        )
        out_cap = self._match_count_capacity(
            key_a, prelude, in_specs, p_leaves + b_leaves
        )

        # reserve the per-device join working set (probe shard + build
        # + expansion output) through the memory context — sharded
        # joins answer to query_max_memory_per_node like local ones
        lane_bytes = lambda cols: sum(  # noqa: E731
            (2 if jnp.ndim(c.data) == 2 else 1) * 8 for c in cols
        )
        out_row = sum(
            (2 if jnp.ndim((p_cols.get(s) or b_cols[s]).data) == 2
             else 1) * 8
            for s in out_syms
        )
        working_set = (
            p_cap * lane_bytes(probe.columns)
            + b_cap * lane_bytes(build.columns)
            + out_cap * (out_row + 8)
        )
        ctx = self.memory_ctx.child("mesh-join")
        ctx.reserve(working_set)
        ctx.free(working_set)

        # output column metadata
        filter_c = None
        if node.filter is not None:
            filter_c = compile_expr(
                node.filter,
                ColumnLayout(
                    types={s: node.outputs[s] for s in out_syms},
                    dictionaries={
                        s: (p_cols.get(s) or b_cols.get(s)).dictionary
                        for s in out_syms
                    },
                ),
            )
        out_meta = []
        for s in out_syms:
            from_probe = s in p_cols
            col = p_cols[s] if from_probe else b_cols[s]
            has_valid = col.valid is not None
            if kind in ("left", "full") and not from_probe:
                has_valid = True
            if kind == "full" and from_probe:
                has_valid = True
            out_meta.append((s, from_probe, has_valid))

        key_b = (
            "mesh-joinB", tuple(criteria), kind, out_cap,
            tuple(out_meta), repr(node.filter),
            self._join_sig(probe, False), self._join_sig(build, replicated),
        )
        prog_b = self._mesh_jit_cache.get(key_b)
        if prog_b is None:
            def fb(*ls):
                (p_env, p_mask, b_env, b_mask,
                 pk, bk, probe_live, build_live, p_bits, b_bits) = (
                    prelude(ls)
                )
                order, lo, cnt = K.join_ranges(
                    bk, build_live, pk, probe_live
                )
                probe_idx, build_idx, out_live = K.expand_matches(
                    order, lo, cnt, out_cap
                )
                if verify:
                    for pb, bb in zip(p_bits, b_bits):
                        out_live = out_live & (
                            pb[probe_idx] == bb[build_idx]
                        )
                inner = {}
                for s, from_probe, _ in out_meta:
                    env, idx = (
                        (p_env, probe_idx) if from_probe
                        else (b_env, build_idx)
                    )
                    d, v = env[s]
                    inner[s] = (
                        d[idx], None if v is None else v[idx]
                    )
                if filter_c is not None:
                    fd, fv = filter_c.fn(inner)
                    out_live = out_live & (
                        fd if fv is None else (fd & fv)
                    )
                col_sections = {
                    s: [inner[s]] for s, _, _ in out_meta
                }
                mask_sections = [out_live]
                if kind in ("left", "full"):
                    matched = K.range_any(cnt, out_live)
                    unmatched = p_mask & ~matched
                    for s, from_probe, _ in out_meta:
                        if from_probe:
                            d, v = p_env[s]
                            col_sections[s].append((d, v))
                        else:
                            d0, _ = b_env[s]
                            # match trailing dims (two-limb decimals
                            # are [n, 2])
                            col_sections[s].append((
                                jnp.zeros(
                                    (p_cap,) + d0.shape[1:],
                                    dtype=d0.dtype,
                                ),
                                jnp.zeros((p_cap,), dtype=jnp.bool_),
                            ))
                    mask_sections.append(unmatched)
                if kind == "full":
                    bmatched = K.scatter_any(build_idx, out_live, b_cap)
                    bunmatched = b_mask & ~bmatched
                    for s, from_probe, _ in out_meta:
                        if from_probe:
                            d0, _ = p_env[s]
                            col_sections[s].append((
                                jnp.zeros(
                                    (b_cap,) + d0.shape[1:],
                                    dtype=d0.dtype,
                                ),
                                jnp.zeros((b_cap,), dtype=jnp.bool_),
                            ))
                        else:
                            d, v = b_env[s]
                            col_sections[s].append((d, v))
                    mask_sections.append(bunmatched)
                outs = []
                for s, _, has_valid in out_meta:
                    parts = col_sections[s]
                    data = jnp.concatenate([d for d, _ in parts])
                    outs.append(data)
                    if has_valid:
                        vs = [
                            (jnp.ones(d.shape[0], dtype=jnp.bool_)
                             if v is None else v)
                            for d, v in parts
                        ]
                        outs.append(jnp.concatenate(vs))
                return outs, jnp.concatenate(mask_sections)

            n_out = sum(2 if hv else 1 for _, _, hv in out_meta)
            prog_b = jax.jit(
                jax.shard_map(
                    fb, mesh=self.mesh, in_specs=in_specs,
                    out_specs=([PS(axis)] * n_out, PS(axis)),
                    check_vma=False,
                )
            )
            self._mesh_jit_cache[key_b] = prog_b
        outs, mask = self._attempt(
            "join-expand", lambda: prog_b(*p_leaves, *b_leaves)
        )
        cols, i = [], 0
        for s, from_probe, has_valid in out_meta:
            src = p_cols[s] if from_probe else b_cols[s]
            data = outs[i]
            i += 1
            valid = None
            if has_valid:
                valid = outs[i]
                i += 1
            cols.append(Column(node.outputs[s], data, valid, src.dictionary, src.hash_pool))
        return ShardedPage(
            [s for s, _, _ in out_meta], cols, mask, self.n_shards
        )

    def _dist_cross(self, node: P.Join) -> ShardedPage:
        probe = self.execute_dist(node.left)
        build = self._broadcast_page(node.right)
        nb = build.num_rows()
        axis = self.axis
        p_leaves, p_meta = _page_leaves(probe)
        b_leaves, b_meta = _page_leaves(build)
        n_p = len(p_leaves)
        # phase A: max live probe rows on any shard
        key_a = ("mesh-crossA", self._join_sig(probe, False))
        prog_a = self._mesh_jit_cache.get(key_a)
        if prog_a is None:
            def fa(mask):
                return jnp.sum(mask.astype(jnp.int32)).reshape(1)

            prog_a = jax.jit(
                jax.shard_map(
                    fa, mesh=self.mesh, in_specs=(PS(axis),),
                    out_specs=PS(axis), check_vma=False,
                )
            )
            self._mesh_jit_cache[key_a] = prog_a
        lmax = int(jax.device_get(prog_a(probe.mask)).max())
        out_cap = pad_capacity(max(lmax * nb, 1))
        p_cols = {n: c for n, c in zip(probe.names, probe.columns)}
        b_cols = {n: c for n, c in zip(build.names, build.columns)}
        out_meta = [
            (s, s in p_cols,
             (p_cols.get(s) or b_cols.get(s)).valid is not None)
            for s in node.outputs
        ]
        key_b = (
            "mesh-crossB", out_cap, nb, tuple(out_meta),
            self._join_sig(probe, False), self._join_sig(build, True),
        )
        prog_b = self._mesh_jit_cache.get(key_b)
        if prog_b is None:
            def fb(*ls):
                p_env, p_mask = _env_from_leaves(list(ls[:n_p]), p_meta)
                b_env, b_mask = _env_from_leaves(list(ls[n_p:]), b_meta)
                n_live = jnp.sum(p_mask.astype(jnp.int32))
                perm = jnp.argsort(~p_mask, stable=True)
                p_cap = p_mask.shape[0]
                b_cap = b_mask.shape[0]
                j = jnp.arange(out_cap)
                li = perm[jnp.clip(j // max(nb, 1), 0, p_cap - 1)]
                ri = jnp.clip(j % max(nb, 1), 0, b_cap - 1)
                out_live = j < n_live * nb
                outs = []
                for s, from_probe, has_valid in out_meta:
                    env, idx = (p_env, li) if from_probe else (b_env, ri)
                    d, v = env[s]
                    outs.append(d[idx])
                    if has_valid:
                        outs.append(
                            jnp.ones(out_cap, dtype=jnp.bool_)
                            if v is None else v[idx]
                        )
                return outs, out_live

            n_out = sum(2 if hv else 1 for _, _, hv in out_meta)
            in_specs = (PS(axis),) * n_p + (PS(),) * len(b_leaves)
            prog_b = jax.jit(
                jax.shard_map(
                    fb, mesh=self.mesh, in_specs=in_specs,
                    out_specs=([PS(axis)] * n_out, PS(axis)),
                    check_vma=False,
                )
            )
            self._mesh_jit_cache[key_b] = prog_b
        outs, mask = self._attempt(
            "join-expand", lambda: prog_b(*p_leaves, *b_leaves)
        )
        cols, i = [], 0
        for s, from_probe, has_valid in out_meta:
            src = p_cols[s] if from_probe else b_cols[s]
            data = outs[i]
            i += 1
            valid = None
            if has_valid:
                valid = outs[i]
                i += 1
            cols.append(Column(node.outputs[s], data, valid, src.dictionary, src.hash_pool))
        return ShardedPage(
            [s for s, _, _ in out_meta], cols, mask, self.n_shards
        )

    # ---- distributed semi join ------------------------------------------

    def _dist_semi(self, node: P.SemiJoin) -> ShardedPage:
        sp = self.execute_dist(node.source)
        filt = self._broadcast_page(node.filter_source)
        self._unify_key_dicts(sp, filt, node.keys)
        key_nullable = any(
            sp.column(a).valid is not None for a, _ in node.keys
        ) or any(
            filt.column(b).valid is not None for _, b in node.keys
        )
        if node.null_aware and key_nullable:
            # 3VL NULL semantics need global build-NULL knowledge and
            # host-driven per-probe set checks: run the single-device
            # path on gathered rows, then re-shard the result
            page = self.gather(sp)
            return self.scatter(self._semi_join_pages(node, page, filt))
        axis = self.axis
        p_leaves, p_meta = _page_leaves(sp)
        b_leaves, b_meta = _page_leaves(filt)
        n_p = len(p_leaves)
        criteria = list(node.keys)
        kinds = self._join_key_kinds(sp, filt, criteria)
        verify = len(criteria) > 1 or any(
            k != "hash" and jnp.ndim(sp.column(a).data) == 2
            for k, (a, _) in zip(kinds, criteria)
        )
        needs_expand = verify or node.filter is not None
        p_cap = sp.shard_capacity
        in_specs = (PS(axis),) * n_p + (PS(),) * len(b_leaves)

        filter_c = None
        if node.filter is not None:
            pair_types = {
                **{n: c.type for n, c in zip(sp.names, sp.columns)},
                **{n: c.type for n, c in zip(filt.names, filt.columns)},
            }
            pair_dicts = {
                **{n: c.dictionary for n, c in zip(sp.names, sp.columns)},
                **{
                    n: c.dictionary
                    for n, c in zip(filt.names, filt.columns)
                },
            }
            filter_c = compile_expr(
                node.filter,
                ColumnLayout(types=pair_types, dictionaries=pair_dicts),
            )

        prelude = _make_prelude(criteria, p_meta, b_meta, n_p, verify, kinds)
        out_cap = None
        if needs_expand:
            key_a = (
                "mesh-semiA", tuple(criteria),
                self._join_sig(sp, False), self._join_sig(filt, True),
            )
            out_cap = self._match_count_capacity(
                key_a, prelude, in_specs, p_leaves + b_leaves
            )

        key_b = (
            "mesh-semiB", tuple(criteria), out_cap, repr(node.filter),
            self._join_sig(sp, False), self._join_sig(filt, True),
        )
        prog_b = self._mesh_jit_cache.get(key_b)
        if prog_b is None:
            def fb(*ls):
                (p_env, p_mask, b_env, b_mask,
                 pk, bk, probe_live, build_live, p_bits, b_bits) = (
                    prelude(ls)
                )
                order, lo, cnt = K.join_ranges(
                    bk, build_live, pk, probe_live
                )
                if needs_expand:
                    probe_idx, build_idx, out_live = K.expand_matches(
                        order, lo, cnt, out_cap
                    )
                    for pb, bb in zip(p_bits, b_bits):
                        out_live = out_live & (
                            pb[probe_idx] == bb[build_idx]
                        )
                    if filter_c is not None:
                        pair = {}
                        for s in p_env:
                            d, v = p_env[s]
                            pair[s] = (
                                d[probe_idx],
                                None if v is None else v[probe_idx],
                            )
                        for s in b_env:
                            d, v = b_env[s]
                            pair[s] = (
                                d[build_idx],
                                None if v is None else v[build_idx],
                            )
                        fd, fv = filter_c.fn(pair)
                        out_live = out_live & (
                            fd if fv is None else (fd & fv)
                        )
                    matched = K.range_any(cnt, out_live)
                else:
                    matched = cnt > 0
                return matched

            prog_b = jax.jit(
                jax.shard_map(
                    fb, mesh=self.mesh, in_specs=in_specs,
                    out_specs=PS(axis), check_vma=False,
                )
            )
            self._mesh_jit_cache[key_b] = prog_b
        matched = self._attempt(
            "semi-join", lambda: prog_b(*p_leaves, *b_leaves)
        )
        from trino_tpu import types as T

        names = list(sp.names) + [node.match_symbol]
        cols = list(sp.columns) + [Column(T.BOOLEAN, matched, None, None)]
        return ShardedPage(names, cols, sp.mask, self.n_shards)
