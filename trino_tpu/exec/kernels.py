"""Device kernels for relational operators.

The TPU-native replacements for the reference's hot loops
(SURVEY.md §3.3): instead of per-row probe loops (FlatHash.putIfAbsent,
MAIN/operator/FlatHash.java:190; JoinProbe per-row lookup,
MAIN/operator/join/JoinProbe.java:27), everything here is a
whole-column computation XLA can tile onto the MXU/VPU:

- ``assign_groups``: group-by key -> slot assignment via a vectorized
  open-addressing claim-by-scatter loop (the FlatHash analog; the
  control-byte probe of FlatHash.java:58 becomes a lane-parallel
  scatter-min race).
- ``segment_*``: aggregate accumulation as segment reductions (the
  Accumulator analog, MAIN/operator/aggregation/).
- ``join_expand``: equi-join via sort + searchsorted range expansion
  (the PagesHash/LookupSource analog, MAIN/operator/join/PagesHash.java:19).
- ``sort_perm``: multi-key order-by via iterated stable argsort
  (the PagesIndex sort analog, MAIN/operator/OrderByOperator.java).

All shapes are static; data-dependent sizes are carried as masks, and
the only host syncs are capacity decisions at operator boundaries.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "hash_columns",
    "normalize_key",
    "assign_groups",
    "sort_perm",
    "join_ranges",
    "expand_matches",
]


# ---- hashing ---------------------------------------------------------------

_MIX_1 = np.uint64(0xFF51AFD7ED558CCD)
_MIX_2 = np.uint64(0xC4CEB9FE1A85EC53)
_NULL_SALT = np.uint64(0x9E3779B97F4A7C15)


def _mix64(h: jnp.ndarray) -> jnp.ndarray:
    """splitmix64-style finalizer (wraparound uint64 math)."""
    h = h ^ (h >> 33)
    h = h * _MIX_1
    h = h ^ (h >> 33)
    h = h * _MIX_2
    h = h ^ (h >> 33)
    return h


def _canonical_float(data: jnp.ndarray) -> jnp.ndarray:
    """Canonicalize float values so equal keys have equal bits:
    -0.0 -> +0.0, every NaN payload -> the canonical quiet NaN
    (grouping/join semantics treat NaN as one value, reference
    TypeOperators equality)."""
    data = jnp.where(data == 0.0, jnp.zeros_like(data), data)
    return jnp.where(jnp.isnan(data), jnp.full_like(data, jnp.nan), data)


def _to_bits(data: jnp.ndarray) -> jnp.ndarray:
    """Reinterpret a key column as uint64 bits (floats canonicalized)."""
    if data.dtype == jnp.float64:
        return jax.lax.bitcast_convert_type(_canonical_float(data), jnp.uint64)
    if data.dtype == jnp.float32:
        return jax.lax.bitcast_convert_type(
            _canonical_float(data), jnp.uint32
        ).astype(jnp.uint64)
    if data.dtype == jnp.bool_:
        return data.astype(jnp.uint64)
    return data.astype(jnp.uint64)


def normalize_key(data: jnp.ndarray, valid: jnp.ndarray | None):
    """(bits, null_flag) with NULL data zeroed so equal keys have equal
    bits (SQL GROUP BY / join keys treat NULLs as one group)."""
    bits = _to_bits(data)
    if valid is None:
        return bits, jnp.zeros(bits.shape, dtype=jnp.bool_)
    return jnp.where(valid, bits, jnp.uint64(0)), ~valid


def hash_columns(cols: list[tuple[jnp.ndarray, jnp.ndarray | None]]) -> jnp.ndarray:
    """Combined 64-bit hash of key columns (nulls hash to a salt)."""
    h = jnp.zeros(cols[0][0].shape, dtype=jnp.uint64)
    for data, valid in cols:
        bits, isnull = normalize_key(data, valid)
        bits = jnp.where(isnull, _NULL_SALT, bits)
        h = _mix64(h ^ _mix64(bits))
    return h


# ---- group-by slot assignment ---------------------------------------------

@partial(jax.jit, static_argnames=("capacity",))
def assign_groups(
    norm_bits: tuple[jnp.ndarray, ...],
    null_flags: tuple[jnp.ndarray, ...],
    live: jnp.ndarray,
    capacity: int,
):
    """Assign each live row a slot in an open-addressed table.

    The vectorized FlatHash (MAIN/operator/FlatHash.java:42): all rows
    probe in lockstep; unclaimed slots are claimed by a scatter-min
    race on row index; losers compare keys against the winner by
    gather and advance their probe. Terminates in <= capacity rounds
    (capacity must exceed the distinct-key count; callers size it at
    2x the live rows).

    Returns (group, owner): ``group[i]`` = slot of row i (== capacity
    for dead rows AND for unresolved rows when the table overflowed —
    callers detect ``live & (group == capacity)`` and retry with a
    larger capacity, the FlatHash rehash analog), ``owner[s]`` = row
    index owning slot s (== n when the slot is empty).
    """
    n = live.shape[0]
    row_idx = jnp.arange(n, dtype=jnp.int32)
    h = hash_columns(
        [(b, None) for b in norm_bits]
        + [(f, None) for f in null_flags]
    )
    base = (h & jnp.uint64(capacity - 1)).astype(jnp.int32)

    owner0 = jnp.full((capacity,), n, dtype=jnp.int32)
    group0 = jnp.full((n,), capacity, dtype=jnp.int32)
    probe0 = jnp.zeros((n,), dtype=jnp.int32)
    resolved0 = ~live

    def cond(state):
        probe, resolved, _, _ = state
        # bounded probing: a full sweep without resolution = overflow
        return jnp.any(~resolved) & (probe.max() < capacity)

    def body(state):
        probe, resolved, group, owner = state
        slot = (base + probe) & (capacity - 1)
        pending = ~resolved
        # claim empty slots: lowest row index wins
        claim_slot = jnp.where(pending & (owner[slot] == n), slot, capacity)
        owner = owner.at[claim_slot].min(row_idx, mode="drop")
        own = owner[slot]
        own_g = jnp.clip(own, 0, n - 1)
        match = jnp.ones((n,), dtype=jnp.bool_)
        for bits in norm_bits:
            match = match & (bits == bits[own_g])
        for flag in null_flags:
            match = match & (flag == flag[own_g])
        resolved_now = pending & match
        group = jnp.where(resolved_now, slot, group)
        resolved = resolved | resolved_now
        probe = probe + jnp.where(resolved, 0, 1)
        return probe, resolved, group, owner

    _, _, group, owner = jax.lax.while_loop(
        cond, body, (probe0, resolved0, group0, owner0)
    )
    return group, owner


# ---- sorting ---------------------------------------------------------------

def sort_perm(
    keys: list[tuple[jnp.ndarray, jnp.ndarray | None, bool, bool]],
    live: jnp.ndarray,
) -> jnp.ndarray:
    """Permutation ordering rows by the sort keys, dead rows last.

    Each key is (data, valid, ascending, nulls_first). Implemented as
    iterated stable argsorts (lexsort), two passes per key — data then
    null flag — so no in-band sentinels are needed and int64 keys keep
    full precision. The reference default null ordering (nulls treated
    as largest: last for ASC, first for DESC) is resolved by the
    caller into ``nulls_first``.
    """
    n = live.shape[0]
    perm = jnp.arange(n, dtype=jnp.int32)
    for data, valid, ascending, nulls_first in reversed(keys):
        if jnp.issubdtype(data.dtype, jnp.floating):
            # order-preserving bit transform: NaN sorts as the largest
            # value under both directions (reference treats NaN as
            # largest: last for ASC, first for DESC) — negating would
            # leave NaN last either way
            kd = _float_sort_bits(data)
            if not ascending:
                kd = ~kd
        else:
            kd = data if ascending else _invert(data)
        perm = perm[jnp.argsort(kd[perm], stable=True)]
        if valid is not None:
            flag = (~valid).astype(jnp.int8)  # 1 = null
            if nulls_first:
                flag = -flag
            perm = perm[jnp.argsort(flag[perm], stable=True)]
    dead = (~live).astype(jnp.int8)
    perm = perm[jnp.argsort(dead[perm], stable=True)]
    return perm


def _invert(data: jnp.ndarray) -> jnp.ndarray:
    if data.dtype == jnp.bool_:
        return ~data
    return -data  # int64 min overflow is accepted (reference wraps too)


def _float_sort_bits(data: jnp.ndarray) -> jnp.ndarray:
    """Monotone unsigned encoding of a float column: flips the
    sign-magnitude representation so unsigned compare == float total
    order, with -0.0 == +0.0 and NaN canonicalized above +inf."""
    if data.dtype == jnp.float32:
        bits = jax.lax.bitcast_convert_type(_canonical_float(data), jnp.uint32)
        sign_mask = jnp.uint32(0x80000000)
        full_mask = jnp.uint32(0xFFFFFFFF)
    else:
        bits = jax.lax.bitcast_convert_type(_canonical_float(data), jnp.uint64)
        sign_mask = jnp.uint64(0x8000000000000000)
        full_mask = jnp.uint64(0xFFFFFFFFFFFFFFFF)
    negative = (bits & sign_mask) != 0
    return bits ^ jnp.where(negative, full_mask, sign_mask)


# ---- equi-join -------------------------------------------------------------

@jax.jit
def join_ranges(
    build_key: jnp.ndarray,
    build_live: jnp.ndarray,
    probe_key: jnp.ndarray,
    probe_live: jnp.ndarray,
):
    """Sorted-range probe: the LookupSource analog.

    ``build_key``/``probe_key`` are combined uint64 keys (exact for a
    single fixed-width column; hashed for multi-column — callers must
    re-verify matches after expansion). Rows with live=False never
    match; the caller has already excluded NULL keys.

    Returns (order, lo, cnt): ``order`` sorts the build side by key
    (dead rows last), ``lo[i]``/``cnt[i]`` give each probe row's match
    range inside the sorted build side.
    """
    # sort build: dead rows pushed past every live key via a 2-key sort
    dead = (~build_live).astype(jnp.uint64)
    order = jnp.argsort(build_key, stable=True)
    order = order[jnp.argsort(dead[order], stable=True)]
    n_build_live = jnp.sum(build_live)
    # dead tail keys are arbitrary; pin them to MAX so the whole array
    # is globally sorted (binary-search precondition), then clamp the
    # ranges to the live prefix
    pos = jnp.arange(build_key.shape[0])
    sorted_key = jnp.where(
        pos < n_build_live, build_key[order], jnp.uint64(0xFFFFFFFFFFFFFFFF)
    )
    lo = jnp.searchsorted(sorted_key, probe_key, side="left")
    hi = jnp.searchsorted(sorted_key, probe_key, side="right")
    lo = jnp.minimum(lo, n_build_live)
    hi = jnp.minimum(hi, n_build_live)
    cnt = jnp.where(probe_live, hi - lo, 0)
    return order.astype(jnp.int32), lo.astype(jnp.int32), cnt.astype(jnp.int32)


@partial(jax.jit, static_argnames=("out_capacity",))
def expand_matches(
    order: jnp.ndarray,
    lo: jnp.ndarray,
    cnt: jnp.ndarray,
    out_capacity: int,
):
    """Expand per-probe match ranges into (probe_idx, build_idx) pairs.

    Output position j belongs to the probe row whose cumulative match
    count covers j (searchsorted over the prefix sums — the vectorized
    form of JoinProbe's nested emit loop).

    Returns (probe_idx, build_idx, out_live).
    """
    offsets = jnp.cumsum(cnt)  # inclusive
    total = offsets[-1] if cnt.shape[0] else jnp.int32(0)
    j = jnp.arange(out_capacity, dtype=jnp.int32)
    probe_idx = jnp.searchsorted(offsets, j, side="right").astype(jnp.int32)
    probe_c = jnp.clip(probe_idx, 0, cnt.shape[0] - 1)
    start = offsets[probe_c] - cnt[probe_c]
    k = j - start
    build_pos = lo[probe_c] + k
    build_pos = jnp.clip(build_pos, 0, order.shape[0] - 1)
    build_idx = order[build_pos]
    out_live = j < total
    return probe_c, build_idx, out_live


# ---- segment aggregation ---------------------------------------------------

def seg_sum(vals, group, num_segments):
    return jax.ops.segment_sum(vals, group, num_segments=num_segments + 1)[
        :num_segments
    ]


def seg_min(vals, group, num_segments):
    return jax.ops.segment_min(vals, group, num_segments=num_segments + 1)[
        :num_segments
    ]


def seg_max(vals, group, num_segments):
    return jax.ops.segment_max(vals, group, num_segments=num_segments + 1)[
        :num_segments
    ]
