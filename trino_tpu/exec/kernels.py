"""Device kernels for relational operators.

The TPU-native replacements for the reference's hot loops
(SURVEY.md §3.3): instead of per-row probe loops (FlatHash.putIfAbsent,
MAIN/operator/FlatHash.java:190; JoinProbe per-row lookup,
MAIN/operator/join/JoinProbe.java:27), everything here is a
whole-column computation XLA can tile onto the MXU/VPU:

- ``assign_groups``: group-by key -> slot assignment via a vectorized
  open-addressing claim-by-scatter loop (the FlatHash analog; the
  control-byte probe of FlatHash.java:58 becomes a lane-parallel
  scatter-min race).
- ``segment_*``: aggregate accumulation as segment reductions (the
  Accumulator analog, MAIN/operator/aggregation/).
- ``join_expand``: equi-join via sort + searchsorted range expansion
  (the PagesHash/LookupSource analog, MAIN/operator/join/PagesHash.java:19).
- ``sort_perm``: multi-key order-by via iterated stable argsort
  (the PagesIndex sort analog, MAIN/operator/OrderByOperator.java).

All shapes are static; data-dependent sizes are carried as masks, and
the only host syncs are capacity decisions at operator boundaries.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import telemetry

# this module holds the engine's jitted device kernels: make sure every
# backend compile they trigger lands in trino_xla_compile_total before
# the first jit call anywhere in the process
telemetry.install_jax_compile_hook()

__all__ = [
    "hash_columns",
    "searchsorted",
    "normalize_key",
    "GroupInfo",
    "sort_group",
    "assign_groups",
    "sort_perm",
    "join_ranges",
    "expand_matches",
    "range_any",
    "scatter_any",
    "seg_sum_ranges",
    "seg_minmax_scan",
    "seg_first_index",
]


# ---- hashing ---------------------------------------------------------------

_MIX_1 = np.uint64(0xFF51AFD7ED558CCD)
_MIX_2 = np.uint64(0xC4CEB9FE1A85EC53)
_NULL_SALT = np.uint64(0x9E3779B97F4A7C15)


def _mix64(h: jnp.ndarray) -> jnp.ndarray:
    """splitmix64-style finalizer (wraparound uint64 math)."""
    h = h ^ (h >> 33)
    h = h * _MIX_1
    h = h ^ (h >> 33)
    h = h * _MIX_2
    h = h ^ (h >> 33)
    return h


def _canonical_float(data: jnp.ndarray) -> jnp.ndarray:
    """Canonicalize float values so equal keys have equal bits:
    -0.0 -> +0.0, every NaN payload -> the canonical quiet NaN
    (grouping/join semantics treat NaN as one value, reference
    TypeOperators equality)."""
    data = jnp.where(data == 0.0, jnp.zeros_like(data), data)
    return jnp.where(jnp.isnan(data), jnp.full_like(data, jnp.nan), data)


def _to_bits(data: jnp.ndarray) -> jnp.ndarray:
    """Reinterpret a key column as uint64 bits (floats canonicalized)."""
    if data.dtype == jnp.float64:
        return jax.lax.bitcast_convert_type(_canonical_float(data), jnp.uint64)
    if data.dtype == jnp.float32:
        return jax.lax.bitcast_convert_type(
            _canonical_float(data), jnp.uint32
        ).astype(jnp.uint64)
    if data.dtype == jnp.bool_:
        return data.astype(jnp.uint64)
    return data.astype(jnp.uint64)


def searchsorted(a: jnp.ndarray, v: jnp.ndarray, side: str = "left") -> jnp.ndarray:
    """searchsorted with a TPU-friendly method choice: the default
    binary-search 'scan' lowers to ~log2(n) serialized gather rounds
    over every query (measured 1.7 s for 4M queries on v5e); the
    'sort' method is one argsort of queries+haystack (~0.06 s). Shapes
    are static under jit, so the choice is made at trace time."""
    method = "sort" if v.size > 16384 else "scan"
    return jnp.searchsorted(a, v, side=side, method=method)


def limb_parts(data: jnp.ndarray) -> list[jnp.ndarray]:
    """A key column as 1D pieces: two-limb decimal columns ([n, 2])
    contribute their hi and lo limbs as separate key parts."""
    if jnp.ndim(data) == 2:
        return [data[:, 0], data[:, 1]]
    return [data]


def normalize_key(data: jnp.ndarray, valid: jnp.ndarray | None):
    """(bits, null_flag) with NULL data zeroed so equal keys have equal
    bits (SQL GROUP BY / join keys treat NULLs as one group)."""
    bits = _to_bits(data)
    if valid is None:
        return bits, jnp.zeros(bits.shape, dtype=jnp.bool_)
    return jnp.where(valid, bits, jnp.uint64(0)), ~valid


def hash_columns(cols: list[tuple[jnp.ndarray, jnp.ndarray | None]]) -> jnp.ndarray:
    """Combined 64-bit hash of key columns (nulls hash to a salt)."""
    h = jnp.zeros(cols[0][0].shape, dtype=jnp.uint64)
    for data, valid in cols:
        bits, isnull = normalize_key(data, valid)
        bits = jnp.where(isnull, _NULL_SALT, bits)
        h = _mix64(h ^ _mix64(bits))
    return h


# ---- group-by via sort ------------------------------------------------------
#
# TPU scatters serialize (measured ~115 ms per segment_sum over 1M rows
# on v5e vs ~1-7 ms for sorts/gathers/cumsums), so the FlatHash-style
# scatter-race table was replaced by sort-based grouping: lexsort the
# key columns, mark group boundaries by adjacent compare, and derive
# dense group ids by cumsum. This is exact (no hash collisions) and
# every primitive it touches — argsort, gather, cumsum, searchsorted —
# is fast on the MXU/VPU path.


class GroupInfo(NamedTuple):
    """Sorted-group context shared by every aggregate over one GROUP BY.

    ``perm`` sorts rows so each group is one contiguous run (dead rows
    last); ``gid_sorted[p]`` is the dense group id at sorted position p
    (== capacity for dead/overflowed rows); ``group[i]`` maps original
    rows to ids; ``starts``/``ends`` delimit each id's run in sorted
    order; ``owner[s]`` is the first (original-index) row of group s or
    n when s is unused; ``num_groups`` is the exact distinct count.
    """

    perm: jnp.ndarray
    gid_sorted: jnp.ndarray
    group: jnp.ndarray
    starts: jnp.ndarray
    ends: jnp.ndarray
    owner: jnp.ndarray
    num_groups: jnp.ndarray


@partial(jax.jit, static_argnames=("capacity", "widths"))
def sort_group(
    norm_bits: tuple[jnp.ndarray, ...],
    null_flags: tuple[jnp.ndarray, ...],
    live: jnp.ndarray,
    capacity: int,
    widths: tuple[int, ...] | None = None,
    pre_perm: jnp.ndarray | None = None,
) -> GroupInfo:
    """Exact multi-key grouping by lexsort + boundary cumsum.

    The TPU-native FlatHash replacement (MAIN/operator/FlatHash.java:42):
    instead of per-row probing, all key columns are stably sorted,
    equal keys become adjacent runs, and a cumsum over run boundaries
    yields dense group ids 0..num_groups-1 in key-sorted order. Groups
    beyond ``capacity`` report via num_groups > capacity (callers retry
    larger, the rehash analog) — the assignment itself never collides.

    When ``widths`` gives a per-key value bit width and everything
    (plus null flags plus one liveness bit) fits in 64 bits, all keys
    pack into ONE u64 — a single argsort replaces the multi-pass
    lexsort and dead rows fall to the tail for free. Otherwise each key
    costs a stable argsort pass (plus one for its null flag when the
    column is nullable — pass flag None for non-nullable).
    """
    n = live.shape[0]
    words = _pack_words(norm_bits, null_flags, live, widths)
    if words is not None:
        packed_words, live_folded, total_bits = words
        if pre_perm is None and len(packed_words) == 1 and live_folded:
            # hot path: ONE multi-operand lax.sort yields the sorted
            # keys AND the permutation together (an argsort + gather
            # costs ~2.3x as much on TPU), and liveness reads off the
            # folded MSB instead of a second gather
            idx = jnp.arange(n, dtype=jnp.int32)
            ps, perm = jax.lax.sort(
                (packed_words[0], idx), num_keys=1, is_stable=True
            )
            live_s = (ps >> jnp.uint64(total_bits)) == 0
            same = ps == jnp.roll(ps, 1)
        else:
            # stable sort preserves the caller's row order within each
            # group (window functions: order-by within partition);
            # multi-word packs lexsort least-significant word first
            perm = (
                jnp.arange(n, dtype=jnp.int32)
                if pre_perm is None else pre_perm.astype(jnp.int32)
            )
            for w in reversed(packed_words):
                ws, perm = jax.lax.sort(
                    (w[perm], perm), num_keys=1, is_stable=True
                )
            if not live_folded:
                perm = perm[jnp.argsort((~live)[perm], stable=True)]
            live_s = live[perm]
            same = jnp.ones((n,), dtype=jnp.bool_)
            for w in packed_words:
                ws = w[perm]
                same = same & (ws == jnp.roll(ws, 1))
    else:
        perm = (
            jnp.arange(n, dtype=jnp.int32)
            if pre_perm is None else pre_perm.astype(jnp.int32)
        )
        for bits, flag in reversed(list(zip(norm_bits, null_flags))):
            perm = perm[jnp.argsort(bits[perm], stable=True)]
            if flag is not None:
                perm = perm[jnp.argsort(flag[perm], stable=True)]
        # dead rows last (live is a prefix after this stable pass)
        perm = perm[jnp.argsort((~live)[perm], stable=True)]
        live_s = live[perm]
        same = jnp.ones((n,), dtype=jnp.bool_)
        for bits, flag in zip(norm_bits, null_flags):
            bs = bits[perm]
            same = same & (bs == jnp.roll(bs, 1))
            if flag is not None:
                fs = flag[perm]
                same = same & (fs == jnp.roll(fs, 1))
    pos = jnp.arange(n, dtype=jnp.int32)
    boundary = live_s & ((pos == 0) | ~same)
    gid1 = jnp.cumsum(boundary.astype(jnp.int32))  # 1-based within live
    num_groups = gid1[-1] if n else jnp.int32(0)
    gid_sorted = jnp.where(live_s, gid1 - 1, capacity)
    gid_sorted = jnp.minimum(gid_sorted, capacity)
    inv = jnp.argsort(perm, stable=True)  # inverse permutation
    group = gid_sorted[inv]
    sids = jnp.arange(capacity, dtype=jnp.int32)
    starts = searchsorted(gid_sorted, sids, side="left").astype(jnp.int32)
    # dense contiguous ids: each group's end IS the next group's start
    # (a second searchsorted would cost ~160ms at 6M rows)
    n_live = searchsorted(
        gid_sorted, jnp.asarray(capacity, dtype=gid_sorted.dtype),
        side="left",
    ).astype(jnp.int32)
    ends = jnp.concatenate([starts[1:], n_live.reshape(1)])
    owner = jnp.where(
        sids < num_groups, perm[jnp.clip(starts, 0, max(n - 1, 0))], n
    ).astype(jnp.int32)
    return GroupInfo(perm, gid_sorted, group, starts, ends, owner, num_groups)


def _pack_words(norm_bits, null_flags, live, widths):
    """(packed_words, live_folded, total_bits) — the keys packed into
    as few u64 sort words as possible, or None when no widths are
    known. ``total_bits`` is the key+flag bit count of the (single)
    word when ``live_folded`` (the liveness bit sits at that
    position).

    Each word combines consecutive keys (significance order preserved:
    word list is most-significant first, callers lexsort
    least-significant word first). Equal keys map to equal packed
    values (the low ``w`` bits of each key's normalized bits are
    injective for values of that width). When the whole pack is one
    word with a 65th bit free, liveness folds in as the MSB so dead
    rows sort last with no extra pass."""
    if widths is None:
        return None
    keys = list(zip(norm_bits, null_flags, widths))
    # greedy chunking into <=64-bit words, significance order kept
    chunks: list[list] = []
    cur: list = []
    cur_bits = 0
    for bits, flag, w in keys:
        need = w + (0 if flag is None else 1)
        if need > 64:
            return None  # a single over-wide key defeats packing
        if cur and cur_bits + need > 64:
            chunks.append(cur)
            cur, cur_bits = [], 0
        cur.append((bits, flag, w))
        cur_bits += need
    if cur:
        chunks.append(cur)
    one_word = len(chunks) == 1
    live_folded = one_word and cur_bits + 1 <= 64
    words = []
    for ci, chunk in enumerate(chunks):
        # start from the liveness bit (or the first key) rather than a
        # zeros << width chain — a shift by the full 64-bit width is
        # undefined in XLA and would corrupt single-wide-key packing
        packed = (
            (~live).astype(jnp.uint64)
            if live_folded and ci == 0 else None
        )
        for bits, flag, w in chunk:
            piece = bits & jnp.uint64((1 << w) - 1) if w < 64 else bits
            packed = (
                piece if packed is None
                else (packed << jnp.uint64(w)) | piece
            )
            if flag is not None:
                packed = (packed << jnp.uint64(1)) | flag.astype(jnp.uint64)
        words.append(packed)
    return words, live_folded, cur_bits


def assign_groups(
    norm_bits: tuple[jnp.ndarray, ...],
    null_flags: tuple[jnp.ndarray, ...],
    live: jnp.ndarray,
    capacity: int,
    widths: tuple[int, ...] | None = None,
):
    """(group, owner) compatibility wrapper over :func:`sort_group`.

    ``group[i]`` = dense id of row i (== capacity for dead rows and for
    overflow rows when more than ``capacity`` distinct keys exist),
    ``owner[s]`` = representative row of group s (== n when unused).
    """
    info = sort_group(norm_bits, null_flags, live, capacity, widths=widths)
    return info.group, info.owner


# ---- segment reductions over sorted groups ---------------------------------


def _range_gather(cs: jnp.ndarray, idx: jnp.ndarray, zero):
    """cs[idx-1] with 0 for idx==0 (prefix-sum boundary read)."""
    n = cs.shape[0]
    at = jnp.clip(idx - 1, 0, max(n - 1, 0))
    return jnp.where(idx > 0, cs[at], zero)


def seg_sum_ranges(vals_sorted, info: GroupInfo, zero=None):
    """Per-group sums of an already group-sorted, contribution-masked
    value column — scatter-free.

    Integers use cumsum + boundary differences (exact). Floats use a
    segmented associative scan accumulated in float64 so each group's
    rounding error is bounded by its own magnitude, not the whole
    page's running prefix (a cumsum-difference would lose ~ulp(global
    prefix) per group).
    """
    dtype = vals_sorted.dtype
    if zero is None:
        zero = jnp.zeros((), dtype=dtype)
    if jnp.issubdtype(dtype, jnp.floating):
        acc = vals_sorted.astype(jnp.float64)

        def op(a, b):
            ga, va = a
            gb, vb = b
            return gb, jnp.where(ga == gb, va + vb, vb)

        _, s = jax.lax.associative_scan(op, (info.gid_sorted, acc))
        n = s.shape[0]
        at = jnp.clip(info.ends - 1, 0, max(n - 1, 0))
        out = jnp.where(info.ends > info.starts, s[at], 0.0)
        return out.astype(dtype)
    cs = jnp.cumsum(vals_sorted)
    # ends[g] == starts[g+1] (dense contiguous groups), so the hi
    # prefix is the lo prefix shifted by one — one [capacity] gather
    # instead of two
    lo = _range_gather(cs, info.starts, zero)
    total = _range_gather(cs, info.ends[-1:], zero)
    hi = jnp.concatenate([lo[1:], total])
    return jnp.where(info.ends > info.starts, hi - lo, zero)


def seg_minmax_scan(vals_sorted, info: GroupInfo, fill, is_min: bool):
    """Per-group min/max via a segmented associative scan over the
    group-sorted values (positions outside the group reset the run)."""
    red = jnp.minimum if is_min else jnp.maximum

    def op(a, b):
        ga, va = a
        gb, vb = b
        return gb, jnp.where(ga == gb, red(va, vb), vb)

    _, m = jax.lax.associative_scan(op, (info.gid_sorted, vals_sorted))
    n = m.shape[0]
    at = jnp.clip(info.ends - 1, 0, max(n - 1, 0))
    out = m[at]
    return jnp.where(info.ends > info.starts, out, fill)


def order_bits(data: jnp.ndarray) -> jnp.ndarray:
    """Monotone u64 encoding: unsigned compare == value order (for
    argmin/argmax-style reductions over arbitrary key types)."""
    if jnp.issubdtype(data.dtype, jnp.floating):
        return _float_sort_bits(data).astype(jnp.uint64)
    if data.dtype == jnp.bool_:
        return data.astype(jnp.uint64)
    bits = data.astype(jnp.int64)
    return jax.lax.bitcast_convert_type(bits, jnp.uint64) ^ jnp.uint64(
        0x8000000000000000
    )


def seg_arg_extreme(
    key_sorted: jnp.ndarray,
    contrib_sorted: jnp.ndarray,
    info: GroupInfo,
    is_min: bool,
):
    """Original row index of each group's key-extremal contributing row
    (segmented argmin/argmax; ties take the first sorted position).
    The contribution flag rides through the comparison — a real key
    equal to a would-be sentinel can never lose to an excluded row.
    Rows are garbage for empty groups — callers mask with count > 0."""
    n = key_sorted.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)

    def op(a, b):
        ga, ca, ka, pa = a
        gb, cb, kb, pb = b
        same = ga == gb
        if is_min:
            key_better = (ka < kb) | ((ka == kb) & (pa < pb))
        else:
            key_better = (ka > kb) | ((ka == kb) & (pa < pb))
        a_better = (ca & ~cb) | ((ca == cb) & key_better)
        take_a = same & a_better
        return (
            gb,
            jnp.where(take_a, ca, cb),
            jnp.where(take_a, ka, kb),
            jnp.where(take_a, pa, pb),
        )

    _, _, _, best = jax.lax.associative_scan(
        op, (info.gid_sorted, contrib_sorted, key_sorted, pos)
    )
    at = jnp.clip(info.ends - 1, 0, max(n - 1, 0))
    bp = best[at]
    return info.perm[jnp.clip(bp, 0, max(n - 1, 0))]


def seg_first_index(contrib_sorted, info: GroupInfo):
    """Original row index of the first contributing row per group
    (== n when the group has none)."""
    n = contrib_sorted.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    masked = jnp.where(contrib_sorted, pos, n)
    first_pos = seg_minmax_scan(masked, info, jnp.int32(n), is_min=True)
    has = first_pos < n
    rows = info.perm[jnp.clip(first_pos, 0, max(n - 1, 0))]
    return jnp.where(has, rows, n), has


def blocked_sum(x: jnp.ndarray) -> jnp.ndarray:
    """Scalar int64 sum via a two-stage blocked reduce (same AOT
    compiler workaround as count_true)."""
    v = x.astype(jnp.int64)
    n = v.shape[0]
    block = 256 if n % 256 == 0 else n
    return v.reshape(-1, block).sum(axis=1).sum()


def count_true(mask: jnp.ndarray) -> jnp.ndarray:
    """Scalar count of True values via a two-stage blocked reduce.

    (The tunnel AOT compiler crashes on a flat 1D reduce of a large
    bool output in some program contexts; the blocked form compiles
    everywhere and is equally fast.)"""
    x = mask.astype(jnp.int32)
    n = x.shape[0]
    block = 256 if n % 256 == 0 else n
    return x.reshape(-1, block).sum(axis=1).sum()


# ---- join-side match marks (scatter-free) ----------------------------------


def range_any(cnt: jnp.ndarray, out_live: jnp.ndarray) -> jnp.ndarray:
    """Per-probe 'any live expanded output in my range' — the
    scatter-free form of segment-any over the (sorted) probe_idx that
    ``expand_matches`` emits. ``cnt`` is the per-probe match count that
    produced the expansion; ``out_live`` the expanded liveness."""
    offsets = jnp.cumsum(cnt)
    c = jnp.cumsum(out_live.astype(jnp.int32))
    zero = jnp.int32(0)
    hi = _range_gather(c, jnp.minimum(offsets, out_live.shape[0]), zero)
    lo = _range_gather(c, jnp.minimum(offsets - cnt, out_live.shape[0]), zero)
    return (hi - lo) > 0


def scatter_any(idx: jnp.ndarray, flags: jnp.ndarray, capacity: int) -> jnp.ndarray:
    """``any(flags[idx == b])`` per b in [0, capacity) for arbitrary
    (unsorted) idx — sort + membership probe instead of a scatter."""
    key = jnp.where(flags, idx, capacity).astype(jnp.int32)
    ks = jnp.sort(key)
    targets = jnp.arange(capacity, dtype=jnp.int32)
    pos = searchsorted(ks, targets, side="left")
    at = jnp.clip(pos, 0, max(ks.shape[0] - 1, 0))
    return (pos < ks.shape[0]) & (ks[at] == targets)


# ---- sorting ---------------------------------------------------------------

def sort_perm(
    keys: list[tuple[jnp.ndarray, jnp.ndarray | None, bool, bool]],
    live: jnp.ndarray,
) -> jnp.ndarray:
    """Permutation ordering rows by the sort keys, dead rows last.

    Each key is (data, valid, ascending, nulls_first). Implemented as
    iterated stable argsorts (lexsort), two passes per key — data then
    null flag — so no in-band sentinels are needed and int64 keys keep
    full precision. The reference default null ordering (nulls treated
    as largest: last for ASC, first for DESC) is resolved by the
    caller into ``nulls_first``.
    """
    n = live.shape[0]
    perm = jnp.arange(n, dtype=jnp.int32)
    for data, valid, ascending, nulls_first in reversed(keys):
        if jnp.issubdtype(data.dtype, jnp.floating):
            # order-preserving bit transform: NaN sorts as the largest
            # value under both directions (reference treats NaN as
            # largest: last for ASC, first for DESC) — negating would
            # leave NaN last either way
            kd = _float_sort_bits(data)
            if not ascending:
                kd = ~kd
        else:
            kd = data if ascending else _invert(data)
        perm = perm[jnp.argsort(kd[perm], stable=True)]
        if valid is not None:
            flag = (~valid).astype(jnp.int8)  # 1 = null
            if nulls_first:
                flag = -flag
            perm = perm[jnp.argsort(flag[perm], stable=True)]
    dead = (~live).astype(jnp.int8)
    perm = perm[jnp.argsort(dead[perm], stable=True)]
    return perm


def _invert(data: jnp.ndarray) -> jnp.ndarray:
    if data.dtype == jnp.bool_:
        return ~data
    return -data  # int64 min overflow is accepted (reference wraps too)


def _float_sort_bits(data: jnp.ndarray) -> jnp.ndarray:
    """Monotone unsigned encoding of a float column: flips the
    sign-magnitude representation so unsigned compare == float total
    order, with -0.0 == +0.0 and NaN canonicalized above +inf."""
    if data.dtype == jnp.float32:
        bits = jax.lax.bitcast_convert_type(_canonical_float(data), jnp.uint32)
        sign_mask = jnp.uint32(0x80000000)
        full_mask = jnp.uint32(0xFFFFFFFF)
    else:
        bits = jax.lax.bitcast_convert_type(_canonical_float(data), jnp.uint64)
        sign_mask = jnp.uint64(0x8000000000000000)
        full_mask = jnp.uint64(0xFFFFFFFFFFFFFFFF)
    negative = (bits & sign_mask) != 0
    return bits ^ jnp.where(negative, full_mask, sign_mask)


# ---- equi-join -------------------------------------------------------------

@jax.jit
def join_ranges(
    build_key: jnp.ndarray,
    build_live: jnp.ndarray,
    probe_key: jnp.ndarray,
    probe_live: jnp.ndarray,
):
    """Sorted-range probe: the LookupSource analog.

    ``build_key``/``probe_key`` are combined uint64 keys (exact for a
    single fixed-width column; hashed for multi-column — callers must
    re-verify matches after expansion). Rows with live=False never
    match; the caller has already excluded NULL keys.

    Returns (order, lo, cnt): ``order`` sorts the build side by key
    (dead rows last), ``lo[i]``/``cnt[i]`` give each probe row's match
    range inside the sorted build side.
    """
    # sort build: dead rows pushed past every live key via two
    # multi-operand sorts that carry key+index as payload (each costs
    # ~40% of an argsort+gather pair on TPU)
    dead = ~build_live
    idx = jnp.arange(build_key.shape[0], dtype=jnp.int32)
    k1, d1, o1 = jax.lax.sort(
        (build_key, dead, idx), num_keys=1, is_stable=True
    )
    _, k2, order = jax.lax.sort((d1, k1, o1), num_keys=1, is_stable=True)
    n_build_live = jnp.sum(build_live)
    # dead tail keys are arbitrary; pin them to MAX so the whole array
    # is globally sorted (binary-search precondition), then clamp the
    # ranges to the live prefix
    pos = jnp.arange(build_key.shape[0])
    sorted_key = jnp.where(
        pos < n_build_live, k2, jnp.uint64(0xFFFFFFFFFFFFFFFF)
    )
    lo = searchsorted(sorted_key, probe_key, side="left")
    hi = searchsorted(sorted_key, probe_key, side="right")
    lo = jnp.minimum(lo, n_build_live)
    hi = jnp.minimum(hi, n_build_live)
    cnt = jnp.where(probe_live, hi - lo, 0)
    return order.astype(jnp.int32), lo.astype(jnp.int32), cnt.astype(jnp.int32)


@partial(jax.jit, static_argnames=("out_capacity",))
def expand_matches(
    order: jnp.ndarray,
    lo: jnp.ndarray,
    cnt: jnp.ndarray,
    out_capacity: int,
):
    """Expand per-probe match ranges into (probe_idx, build_idx) pairs.

    Output position j belongs to the probe row whose cumulative match
    count covers j (searchsorted over the prefix sums — the vectorized
    form of JoinProbe's nested emit loop).

    Returns (probe_idx, build_idx, out_live).
    """
    offsets = jnp.cumsum(cnt)  # inclusive
    total = offsets[-1] if cnt.shape[0] else jnp.int32(0)
    j = jnp.arange(out_capacity, dtype=jnp.int32)
    probe_idx = searchsorted(offsets, j, side="right").astype(jnp.int32)
    probe_c = jnp.clip(probe_idx, 0, cnt.shape[0] - 1)
    start = offsets[probe_c] - cnt[probe_c]
    k = j - start
    build_pos = lo[probe_c] + k
    build_pos = jnp.clip(build_pos, 0, order.shape[0] - 1)
    build_idx = order[build_pos]
    out_live = j < total
    return probe_c, build_idx, out_live


# ---- segment aggregation ---------------------------------------------------

def seg_sum(vals, group, num_segments):
    return jax.ops.segment_sum(vals, group, num_segments=num_segments + 1)[
        :num_segments
    ]


def seg_min(vals, group, num_segments):
    return jax.ops.segment_min(vals, group, num_segments=num_segments + 1)[
        :num_segments
    ]


def seg_max(vals, group, num_segments):
    return jax.ops.segment_max(vals, group, num_segments=num_segments + 1)[
        :num_segments
    ]
