"""Pipeline compiler: a chain of row-level operators becomes ONE
jitted XLA program.

The analog of the reference's compiled pipelines: Trino JIT-compiles
query-specific operator internals per pipeline
(ExpressionCompiler/PageFunctionCompiler, MAIN/sql/gen/) and runs them
page-at-a-time through Driver's pull loop (MAIN/operator/Driver.java:367).
On TPU the batch IS the table, so the whole chain
Filter -> Project -> Aggregate -> Sort -> Limit fuses into a single
XLA computation: one device dispatch, one result, no per-operator
round trips (which dominate when the device link has latency).

Chains break at joins/exchanges (data-dependent capacities need a host
decision) — those are the stage boundaries, exactly where the
reference splits pipelines.

Aggregations inside a chain carry a static slot-table capacity; the
program returns per-aggregate overflow flags and the caller re-builds
with an 8x larger table when one trips (FlatHash rehash analog).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace as dc_replace

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import telemetry, types as T
from trino_tpu.exec import kernels as K
from trino_tpu.exec.aggregates import (
    VARIANCE_FNS,
    compute_aggregate,
    dev_hash64,
)
from trino_tpu.expr.ir import InputRef
from trino_tpu.expr.compiler import ColumnLayout, compile_expr
from trino_tpu.page import StringDictionary, content_hash64, pad_capacity
from trino_tpu.plan import nodes as P

__all__ = ["FUSABLE", "ChainLayout", "plan_capacities", "build_chain"]

#: node types that fuse into one program (single-source, static shapes).
#: Exchange is a stage boundary (collective / gather), never fused.
FUSABLE = (P.Filter, P.Project, P.Aggregate, P.Sort, P.TopN, P.Limit)


@dataclass
class ChainLayout:
    """Host-side metadata flowing through the chain builder."""

    names: list[str]
    types: dict[str, T.DataType]
    dicts: dict[str, StringDictionary | None]
    capacity: int
    #: hash-coded varchar pools by symbol (data = [cap,2] hash+id)
    pools: dict = field(default_factory=dict)
    #: ARRAY-column pools by symbol (page.ArrayPool)
    arrays: dict = field(default_factory=dict)

    def expr_layout(self) -> ColumnLayout:
        return ColumnLayout(
            types=dict(self.types), dictionaries=dict(self.dicts),
            array_pools=dict(self.arrays),
        )


def _norm_opt(data, valid):
    """normalize_key, with the null flag elided (None) for columns
    that cannot be NULL — saves a sort pass + compare in sort_group."""
    bits, flag = K.normalize_key(data, valid)
    return bits, (None if valid is None else flag)


def _key_width(t: T.DataType, dictionary, value_range=None) -> int:
    """Bit width that injectively covers a key column's values — lets
    sort_group pack several keys into one u64 sort pass. An EXACT
    ``value_range`` (lo, hi) from stats narrows the width to
    bit_length(hi - lo): the caller shifts the column by lo first
    (value-range key packing)."""
    if dictionary is not None:
        return max(1, len(dictionary).bit_length())
    if isinstance(t, T.DecimalType) and t.is_long:
        raise NotImplementedError(
            "GROUP BY / DISTINCT on decimal(38) keys"
        )
    if isinstance(t, T.BooleanType):
        return 1
    dt = np.dtype(t.np_dtype)
    full = min(dt.itemsize * 8, 64)
    if value_range is not None:
        lo, hi = value_range
        return min(max(1, int(hi - lo).bit_length()), full)
    return full


def _shift_key(data, valid, value_range):
    """Shift an integer key column to its range origin so the low
    bit_length(hi-lo) bits are injective. Dead/NULL rows may wrap —
    they are excluded from grouping by liveness/null flags."""
    if value_range is None:
        return data, valid
    lo, _hi = value_range
    if lo == 0:
        return data, valid
    return data - jnp.asarray(lo, dtype=data.dtype), valid


def _bcast(data, valid, capacity):
    if jnp.ndim(data) == 0:
        data = jnp.broadcast_to(data, (capacity,))
    if valid is not None and jnp.ndim(valid) == 0:
        valid = jnp.broadcast_to(valid, (capacity,))
    return data, valid


def plan_capacities(
    chain: list[P.PlanNode], in_capacity: int, n_shards: int = 1
) -> dict[int, list[int]]:
    """Initial [capacity, max_capacity] per Aggregate position.

    With stats (``est_groups`` from plan.stats.annotate) the group
    table starts at the estimated distinct count — overflow retries
    become the exception, not the warm-up path (the reference reserves
    FlatHash capacity from connector stats the same way). FINAL/SINGLE
    steps in a sharded chain see only their hash partition of the key
    space, so the estimate divides by the shard count (×1.5 margin for
    partition imbalance); PARTIAL steps may see every key on every
    shard. Estimates being wrong is safe: the overflow flag still
    triggers the retry-larger loop."""
    caps: dict[int, list[int]] = {}
    cap = in_capacity
    for i, nd in enumerate(chain):
        if isinstance(nd, P.Aggregate):
            if not nd.group_keys:
                caps[i] = [1, 1]
                cap = 8
            else:
                from trino_tpu.exec import shapes

                max_cap = pad_capacity(max(2 * cap, 8))
                if nd.est_groups is not None:
                    est = nd.est_groups
                    if n_shards > 1 and nd.step in ("FINAL", "SINGLE"):
                        est = est / n_shards * 1.5
                    start = shapes.table_bucket(est, max_cap)
                else:
                    start = min(
                        shapes.bucket(
                            max(cap // 16, 1024), site="agg-table"
                        ),
                        max_cap,
                    )
                caps[i] = [start, max_cap]
                cap = start
        elif isinstance(nd, P.TopN):
            from trino_tpu.exec import shapes

            cap = shapes.bucket(min(nd.count, cap), site="topn")
    return caps


def build_chain(chain: list[P.PlanNode], layout: ChainLayout, caps: dict[int, list[int]]):
    """Build (fn, out_layout): ``fn(env, mask) -> (env', mask', flags)``
    is pure and jittable; ``flags`` maps chain position -> overflow
    scalar for each grouped Aggregate."""
    # each build feeds a fresh trace to jax.jit downstream: the count,
    # against trino_xla_compile_total, shows how much chain churn turns
    # into real backend compiles vs jit-cache hits
    telemetry.CHAINS_BUILT.inc()
    steps = []
    for i, nd in enumerate(chain):
        # positional scope label: jax.named_scope stamps it into the
        # per-instruction HLO op_name metadata (fusions included), so
        # a captured device profile can attribute time to this plan
        # operator INSIDE the fused program (kernel observatory)
        scope = f"op{i}:{type(nd).__name__}"
        if isinstance(nd, P.Filter):
            steps.append((scope, _filter_step(nd, layout)))
        elif isinstance(nd, P.Project):
            step, layout = _project_step(nd, layout)
            steps.append((scope, step))
        elif isinstance(nd, P.Aggregate):
            step, layout = _aggregate_step(nd, layout, caps[i][0], i)
            steps.append((scope, step))
        elif isinstance(nd, (P.Sort, P.TopN)):
            step, layout = _sort_step(nd, layout)
            steps.append((scope, step))
        elif isinstance(nd, P.Limit):
            steps.append((scope, _limit_step(nd)))
        else:
            raise NotImplementedError(type(nd).__name__)

    def fn(env, mask):
        flags = {}
        for scope, step in steps:
            with jax.named_scope(scope):
                env, mask, flags = step(env, mask, flags)
        return env, mask, flags

    return fn, layout


def _filter_step(nd: P.Filter, layout: ChainLayout):
    compiled = compile_expr(nd.predicate, layout.expr_layout())

    def step(env, mask, flags):
        data, valid = compiled.fn(env)
        keep = data if valid is None else (data & valid)
        return env, mask & keep, flags

    return step


def _project_step(nd: P.Project, layout: ChainLayout):
    compiled = {
        sym: compile_expr(e, layout.expr_layout())
        for sym, e in nd.assignments.items()
    }
    cap = layout.capacity
    from trino_tpu.expr.ir import InputRef as _Ref

    out_layout = ChainLayout(
        names=list(nd.assignments),
        types={s: e.type for s, e in nd.assignments.items()},
        dicts={s: c.dictionary for s, c in compiled.items()},
        capacity=cap,
        pools={
            s: layout.pools.get(e.name)
            for s, e in nd.assignments.items()
            if isinstance(e, _Ref) and layout.pools.get(e.name) is not None
        },
        arrays={
            s: (
                compiled[s].pool
                if compiled[s].pool is not None
                else layout.arrays.get(e.name)
                if isinstance(e, _Ref) else None
            )
            for s, e in nd.assignments.items()
            if compiled[s].pool is not None
            or (isinstance(e, _Ref) and layout.arrays.get(e.name) is not None)
        },
    )

    def step(env, mask, flags):
        env2 = {
            sym: _bcast(*c.fn(env), cap) for sym, c in compiled.items()
        }
        return env2, mask, flags

    return step, out_layout


def _aggregate_step(nd: P.Aggregate, layout: ChainLayout, capacity: int, pos: int):
    is_global = not nd.group_keys
    expr_layout = layout.expr_layout()
    agg_meta = []
    for sym, call in nd.aggregates.items():
        arg_c = [compile_expr(a, expr_layout) for a in call.args] or None
        filter_c = (
            compile_expr(call.filter, expr_layout)
            if call.filter is not None else None
        )
        agg_meta.append((sym, call, arg_c, filter_c))
    group_keys = list(nd.group_keys)
    in_cap = layout.capacity
    out_cap = 8 if is_global else capacity

    # approx_distinct hashes VALUES, not codes: dictionary/pool columns
    # get a compile-time content-hash table (deterministic across
    # processes — fleet partial states must merge consistently), gather
    # replaces codes with hashes inside the traced step.
    hll_tables = {}
    for sym, call, arg_c, _f in agg_meta:
        if call.name not in ("approx_distinct", "approx_distinct_partial"):
            continue
        if not arg_c:
            continue
        d = arg_c[0].dictionary
        if d is not None:
            vals = d.values
            tbl = (
                content_hash64(vals) if len(vals)
                else np.zeros(1, dtype=np.uint64)
            )
            hll_tables[sym] = ("code", jnp.asarray(tbl))
        else:
            a0 = call.args[0]
            pool = (
                layout.pools.get(a0.name)
                if isinstance(a0, InputRef) else None
            )
            if pool is not None:
                tbl = (
                    content_hash64(pool.values) if len(pool.values)
                    else np.zeros(1, dtype=np.uint64)
                )
                hll_tables[sym] = ("id", jnp.asarray(tbl))

    out_layout = ChainLayout(
        names=group_keys + [sym for sym, *_ in agg_meta],
        types={
            **{s: layout.types[s] for s in group_keys},
            **{sym: call.type for sym, call, *_ in agg_meta},
        },
        dicts={
            **{s: layout.dicts[s] for s in group_keys},
            **{
                sym: (arg_c[0].dictionary if isinstance(call.type, T.VarcharType) and arg_c else None)
                for sym, call, arg_c, _ in agg_meta
            },
        },
        capacity=out_cap,
        pools={
            s: layout.pools[s]
            for s in group_keys if layout.pools.get(s) is not None
        },
    )

    key_ranges = nd.key_ranges or {}

    def step(env, mask, flags):
        if is_global:
            info = None
            widths = ()
            shifted = []
            out_mask = jnp.zeros((8,), dtype=jnp.bool_).at[0].set(True)
            env2 = {}
        else:
            shifted = []
            width_list = []
            for s in group_keys:
                data, valid = env[s]
                if layout.pools.get(s) is not None:
                    # hash-coded varchar: the hash lane IS the key (the
                    # id lane is row identity, not value identity)
                    shifted.append((data[:, 0], valid))
                    width_list.append(64)
                    continue
                shifted.append(_shift_key(data, valid, key_ranges.get(s)))
                width_list.append(
                    _key_width(
                        layout.types[s], layout.dicts.get(s),
                        key_ranges.get(s),
                    )
                )
            norm = [_norm_opt(d, v) for d, v in shifted]
            widths = tuple(width_list)
            info = K.sort_group(
                tuple(b for b, _ in norm),
                tuple(fl for _, fl in norm),
                mask, capacity, widths=widths,
            )
            flags = {**flags, pos: info.num_groups > capacity}
            env2 = {}
            occupied = (
                jnp.arange(capacity, dtype=jnp.int32) < info.num_groups
            )
            own = jnp.clip(info.owner, 0, in_cap - 1)
            for s in group_keys:
                data, valid = env[s]
                env2[s] = (
                    data[own],
                    None if valid is None else (valid[own] & occupied),
                )
            out_mask = occupied
        cap_seg = 1 if is_global else capacity
        share = {"#mask": mask}  # per-step cache of sorted cols/counts
        prepared = []
        for sym, call, arg_c, filter_c in agg_meta:
            arg = None
            contrib = mask
            if arg_c is not None:
                vals = [_bcast(*c.fn(env), in_cap) for c in arg_c]
                arg = vals[0] if len(vals) == 1 else vals
            if (
                call.name in VARIANCE_FNS
                and isinstance(call.args[0].type, T.DecimalType)
            ):
                # variance of DECIMAL is computed as DOUBLE over true
                # values, not unscaled ints (reference:
                # DoubleVarianceAggregation via implicit cast)
                d, v = arg
                arg = (
                    d.astype(jnp.float64)
                    / (10.0 ** call.args[0].type.scale),
                    v,
                )
            if call.name in ("approx_distinct", "approx_distinct_partial"):
                d, v = arg
                tb = hll_tables.get(sym)
                if tb is not None:
                    kind, table = tb
                    idx = d[:, 1] if kind == "id" else d
                    lane = table[
                        jnp.clip(
                            idx.astype(jnp.int32), 0, table.shape[0] - 1
                        )
                    ]
                elif jnp.ndim(d) == 2 and isinstance(
                    call.args[0].type, T.VarcharType
                ):
                    # pool column without a reachable pool object:
                    # process-local hash lane (correct in-process)
                    lane = d[:, 0].astype(jnp.uint64)
                else:
                    lane = dev_hash64(d)
                arg = (lane, v)
            if filter_c is not None:
                fd, fv = filter_c.fn(env)
                contrib = contrib & (fd if fv is None else (fd & fv))
            if call.distinct:
                d_arg = arg
                if (
                    isinstance(call.args[0].type, T.VarcharType)
                    and jnp.ndim(arg[0]) == 2
                ):
                    # hash-coded varchar: dedupe on the hash lane
                    d_arg = (arg[0][:, 0], arg[1])
                    dwidth = 64
                else:
                    dwidth = _key_width(
                        call.args[0].type, arg_c[0].dictionary
                    )
                # shifted key pairs match the narrowed widths
                contrib = _dedupe(list(shifted), d_arg, contrib, in_cap,
                                  widths + (dwidth,))
            prepared.append((sym, call, arg, contrib))
        if info is not None:
            _presort_shared(prepared, info, share)
        for sym, call, arg, contrib in prepared:
            data, valid = compute_aggregate(
                call.name, call.type, arg, info, cap_seg, contrib,
                share=share,
            )
            if is_global:
                data = _pad_to(data, 8)
                valid = None if valid is None else _pad_to(valid, 8)
            env2[sym] = (data, valid)
        return env2, out_mask, flags

    return step, out_layout


def _presort_shared(prepared, info, share):
    """Gather every column the step's aggregates need into group-sorted
    order in as few device gathers as possible: same-dtype columns are
    stacked [n, k] and gathered once (a stacked gather costs barely
    more than a single-column one on TPU), then unstacked into the
    ``share`` cache that ``compute_aggregate``'s reducers consult.
    Mirrors the cache keys of aggregates._Reducer exactly."""
    items: dict[int, object] = {}

    def want(x):
        if x is not None and id(x) not in items:
            items[id(x)] = x

    for _sym, _call, arg, contrib in prepared:
        eff = contrib
        if isinstance(arg, list):
            for pair in arg:
                d, v = pair
                if v is not None:
                    key = ("nulled", id(d), id(v))
                    hit = share.get(key)
                    if hit is None:
                        hit = (
                            d, v,
                            jnp.where(v, d, jnp.zeros((), dtype=d.dtype)),
                        )
                        share[key] = hit
                    want(hit[2])
                else:
                    want(d)
        elif arg is not None:
            d, v = arg
            want(d)
            if v is not None:
                key = ("and", id(contrib), id(v))
                hit = share.get(key)
                if hit is None:
                    hit = (contrib, v, contrib & v)
                    share[key] = hit
                eff = hit[2]
        want(eff)

    by_dtype: dict[tuple, list] = {}
    for x in items.values():
        # key on trailing dims too: multi-lane states ([n, k] sketches,
        # [n, 2] decimal limbs) cannot stack with 1-D columns
        by_dtype.setdefault((str(x.dtype), x.shape[1:]), []).append(x)
    for xs in by_dtype.values():
        if len(xs) == 1:
            x = xs[0]
            share[("sorted", id(x))] = (x, x[info.perm])
        else:
            stacked = jnp.stack(xs, axis=1)[info.perm]
            for i, x in enumerate(xs):
                share[("sorted", id(x))] = (x, stacked[:, i])


def _dedupe(key_cols, arg, live, page_capacity, widths=None):
    """DISTINCT: keep one representative row per (group keys, value).

    Sort-based grouping is exact and dense, so a capacity equal to the
    page capacity can never overflow (num_groups <= live rows)."""
    data, valid = arg
    live_d = live if valid is None else (live & valid)
    norm = [_norm_opt(d, v) for d, v in key_cols]
    # the dedupe key value itself: NULL rows are excluded via live_d,
    # so the flag is never needed
    norm.append((_norm_opt(data, valid)[0], None))
    cap2 = page_capacity
    info2 = K.sort_group(
        tuple(b for b, _ in norm), tuple(fl for _, fl in norm), live_d, cap2,
        widths=widths,
    )
    row_idx = jnp.arange(page_capacity, dtype=jnp.int32)
    rep = live_d & (
        info2.owner[jnp.clip(info2.group, 0, cap2 - 1)] == row_idx
    )
    return rep


def _sort_step(nd, layout: ChainLayout):
    keys = []
    for k in nd.keys:
        nulls_first = k.nulls_first
        if nulls_first is None:
            # reference default: nulls are largest (ASC last, DESC first)
            nulls_first = not k.ascending
        keys.append((k.symbol, k.ascending, nulls_first))
    is_topn = isinstance(nd, P.TopN)
    in_cap = layout.capacity
    out_cap = pad_capacity(min(nd.count, in_cap)) if is_topn else in_cap
    out_layout = dc_replace(layout, capacity=out_cap) if is_topn else layout
    limit = out_cap if out_cap < in_cap else None
    count = nd.count if is_topn else None

    def step(env, mask, flags):
        sort_keys = []
        for s, asc, nf in keys:
            data, valid = env[s]
            if jnp.ndim(data) == 2:
                # two-limb decimal: hi is the major key, lo minor
                # (canonical lo in [0, 2^32) sorts correctly as int64)
                sort_keys.append((data[:, 0], valid, asc, nf))
                sort_keys.append((data[:, 1], valid, asc, nf))
            else:
                sort_keys.append((data, valid, asc, nf))
        perm = K.sort_perm(sort_keys, mask)
        if limit is not None:
            perm = perm[:limit]
        env2 = {}
        for s, (data, valid) in env.items():
            env2[s] = (data[perm], None if valid is None else valid[perm])
        mask2 = mask[perm]
        if count is not None:
            mask2 = mask2 & (jnp.arange(mask2.shape[0]) < count)
        return env2, mask2, flags

    return step, out_layout


def _limit_step(nd: P.Limit):
    def step(env, mask, flags):
        rank = jnp.cumsum(mask.astype(jnp.int64))
        keep = mask & (rank > nd.offset)
        if nd.count >= 0:
            keep = keep & (rank <= nd.offset + nd.count)
        return env, keep, flags

    return step


def _pad_to(arr: jnp.ndarray, capacity: int) -> jnp.ndarray:
    n = arr.shape[0]
    if n >= capacity:
        return arr[:capacity]
    pad_shape = (capacity - n,) + arr.shape[1:]  # limb columns are 2D
    return jnp.concatenate([arr, jnp.zeros(pad_shape, dtype=arr.dtype)])
