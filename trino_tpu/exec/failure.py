"""Failure injection + stage retry (legacy mesh-executor surface).

The analog of the reference's FailureInjector + task-retry unit
(MAIN/execution/FailureInjector.java:39, injectTaskFailure:61; retry
semantics of EventDrivenFaultTolerantQueryScheduler,
MAIN/execution/scheduler/faulttolerant/): tests arm failures for a
stage tag and attempt range; the mesh executor consults the injector
before each stage-shard program and re-invokes the program on an
injected failure. The retry unit works because stage inputs are
retained device arrays — "spooled stage output" in the reference maps
to XLA buffers that outlive the failed invocation here.

Since the unified chaos framework landed this module is a thin
adapter over ``trino_tpu.fault``: ``FailureInjector`` maps its stage
tags onto the ``task-exec`` site of a seeded ``FaultInjector`` (so a
chaos run can arm mesh-stage failures alongside spool/RPC faults from
one injector spec), while keeping the original two-argument
``check(tag, attempt)`` call shape and ``injected``/``attempts`` log
formats that the mesh executor and its tests were built against.
"""

from __future__ import annotations

from trino_tpu.fault import FaultInjector, InjectedFault

__all__ = ["InjectedFailure", "FailureInjector"]


class InjectedFailure(InjectedFault):
    """A test-armed failure (InjectionType.TASK_FAILURE analog)."""


class FailureInjector(FaultInjector):
    """Stage-tag adapter over the unified injector: all rules live on
    the ``task-exec`` site, and ``check`` takes (tag, attempt)."""

    fault_cls = InjectedFailure
    SITE = "task-exec"

    def __init__(self, max_attempts: int = 4, seed: int = 0):
        super().__init__(seed=seed, max_attempts=max_attempts)
        #: log of (tag, attempt) stage executions that ran (armed runs
        #: only — the unarmed fast path keeps zero bookkeeping)
        self.attempts: list[tuple[str, int]] = []

    def fail_stage(self, tag: str, times: int = 1):
        """Arm ``times`` consecutive failures for stages whose tag
        starts with ``tag`` (attempts 0..times-1 fail; the retry at
        attempt ``times`` succeeds)."""
        self.arm(self.SITE, tag=tag, times=times)

    def reset(self):
        super().reset()
        self.attempts.clear()

    def check(self, tag: str = "", attempt: int = 0):  # noqa: D102
        if not self._rules:
            return  # production fast path: no bookkeeping, no lock
        self.attempts.append((tag, attempt))
        super().check(self.SITE, tag=tag, attempt=attempt)
