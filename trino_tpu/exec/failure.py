"""Failure injection + stage retry.

The analog of the reference's FailureInjector + task-retry unit
(MAIN/execution/FailureInjector.java:39, injectTaskFailure:61; retry
semantics of EventDrivenFaultTolerantQueryScheduler,
MAIN/execution/scheduler/faulttolerant/): tests arm failures for a
stage tag and attempt range; the mesh executor consults the injector
before each stage-shard program and re-invokes the program on an
injected failure. The retry unit works because stage inputs are
retained device arrays — "spooled stage output" in the reference maps
to XLA buffers that outlive the failed invocation here.
"""

from __future__ import annotations

import threading

__all__ = ["InjectedFailure", "FailureInjector"]


class InjectedFailure(RuntimeError):
    """A test-armed failure (InjectionType.TASK_FAILURE analog)."""


class FailureInjector:
    def __init__(self, max_attempts: int = 4):
        self.max_attempts = max_attempts
        self._rules: dict[str, int] = {}
        self._lock = threading.Lock()
        #: log of (tag, attempt) failures actually injected
        self.injected: list[tuple[str, int]] = []
        #: log of (tag, attempt) stage executions that ran
        self.attempts: list[tuple[str, int]] = []

    def fail_stage(self, tag: str, times: int = 1):
        """Arm ``times`` consecutive failures for stages whose tag
        starts with ``tag`` (attempts 0..times-1 fail; the retry at
        attempt ``times`` succeeds)."""
        with self._lock:
            self._rules[tag] = times

    def reset(self):
        with self._lock:
            self._rules.clear()
            self.injected.clear()
            self.attempts.clear()

    def check(self, tag: str, attempt: int):
        if not self._rules:
            return  # production fast path: no bookkeeping, no lock
        with self._lock:
            self.attempts.append((tag, attempt))
            for rule, times in self._rules.items():
                if tag.startswith(rule) and attempt < times:
                    self.injected.append((tag, attempt))
                    raise InjectedFailure(
                        f"injected failure: stage {tag!r} attempt {attempt}"
                    )
