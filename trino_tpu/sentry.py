"""Performance sentry: per-plan baselines + live attributed anomalies.

The online counterpart of ``tools/bench_gate.py``: the gate catches
regressions offline against hand-committed BENCH baselines, the sentry
catches them in serving traffic against each plan shape's OWN history.
On every completed statement it:

1. folds the query's wall clock into a rolling robust baseline keyed
   by (plan digest, session-property fingerprint) — median + MAD, a
   warmup minimum before any verdict, bounded retention;
2. when a warmed baseline exists and the query ran anomalously slow,
   names the **driver** — not just "slow" but WHICH flight-recorder
   bucket grew (xla_compile storm vs scan vs exchange vs
   straggler_slack), or ``cache_miss_expected_hit`` when a plan that
   reliably served from the result cache suddenly missed;
3. emits a typed :class:`AnomalyVerdict`, counts it in
   ``trino_anomalies_total{driver=...}``, and captures a diagnostics
   bundle for the anomalous-but-*successful* query (failures already
   get bundles; a silent 3× slowdown deserves the same post-mortem).

Baselines are rebuilt from :mod:`trino_tpu.history`'s JSONL on
startup, so a coordinator restart keeps its learned normal instead of
re-warming from scratch.

Thresholds (all env-tunable) are deliberately conservative — the
contract is zero false positives on a healthy repeat:

* ``TRINO_TPU_SENTRY_MIN_SAMPLES`` (default 5): verdicts only after
  this many clean samples per key;
* ``TRINO_TPU_SENTRY_MADS`` (default 5.0): wall must exceed
  median + MADS × scaled-MAD;
* ``TRINO_TPU_SENTRY_MIN_RATIO`` (default 1.5): AND exceed this
  multiple of the median (a tight MAD alone would flag micro-noise);
* ``TRINO_TPU_SENTRY_MIN_DELTA_MS`` (default 50): AND be this many
  absolute ms over the median (sub-50ms regressions are not worth a
  bundle).

Anomalous samples are NOT folded into the baseline — a regression
must keep looking like one until it is fixed, not become the new
normal after ``retention`` occurrences.

``TRINO_TPU_SENTRY=0`` disables the listener entirely.
"""

from __future__ import annotations

import dataclasses
import os
import statistics
import threading
import time
from collections import deque
from dataclasses import dataclass

from trino_tpu import events, history, telemetry

__all__ = [
    "AnomalyVerdict", "BaselineModel", "Sentry", "SentryListener",
    "active", "set_active", "ensure_installed", "enabled",
    "baseline_footer",
]

#: 1.4826 × MAD estimates the standard deviation for normal data —
#: the usual robust-scale constant
_MAD_SCALE = 1.4826

#: buckets eligible for driver attribution, checked in breakdown
#: order; "other" is a last resort (it names unattributed wall, which
#: is a finding too — "driver: other" means the flight recorder could
#: not see the regression, itself actionable)
_DRIVER_BUCKETS = (
    "queued", "slot_wait", "planning", "xla_compile",
    "admission_wait", "scan", "compute", "exchange",
    "straggler_slack", "other",
)


def enabled() -> bool:
    return os.environ.get("TRINO_TPU_SENTRY", "1") not in ("0", "off", "OFF")


@dataclass(frozen=True)
class AnomalyVerdict:
    """One attributed completion-time anomaly."""

    query_id: str
    ts: float
    plan_digest: str
    fingerprint: str
    wall_ms: float
    baseline_p50_ms: float
    baseline_mad_ms: float
    ratio: float
    #: the bucket that grew the most vs its own baseline median (or
    #: ``cache_miss_expected_hit`` when a reliably-cached plan missed)
    driver: str
    #: how many ms the driver bucket grew vs its baseline median
    driver_delta_ms: float
    samples: int
    message: str

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class BaselineModel:
    """Rolling robust stats for one (plan digest, fingerprint) key.

    Keeps the last ``retention`` clean samples: wall clock, the bucket
    decomposition, and whether the result cache served the statement.
    """

    def __init__(self, retention: int = 64):
        self.retention = max(2, int(retention))
        self._walls: deque[float] = deque(maxlen=self.retention)
        self._buckets: deque[dict] = deque(maxlen=self.retention)
        self._result_hits: deque[bool] = deque(maxlen=self.retention)

    def observe(self, wall_ms: float, buckets: dict | None,
                cache_hit_tier: str | None) -> None:
        self._walls.append(float(wall_ms))
        self._buckets.append(dict(buckets or {}))
        self._result_hits.append(cache_hit_tier == "result")

    @property
    def samples(self) -> int:
        return len(self._walls)

    def p50(self) -> float:
        return statistics.median(self._walls) if self._walls else 0.0

    def mad(self) -> float:
        """Median absolute deviation of the wall samples."""
        if len(self._walls) < 2:
            return 0.0
        med = self.p50()
        return statistics.median(abs(w - med) for w in self._walls)

    def bucket_median(self, name: str) -> float:
        vals = [float(b.get(name, 0.0) or 0.0) for b in self._buckets]
        return statistics.median(vals) if vals else 0.0

    def result_hit_rate(self) -> float:
        if not self._result_hits:
            return 0.0
        return sum(self._result_hits) / len(self._result_hits)


class Sentry:
    """Baseline store + completion-time anomaly detector."""

    def __init__(
        self,
        history_store: history.QueryHistory | None = None,
        *,
        min_samples: int | None = None,
        mads: float | None = None,
        min_ratio: float | None = None,
        min_delta_ms: float | None = None,
        retention: int | None = None,
        max_anomalies: int = 256,
    ):
        env = os.environ.get
        self.min_samples = int(
            min_samples if min_samples is not None
            else env("TRINO_TPU_SENTRY_MIN_SAMPLES", "") or 5
        )
        self.mads = float(
            mads if mads is not None
            else env("TRINO_TPU_SENTRY_MADS", "") or 5.0
        )
        self.min_ratio = float(
            min_ratio if min_ratio is not None
            else env("TRINO_TPU_SENTRY_MIN_RATIO", "") or 1.5
        )
        self.min_delta_ms = float(
            min_delta_ms if min_delta_ms is not None
            else env("TRINO_TPU_SENTRY_MIN_DELTA_MS", "") or 50.0
        )
        self.retention = int(
            retention if retention is not None
            else env("TRINO_TPU_SENTRY_RETENTION", "") or 64
        )
        self._lock = threading.Lock()
        self._models: dict[tuple[str, str], BaselineModel] = {}
        self._anomalies: deque[AnomalyVerdict] = deque(
            maxlen=max_anomalies
        )
        if history_store is not None:
            self.reload(history_store)

    # ---- baseline persistence --------------------------------------
    def reload(self, store: history.QueryHistory) -> int:
        """Rebuild baselines by replaying the history store (restart
        path). Replay never emits verdicts — the past was already
        judged when it happened — but it DOES re-judge: a sample that
        was anomalous then is still excluded from the baseline now,
        so a restart cannot launder a regression into the normal."""
        n = 0
        for entry in store.entries():
            if entry.get("state") != "FINISHED":
                continue
            key = self._key(entry)
            if key is None:
                continue
            with self._lock:
                model = self._models.get(key)
            if model is not None and model.samples >= self.min_samples:
                wall = float(entry.get("wall_ms", 0.0) or 0.0)
                if self._judge(entry, key, model, wall) is not None:
                    continue
            if self._feed(entry):
                n += 1
        return n

    def _key(self, entry: dict) -> tuple[str, str] | None:
        digest = entry.get("plan_digest")
        if not digest:
            return None
        return (str(digest), str(entry.get("fingerprint") or ""))

    def _feed(self, entry: dict) -> bool:
        """Fold one clean FINISHED record into its baseline."""
        if entry.get("state") != "FINISHED":
            return False
        key = self._key(entry)
        if key is None:
            return False
        with self._lock:
            model = self._models.get(key)
            if model is None:
                model = self._models[key] = BaselineModel(self.retention)
            model.observe(
                float(entry.get("wall_ms", 0.0) or 0.0),
                entry.get("buckets"),
                entry.get("cache_hit_tier"),
            )
        return True

    # ---- detection -------------------------------------------------
    def model_for(self, plan_digest: str,
                  fingerprint: str = "") -> BaselineModel | None:
        with self._lock:
            return self._models.get((str(plan_digest), str(fingerprint)))

    def compare(self, plan_digest: str | None, fingerprint: str,
                wall_ms: float) -> dict | None:
        """Non-judging baseline lookup (the EXPLAIN ANALYZE footer):
        ``{"p50_ms", "ratio", "samples", "warm"}`` or None when the
        plan shape has no history at all."""
        if not plan_digest:
            return None
        model = self.model_for(plan_digest, fingerprint)
        if model is None or model.samples == 0:
            return None
        p50 = model.p50()
        return {
            "p50_ms": round(p50, 3),
            "ratio": round(wall_ms / p50, 3) if p50 > 0 else 0.0,
            "samples": model.samples,
            "warm": model.samples >= self.min_samples,
        }

    def observe(self, entry: dict) -> AnomalyVerdict | None:
        """Judge one completed-query record, then (when clean) fold it
        into its baseline. Returns the verdict for an anomalous
        FINISHED query; failures and warmup samples return None."""
        if entry.get("state") != "FINISHED":
            return None  # failures get bundles through their own path
        key = self._key(entry)
        if key is None:
            return None
        wall = float(entry.get("wall_ms", 0.0) or 0.0)
        with self._lock:
            model = self._models.get(key)
        verdict = None
        if model is not None and model.samples >= self.min_samples:
            verdict = self._judge(entry, key, model, wall)
        if verdict is None:
            self._feed(entry)
        else:
            with self._lock:
                self._anomalies.append(verdict)
            telemetry.ANOMALIES.inc(driver=verdict.driver)
        return verdict

    def _judge(self, entry: dict, key: tuple[str, str],
               model: BaselineModel, wall: float
               ) -> AnomalyVerdict | None:
        p50 = model.p50()
        mad = model.mad()
        band = p50 + self.mads * _MAD_SCALE * mad
        anomalous = (
            wall > band
            and p50 > 0
            and wall / p50 >= self.min_ratio
            and wall - p50 >= self.min_delta_ms
        )
        if not anomalous:
            return None
        driver, delta = self._attribute(entry, model)
        ratio = wall / p50 if p50 > 0 else 0.0
        return AnomalyVerdict(
            query_id=str(entry.get("query_id") or ""),
            ts=time.time(),
            plan_digest=key[0],
            fingerprint=key[1],
            wall_ms=round(wall, 3),
            baseline_p50_ms=round(p50, 3),
            baseline_mad_ms=round(mad, 3),
            ratio=round(ratio, 3),
            driver=driver,
            driver_delta_ms=round(delta, 3),
            samples=model.samples,
            message=(
                f"{ratio:.1f}x baseline p50 "
                f"({wall:.0f} ms vs {p50:.0f} ms over "
                f"{model.samples} samples), driver: {driver} "
                f"(+{delta:.0f} ms)"
            ),
        )

    def _attribute(self, entry: dict,
                   model: BaselineModel) -> tuple[str, float]:
        """Name the bucket that grew the most vs its own baseline
        median — the flight-recorder decomposition makes 'slow' say
        WHERE. A plan that reliably hit the result cache and suddenly
        missed is its own driver class: every bucket grew, but the
        cause is the miss, not any one of them."""
        wall = float(entry.get("wall_ms", 0.0) or 0.0)
        if (
            model.result_hit_rate() >= 0.8
            and entry.get("cache_hit_tier") != "result"
        ):
            return (
                "cache_miss_expected_hit",
                max(wall - model.p50(), 0.0),
            )
        buckets = entry.get("buckets") or {}
        best, best_delta = "other", 0.0
        for name in _DRIVER_BUCKETS:
            delta = (
                float(buckets.get(name, 0.0) or 0.0)
                - model.bucket_median(name)
            )
            if delta > best_delta:
                best, best_delta = name, delta
        return best, best_delta

    # ---- reading ---------------------------------------------------
    def anomalies(self, limit: int | None = None) -> list[AnomalyVerdict]:
        with self._lock:
            out = list(self._anomalies)
        if limit is not None and limit >= 0:
            out = out[-limit:]
        return out

    def baseline_count(self) -> int:
        with self._lock:
            return len(self._models)


class SentryListener(events.EventListener):
    """The EventListener that feeds history + sentry on every
    completed statement (both node shapes fire it), and captures a
    diagnostics bundle when a *successful* query judged anomalous."""

    def query_completed(self, event) -> None:
        if not enabled():
            return
        store = history.active()
        entry = history.entry_from_event(event)
        store.append(entry)
        verdict = active().observe(entry)
        if verdict is not None:
            self._capture_bundle(event, verdict)

    def _capture_bundle(self, event, verdict: AnomalyVerdict) -> None:
        """Post-mortem for a query that SUCCEEDED anomalously — today
        only failures get bundles, but a silent regression needs the
        same evidence (plan, trace, task stats, breakdown)."""
        from trino_tpu import diagnostics

        trace = getattr(event, "trace", None)
        bundle = diagnostics.build_bundle(
            event.query_id,
            error="",
            sql=event.sql,
            state=event.state,
            plan=getattr(event, "plan_text", None),
            trace=trace,
            task_stats=list(getattr(event, "task_stats", None) or ()),
            time_breakdown=getattr(event, "time_breakdown", None),
            extra={
                "error_class": "anomaly",
                "anomaly": verdict.to_dict(),
            },
        )
        diagnostics.record_bundle(bundle)


# ---- process-global sentry ----------------------------------------

_active: Sentry | None = None
_active_lock = threading.Lock()


def active() -> Sentry:
    """The process sentry, created on first use with baselines
    replayed from the durable history store (restart survival)."""
    global _active
    with _active_lock:
        if _active is None:
            _active = Sentry(history.active())
        return _active


def set_active(s: Sentry | None) -> None:
    """Install (or drop, for lazy re-creation) the process sentry —
    the test/bench seam."""
    global _active
    with _active_lock:
        _active = s


def ensure_installed(metadata) -> None:
    """Idempotently register the SentryListener on a Metadata's
    EventListener list. Runners call this at construction so the
    sentry observes every statement without any user configuration."""
    if not enabled():
        return
    listeners = getattr(metadata, "event_listeners", None)
    if listeners is None:
        return
    if any(isinstance(lst, SentryListener) for lst in listeners):
        return
    listeners.append(SentryListener())


def baseline_footer(plan_digest: str | None, fingerprint: str,
                    wall_ms: float, breakdown: dict | None) -> str | None:
    """The EXPLAIN ANALYZE footer line ("vs baseline: 2.3x p50,
    driver: xla_compile"), or None when no baseline exists yet. The
    current statement is judged against history that does NOT yet
    include it (the footer renders before completion fires)."""
    if not enabled():
        return None
    sen = active()
    cmp = sen.compare(plan_digest, fingerprint, wall_ms)
    if cmp is None:
        return None
    if not cmp["warm"]:
        return (
            f"vs baseline: warming "
            f"({cmp['samples']}/{sen.min_samples} samples)"
        )
    line = f"vs baseline: {cmp['ratio']:.1f}x p50 ({cmp['p50_ms']:.0f} ms)"
    if cmp["ratio"] >= sen.min_ratio and breakdown:
        model = sen.model_for(plan_digest or "", fingerprint)
        if model is not None:
            driver, _delta = sen._attribute(
                {
                    "wall_ms": wall_ms,
                    "buckets": (breakdown or {}).get("buckets"),
                    "cache_hit_tier": None,
                },
                model,
            )
            line += f", driver: {driver}"
    return line
