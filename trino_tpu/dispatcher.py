"""Multi-query serving: a cluster-wide worker-slot pool with
fair-share dispatch and one shared status-poll reactor.

The analog of the reference's dispatch layer
(MAIN/dispatcher/DispatchManager.java:146 feeding resource groups and
QueryExecution): every PR so far ran one statement at a time through a
FleetRunner that assumed it owned the fleet. This module makes the
fleet a SHARED resource:

- ``Dispatcher`` owns the worker slots. Stage tasks from every running
  query request slots through one queue; grants are dealt
  deficit-round-robin across resource groups in proportion to
  ``ResourceGroup.weight``, so one heavy query (or group) cannot
  starve the mix while a high-weight group still gets its share.
- One poll reactor thread PER WORKER multiplexes task-status polls for
  all in-flight attempts on that worker — coordinator RPC-polling
  thread count is O(workers), not O(queries). The reactor also owns
  hung-worker detection (consecutive-timeout eviction) and dead-worker
  re-admission probing, which used to live inside each query's
  dispatch loop. Queries read cached statuses; an attempt on a worker
  declared dead surfaces as a synthetic ``LOST`` status.
- ``ServingRunner`` is the QueryRunner-compatible facade a Coordinator
  (or N embedded threads) drives concurrently: per statement it builds
  a lightweight FleetRunner wired to the shared workers, dispatcher
  and ClusterMemoryManager, so the memory-kill policy picks its victim
  among ALL live queries (the reference's low-memory killer), not just
  the one that noticed the breach.

Determinism note for chaos runs: reactor polls are free-running, so
call-count-based (``nth``) fault schedules are NOT stable under
serving concurrency; seeded ``times``/``prob`` schedules hash
(seed, site, tag, attempt) and stay order-independent — concurrent
chaos tests use those.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
import uuid
from collections import deque
from dataclasses import dataclass, field

from trino_tpu import memory, telemetry

__all__ = ["Dispatcher", "ServingRunner"]

#: synthetic status the reactor publishes for attempts stranded on a
#: worker it declared dead — the per-query loop treats it exactly like
#: the legacy "poll raised, worker evicted" path
LOST = {"state": "LOST", "error": "worker died"}


@dataclass
class _SlotTicket:
    """One outstanding request for one worker slot."""

    handle: "QueryHandle"
    enqueued_at: float


@dataclass
class Grant:
    """A slot grant: the query may post exactly one stage task to
    ``worker``; it must then bind() the posted attempt or release()."""

    worker: object  # FleetWorker
    ticket: _SlotTicket


@dataclass
class QueryHandle:
    """Per-query dispatch registration (the per-query admission queue
    on top of the group-level fair share)."""

    query_id: str
    group: str
    weight: int = 1
    #: grants dealt to this query, not yet consumed by its loop
    grants: deque = field(default_factory=deque)
    #: outstanding tickets of this handle still in the fair queue
    pending: int = 0
    #: set whenever something this query is waiting on happened (a
    #: grant was dealt, a tracked attempt went terminal/LOST); the
    #: query loop waits on this instead of fixed-cadence polling, so
    #: N queries blocked on 2 slots cost ~no CPU
    wake: threading.Event = field(default_factory=threading.Event)


class Dispatcher:
    """Shared fleet slot pool + fair-share grant queue + poll reactor.

    Thread model: all bookkeeping happens under one lock; the grant
    pump runs inline on the events that can unblock a grant (request,
    release, readmission) — no dedicated pump thread. Reactor threads
    (one per worker, named ``dispatch-poll-*``) only touch the status
    cache, worker liveness and slot releases for LOST attempts.
    """

    def __init__(
        self,
        workers,
        slots_per_worker: int = 1,
        poll_s: float = 0.02,
        rpc_timeout_s: float = 15.0,
        max_poll_fails: int = 4,
        readmit_initial_s: float = 0.5,
        readmit_max_s: float = 8.0,
        readmit_probe_timeout_s: float = 1.0,
        on_pool=None,
    ):
        self.workers = list(workers)
        #: one slot per worker by default: workers serialize all
        #: XLA/device work under their runner lock, so extra slots buy
        #: queueing on the worker, not parallelism
        self.slots_per_worker = max(int(slots_per_worker), 1)
        self.poll_s = poll_s
        self.rpc_timeout_s = rpc_timeout_s
        self.max_poll_fails = max_poll_fails
        self.readmit_initial_s = readmit_initial_s
        self.readmit_max_s = readmit_max_s
        self.readmit_probe_timeout_s = readmit_probe_timeout_s
        #: callback(worker_uri, pool_snapshot) for every status poll
        #: that carried a memory-pool snapshot (the heartbeat surface)
        self.on_pool = on_pool
        self._lock = threading.Lock()
        #: worker uri -> slots in use
        self._in_use: dict[str, int] = {u.uri: 0 for u in self.workers}
        #: group -> FIFO of slot tickets (fair share happens BETWEEN
        #: groups; within a group, requests stay FIFO — per-query
        #: interleave comes from each query keeping only as many
        #: tickets as it has dispatchable tasks)
        self._queues: dict[str, deque] = {}
        self._deficit: dict[str, float] = {}
        self._weights: dict[str, int] = {}
        self._rr: deque[str] = deque()
        #: (task_id, attempt) -> worker uri, for attempts being polled
        self._tracked: dict[tuple[str, int], str] = {}
        #: (task_id, attempt) -> owning QueryHandle, so an abnormally
        #: unwinding query's pinned slots can be swept at unregister
        self._owner: dict[tuple[str, int], QueryHandle] = {}
        #: attempts per worker still needing polls (terminal statuses
        #: drop out so the reactor never re-polls a finished task)
        self._active_by_worker: dict[str, set] = {
            u.uri: set() for u in self.workers
        }
        #: (task_id, attempt) -> latest status dict
        self._status: dict[tuple[str, int], dict] = {}
        self._stop = threading.Event()
        self._threads: dict[str, threading.Thread] = {}
        for w in self.workers:
            t = threading.Thread(
                target=self._worker_loop, args=(w,),
                name=f"dispatch-poll-{w.uri.split('//')[-1]}",
                daemon=True,
            )
            self._threads[w.uri] = t
            t.start()

    # ---- query registration ----------------------------------------------

    def register_query(
        self, query_id: str, group: str = "global", weight: int = 1
    ) -> QueryHandle:
        h = QueryHandle(query_id=query_id, group=group, weight=weight)
        with self._lock:
            if group not in self._queues:
                self._queues[group] = deque()
                self._deficit[group] = 0.0
                self._rr.append(group)
            self._weights[group] = max(int(weight), 1)
        return h

    def unregister_query(self, h: QueryHandle) -> None:
        """Drop the query's pending tickets and return any unconsumed
        grants; tracked attempts are finished by the query loop itself
        — any left over (abnormal unwind) are swept here so their
        slots return to the pool."""
        with self._lock:
            q = self._queues.get(h.group)
            if q is not None:
                kept = deque(t for t in q if t.handle is not h)
                self._queues[h.group] = kept
            h.pending = 0
            while h.grants:
                g = h.grants.popleft()
                self._release_locked(g.worker.uri)
            for key, owner in list(self._owner.items()):
                if owner is not h:
                    continue
                del self._owner[key]
                uri = self._tracked.pop(key, None)
                self._status.pop(key, None)
                if uri is not None:
                    self._active_by_worker[uri].discard(key)
                    self._release_locked(uri)
            self._publish_depth_locked()
            self._pump_locked()

    # ---- slot requests / grants ------------------------------------------

    def want(self, h: QueryHandle, n: int) -> None:
        """Declare that the query currently has ``n`` dispatchable
        tasks: tickets are topped up (or trimmed) so outstanding
        requests + unconsumed grants equals ``n``. Called every loop
        iteration, so a task leaving backoff raises the ask and a
        completed stage lowers it."""
        now = time.monotonic()
        with self._lock:
            have = h.pending + len(h.grants)
            q = self._queues[h.group]
            if n > have:
                for _ in range(n - have):
                    q.append(_SlotTicket(handle=h, enqueued_at=now))
                    h.pending += 1
            elif n < have and h.pending:
                # trim newest-first: the oldest ticket keeps its queue
                # position (and its slot-wait clock)
                drop = min(have - n, h.pending)
                kept: deque = deque()
                while q and drop:
                    t = q.pop()
                    if t.handle is h:
                        h.pending -= 1
                        drop -= 1
                    else:
                        kept.appendleft(t)
                q.extend(kept)
            self._publish_depth_locked()
            self._pump_locked()

    def take_grants(self, h: QueryHandle) -> list[Grant]:
        with self._lock:
            out = list(h.grants)
            h.grants.clear()
        return out

    def release_grant(self, g: Grant) -> None:
        with self._lock:
            self._release_locked(g.worker.uri)
            self._pump_locked()

    def bind(self, g: Grant, task_id: str, attempt: int) -> None:
        """The grant's slot now belongs to a posted attempt: the
        reactor polls it until a terminal status, and finish()
        releases the slot."""
        key = (task_id, attempt)
        with self._lock:
            self._tracked[key] = g.worker.uri
            self._active_by_worker[g.worker.uri].add(key)
            if g.ticket.handle is not None:
                self._owner[key] = g.ticket.handle

    def try_grab_idle(self, exclude=None, handle=None) -> Grant | None:
        """Immediate slot grab bypassing the fair queue — used for
        speculative hedges, which are opportunistic by design: they
        only ever consume capacity nobody queued for. ``handle``
        attributes the bound attempt for unregister-time sweeping."""
        with self._lock:
            for w in self.workers:
                if w is exclude or not w.alive or w.draining:
                    continue
                if self._in_use[w.uri] >= self.slots_per_worker:
                    continue
                self._in_use[w.uri] += 1
                return Grant(
                    worker=w,
                    ticket=_SlotTicket(
                        handle=handle, enqueued_at=time.monotonic()
                    ),
                )
        return None

    def finish(self, task_id: str, attempt: int) -> None:
        """Attempt reached a terminal state (or its post failed after
        bind): stop polling, drop the cached status, free the slot.
        Idempotent — LOST sweeps and loser-cancels may race it."""
        key = (task_id, attempt)
        with self._lock:
            uri = self._tracked.pop(key, None)
            self._status.pop(key, None)
            self._owner.pop(key, None)
            if uri is None:
                return
            self._active_by_worker[uri].discard(key)
            self._release_locked(uri)
            self._pump_locked()

    def status(self, task_id: str, attempt: int) -> dict | None:
        return self._status.get((task_id, attempt))

    def residency(self, task_id: str, attempt: int) -> str | None:
        """Worker URI an attempt is bound to (None once terminal) —
        the reactor's direct-exchange buffer-residency hint: the
        attempt's committed output partitions sit in this worker's
        buffer pool until fetched or evicted, so the scheduler ships
        this URI on consumer admissions that pin the attempt."""
        with self._lock:
            return self._tracked.get((task_id, attempt))

    def mark_dead(self, w) -> None:
        """A query's POST saw this worker die; evict it and strand its
        tracked attempts as LOST (same as a reactor-observed death)."""
        with self._lock:
            self._mark_dead_locked(w)

    def add_worker(self, w) -> None:
        """A worker joined the live cluster (membership layer): give
        it slots and a poll-reactor thread, then pump — queued tickets
        land on it immediately. Idempotent per URI: a re-join of a
        known worker just clears its unschedulable flags."""
        t = None
        with self._lock:
            for existing in self.workers:
                if existing.uri == w.uri:
                    existing.alive = True
                    existing.fails = 0
                    existing.draining = False
                    self._pump_locked()
                    return
            self.workers.append(w)
            self._in_use.setdefault(w.uri, 0)
            self._active_by_worker.setdefault(w.uri, set())
            t = threading.Thread(
                target=self._worker_loop, args=(w,),
                name=f"dispatch-poll-{w.uri.split('//')[-1]}",
                daemon=True,
            )
            self._threads[w.uri] = t
            self._pump_locked()
        t.start()

    def poll_thread_count(self) -> int:
        """Live RPC-poll reactor threads — the O(workers) invariant
        tests assert against."""
        return sum(1 for t in self._threads.values() if t.is_alive())

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads.values():
            t.join(timeout=5)

    # ---- internals (lock held) -------------------------------------------

    def _release_locked(self, uri: str) -> None:
        self._in_use[uri] = max(self._in_use[uri] - 1, 0)

    def _publish_depth_locked(self) -> None:
        for g, q in self._queues.items():
            telemetry.DISPATCH_QUEUE_DEPTH.set(len(q), group=g)

    def _free_worker_locked(self):
        for w in self.workers:
            if not w.alive or w.draining:
                continue
            if self._in_use[w.uri] < self.slots_per_worker:
                return w
        return None

    def _pump_locked(self) -> None:
        """Match free slots to queued tickets, fair-share order."""
        while True:
            w = self._free_worker_locked()
            if w is None:
                return
            t = self._next_ticket_locked()
            if t is None:
                return
            self._in_use[w.uri] += 1
            t.handle.pending -= 1
            t.handle.grants.append(Grant(worker=w, ticket=t))
            t.handle.wake.set()
            telemetry.SLOT_WAIT.observe(
                max(time.monotonic() - t.enqueued_at, 0.0)
            )
            self._publish_depth_locked()

    def _next_ticket_locked(self) -> _SlotTicket | None:
        """Deficit round-robin over groups, cost 1 per grant: a group
        is dealt ``weight`` grants per round while backlogged, but
        every backlogged group is visited each round — weights shape
        shares, they never starve."""
        if not any(self._queues[g] for g in self._rr):
            return None
        for _ in range(2 * len(self._rr) + 1):
            g = self._rr[0]
            q = self._queues[g]
            if not q:
                # no banking while idle: an empty group's unused
                # deficit does not entitle it to a later burst
                self._deficit[g] = 0.0
                self._rr.rotate(-1)
                continue
            if self._deficit[g] < 1.0:
                self._deficit[g] += self._weights.get(g, 1)
            self._deficit[g] -= 1.0
            t = q.popleft()
            if self._deficit[g] < 1.0 or not q:
                self._rr.rotate(-1)
            return t
        return None

    def _mark_dead_locked(self, w) -> None:
        if not w.alive:
            return
        w.alive = False
        w.fails = 0
        # strand every attempt the dead worker held: LOST statuses let
        # each owning query run its own worker-died retry path, and
        # the slots those attempts pinned come back to the pool
        for key in list(self._active_by_worker[w.uri]):
            self._active_by_worker[w.uri].discard(key)
            self._status[key] = dict(LOST)
            if self._tracked.pop(key, None) is not None:
                self._release_locked(w.uri)
            owner = self._owner.get(key)
            if owner is not None:
                owner.wake.set()
        self._pump_locked()

    # ---- reactor ---------------------------------------------------------

    def _worker_loop(self, w) -> None:
        """One thread per worker: poll every active attempt on it,
        publish statuses, count consecutive failures toward eviction,
        probe for re-admission while dead."""
        probe_delay = self.readmit_initial_s
        next_probe = 0.0
        while not self._stop.is_set():
            if not w.alive:
                now = time.monotonic()
                if now < next_probe:
                    self._stop.wait(
                        min(self.poll_s, next_probe - now)
                    )
                    continue
                try:
                    with urllib.request.urlopen(
                        f"{w.uri}/v1/info",
                        timeout=self.readmit_probe_timeout_s,
                    ) as r:
                        info = json.loads(r.read())
                except Exception:
                    probe_delay = min(
                        probe_delay * 2.0, self.readmit_max_s
                    )
                    next_probe = time.monotonic() + probe_delay
                    continue
                with self._lock:
                    w.alive = True
                    w.fails = 0
                    w.draining = info.get("state") != "ACTIVE"
                    probe_delay = self.readmit_initial_s
                    next_probe = 0.0
                    telemetry.WORKERS_READMITTED.inc()
                    self._pump_locked()
                continue
            with self._lock:
                keys = list(self._active_by_worker[w.uri])
            if not keys:
                self._stop.wait(self.poll_s)
                continue
            for key in keys:
                if self._stop.is_set() or not w.alive:
                    break
                tid, attempt = key
                t_rpc = time.perf_counter()
                try:
                    with urllib.request.urlopen(
                        f"{w.uri}/v1/stagetask/{tid}.{attempt}",
                        timeout=self.rpc_timeout_s,
                    ) as resp:
                        st = json.loads(resp.read())
                except Exception as e:
                    refused = isinstance(
                        getattr(e, "reason", None),
                        ConnectionRefusedError,
                    ) or isinstance(e, ConnectionRefusedError)
                    with self._lock:
                        if key not in self._tracked:
                            continue  # finished under us
                        w.fails += 1
                        if refused or w.fails >= self.max_poll_fails:
                            self._mark_dead_locked(w)
                    continue
                finally:
                    telemetry.RPC_LATENCY.observe(
                        time.perf_counter() - t_rpc, op="poll"
                    )
                w.fails = 0
                if self.on_pool is not None and st.get("pool"):
                    try:
                        self.on_pool(w.uri, st.get("pool"))
                    except Exception:
                        pass
                with self._lock:
                    if key not in self._tracked:
                        continue
                    self._status[key] = st
                    if st.get("state") in (
                        "FINISHED", "FAILED", "CANCELED"
                    ):
                        # terminal: stop polling; the slot stays held
                        # until the owning query consumes the status
                        # and calls finish()
                        self._active_by_worker[w.uri].discard(key)
                        owner = self._owner.get(key)
                        if owner is not None:
                            owner.wake.set()
            self._stop.wait(self.poll_s)


class ServingRunner:
    """QueryRunner-compatible facade serving MANY statements at once
    over one shared fleet (the DispatchManager -> QueryExecution layer
    collapsed to a class): each execute() builds a lightweight
    per-query FleetRunner wired to the shared worker list, Dispatcher
    and ClusterMemoryManager. Drop it in as ``Coordinator(runner=...)``
    and the existing thread-per-query submit path becomes genuinely
    concurrent — those threads are lifecycle state machines; all RPC
    polling stays on the dispatcher's O(workers) reactor."""

    def __init__(
        self,
        worker_uris,
        metadata,
        session,
        spool_root,
        n_partitions: int = 4,
        resource_groups=None,
        slots_per_worker: int = 1,
        **fleet_kwargs,
    ):
        from trino_tpu.server.fleet import FleetRunner, FleetWorker
        from trino_tpu.server.resource_groups import (
            ResourceGroup,
            ResourceGroupManager,
        )

        self.metadata = metadata
        self.session = session
        self.spool_root = spool_root
        self.n_partitions = n_partitions
        self._fleet_kwargs = dict(fleet_kwargs)
        self.workers = [FleetWorker(u.rstrip("/")) for u in worker_uris]
        #: shared admission/fair-share config; a Coordinator built on
        #: this runner adopts the same manager so admission counts and
        #: slot-level weights come from one place. The serving default
        #: bounds concurrently-RUNNING statements at 2x the worker
        #: count: enough live queries to keep every slot pipelined
        #: (one running + one next-up per worker) while the rest park
        #: on the admission queue at ~no cost — unbounded admission
        #: just multiplies runnable coordinator threads fighting for
        #: the same cores
        self.resource_groups = resource_groups or ResourceGroupManager(
            groups=[ResourceGroup(
                "global", max_running=max(2 * len(self.workers), 2)
            )]
        )
        self.cluster_memory = memory.ClusterMemoryManager()
        self.dispatcher = Dispatcher(
            self.workers,
            slots_per_worker=slots_per_worker,
            poll_s=fleet_kwargs.get("poll_s", 0.02),
            rpc_timeout_s=fleet_kwargs.get("rpc_timeout_s", 15.0),
            max_poll_fails=fleet_kwargs.get("max_poll_fails", 4),
            readmit_initial_s=fleet_kwargs.get("readmit_initial_s", 0.5),
            readmit_max_s=fleet_kwargs.get("readmit_max_s", 8.0),
            readmit_probe_timeout_s=fleet_kwargs.get(
                "readmit_probe_timeout_s", 1.0
            ),
            on_pool=self.cluster_memory.observe,
        )
        #: probe each worker's device count ONCE; per-query runners
        #: reuse it instead of re-probing per statement
        self.worker_devices = {
            w.uri: FleetRunner._probe_devices(w.uri)
            for w in self.workers
        }
        self._lock = threading.Lock()
        #: public query id -> its live per-query FleetRunner
        self._active: dict[str, "FleetRunner"] = {}
        #: MembershipRegistry once attach_membership() wires one in
        self.membership = None
        self.mesh = None  # duck-typing parity with QueryRunner
        # caching defaults ON for serving (the ISSUE's A/B switch:
        # session-level off is one SET SESSION away; explicit False in
        # the caller's session is respected) — repeat-dominated traffic
        # is exactly what the serving layer exists for
        from trino_tpu import cache as cache_mod

        self.session.properties.setdefault("result_cache_enabled", True)
        self.session.properties.setdefault("device_cache_enabled", True)
        #: ONE semantic result cache shared by every per-query
        #: FleetRunner this facade builds — the cross-query tier
        from trino_tpu import session_properties as sp

        self.result_cache = cache_mod.register_result_cache(
            cache_mod.SemanticResultCache(
                int(sp.get(self.session, "result_cache_max_bytes"))
            )
        )

    # -- per-query machinery ------------------------------------------------

    def _make_runner(self, group) -> object:
        from trino_tpu.server.fleet import FleetRunner

        fr = FleetRunner(
            [w.uri for w in self.workers],
            self.metadata,
            self.session,
            self.spool_root,
            n_partitions=self.n_partitions,
            dispatcher=self.dispatcher,
            workers=self.workers,
            worker_devices=self.worker_devices,
            cluster_memory=self.cluster_memory,
            serving=self,
            resource_group=group.name,
            group_weight=group.weight,
            **self._fleet_kwargs,
        )
        # per-query runners come and go; results persist on the shared
        # serving-scope cache so repeat statements across them hit
        fr.result_cache = self.result_cache
        return fr

    def execute(
        self,
        sql: str,
        cancel_event=None,
        query_id: str | None = None,
        user: str | None = None,
        inject_failures=None,
        admitted: bool = False,
    ):
        """Run one statement through the shared fleet. ``admitted``
        marks a caller (the Coordinator) that already holds a
        running slot in the SAME adopted group manager; embedded
        callers gate here — FIFO admission within the selected group,
        blocking while the group is at ``max_running``."""
        group = self.resource_groups.select(user or self.session.user)
        pub = query_id or uuid.uuid4().hex[:12]
        if not admitted:
            direct = self.resource_groups.enqueue(group, pub)
            ok = self.resource_groups.acquire(
                group, pub,
                cancelled=(
                    cancel_event.is_set if cancel_event is not None
                    else lambda: False
                ),
                admitted=direct,
            )
            if not ok:
                raise RuntimeError(
                    f"query {pub} cancelled while queued for "
                    f"resource group {group.name!r}"
                )
        fr = self._make_runner(group)
        if inject_failures:
            fr.inject_failures = set(inject_failures)
        with self._lock:
            self._active[pub] = fr
        try:
            return fr.execute(
                sql, cancel_event=cancel_event, query_id=pub
            )
        finally:
            with self._lock:
                self._active.pop(pub, None)
            if not admitted:
                self.resource_groups.release(group)

    def running_queries(self) -> list[str]:
        with self._lock:
            return list(self._active)

    # -- live membership ----------------------------------------------------

    def add_worker(self, uri: str):
        """A worker joined the live cluster: it becomes grantable for
        every in-flight query (per-query FleetRunners share this
        worker list by reference, so their dispatch loops see it on
        the next iteration). Idempotent per URI — a re-join just
        clears the unschedulable flags via Dispatcher.add_worker."""
        from trino_tpu.server.fleet import FleetRunner, FleetWorker

        uri = uri.rstrip("/")
        found = None
        with self._lock:
            for w in self.workers:
                if w.uri == uri:
                    found = w
                    break
            if found is None:
                found = FleetWorker(uri)
                self.workers.append(found)
        if uri not in self.worker_devices:
            try:
                self.worker_devices[uri] = FleetRunner._probe_devices(
                    uri
                )
            except Exception:
                self.worker_devices[uri] = 1
        self.dispatcher.add_worker(found)
        return found

    def attach_membership(self, registry) -> None:
        """Wire a MembershipRegistry to the serving fleet: joins add
        live workers, leaves (drain / damped heartbeat loss) mark
        them unschedulable-but-alive, and the registry's drain gate
        consults the union of every live query's residency pins."""
        self.membership = registry
        registry.residency_providers.append(self.pinned_worker_uris)
        registry.on_join.append(self._on_member_join)
        registry.on_leave.append(self._on_member_leave)

    def pinned_worker_uris(self) -> set:
        """Worker URIs any live query's scheduler still pins buffers
        on — while non-empty for a URI, that worker must keep serving
        its exchange buffers/spool reads even when DRAINED."""
        with self._lock:
            runners = list(self._active.values())
        pinned: set = set()
        for fr in runners:
            sched = getattr(fr, "_scheduler", None)
            if sched is not None:
                try:
                    pinned |= sched.pinned_workers()
                except Exception:
                    pass
        return pinned

    def _on_member_join(self, member) -> None:
        self.add_worker(member.uri)

    def _on_member_leave(self, member, reason: str) -> None:
        # unschedulable-but-alive: the FTE tier (poll eviction +
        # re-admission probes) stays the only path that declares a
        # worker dead
        uri = member.uri.rstrip("/")
        for w in self.workers:
            if w.uri == uri:
                w.draining = True

    # -- cluster memory governance across queries ---------------------------

    def enforce_memory(self, cap_bytes: int, my_attempt_qid: str) -> None:
        """Called from each query's dispatch loop: the kill victim is
        picked among ALL live queries; when it is someone else, a kill
        is requested on that query's loop and the caller proceeds."""
        if not cap_bytes:
            return
        with self._lock:
            running = {
                fr._query_id: fr
                for fr in self._active.values()
                if fr._query_id
            }
        picked = self.cluster_memory.pick_victim(
            cap_bytes, set(running)
        )
        if picked is None:
            return
        qid, msg = picked
        fr = running.get(qid)
        if fr is None or qid == my_attempt_qid:
            telemetry.MEMORY_KILLS.inc()
            raise memory.ExceededMemoryLimitError(msg)
        if fr.request_kill(msg):
            telemetry.MEMORY_KILLS.inc()

    def stop(self) -> None:
        self.dispatcher.stop()
