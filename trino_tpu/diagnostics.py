"""Post-mortem diagnostic bundles.

When a query dies — failure, memory kill, deadline, retry exhaustion —
the fleet assembles ONE JSON document holding everything a post-mortem
needs: the final plan and fragmented stage DAG, the full trace tree,
per-task stats (with per-partition exchange histograms), the fault
injections the attempt absorbed, registry metric deltas over the query
window, and the scheduler's worker residency/attempt map. The bundle is
retained on :data:`~trino_tpu.tracker.QUERY_INFO` (served at
``GET /v1/query/{id}/diagnostics``) and, when ``TRINO_TPU_DIAG_DIR`` is
set, written to ``<dir>/<query_id>.json`` so it survives the process.

Assembly is best-effort by design: a diagnostics failure must never
mask the original query error, so :func:`record_bundle` swallows its
own exceptions and every section degrades to ``None`` independently.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Optional

from trino_tpu import telemetry, tracker

__all__ = ["build_bundle", "write_bundle", "record_bundle", "diag_dir"]

#: bundle layout version, bumped on schema changes
SCHEMA_VERSION = 1


def diag_dir() -> Optional[str]:
    """Bundle output directory, or None (= in-memory retention only)
    when ``TRINO_TPU_DIAG_DIR`` is unset/empty."""
    return os.environ.get("TRINO_TPU_DIAG_DIR") or None


def _metric_deltas(before: Optional[Dict[str, float]],
                   after: Optional[Dict[str, float]]
                   ) -> Optional[Dict[str, float]]:
    """Registry movement over the query window; absent series count
    from zero, zero-delta series are dropped for signal."""
    if before is None or after is None:
        return None
    out: Dict[str, float] = {}
    for name, val in after.items():
        d = val - before.get(name, 0.0)
        if d:
            out[name] = round(d, 6)
    return out


def build_bundle(
    query_id: str,
    *,
    error: str,
    sql: Optional[str] = None,
    state: str = "FAILED",
    plan: Optional[str] = None,
    stages: Optional[Any] = None,
    trace=None,
    task_stats: Optional[list] = None,
    residency: Optional[Dict[Any, str]] = None,
    fault_records: Optional[list] = None,
    metrics_before: Optional[Dict[str, float]] = None,
    metrics_after: Optional[Dict[str, float]] = None,
    time_breakdown: Optional[Dict[str, Any]] = None,
    extra: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Assemble one post-mortem bundle dict (pure — no I/O)."""
    task_stats = list(task_stats or ())
    histograms = [
        {
            "stage_id": row.get("stage_id"),
            "task_id": row.get("task_id"),
            "attempt": row.get("attempt"),
            "partition_rows": row.get("partition_rows"),
            "partition_bytes": row.get("partition_bytes"),
        }
        for row in task_stats
        if row.get("partition_rows")
    ]
    bundle = {
        "schema_version": SCHEMA_VERSION,
        "query_id": query_id,
        "state": state,
        "error": error,
        "error_class": error.split(":", 1)[0] if error else "unknown",
        "sql": sql,
        "created_at": time.time(),
        "plan": plan,
        "stages": stages,
        "trace": trace.to_dict() if hasattr(trace, "to_dict") else trace,
        "task_stats": task_stats,
        "partition_histograms": histograms,
        "residency": {
            "/".join(str(p) for p in key) if isinstance(key, tuple)
            else str(key): worker
            for key, worker in (residency or {}).items()
        },
        "fault_injections": list(fault_records or ()),
        "metric_deltas": _metric_deltas(metrics_before, metrics_after),
        "time_breakdown": time_breakdown,
        "programs": _program_snapshot(),
    }
    if extra:
        bundle.update(extra)
    return bundle


def _program_snapshot() -> Optional[list]:
    """Compiled-program catalog at failure time (kernel observatory):
    which XLA programs the process was serving, their cost/HBM
    analysis and hit counts — a post-mortem often starts with 'what
    was the device running'. resolve=False: a bundle assembled on the
    failure path must not trigger lazy AOT lowering."""
    try:
        from trino_tpu import program_catalog

        return program_catalog.CATALOG.snapshot(resolve=False)
    except Exception:
        return None


def write_bundle(bundle: Dict[str, Any],
                 directory: Optional[str] = None) -> Optional[str]:
    """Persist a bundle under ``directory`` (default
    ``TRINO_TPU_DIAG_DIR``); returns the path, or None when no
    directory is configured."""
    directory = directory or diag_dir()
    if not directory:
        return None
    os.makedirs(directory, exist_ok=True)
    safe = str(bundle.get("query_id", "query")).replace("/", "_")
    path = os.path.join(directory, f"{safe}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(bundle, f, default=str)
    os.replace(tmp, path)
    return path


def record_bundle(bundle: Dict[str, Any]) -> Optional[str]:
    """Retain a bundle on QUERY_INFO and write it to disk if a diag
    directory is configured. Never raises — the bundle documents a
    failure, it must not cause one."""
    path = None
    try:
        telemetry.DIAG_BUNDLES.inc(
            error_class=str(bundle.get("error_class") or "unknown")
        )
        tracker.QUERY_INFO.set_diagnostics(
            str(bundle.get("query_id") or ""), bundle
        )
        path = write_bundle(bundle)
        if path:
            bundle["path"] = path
    except Exception:
        pass
    return path
