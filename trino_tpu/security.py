"""Access control: the security SPI and its built-in implementations.

The analog of the reference's AccessControlManager stack
(MAIN/security/AccessControlManager.java + SPI security): a pluggable
``AccessControl`` checked at analysis time for reads and at the DML/DDL
execution points for writes. The default is allow-all (the reference's
default system access control); ``RuleBasedAccessControl`` mirrors the
file-based rules plugin (plugin/trino-password-authenticators' sibling
file-based system access control): ordered rules matched on
(user, catalog, schema, table) granting privilege sets, first match
wins, no match denies.
"""

from __future__ import annotations

import fnmatch
from dataclasses import dataclass, field

__all__ = [
    "AccessDeniedError", "AccessControl", "AllowAllAccessControl",
    "RuleBasedAccessControl", "Rule",
]

#: privilege names (a subset of the reference's Privilege enum)
PRIVILEGES = ("select", "insert", "delete", "update", "ddl")


class AccessDeniedError(PermissionError):
    """Raised when a privilege check fails (AccessDeniedException
    analog, SPI/security/AccessDeniedException.java)."""


class AccessControl:
    """SPI: every method raises AccessDeniedError to deny."""

    def check_can_select(self, user: str, catalog: str, schema: str, table: str):
        pass

    # -- view expressions (SPI/security/ViewExpression.java): SQL text
    # the analyzer parses and applies at every table reference --------

    def get_row_filters(
        self, user: str, catalog: str, schema: str, table: str
    ) -> list[str]:
        """SQL boolean expressions over the table's columns; rows
        failing ANY filter are invisible to this user (the analyzer
        ANDs them into the scan, like the reference applies
        ViewExpressions in StatementAnalyzer)."""
        return []

    def get_column_mask(
        self, user: str, catalog: str, schema: str, table: str,
        column: str, type_,
    ) -> str | None:
        """SQL expression replacing ``column`` for this user (same
        type), or None for no mask."""
        return None

    def check_can_insert(self, user: str, catalog: str, schema: str, table: str):
        pass

    def check_can_delete(self, user: str, catalog: str, schema: str, table: str):
        pass

    def check_can_update(self, user: str, catalog: str, schema: str, table: str):
        pass

    def check_can_ddl(self, user: str, catalog: str, schema: str, table: str):
        pass


class AllowAllAccessControl(AccessControl):
    """The default: everything permitted."""


@dataclass(frozen=True)
class Rule:
    """One access rule: glob patterns over the identity and the object,
    plus the granted privilege set."""

    user: str = "*"
    catalog: str = "*"
    schema: str = "*"
    table: str = "*"
    privileges: tuple = PRIVILEGES
    #: SQL boolean expression over the table's columns limiting which
    #: rows this identity sees (file-based access control's ``filter``)
    row_filter: str | None = None
    #: column name -> SQL masking expression (``mask`` in the file
    #: rules; must type like the column)
    column_masks: dict = field(default_factory=dict)

    def matches(self, user, catalog, schema, table) -> bool:
        return (
            fnmatch.fnmatchcase(user, self.user)
            and fnmatch.fnmatchcase(catalog, self.catalog)
            and fnmatch.fnmatchcase(schema, self.schema)
            and fnmatch.fnmatchcase(table, self.table)
        )


@dataclass
class RuleBasedAccessControl(AccessControl):
    """First-match-wins rule list; no match denies (the file-based
    system access control's table-rules semantics)."""

    rules: list[Rule] = field(default_factory=list)

    def _check(self, privilege, user, catalog, schema, table):
        for r in self.rules:
            if r.matches(user, catalog, schema, table):
                if privilege in r.privileges:
                    return
                break
        raise AccessDeniedError(
            f"Access Denied: user {user!r} cannot {privilege} "
            f"{catalog}.{schema}.{table}"
        )

    def check_can_select(self, user, catalog, schema, table):
        self._check("select", user, catalog, schema, table)

    def check_can_insert(self, user, catalog, schema, table):
        self._check("insert", user, catalog, schema, table)

    def check_can_delete(self, user, catalog, schema, table):
        self._check("delete", user, catalog, schema, table)

    def check_can_update(self, user, catalog, schema, table):
        self._check("update", user, catalog, schema, table)

    def check_can_ddl(self, user, catalog, schema, table):
        self._check("ddl", user, catalog, schema, table)

    def get_row_filters(self, user, catalog, schema, table):
        for r in self.rules:
            if r.matches(user, catalog, schema, table):
                return [r.row_filter] if r.row_filter else []
        return []

    def get_column_mask(self, user, catalog, schema, table, column, type_):
        for r in self.rules:
            if r.matches(user, catalog, schema, table):
                return r.column_masks.get(column)
        return None
