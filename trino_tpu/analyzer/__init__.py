from trino_tpu.analyzer.analyzer import Analyzer, AnalysisError

__all__ = ["Analyzer", "AnalysisError"]
