"""Scopes, symbols, and function/type resolution for the analyzer.

The analog of the reference's Scope/RelationType
(MAIN/sql/analyzer/Scope.java) and function binding
(MAIN/metadata/FunctionResolver.java), reduced to what a columnar
TPU engine needs.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from trino_tpu import types as T

__all__ = [
    "AnalysisError", "Field", "Scope", "SymbolAllocator",
    "agg_result_type", "arith_result_type", "SCALAR_FNS", "AGG_FNS",
]


class AnalysisError(Exception):
    pass


@dataclass(frozen=True)
class Field:
    name: str        # column name as visible in SQL ('' = anonymous)
    symbol: str      # plan symbol
    type: T.DataType
    relation: str | None = None  # alias / table name qualifier


class Scope:
    """Visible fields of a relation + link to the enclosing (outer)
    query scope for correlated subqueries."""

    def __init__(self, fields: list[Field], parent: "Scope | None" = None):
        self.fields = fields
        self.parent = parent

    def resolve(self, parts: tuple[str, ...]) -> tuple[Field, bool]:
        """Resolve a possibly-qualified name.

        Returns (field, is_outer). Raises AnalysisError when ambiguous
        or missing.
        """
        name = parts[-1].lower()
        qualifier = parts[-2].lower() if len(parts) > 1 else None
        matches = [
            f
            for f in self.fields
            if f.name == name and (qualifier is None or f.relation == qualifier)
        ]
        if len(matches) > 1:
            raise AnalysisError(f"column {'.'.join(parts)!r} is ambiguous")
        if matches:
            return matches[0], False
        if self.parent is not None:
            f, _ = self.parent.resolve(parts)
            return f, True
        raise AnalysisError(f"column {'.'.join(parts)!r} cannot be resolved")

    def visible_fields(self, qualifier: str | None = None) -> list[Field]:
        if qualifier is None:
            return list(self.fields)
        out = [f for f in self.fields if f.relation == qualifier]
        if not out:
            raise AnalysisError(f"relation {qualifier!r} not found")
        return out


class SymbolAllocator:
    def __init__(self):
        self._counter = 0
        self.types: dict[str, T.DataType] = {}

    def new(self, hint: str, type_: T.DataType) -> str:
        base = "".join(c if c.isalnum() or c == "_" else "_" for c in hint) or "expr"
        self._counter += 1
        sym = f"{base}_{self._counter}"
        self.types[sym] = type_
        return sym


# ---- type rules ----------------------------------------------------------

def arith_result_type(op: str, lt: T.DataType, rt: T.DataType) -> T.DataType:
    """Result types of +,-,*,/,% (reference: io.trino.type arithmetic
    operators; decimal rules from SPI DecimalType precision math,
    capped at precision 18 until int128 lands)."""
    if isinstance(lt, T.DoubleType) or isinstance(rt, T.DoubleType):
        return T.DOUBLE
    if isinstance(lt, T.RealType) or isinstance(rt, T.RealType):
        return T.REAL if not (isinstance(lt, T.DoubleType) or isinstance(rt, T.DoubleType)) else T.DOUBLE
    ld = lt if isinstance(lt, T.DecimalType) else None
    rd = rt if isinstance(rt, T.DecimalType) else None
    if ld is None and rd is None:
        # integer op integer
        if not (lt.is_integer and rt.is_integer):
            raise AnalysisError(f"cannot apply {op} to {lt}, {rt}")
        return T.common_super_type(lt, rt)
    ld = ld or T.DecimalType(18, 0)
    rd = rd or T.DecimalType(18, 0)
    if ld.is_long or rd.is_long:
        # arithmetic over two-limb decimals routes through DOUBLE
        # (exact limb math is reserved for sum/avg; see types.py)
        return T.DOUBLE
    if op in ("add", "subtract"):
        s = max(ld.scale, rd.scale)
        p = min(18, max(ld.precision - ld.scale, rd.precision - rd.scale) + s + 1)
        return T.DecimalType(p, s)
    if op == "multiply":
        s = min(18, ld.scale + rd.scale)
        p = min(18, ld.precision + rd.precision)
        return T.DecimalType(max(p, s), s)
    if op == "divide":
        s = max(ld.scale, rd.scale)
        return T.DecimalType(18, s)
    if op == "modulus":
        s = max(ld.scale, rd.scale)
        p = min(18, max(ld.precision - ld.scale, rd.precision - rd.scale) + s)
        return T.DecimalType(max(p, 1), s)
    raise AnalysisError(f"unknown arithmetic {op}")


def agg_result_type(
    name: str,
    arg_type: T.DataType | None,
    arg2_type: T.DataType | None = None,
) -> T.DataType:
    """Aggregate result types (reference: MAIN/operator/aggregation)."""
    if name in ("count", "count_all"):
        return T.BIGINT
    if name == "map_agg":
        if arg_type is None or arg2_type is None:
            raise AnalysisError("map_agg takes (key, value) arguments")
        return T.MapType(arg_type, arg2_type)
    if arg_type is None:
        raise AnalysisError(f"aggregate {name} needs an argument")
    if name == "sum":
        if arg_type.is_integer:
            return T.BIGINT
        if isinstance(arg_type, T.DecimalType):
            # Trino: sum(decimal(p,s)) -> decimal(38,s); the engine
            # computes it exactly in two int64 limbs
            return T.DecimalType(38, arg_type.scale)
        if isinstance(arg_type, (T.DoubleType, T.RealType)):
            return T.DOUBLE
        raise AnalysisError(f"cannot sum {arg_type}")
    if name == "avg":
        if isinstance(arg_type, T.DecimalType):
            return arg_type
        return T.DOUBLE
    if name in ("min", "max", "any_value", "arbitrary"):
        return arg_type
    if name in ("stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop"):
        return T.DOUBLE
    if name == "bool_and" or name == "bool_or":
        return T.BOOLEAN
    if name in ("count_if", "approx_distinct"):
        return T.BIGINT
    if name == "approx_percentile":
        return arg_type  # value argument's type
    if name in ("max_by", "min_by"):
        return arg_type  # first argument's type
    if name == "array_agg":
        return T.ArrayType(arg_type)
    raise AnalysisError(f"unknown aggregate function {name}")


AGG_FNS = {
    "count", "sum", "avg", "min", "max", "any_value", "arbitrary",
    "stddev", "stddev_samp", "stddev_pop", "variance", "var_samp", "var_pop",
    "bool_and", "bool_or", "count_if", "approx_distinct",
    "approx_percentile",
    "max_by", "min_by", "array_agg", "map_agg",
}

#: scalar fn name -> (ir_name, result_type fn(arg_types))
def _array_elem(ts):
    if ts and isinstance(ts[0], T.MapType):
        # element_at(map, key) -> value type (ElementAt over MapBlock)
        return ts[0].value
    if not ts or not isinstance(ts[0], T.ArrayType):
        raise AnalysisError("argument must be an ARRAY or MAP")
    return ts[0].element


def _map_keys_type(ts):
    if not ts or not isinstance(ts[0], T.MapType):
        raise AnalysisError("argument must be a MAP")
    return T.ArrayType(ts[0].key)


def _map_values_type(ts):
    if not ts or not isinstance(ts[0], T.MapType):
        raise AnalysisError("argument must be a MAP")
    return T.ArrayType(ts[0].value)


SCALAR_FNS = {
    "abs": ("abs", lambda ts: ts[0]),
    # arrays (reference: MAIN/operator/scalar/ArrayFunctions +
    # CardinalityFunction, ArrayContains, ElementAt)
    "cardinality": ("cardinality", lambda ts: T.BIGINT),
    "contains": ("contains", lambda ts: T.BOOLEAN),
    "element_at": ("subscript", lambda ts: _array_elem(ts)),
    # maps (reference: MAIN/operator/scalar/MapKeys/MapValues/
    # MapCardinalityFunction/MapSubscriptOperator)
    "map_keys": ("map_keys", _map_keys_type),
    "map_values": ("map_values", _map_values_type),
    "sqrt": ("sqrt", lambda ts: T.DOUBLE),
    "floor": ("floor", lambda ts: ts[0]),
    "ceil": ("ceil", lambda ts: ts[0]),
    "ceiling": ("ceil", lambda ts: ts[0]),
    "round": ("round", lambda ts: ts[0]),
    "substr": ("substr", lambda ts: T.VARCHAR),
    "lower": ("lower", lambda ts: T.VARCHAR),
    # regex (reference: joni-backed MAIN/operator/scalar/JoniRegexpFunctions.java;
    # here python `re` over dictionary values -> device LUT/remap)
    "regexp_like": ("regexp_like", lambda ts: T.BOOLEAN),
    "regexp_extract": ("regexp_extract", lambda ts: T.VARCHAR),
    "regexp_replace": ("regexp_replace", lambda ts: T.VARCHAR),
    "upper": ("upper", lambda ts: T.VARCHAR),
    "trim": ("trim", lambda ts: T.VARCHAR),
    # year/month/day and the rest of the date-field family dispatch
    # through the analyzer's _extract_field branch, not this table
    "coalesce": ("coalesce", None),  # special typing
    # math (reference: MAIN/operator/scalar/MathFunctions.java)
    "exp": ("exp", lambda ts: T.DOUBLE),
    "ln": ("ln", lambda ts: T.DOUBLE),
    "log2": ("log2", lambda ts: T.DOUBLE),
    "log10": ("log10", lambda ts: T.DOUBLE),
    "power": ("power", lambda ts: T.DOUBLE),
    "pow": ("power", lambda ts: T.DOUBLE),
    "cbrt": ("cbrt", lambda ts: T.DOUBLE),
    # sign of DECIMAL computes on the unscaled int: type it BIGINT so
    # +-1/0 is not reinterpreted at the column's scale
    "sign": (
        "sign",
        lambda ts: T.BIGINT if isinstance(ts[0], T.DecimalType) else ts[0],
    ),
    "sin": ("sin", lambda ts: T.DOUBLE),
    "cos": ("cos", lambda ts: T.DOUBLE),
    "tan": ("tan", lambda ts: T.DOUBLE),
    "asin": ("asin", lambda ts: T.DOUBLE),
    "acos": ("acos", lambda ts: T.DOUBLE),
    "atan": ("atan", lambda ts: T.DOUBLE),
    "degrees": ("degrees", lambda ts: T.DOUBLE),
    "radians": ("radians", lambda ts: T.DOUBLE),
    "mod": ("modulus", lambda ts: ts[0]),
    # strings (reference: MAIN/operator/scalar/StringFunctions.java)
    "replace": ("replace", lambda ts: T.VARCHAR),
    "reverse": ("reverse", lambda ts: T.VARCHAR),
    "ltrim": ("ltrim", lambda ts: T.VARCHAR),
    "rtrim": ("rtrim", lambda ts: T.VARCHAR),
    "length": ("length", lambda ts: T.BIGINT),
    "strpos": ("strpos", lambda ts: T.BIGINT),
    "starts_with": ("starts_with", lambda ts: T.BOOLEAN),
}
