"""Analyzer + relation planner: AST -> typed logical plan.

The analog of the reference's StatementAnalyzer + RelationPlanner +
QueryPlanner (MAIN/sql/analyzer/StatementAnalyzer.java,
MAIN/sql/planner/QueryPlanner.java): resolves names against catalog
metadata, types every expression (inserting casts), lowers SELECT
blocks to plan nodes, and decorrelates subqueries:

- uncorrelated scalar subquery  -> cross join with a one-row subplan
- correlated scalar aggregate   -> group the subquery by its
  correlation keys, left-join on them (classic decorrelation; the
  reference routes this through ApplyNode +
  TransformCorrelatedScalarAggregationToJoin)
- [NOT] EXISTS / [NOT] IN (q)   -> SemiJoin on extracted equality keys
"""

from __future__ import annotations

import datetime
from dataclasses import dataclass

from trino_tpu import types as T
from trino_tpu.expr.ir import (
    AggCall,
    Call,
    Cast,
    InputRef,
    Literal,
    RowExpression,
    join_key_compatible,
)
from trino_tpu.metadata import Metadata, Session
from trino_tpu.plan import nodes as P
from trino_tpu.analyzer.scope import (
    AGG_FNS,
    SCALAR_FNS,
    AnalysisError,
    Field,
    Scope,
    SymbolAllocator,
    agg_result_type,
    arith_result_type,
)
from trino_tpu.sql import ast

__all__ = ["Analyzer", "AnalysisError"]


@dataclass
class RelationPlan:
    node: P.PlanNode
    scope: Scope


def _ast_key(node) -> str:
    """Structural key for matching group-by exprs / duplicate aggregates
    (dataclass reprs are deterministic and structural)."""
    return repr(node)


def split_conjuncts(e: ast.Expr) -> list[ast.Expr]:
    if isinstance(e, ast.Binary) and e.op == "and":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def _contains_subquery(e) -> bool:
    if isinstance(e, (ast.Exists, ast.InSubquery, ast.ScalarSubquery)):
        return True
    for v in vars(e).values() if hasattr(e, "__dict__") else []:
        if isinstance(v, ast.Expr) and _contains_subquery(v):
            return True
        if isinstance(v, (list, tuple)):
            for x in v:
                if isinstance(x, ast.Expr) and _contains_subquery(x):
                    return True
                if isinstance(x, tuple):
                    for y in x:
                        if isinstance(y, ast.Expr) and _contains_subquery(y):
                            return True
    return False


def _find_scalar_subqueries(e, out: list):
    if isinstance(e, ast.ScalarSubquery):
        out.append(e)
        return
    for v in vars(e).values() if hasattr(e, "__dict__") else []:
        if isinstance(v, ast.Expr):
            _find_scalar_subqueries(v, out)
        elif isinstance(v, (list, tuple)):
            for x in v:
                if isinstance(x, ast.Expr):
                    _find_scalar_subqueries(x, out)
                elif isinstance(x, tuple):
                    for y in x:
                        if isinstance(y, ast.Expr):
                            _find_scalar_subqueries(y, out)


def _find_subquery_predicates(e, out: list):
    """Collect [NOT] EXISTS / [NOT] IN (query) terms at any depth of a
    boolean expression (they may sit under OR — reference: these plan
    as SemiJoins whose match symbol substitutes into the predicate)."""
    if isinstance(e, (ast.Exists, ast.InSubquery)):
        out.append(e)
        return
    if isinstance(e, ast.ScalarSubquery):
        return
    for v in vars(e).values() if hasattr(e, "__dict__") else []:
        if isinstance(v, ast.Expr):
            _find_subquery_predicates(v, out)
        elif isinstance(v, (list, tuple)):
            for x in v:
                if isinstance(x, ast.Expr):
                    _find_subquery_predicates(x, out)
                elif isinstance(x, tuple):
                    for y in x:
                        if isinstance(y, ast.Expr):
                            _find_subquery_predicates(y, out)


class Analyzer:
    def __init__(self, metadata: Metadata, session: Session):
        self.metadata = metadata
        self.session = session
        self.symbols = SymbolAllocator()

    # ---- entry -----------------------------------------------------------
    def analyze(self, stmt: ast.Statement) -> P.PlanNode:
        if isinstance(stmt, ast.Query):
            rp, names = self.plan_query(stmt, outer=None, ctes={})
            symbols = [f.symbol for f in rp.scope.fields]
            return P.Output(
                outputs=dict(rp.node.outputs),
                source=rp.node,
                names=names,
                symbols=symbols,
            )
        if isinstance(stmt, ast.CreateTableAs):
            return self._plan_create_table_as(stmt)
        if isinstance(stmt, ast.InsertInto) and stmt.query is not None:
            return self._plan_insert_query(stmt)
        raise AnalysisError(f"unsupported statement {type(stmt).__name__}")

    # ---- writes (TableWriterNode planning, the StatementAnalyzer half
    # of MAIN/sql/planner/LogicalPlanner.java createTableCreate/
    # createInsert) ---------------------------------------------------------

    def _qualify_target(self, parts) -> tuple[str, str, str]:
        parts = list(parts)
        if len(parts) == 3:
            return parts[0], parts[1], parts[2]
        if len(parts) == 2:
            return self.session.catalog, parts[0], parts[1]
        return self.session.catalog, self.session.schema, parts[0]

    def _write_properties(self, props) -> tuple[list[str], dict]:
        """Evaluate CTAS WITH (...) literal properties host-side:
        partitioned_by = ARRAY['k', ...] and row_group_size = n."""
        partition_by: list[str] = []
        out: dict = {}
        for k, e in props or []:
            key = k.lower()
            if key in ("partitioned_by", "partition_by"):
                if not (
                    isinstance(e, ast.ArrayLit)
                    and all(isinstance(x, ast.StrLit) for x in e.items)
                ):
                    raise AnalysisError(
                        "partitioned_by must be ARRAY['col', ...]"
                    )
                partition_by = [x.value for x in e.items]
            elif key == "row_group_size":
                if not isinstance(e, ast.IntLit):
                    raise AnalysisError(
                        "row_group_size must be an integer literal"
                    )
                out["row_group_size"] = e.value
            else:
                raise AnalysisError(f"unknown table property {k!r}")
        return partition_by, out

    def _plan_create_table_as(self, stmt: ast.CreateTableAs) -> P.PlanNode:
        from trino_tpu.connectors.base import TableSchema

        cat, sch, tab = self._qualify_target(stmt.name)
        self.metadata.access_control.check_can_ddl(
            self.session.user, cat, sch, tab
        )
        try:
            conn = self.metadata.connector(cat)
        except KeyError:
            raise AnalysisError(f"catalog {cat!r} does not exist")
        if tab in conn.list_tables(sch):
            if stmt.if_not_exists:
                return self._noop_write_plan()
            raise AnalysisError(f"table {cat}.{sch}.{tab} already exists")
        partition_by, props = self._write_properties(stmt.properties)
        rp, names = self.plan_query(stmt.query, outer=None, ctes={})
        symbols = [f.symbol for f in rp.scope.fields]
        lowered = [n.lower() for n in names]
        if len(set(lowered)) != len(lowered):
            raise AnalysisError(
                "CREATE TABLE AS query produces duplicate column names"
            )
        if any(not n or n.startswith("_col") for n in names):
            raise AnalysisError(
                "CREATE TABLE AS requires a name for every column "
                "(alias unnamed expressions)"
            )
        ts = TableSchema(tab, [
            (n.lower(), rp.node.outputs[s])
            for n, s in zip(names, symbols)
        ])
        for k in partition_by:
            if k.lower() not in ts.column_names:
                raise AnalysisError(
                    f"partition column {k!r} is not produced by the query"
                )
        try:
            handle = conn.begin_create(
                sch, tab, ts,
                partition_by=[k.lower() for k in partition_by],
                properties=props or None,
            )
        except NotImplementedError:
            raise AnalysisError(
                f"catalog {cat!r} does not support CREATE TABLE AS"
            )
        except (ValueError, KeyError) as e:
            raise AnalysisError(str(e))
        handle["catalog"] = cat
        return self._wrap_write(rp.node, symbols, handle)

    def _plan_insert_query(self, stmt: ast.InsertInto) -> P.PlanNode:
        cat, sch, tab = self._qualify_target(stmt.name)
        self.metadata.access_control.check_can_insert(
            self.session.user, cat, sch, tab
        )
        try:
            conn = self.metadata.connector(cat)
        except KeyError:
            raise AnalysisError(f"catalog {cat!r} does not exist")
        try:
            ts = conn.table_schema(sch, tab)
        except (KeyError, FileNotFoundError):
            raise AnalysisError(f"table {cat}.{sch}.{tab} does not exist")
        target_cols = [c.lower() for c in (stmt.columns or ts.column_names)]
        for c in target_cols:
            if c not in ts.column_names:
                raise AnalysisError(
                    f"column {c!r} does not exist in {sch}.{tab}"
                )
        if len(set(target_cols)) != len(target_cols):
            raise AnalysisError("duplicate INSERT target column")
        rp, _names = self.plan_query(stmt.query, outer=None, ctes={})
        symbols = [f.symbol for f in rp.scope.fields]
        if len(symbols) != len(target_cols):
            raise AnalysisError(
                f"INSERT has {len(target_cols)} target columns but the "
                f"query produces {len(symbols)}"
            )
        try:
            handle = conn.begin_insert(sch, tab)
        except NotImplementedError:
            raise AnalysisError(f"catalog {cat!r} does not support INSERT")
        handle["catalog"] = cat
        # align the query outputs to full table column order: absent
        # columns take NULL, mismatched types get a cast
        by_target = dict(zip(target_cols, symbols))
        assigns: dict[str, RowExpression] = {}
        outputs: dict[str, T.DataType] = {}
        writer_cols: list[str] = []
        for c, t in ts.columns:
            sym = by_target.get(c)
            if sym is None:
                expr: RowExpression = Literal(t, None)
            else:
                st = rp.node.outputs[sym]
                ref = InputRef(st, sym)
                expr = ref if st == t else Cast(t, ref)
            out_sym = self.symbols.new(f"ins_{c}", t)
            assigns[out_sym] = expr
            outputs[out_sym] = t
            writer_cols.append(out_sym)
        aligned = P.Project(outputs, source=rp.node, assignments=assigns)
        return self._wrap_write(aligned, writer_cols, handle)

    def _noop_write_plan(self) -> P.PlanNode:
        """CREATE TABLE IF NOT EXISTS ... AS with the table present:
        a constant 0-rows result, no write."""
        sym = self.symbols.new("rows", T.BIGINT)
        vals = P.Values({sym: T.BIGINT}, rows=[(0,)])
        return P.Output(
            outputs={sym: T.BIGINT}, source=vals,
            names=["rows"], symbols=[sym],
        )

    def _wrap_write(
        self, child: P.PlanNode, columns: list[str], handle: dict
    ) -> P.PlanNode:
        writer = P.TableWriter(
            {
                "$rows": T.BIGINT,
                "$bytes": T.BIGINT,
                "$fragment": T.VARCHAR,
            },
            source=child, handle=handle, columns=list(columns),
        )
        finish = P.TableFinish(
            {"$written": T.BIGINT}, source=writer, handle=handle,
        )
        return P.Output(
            outputs={"$written": T.BIGINT}, source=finish,
            names=["rows"], symbols=["$written"],
        )

    # ---- queries ---------------------------------------------------------
    def plan_query(
        self, q: ast.Query, outer: Scope | None, ctes: dict
    ) -> tuple[RelationPlan, list[str]]:
        ctes = dict(ctes)
        for name, cte_q in q.with_:
            ctes[name.lower()] = cte_q
        if isinstance(q.select, ast.ValuesQuery):
            rp, names = self._plan_values(q.select, outer)
            alias_syms = {
                n.lower(): f.symbol
                for n, f in zip(names, rp.scope.fields)
            }
            pre_scope = None
        elif isinstance(q.select, ast.SetOp):
            rp, names = self._plan_setop(q.select, outer, ctes)
            alias_syms = {
                n.lower(): f.symbol
                for n, f in zip(names, rp.scope.fields)
            }
            pre_scope = None
        else:
            rp, names, alias_syms, pre_scope = self.plan_select(
                q.select, outer, ctes
            )
        node = rp.node
        if q.order_by:
            keys, node = self._order_keys(
                q.order_by, node, rp.scope, alias_syms,
                src_scope=pre_scope,
            )
            if q.limit is not None and q.offset is None:
                node = P.TopN(dict(node.outputs), source=node, count=q.limit, keys=keys)
            else:
                node = P.Sort(dict(node.outputs), source=node, keys=keys)
                if q.limit is not None or q.offset:
                    node = P.Limit(
                        dict(node.outputs), source=node,
                        count=q.limit if q.limit is not None else -1,
                        offset=q.offset or 0,
                    )
        elif q.limit is not None or q.offset:
            node = P.Limit(
                dict(node.outputs), source=node,
                count=q.limit if q.limit is not None else -1, offset=q.offset or 0
            )
        return RelationPlan(node, rp.scope), names

    # ---- set operations --------------------------------------------------
    def _plan_setop(
        self, so: ast.SetOp, outer: Scope | None, ctes: dict
    ) -> tuple[RelationPlan, list[str]]:
        """UNION [ALL] / INTERSECT / EXCEPT (reference: SetOperationNode
        family, MAIN/sql/planner/plan/UnionNode.java; the engine plans
        UNION ALL as concatenation, distinct set semantics as a
        group-by above it, and INTERSECT/EXCEPT as a side-marker column
        + per-group min/max filter — the TPU-friendly form of the
        reference's SetOperator ChannelSet.)"""
        lrp, lnames = self._plan_setop_side(so.left, outer, ctes)
        rrp, rnames = self._plan_setop_side(so.right, outer, ctes)
        lsyms = list(lrp.node.outputs)
        rsyms = list(rrp.node.outputs)
        if len(lsyms) != len(rsyms):
            raise AnalysisError(
                f"{so.op.upper()} branch column counts differ: "
                f"{len(lsyms)} vs {len(rsyms)}"
            )
        if so.all and so.op != "union":
            raise AnalysisError(
                f"{so.op.upper()} ALL is not supported yet"
            )
        common = [
            T.common_super_type(lrp.node.outputs[a], rrp.node.outputs[b])
            for a, b in zip(lsyms, rsyms)
        ]
        marker = None if so.op == "union" else True
        lnode, lsyms = self._coerce_branch(lrp.node, lsyms, common, 0, marker)
        rnode, rsyms = self._coerce_branch(rrp.node, rsyms, common, 1, marker)
        out_types = list(common) + ([T.BIGINT] if marker else [])
        out_names = list(lnames) + (["$side"] if marker else [])
        out_syms = [
            self.symbols.new(n or "col", t)
            for n, t in zip(out_names, out_types)
        ]
        node = P.Union(
            dict(zip(out_syms, out_types)),
            all_sources=[lnode, rnode],
            symbol_map={
                s: [a, b]
                for s, a, b in zip(out_syms, lsyms, rsyms)
            },
        )
        value_syms = out_syms[: len(common)]
        if so.op == "union":
            if not so.all:
                node = P.Aggregate(
                    dict(node.outputs), source=node,
                    group_keys=list(node.outputs), aggregates={},
                )
        else:
            side = out_syms[-1]
            mn = self.symbols.new("side_min", T.BIGINT)
            mx = self.symbols.new("side_max", T.BIGINT)
            aggs = {
                mn: AggCall("min", (InputRef(T.BIGINT, side),), T.BIGINT),
                mx: AggCall("max", (InputRef(T.BIGINT, side),), T.BIGINT),
            }
            outputs = {s: t for s, t in zip(value_syms, common)}
            node = P.Aggregate(
                {**outputs, mn: T.BIGINT, mx: T.BIGINT}, source=node,
                group_keys=value_syms, aggregates=aggs,
            )
            if so.op == "intersect":
                pred = Call(
                    T.BOOLEAN, "and", (
                        Call(T.BOOLEAN, "eq", (
                            InputRef(T.BIGINT, mn), Literal(T.BIGINT, 0),
                        )),
                        Call(T.BOOLEAN, "eq", (
                            InputRef(T.BIGINT, mx), Literal(T.BIGINT, 1),
                        )),
                    ),
                )
            else:  # except: present in left (0), absent from right (1)
                pred = Call(
                    T.BOOLEAN, "eq", (
                        InputRef(T.BIGINT, mx), Literal(T.BIGINT, 0),
                    ),
                )
            node = P.Filter(dict(node.outputs), source=node, predicate=pred)
            node = P.Project(
                dict(outputs), source=node,
                assignments={
                    s: InputRef(t, s) for s, t in outputs.items()
                },
            )
        fields = [
            Field((n or "").lower(), s, t)
            for n, s, t in zip(lnames, value_syms, common)
        ]
        return RelationPlan(node, Scope(fields, parent=outer)), list(lnames)

    def _plan_setop_side(self, side, outer, ctes):
        if isinstance(side, ast.SetOp):
            return self._plan_setop(side, outer, ctes)
        if isinstance(side, ast.Query):
            return self.plan_query(side, outer, ctes)
        if isinstance(side, ast.ValuesQuery):
            return self._plan_values(side, outer)
        rp, names, _alias, _pre = self.plan_select(side, outer, ctes)
        return rp, names

    def _coerce_branch(self, node, syms, common, side_idx, marker):
        """Cast a branch's columns to the common types and append the
        side marker when set difference/intersection needs one."""
        need = any(
            node.outputs[s] != t for s, t in zip(syms, common)
        ) or marker is not None
        if not need:
            return node, syms
        assignments = {}
        out_syms = []
        for s, t in zip(syms, common):
            ir = _cast_to(InputRef(node.outputs[s], s), t)
            s2 = s if node.outputs[s] == t else self.symbols.new("cast", t)
            assignments[s2] = ir
            out_syms.append(s2)
        if marker is not None:
            ms = self.symbols.new("side", T.BIGINT)
            assignments[ms] = Literal(T.BIGINT, side_idx)
            out_syms.append(ms)
        node = P.Project(
            {s: e.type for s, e in assignments.items()},
            source=node, assignments=assignments,
        )
        return node, out_syms

    def _order_keys(
        self, order_by, node, scope: Scope, alias_syms: dict,
        src_scope: Scope | None = None,
    ):
        keys = []
        extras: dict[str, RowExpression] = {}
        src_extras: dict[str, RowExpression] = {}
        for item in order_by:
            e = item.expr
            sym = None
            if isinstance(e, ast.Ident) and len(e.parts) == 1 and e.parts[0] in alias_syms:
                sym = alias_syms[e.parts[0]]
            elif isinstance(e, ast.IntLit):
                syms = list(node.outputs)
                if not (1 <= e.value <= len(syms)):
                    raise AnalysisError(f"ORDER BY position {e.value} out of range")
                sym = syms[e.value - 1]
            elif _ast_key(e) in alias_syms:
                sym = alias_syms[_ast_key(e)]
            else:
                # general expression over the output columns (the
                # reference's OrderingScheme allows any expression over
                # the query's output scope): compute it in a pre-sort
                # Project; the Output node above prunes it afterwards.
                # Falls back to the FROM scope for non-selected source
                # columns (SQL: ORDER BY may reach the source relation
                # when no aggregation/DISTINCT intervenes).
                try:
                    ea = ExprAnalyzer(self, scope)
                    ir = ea.analyze(e)
                except AnalysisError:
                    if src_scope is None or not isinstance(node, P.Project):
                        raise
                    ea = ExprAnalyzer(self, src_scope)
                    ir = ea.analyze(e)
                    sym = self.symbols.new("orderkey", ir.type)
                    src_extras[sym] = ir
                if sym is None:
                    if isinstance(ir, InputRef) and ir.name in node.outputs:
                        sym = ir.name
                    else:
                        sym = self.symbols.new("orderkey", ir.type)
                        extras[sym] = ir
            if isinstance(node.outputs.get(sym), T.ArrayType) or (
                sym in extras and isinstance(extras[sym].type, T.ArrayType)
            ) or (
                sym in src_extras
                and isinstance(src_extras[sym].type, T.ArrayType)
            ):
                raise AnalysisError("ORDER BY over ARRAY is not supported")
            keys.append(P.SortKey(sym, item.ascending, item.nulls_first))
        if src_extras:
            # widen the select Project to carry the source-scope sort
            # columns through (pruned above the Sort by Output)
            assignments = dict(node.assignments)
            assignments.update(src_extras)
            node = P.Project(
                {s: x.type for s, x in assignments.items()},
                source=node.source, assignments=assignments,
            )
        if extras:
            assignments: dict[str, RowExpression] = {
                s: InputRef(t, s) for s, t in node.outputs.items()
            }
            assignments.update(extras)
            node = P.Project(
                {s: x.type for s, x in assignments.items()},
                source=node, assignments=assignments,
            )
        return keys, node

    # ---- select ----------------------------------------------------------
    def _plan_values(self, vq: "ast.ValuesQuery", outer: Scope | None):
        """VALUES (..), (..) -> P.Values over constant rows in storage
        form (PARSER/tree/Values.java:25; rows must be constants —
        Trino also allows row expressions, which fold here or reject)."""
        from trino_tpu.expr.compiler import _literal_device_value

        if not vq.rows:
            raise AnalysisError("VALUES requires at least one row")
        width = len(vq.rows[0])
        for r in vq.rows:
            if len(r) != width:
                raise AnalysisError("VALUES rows must all be the same width")
        ea = ExprAnalyzer(self, Scope([], parent=None))
        irs = [[ea.analyze(e) for e in row] for row in vq.rows]
        types: list[T.DataType] = []
        for c in range(width):
            t = irs[0][c].type
            for row in irs[1:]:
                t = T.common_super_type(t, row[c].type)
            if t is T.UNKNOWN:
                raise AnalysisError(
                    f"VALUES column {c + 1} has no type (all NULL)"
                )
            types.append(t)
        rows = []
        for row in irs:
            vals = []
            for c, ir in enumerate(row):
                base = ir.arg if isinstance(ir, Cast) else ir
                if not isinstance(base, Literal):
                    raise AnalysisError(
                        "VALUES entries must be constants"
                    )
                if base.value is None:
                    vals.append(None)
                elif base.type == types[c]:
                    vals.append(_literal_device_value(base))
                else:
                    vals.append(
                        _literal_device_value(Literal(types[c], base.value))
                    )
            rows.append(tuple(vals))
        names = [f"_col{i}" for i in range(width)]
        fields = []
        outputs = {}
        for name, t in zip(names, types):
            sym = self.symbols.new(name, t)
            fields.append(Field(name, sym, t))
            outputs[sym] = t
        node = P.Values(outputs, rows=rows)
        return RelationPlan(node, Scope(fields, parent=outer)), names

    def plan_select(self, sel: ast.Select, outer: Scope | None, ctes: dict):
        # FROM
        if sel.relations:
            first = sel.relations[0]
            if isinstance(first, ast.UnnestRel):
                base = RelationPlan(
                    P.Values({}, rows=[[]]), Scope([], parent=outer)
                )
                rp = self._plan_unnest(first, base)
            else:
                rp = self.plan_relation(first, outer, ctes)
            for r in sel.relations[1:]:
                if isinstance(r, ast.UnnestRel):
                    # lateral: the array expressions may reference the
                    # relations to the left (the common
                    # "t, unnest(array[t.a, t.b])" pivot shape)
                    rp = self._plan_unnest(r, rp)
                    continue
                right = self.plan_relation(r, outer, ctes)
                rp = self._cross_join(rp, right)
        else:
            rp = RelationPlan(P.Values({}, rows=[[]]), Scope([], parent=outer))
        node, scope = rp.node, rp.scope

        outer_refs: set[str] = set()

        # WHERE (with subquery handling)
        if sel.where is not None:
            node, scope = self._apply_where(node, scope, sel.where, ctes, outer_refs)

        # aggregation
        agg_items = self._collect_aggs(sel)
        replacements: dict[str, InputRef] = {}
        group_syms: list[str] = []
        if sel.group_by or agg_items:
            node, scope, replacements, group_syms = self._plan_aggregation(
                node, scope, sel, agg_items, ctes, outer_refs
            )

        # HAVING
        if sel.having is not None:
            node, scope = self._apply_where(
                node, scope, sel.having, ctes, outer_refs,
                replacements=replacements, restrict_to=group_syms or None,
            )

        # window functions (evaluate after aggregation/HAVING)
        win_items = self._collect_windows(sel)
        win_syms: list[str] = []
        if win_items:
            node, win_syms = self._plan_windows(
                node, scope, win_items, outer_refs, replacements,
                group_syms if (sel.group_by or agg_items) else None,
            )

        # SELECT items
        assignments: dict[str, RowExpression] = {}
        names: list[str] = []
        fields: list[Field] = []
        alias_syms: dict[str, str] = {}
        restrict = group_syms if (sel.group_by or agg_items) else None
        for item in sel.items:
            if isinstance(item.expr, ast.Star):
                for f in scope.visible_fields(
                    item.expr.qualifier[-1] if item.expr.qualifier else None
                ):
                    sym = self.symbols.new(f.name, f.type)
                    assignments[sym] = InputRef(f.type, f.symbol)
                    names.append(f.name)
                    fields.append(Field(f.name, sym, f.type))
                continue
            # scalar subqueries in the SELECT list (e.g. CASE WHEN
            # (SELECT count(*) ...) THEN ...) plan as joins whose value
            # symbol substitutes into the projection
            item_subqs: list[ast.ScalarSubquery] = []
            _find_scalar_subqueries(item.expr, item_subqs)
            item_repl = dict(replacements)
            for sq in item_subqs:
                node, scope_sq, v_sym, v_typ = self._plan_scalar_subquery(
                    node, scope, sq, ctes
                )
                item_repl[_ast_key(sq)] = InputRef(v_typ, v_sym)
            ea = ExprAnalyzer(
                self, scope, replacements=item_repl,
                restrict_to=restrict, outer_refs=outer_refs,
            )
            ir = ea.analyze(item.expr)
            name = item.alias or _derive_name(item.expr)
            sym = self.symbols.new(name or "expr", ir.type)
            assignments[sym] = ir
            names.append(name or f"_col{len(names)}")
            fields.append(Field((name or "").lower(), sym, ir.type))
            if item.alias:
                alias_syms[item.alias.lower()] = sym
            alias_syms[_ast_key(item.expr)] = sym
        src_scope = scope  # the FROM scope (ORDER BY may reach it)
        node = P.Project(
            {s: e.type for s, e in assignments.items()},
            source=node,
            assignments=assignments,
        )
        scope = Scope(fields, parent=outer)

        if sel.distinct:
            if any(
                isinstance(t, T.ArrayType) for t in node.outputs.values()
            ):
                raise AnalysisError(
                    "SELECT DISTINCT over ARRAY columns is not supported"
                )
            node = P.Aggregate(
                dict(node.outputs), source=node,
                group_keys=list(node.outputs), aggregates={},
            )
        # the source scope is usable for ORDER BY only when the select
        # neither aggregates nor deduplicates (SQL scoping rules)
        order_src = (
            None if (sel.distinct or sel.group_by or agg_items)
            else src_scope
        )
        return RelationPlan(node, scope), names, alias_syms, order_src

    # ---- FROM relations --------------------------------------------------
    def plan_relation(self, rel: ast.Relation, outer: Scope | None, ctes: dict) -> RelationPlan:
        if isinstance(rel, ast.TableRef):
            if len(rel.parts) == 1 and rel.parts[0].lower() in ctes:
                sub_rp, names = self.plan_query(ctes[rel.parts[0].lower()], outer, ctes)
                alias = (rel.alias or rel.parts[0]).lower()
                fields = [
                    Field(n.lower(), f.symbol, f.type, alias)
                    for n, f in zip(names, sub_rp.scope.fields)
                ]
                return RelationPlan(sub_rp.node, Scope(fields, parent=outer))
            hit = self.metadata.get_view(self.session, rel.parts)
            if hit is not None:
                # view expansion at analysis time (TableRef -> the
                # stored Query, exactly like a named subquery); the
                # expanding-set guard turns view cycles into an error
                # instead of infinite recursion
                key, view_q = hit
                expanding = getattr(self, "_expanding_views", None)
                if expanding is None:
                    expanding = self._expanding_views = set()
                if key in expanding:
                    raise AnalysisError(
                        f"view {'.'.join(key)} is recursive"
                    )
                expanding.add(key)
                try:
                    sub_rp, names = self.plan_query(view_q, outer, {})
                finally:
                    expanding.discard(key)
                alias = (rel.alias or rel.parts[-1]).lower()
                fields = [
                    Field(n.lower(), f.symbol, f.type, alias)
                    for n, f in zip(names, sub_rp.scope.fields)
                ]
                return RelationPlan(sub_rp.node, Scope(fields, parent=outer))
            qt, schema = self.metadata.resolve_table(self.session, rel.parts)
            self.metadata.access_control.check_can_select(
                self.session.user, qt.catalog, qt.schema, qt.table
            )
            alias = (rel.alias or qt.table).lower()
            assignments = {}
            fields = []
            outputs = {}
            for col, typ in schema.columns:
                sym = self.symbols.new(col, typ)
                assignments[sym] = col
                outputs[sym] = typ
                fields.append(Field(col.lower(), sym, typ, alias))
            node = P.TableScan(
                outputs, catalog=qt.catalog, schema=qt.schema, table=qt.table,
                assignments=assignments,
            )
            node = self._apply_security_policies(
                node, qt, schema, fields
            )
            return RelationPlan(node, Scope(fields, parent=outer))
        if isinstance(rel, ast.SubqueryRel):
            sub_rp, names = self.plan_query(rel.query, outer, ctes)
            alias = rel.alias.lower() if rel.alias else None
            fields = [
                Field(n.lower(), f.symbol, f.type, alias)
                for n, f in zip(names, sub_rp.scope.fields)
            ]
            return RelationPlan(sub_rp.node, Scope(fields, parent=outer))
        if isinstance(rel, ast.JoinRel):
            return self._plan_join(rel, outer, ctes)
        raise AnalysisError(f"unsupported relation {type(rel).__name__}")

    def _apply_security_policies(self, node, qt, schema, fields):
        """Row filters and column masks (the reference applies the
        SPI's ViewExpressions at each table reference in
        StatementAnalyzer; same shape here: filters AND into a Filter
        over the scan — evaluated over UNMASKED columns, reference
        semantics — then masks rewrite columns via a Project that
        keeps the original symbols)."""
        ac = self.metadata.access_control
        user = self.session.user
        filters = ac.get_row_filters(user, qt.catalog, qt.schema, qt.table)
        masks = {}
        for f in fields:
            m = ac.get_column_mask(
                user, qt.catalog, qt.schema, qt.table, f.name, f.type
            )
            if m is not None:
                masks[f.symbol] = (m, f.type)
        if not filters and not masks:
            return node
        from trino_tpu.sql.parser import parse_expression

        policy_scope = Scope(list(fields), parent=None)
        for fsql in filters:
            ir = ExprAnalyzer(self, policy_scope).analyze(
                parse_expression(fsql)
            )
            if not isinstance(ir.type, T.BooleanType):
                raise AnalysisError(
                    f"row filter {fsql!r} must be boolean, is {ir.type}"
                )
            node = P.Filter(dict(node.outputs), source=node, predicate=ir)
        if masks:
            assignments = {}
            for sym, t in node.outputs.items():
                if sym in masks:
                    msql, mt = masks[sym]
                    mir = ExprAnalyzer(self, policy_scope).analyze(
                        parse_expression(msql)
                    )
                    assignments[sym] = _cast_to(mir, mt)
                else:
                    assignments[sym] = InputRef(t, sym)
            node = P.Project(
                {s: e.type for s, e in assignments.items()},
                source=node, assignments=assignments,
            )
        return node

    def _cross_join(self, left: RelationPlan, right: RelationPlan) -> RelationPlan:
        outputs = {**left.node.outputs, **right.node.outputs}
        node = P.Join(outputs, kind="cross", left=left.node, right=right.node)
        return RelationPlan(
            node, Scope(left.scope.fields + right.scope.fields, parent=left.scope.parent)
        )

    def _plan_unnest(
        self, rel: "ast.UnnestRel", left: RelationPlan
    ) -> RelationPlan:
        """UNNEST(ARRAY[...], ...) laterally over ``left``."""
        ea = ExprAnalyzer(self, left.scope)
        arrays = []
        elem_types = []
        for items in rel.args:
            if not isinstance(items, list):
                # UNNEST over an ARRAY-typed expression (a real array
                # COLUMN): the executor expands it from its pool
                ir = ea.analyze(items)
                if not isinstance(ir.type, T.ArrayType):
                    raise AnalysisError(
                        f"UNNEST argument must be an ARRAY, got {ir.type}"
                    )
                arrays.append(ir)
                elem_types.append(ir.type.element)
                continue
            irs = [ea.analyze(e) for e in items]
            if not irs:
                raise AnalysisError(
                    "UNNEST over an empty ARRAY[] has no element type"
                )
            t = irs[0].type
            for ir in irs[1:]:
                t = T.common_super_type(t, ir.type)
            irs = [
                ir if ir.type == t else Cast(t, ir) for ir in irs
            ]
            arrays.append(tuple(irs))
            elem_types.append(t)
        alias = rel.alias.lower() if rel.alias else None
        names = rel.column_aliases or [
            f"col{i + 1}" for i in range(len(arrays))
        ]
        if len(names) != len(arrays):
            raise AnalysisError(
                f"UNNEST has {len(arrays)} arrays but "
                f"{len(names)} column aliases"
            )
        symbols = []
        fields = list(left.scope.fields)
        outputs = dict(left.node.outputs)
        for name, t in zip(names, elem_types):
            sym = self.symbols.new(name, t)
            symbols.append(sym)
            outputs[sym] = t
            fields.append(Field(name.lower(), sym, t, alias))
        node = P.Unnest(
            outputs, source=left.node, arrays=arrays,
            element_symbols=symbols,
        )
        return RelationPlan(
            node, Scope(fields, parent=left.scope.parent)
        )

    def _plan_join(self, rel: ast.JoinRel, outer: Scope | None, ctes: dict) -> RelationPlan:
        left = self.plan_relation(rel.left, outer, ctes)
        if isinstance(rel.right, ast.UnnestRel):
            if rel.kind not in ("cross", "inner"):
                raise AnalysisError(
                    f"{rel.kind} join with UNNEST is not supported"
                )
            if rel.using:
                raise AnalysisError("JOIN UNNEST does not support USING")
            combined = self._plan_unnest(rel.right, left)
            if rel.on is not None:
                # the ON predicate filters the expanded rows (silently
                # dropping it would return the raw cross product)
                ea = ExprAnalyzer(self, combined.scope)
                ir = ea.analyze(rel.on)
                node = P.Filter(
                    dict(combined.node.outputs),
                    source=combined.node, predicate=ir,
                )
                return RelationPlan(node, combined.scope)
            return combined
        right = self.plan_relation(rel.right, outer, ctes)
        combined = self._cross_join(left, right)
        if rel.kind == "cross":
            return combined
        join_node: P.Join = combined.node  # type: ignore[assignment]
        join_node.kind = rel.kind
        if rel.using:
            conds = []
            for col in rel.using:
                lf, _ = left.scope.resolve((col,))
                rf, _ = right.scope.resolve((col,))
                conds.append((lf, rf))
            join_node.criteria = [(lf.symbol, rf.symbol) for lf, rf in conds]
            return combined
        if rel.on is None:
            raise AnalysisError("JOIN requires ON or USING")
        # split ON into equi criteria (left vs right) and residual filter
        left_syms = {f.symbol for f in left.scope.fields}
        right_syms = {f.symbol for f in right.scope.fields}
        residual: list[RowExpression] = []
        ea = ExprAnalyzer(self, combined.scope)
        for c in split_conjuncts(rel.on):
            ir = ea.analyze(c)
            pair = _equi_pair(ir, left_syms, right_syms)
            if pair is not None:
                join_node.criteria.append(pair)
            else:
                residual.append(ir)
        if residual:
            join_node.filter = _and_all(residual)
        return combined

    # ---- WHERE / subqueries ----------------------------------------------
    def _apply_where(
        self, node, scope, where_ast, ctes, outer_refs,
        replacements=None, restrict_to=None,
    ):
        for c in split_conjuncts(where_ast):
            c, negated = _strip_not(c)
            if isinstance(c, (ast.Exists, ast.InSubquery)):
                neg = negated != getattr(c, "negated", False)
                node, scope, match_sym = self._plan_semijoin(node, scope, c, ctes)
                pred = InputRef(T.BOOLEAN, match_sym)
                if neg:
                    pred = Call(T.BOOLEAN, "not", (pred,))
                node = P.Filter(
                    {k: v for k, v in node.outputs.items() if k != match_sym},
                    source=node, predicate=pred,
                )
                continue
            subqueries: list[ast.ScalarSubquery] = []
            _find_scalar_subqueries(c, subqueries)
            repl = dict(replacements or {})
            if subqueries:
                for sq in subqueries:
                    node, scope, sym, typ = self._plan_scalar_subquery(
                        node, scope, sq, ctes
                    )
                    repl[_ast_key(sq)] = InputRef(typ, sym)
            # EXISTS / IN (query) nested under OR (non-conjunct
            # position): plan each as a SemiJoin and substitute its
            # match symbol into the predicate
            sub_preds: list = []
            _find_subquery_predicates(c, sub_preds)
            match_syms: set[str] = set()
            for sp in sub_preds:
                node, scope, match_sym = self._plan_semijoin(
                    node, scope, sp, ctes
                )
                match_syms.add(match_sym)
                pred: RowExpression = InputRef(T.BOOLEAN, match_sym)
                if getattr(sp, "negated", False):
                    pred = Call(T.BOOLEAN, "not", (pred,))
                repl[_ast_key(sp)] = pred
            ea = ExprAnalyzer(
                self, scope, replacements=repl,
                restrict_to=restrict_to, outer_refs=outer_refs,
            )
            ir = ea.analyze(c if not negated else ast.Unary("not", c))
            if ir.type != T.BOOLEAN:
                raise AnalysisError("WHERE/HAVING predicate must be boolean")
            node = P.Filter(
                {
                    k: v for k, v in node.outputs.items()
                    if k not in match_syms
                },
                source=node, predicate=ir,
            )
        return node, scope

    def _plan_semijoin(self, node, scope, c, ctes):
        """[NOT] EXISTS(q) / x [NOT] IN (q) -> SemiJoin."""
        q = c.query
        sub_refs: set[str] = set()
        sub_rp, _ = self._plan_subquery(q, scope, ctes, sub_refs)
        sub_node, sub_scope = sub_rp.node, sub_rp.scope
        keys: list[tuple[str, str]] = []
        if isinstance(c, ast.InSubquery):
            ea = ExprAnalyzer(self, scope)
            arg = ea.analyze(c.arg)
            if not isinstance(arg, InputRef):
                sym = self.symbols.new("in_arg", arg.type)
                node = P.Project(
                    {**node.outputs, sym: arg.type}, source=node,
                    assignments={
                        **{s: InputRef(t, s) for s, t in node.outputs.items()},
                        sym: arg,
                    },
                )
                arg = InputRef(arg.type, sym)
            inner_sym = list(sub_node.outputs)[0]
            if not join_key_compatible(arg.type, sub_node.outputs[inner_sym]):
                raise AnalysisError(
                    f"IN subquery key types are not comparable as join "
                    f"keys: {arg.type} vs {sub_node.outputs[inner_sym]}"
                )
            keys.append((arg.name, inner_sym))
        residual: list[RowExpression] = []
        if sub_refs:
            sub_node, corr, residual = _extract_correlation(
                sub_node, sub_refs, allow_residual=True
            )
            for outer_sym, inner_sym in corr:
                keys.append((outer_sym, inner_sym))
        if not keys:
            raise AnalysisError(
                "EXISTS subquery must be correlated by an equality predicate"
            )
        match_sym = self.symbols.new("match", T.BOOLEAN)
        sj = P.SemiJoin(
            {**node.outputs, match_sym: T.BOOLEAN},
            source=node, filter_source=sub_node,
            keys=keys, match_symbol=match_sym,
            filter=_and_all(residual) if residual else None,
            null_aware=isinstance(c, ast.InSubquery),
        )
        return sj, scope, match_sym

    def _plan_scalar_subquery(self, node, scope, sq: ast.ScalarSubquery, ctes):
        sub_refs: set[str] = set()
        sub_rp, _ = self._plan_subquery(sq.query, scope, ctes, sub_refs)
        sub_node = sub_rp.node
        value_sym = list(sub_node.outputs)[0]
        value_type = sub_node.outputs[value_sym]
        if not sub_refs:
            # uncorrelated: cross join the (single-row) subplan
            outputs = {**node.outputs, **sub_node.outputs}
            node = P.Join(outputs, kind="cross", left=node, right=sub_node)
            return node, scope, value_sym, value_type
        # correlated scalar aggregate: group by correlation keys + left join
        sub_node, corr = _extract_correlation_through_agg(
            sub_node, sub_refs, self.symbols
        )
        criteria = [(outer_sym, inner_sym) for outer_sym, inner_sym in corr]
        join = P.Join(
            {**node.outputs, **sub_node.outputs},
            kind="left", left=node, right=sub_node, criteria=criteria,
        )
        return join, scope, value_sym, value_type

    def _plan_subquery(self, q: ast.Query, outer_scope: Scope, ctes, refs_out: set):
        """Plan a subquery allowing references to the outer scope;
        collect the outer symbols used."""
        analyzer_refs: set[str] = set()
        rp, names = SubqueryPlanner(self, outer_scope, analyzer_refs).plan(q, ctes)
        refs_out |= analyzer_refs
        return rp, names

    # ---- aggregation -----------------------------------------------------
    def _collect_aggs(self, sel: ast.Select) -> list[ast.FnCall]:
        found: list[ast.FnCall] = []
        seen: set[str] = set()

        def walk(e):
            if isinstance(e, ast.FnCall) and e.over is not None:
                # a window call is not an aggregate — but aggregates may
                # appear in its arguments / window spec (evaluated
                # before the window, per SQL semantics)
                for a in e.args:
                    walk(a)
                for p in e.over.partition_by:
                    walk(p)
                for oi in e.over.order_by:
                    walk(oi.expr)
                return
            if isinstance(e, ast.FnCall) and (
                e.name.lower() in AGG_FNS or e.star
            ):
                k = _ast_key(e)
                if k not in seen:
                    seen.add(k)
                    found.append(e)
                return  # no nested aggregates
            if isinstance(e, (ast.Exists, ast.InSubquery, ast.ScalarSubquery)):
                return
            for v in vars(e).values() if hasattr(e, "__dict__") else []:
                if isinstance(v, ast.Expr):
                    walk(v)
                elif isinstance(v, (list, tuple)):
                    for x in v:
                        if isinstance(x, ast.Expr):
                            walk(x)
                        elif isinstance(x, tuple):
                            for y in x:
                                if isinstance(y, ast.Expr):
                                    walk(y)

        for item in sel.items:
            if not isinstance(item.expr, ast.Star):
                walk(item.expr)
        if sel.having is not None:
            walk(sel.having)
        return found

    # ---- window functions ------------------------------------------------

    WINDOW_ONLY_FNS = {
        "row_number", "rank", "dense_rank", "ntile", "lead", "lag",
        "first_value", "last_value", "percent_rank", "cume_dist",
        "nth_value",
    }

    def _collect_windows(self, sel: ast.Select) -> list[ast.FnCall]:
        found: list[ast.FnCall] = []
        seen: set[str] = set()

        def walk(e):
            if isinstance(e, ast.FnCall) and e.over is not None:
                k = _ast_key(e)
                if k not in seen:
                    seen.add(k)
                    found.append(e)
                return
            if isinstance(e, (ast.Exists, ast.InSubquery, ast.ScalarSubquery)):
                return
            for v in vars(e).values() if hasattr(e, "__dict__") else []:
                if isinstance(v, ast.Expr):
                    walk(v)
                elif isinstance(v, (list, tuple)):
                    for x in v:
                        if isinstance(x, ast.Expr):
                            walk(x)
                        elif isinstance(x, tuple):
                            # CASE branches are list[tuple[Expr, Expr]]
                            for y in x:
                                if isinstance(y, ast.Expr):
                                    walk(y)

        for item in sel.items:
            if not isinstance(item.expr, ast.Star):
                walk(item.expr)
        return found

    def _plan_windows(
        self, node, scope, win_items, outer_refs, replacements, restrict
    ):
        """Plan Window nodes (one per distinct window specification),
        the analog of the reference's WindowNode planning in
        QueryPlanner.window (MAIN/sql/planner/QueryPlanner.java)."""
        buckets: dict[str, list[ast.FnCall]] = {}
        for fc in win_items:
            buckets.setdefault(repr(fc.over), []).append(fc)
        new_syms: list[str] = []
        for fcs in buckets.values():
            over = fcs[0].over
            ea = ExprAnalyzer(
                self, scope, replacements=replacements,
                restrict_to=restrict, outer_refs=outer_refs,
            )
            pre = {s: InputRef(t, s) for s, t in node.outputs.items()}
            need_pre = [False]

            def to_ref(e) -> InputRef:
                ir = ea.analyze(e)
                if isinstance(ir, InputRef):
                    return ir
                sym = self.symbols.new("w", ir.type)
                pre[sym] = ir
                need_pre[0] = True
                return InputRef(ir.type, sym)

            def to_arg(e):
                # literal arguments (lead/lag offsets, ntile buckets,
                # defaults) stay literals for the executor
                ir = ea.analyze(e)
                if isinstance(ir, (InputRef, Literal)):
                    return ir
                return to_ref(e)

            part_syms = [to_ref(p).name for p in over.partition_by]
            order_keys = []
            for oi in over.order_by:
                r = to_ref(oi.expr)
                order_keys.append(
                    P.SortKey(r.name, oi.ascending, oi.nulls_first)
                )
            fns: dict[str, P.WindowCall] = {}
            for fc in fcs:
                name = fc.name.lower()
                args = tuple(to_arg(a) for a in fc.args)
                frame = (
                    (over.frame.mode, over.frame.start, over.frame.end)
                    if over.frame is not None else None
                )
                if fc.star:
                    call = P.WindowCall("count_all", (), T.BIGINT, frame)
                elif name in ("row_number", "rank", "dense_rank", "ntile"):
                    if name != "ntile" and args:
                        raise AnalysisError(f"{name}() takes no arguments")
                    call = P.WindowCall(name, args, T.BIGINT, frame)
                elif name in ("percent_rank", "cume_dist"):
                    if args:
                        raise AnalysisError(f"{name}() takes no arguments")
                    call = P.WindowCall(name, args, T.DOUBLE, frame)
                elif name == "nth_value":
                    if len(args) != 2:
                        raise AnalysisError("nth_value takes 2 arguments")
                    call = P.WindowCall(name, args, args[0].type, frame)
                elif name in ("lead", "lag", "first_value", "last_value"):
                    if not args:
                        raise AnalysisError(f"{name} requires an argument")
                    call = P.WindowCall(name, args, args[0].type, frame)
                elif name == "count":
                    call = P.WindowCall("count", args, T.BIGINT, frame)
                elif name in AGG_FNS:
                    rt = agg_result_type(
                        name, args[0].type if args else None
                    )
                    call = P.WindowCall(name, args, rt, frame)
                else:
                    raise AnalysisError(
                        f"{name} is not a window function"
                    )
                sym = self.symbols.new(name, call.type)
                fns[sym] = call
                new_syms.append(sym)
                replacements[_ast_key(fc)] = InputRef(call.type, sym)
            if need_pre[0]:
                node = P.Project(
                    {s: e.type for s, e in pre.items()},
                    source=node, assignments=pre,
                )
            outputs = dict(node.outputs)
            outputs.update({s: c.type for s, c in fns.items()})
            node = P.Window(
                outputs, source=node, partition_by=part_syms,
                order_keys=order_keys, functions=fns,
            )
        return node, new_syms

    def _plan_aggregation(self, node, scope, sel, agg_items, ctes, outer_refs):
        # group keys. Each GROUP BY element contributes a list of
        # candidate sets; their cross product is the effective grouping
        # sets (PARSER grammar semantics: GROUP BY a, ROLLUP(b,c) =
        # sets {a,b,c},{a,b},{a}).
        group_syms: list[str] = []
        key_replacements: dict[str, InputRef] = {}
        pre_assignments: dict[str, RowExpression] = {
            s: InputRef(t, s) for s, t in node.outputs.items()
        }
        need_pre_project = False

        def key_sym(g):
            nonlocal need_pre_project
            if isinstance(g, ast.IntLit):  # ordinal
                if not (1 <= g.value <= len(sel.items)):
                    raise AnalysisError(f"GROUP BY position {g.value} out of range")
                g = sel.items[g.value - 1].expr
            k = _ast_key(g)
            if k in key_replacements:
                return key_replacements[k].name
            ea = ExprAnalyzer(self, scope, outer_refs=outer_refs)
            ir = ea.analyze(g)
            if isinstance(ir.type, T.ArrayType):
                raise AnalysisError(
                    "GROUP BY over ARRAY is not supported (array "
                    "handles carry no value equality)"
                )
            if isinstance(ir, InputRef):
                sym = ir.name
            else:
                sym = self.symbols.new("group", ir.type)
                pre_assignments[sym] = ir
                need_pre_project = True
            key_replacements[k] = InputRef(ir.type, sym)
            return sym

        #: per element: list of symbol-lists (the element's sets)
        element_sets: list[list[list[str]]] = []
        for g in sel.group_by:
            if isinstance(g, ast.GroupingElement):
                if g.kind == "rollup":
                    syms = [key_sym(e) for e in g.exprs]
                    element_sets.append(
                        [syms[:i] for i in range(len(syms), -1, -1)]
                    )
                elif g.kind == "cube":
                    syms = [key_sym(e) for e in g.exprs]
                    sets = [
                        [s for j, s in enumerate(syms) if mask & (1 << j)]
                        for mask in range((1 << len(syms)) - 1, -1, -1)
                    ]
                    element_sets.append(sets)
                else:  # explicit GROUPING SETS
                    element_sets.append(
                        [[key_sym(e) for e in st] for st in g.sets]
                    )
            else:
                element_sets.append([[key_sym(g)]])
        # cross product across elements
        grouping_sets: list[list[str]] = [[]]
        for sets in element_sets:
            grouping_sets = [
                prefix + s for prefix in grouping_sets for s in sets
            ]
        # all distinct keys, in first-appearance order
        group_syms = list(dict.fromkeys(
            s for st in grouping_sets for s in st
        ))
        multi_sets = len(grouping_sets) > 1
        if need_pre_project:
            node = P.Project(
                {s: e.type for s, e in pre_assignments.items()},
                source=node, assignments=pre_assignments,
            )
        groupid_sym = None
        pre_node = node
        n_nonempty = len(grouping_sets)
        if multi_sets:
            # dedupe keys WITHIN each set (GROUP BY a, ROLLUP(a,b)),
            # and order non-empty sets first: empty (global) sets plan
            # as separate global-aggregate branches below so they emit
            # exactly one row even over EMPTY input (the reference's
            # AggregationNode.globalGroupingSets semantics)
            grouping_sets = [
                list(dict.fromkeys(st)) for st in grouping_sets
            ]
            grouping_sets = (
                [st for st in grouping_sets if st]
                + [st for st in grouping_sets if not st]
            )
            n_nonempty = sum(1 for st in grouping_sets if st)
            groupid_sym = self.symbols.new("groupid", T.BIGINT)
            if n_nonempty:
                node = P.GroupId(
                    {**dict(node.outputs), groupid_sym: T.BIGINT},
                    source=node,
                    grouping_sets=grouping_sets[:n_nonempty],
                    id_symbol=groupid_sym,
                )
        # aggregate calls
        aggs: dict[str, AggCall] = {}
        replacements = dict(key_replacements)
        for fc in agg_items:
            name = fc.name.lower()
            ea = ExprAnalyzer(self, scope, outer_refs=outer_refs)
            if fc.star:
                call = AggCall("count_all", (), T.BIGINT)
            else:
                args = tuple(ea.analyze(a) for a in fc.args)
                if name == "count":
                    call = AggCall("count", args, T.BIGINT, distinct=fc.distinct)
                elif name == "approx_distinct":
                    # HLL sketch aggregation (reference:
                    # ApproximateCountDistinctAggregations.java):
                    # O(registers) state instead of O(NDV) — the
                    # optional max-standard-error argument is accepted
                    # and ignored (register count is fixed per plan
                    # shape, exec.aggregates HLL_*_BUCKETS)
                    call = AggCall(
                        "approx_distinct", args[:1], T.BIGINT
                    )
                elif name == "approx_percentile":
                    if len(args) != 2:
                        raise AnalysisError(
                            "approx_percentile takes (value, percentile)"
                        )
                    if fc.distinct:
                        raise AnalysisError(
                            "DISTINCT is not supported for "
                            "approx_percentile"
                        )
                    qarg = args[1]
                    q_lit = qarg
                    q_neg = False
                    while True:
                        if isinstance(q_lit, Cast):
                            q_lit = q_lit.arg
                            continue
                        if (
                            isinstance(q_lit, Call)
                            and q_lit.name == "negate"
                            and len(q_lit.args) == 1
                        ):
                            q_neg = not q_neg
                            q_lit = q_lit.args[0]
                            continue
                        break
                    if not isinstance(q_lit, Literal) or q_lit.value is None:
                        raise AnalysisError(
                            "approx_percentile percentile must be a "
                            "constant (the executor applies ONE "
                            "fraction per aggregate)"
                        )
                    try:
                        q_val = float(q_lit.value)
                        if q_neg:
                            q_val = -q_val
                    except (TypeError, ValueError):
                        raise AnalysisError(
                            "approx_percentile percentile must be numeric"
                        ) from None
                    if not (0.0 <= q_val <= 1.0):
                        raise AnalysisError(
                            f"percentile must be in [0, 1], got {q_val}"
                        )
                    call = AggCall(
                        name,
                        (args[0], Literal(T.DOUBLE, q_val)),
                        agg_result_type(name, args[0].type),
                    )
                else:
                    rt = agg_result_type(
                        name,
                        args[0].type if args else None,
                        args[1].type if len(args) > 1 else None,
                    )
                    call = AggCall(name, args, rt, distinct=fc.distinct)
            sym = self.symbols.new(name, call.type)
            aggs[sym] = call
            replacements[_ast_key(fc)] = InputRef(call.type, sym)
        agg_keys = (
            [groupid_sym] + group_syms if multi_sets else group_syms
        )
        outputs = {s: self.symbols.types[s] for s in agg_keys}
        outputs.update({s: a.type for s, a in aggs.items()})
        if multi_sets and n_nonempty == 0:
            node = None
        else:
            node = P.Aggregate(
                outputs, source=node, group_keys=agg_keys, aggregates=aggs
            )
        if multi_sets and n_nonempty < len(grouping_sets):
            # one global-aggregate branch per empty set, projected into
            # the main output layout with its constant set id and NULL
            # keys, then UNION ALLed with the GroupId aggregation
            branches = [] if node is None else [node]
            for j in range(n_nonempty, len(grouping_sets)):
                fresh = {
                    self.symbols.new("gagg", call.type): (sym, call)
                    for sym, call in aggs.items()
                }
                glob = P.Aggregate(
                    {fs: call.type for fs, (_s, call) in fresh.items()},
                    source=pre_node, group_keys=[],
                    aggregates={fs: call for fs, (_s, call) in fresh.items()},
                )
                assignments: dict[str, RowExpression] = {
                    groupid_sym: Literal(T.BIGINT, j)
                }
                for s in group_syms:
                    assignments[s] = Literal(self.symbols.types[s], None)
                for fs, (sym, _call) in fresh.items():
                    assignments[sym] = InputRef(self.symbols.types[fs], fs)
                branches.append(P.Project(
                    dict(outputs), source=glob,
                    assignments={s: assignments[s] for s in outputs},
                ))
            if len(branches) == 1:
                node = branches[0]
            else:
                node = P.Union(
                    dict(outputs), all_sources=branches,
                    symbol_map={s: [s] * len(branches) for s in outputs},
                )
        if multi_sets:
            # grouping(c1..cn) -> bitmask from the set id (bit i set
            # when ci is NOT in the row's grouping set — reference
            # semantics, MAIN/sql/analyzer/GroupingOperationRewriter):
            # a constant per set, compiled as nested ifs over $groupid
            for fc in self._collect_grouping_calls(sel):
                arg_syms = []
                for a in fc.args:
                    k = _ast_key(a)
                    if k not in key_replacements:
                        raise AnalysisError(
                            "grouping() arguments must be grouping "
                            "columns"
                        )
                    arg_syms.append(key_replacements[k].name)
                gid = InputRef(T.BIGINT, groupid_sym)
                expr: RowExpression = Literal(T.BIGINT, 0)
                n_args = len(arg_syms)
                for i, st in enumerate(grouping_sets):
                    bits = 0
                    for j, s in enumerate(arg_syms):
                        if s not in st:
                            bits |= 1 << (n_args - 1 - j)
                    expr = Call(
                        T.BIGINT, "if",
                        (
                            Call(T.BOOLEAN, "eq", (gid, Literal(T.BIGINT, i))),
                            Literal(T.BIGINT, bits),
                            expr,
                        ),
                    )
                replacements[_ast_key(fc)] = expr
        # scope keeps all fields so that references to ungrouped columns
        # produce a "must appear in GROUP BY" error (via restrict_to)
        # instead of a resolution failure
        return node, scope, replacements, (
            agg_keys if multi_sets else group_syms
        )

    def _collect_grouping_calls(self, sel: ast.Select) -> list[ast.FnCall]:
        found: list[ast.FnCall] = []
        seen: set[str] = set()

        def walk(e):
            if isinstance(e, ast.FnCall) and e.name.lower() == "grouping" \
                    and e.over is None and not e.star:
                k = _ast_key(e)
                if k not in seen:
                    seen.add(k)
                    found.append(e)
                return
            if isinstance(e, (ast.Exists, ast.InSubquery, ast.ScalarSubquery)):
                return
            for v in vars(e).values() if hasattr(e, "__dict__") else []:
                if isinstance(v, ast.Expr):
                    walk(v)
                elif isinstance(v, (list, tuple)):
                    for x in v:
                        if isinstance(x, ast.Expr):
                            walk(x)
                        elif isinstance(x, tuple):
                            for y in x:
                                if isinstance(y, ast.Expr):
                                    walk(y)

        for item in sel.items:
            if not isinstance(item.expr, ast.Star):
                walk(item.expr)
        if sel.having is not None:
            walk(sel.having)
        return found


class SubqueryPlanner:
    """Plans a subquery whose scope chains to the outer query's scope,
    recording which outer symbols were referenced."""

    def __init__(self, parent: Analyzer, outer_scope: Scope, refs: set):
        self.parent = parent
        self.outer_scope = outer_scope
        self.refs = refs

    def plan(self, q: ast.Query, ctes):
        a = self.parent
        marker = _OuterRefRecorder(self.outer_scope, self.refs)
        rp, names = a.plan_query(q, outer=marker, ctes=ctes)
        return rp, names


class _OuterRefRecorder(Scope):
    """Scope wrapper that records which outer fields get resolved."""

    def __init__(self, inner: Scope, refs: set):
        super().__init__(inner.fields, inner.parent)
        self._refs = refs

    def resolve(self, parts):
        f, outer = super().resolve(parts)
        self._refs.add(f.symbol)
        return f, True


# ---- correlation extraction ----------------------------------------------

def _extract_correlation(
    node: P.PlanNode, outer_syms: set[str], allow_residual: bool = False
):
    """Remove Filter conjuncts of the form inner = outer from the
    subplan; return (new plan, [(outer_sym, inner_sym)], residual).

    With ``allow_residual``, correlated non-equi conjuncts (e.g.
    ``l2.l_suppkey <> l1.l_suppkey`` in q21's EXISTS) are collected
    instead of rejected; the caller evaluates them over matched pairs."""
    corr: list[tuple[str, str]] = []
    residual: list[RowExpression] = []

    def rewrite(n: P.PlanNode) -> P.PlanNode:
        if isinstance(n, P.Filter):
            # factor conjuncts common to every OR branch first: the
            # spec pattern "(corr = x AND a) OR (corr = x AND b)"
            # hoists its correlation equality to the top level
            # (TPC-DS q41's shape)
            from trino_tpu.plan.optimizer import _factor_or_common

            kept: list[RowExpression] = []
            for cj in _ir_conjuncts(_factor_or_common(n.predicate)):
                pair = _corr_eq_pair(cj, outer_syms)
                if pair is not None:
                    corr.append(pair)
                elif _ir_refs(cj) & outer_syms:
                    if allow_residual:
                        residual.append(cj)
                    else:
                        raise AnalysisError(
                            f"unsupported correlated predicate: {cj!r}"
                        )
                else:
                    kept.append(cj)
            src = rewrite(n.source)
            if not kept:
                return src
            return P.Filter(dict(src.outputs), source=src, predicate=_and_all(kept))
        if isinstance(n, P.Project):
            src = rewrite(n.source)
            # keep correlated inner symbols visible through projections
            assignments = dict(n.assignments)
            outputs = dict(n.outputs)
            inner_needed = [i for _, i in corr]
            for cj in residual:
                inner_needed.extend(_ir_refs(cj) - outer_syms)
            for inner in inner_needed:
                if inner not in assignments and inner in src.outputs:
                    assignments[inner] = InputRef(src.outputs[inner], inner)
                    outputs[inner] = src.outputs[inner]
            return P.Project(outputs, source=src, assignments=assignments)
        # any node below the Filter/Project spine (including an
        # aggregate from an inlined CTE) ends the walk: correlation may
        # not hide below it — verify and keep the subtree as-is
        _assert_no_outer_refs(n, outer_syms)
        return n

    return rewrite(node), corr, residual


def _assert_no_outer_refs(node: P.PlanNode, outer_syms: set[str]):
    preds: list[RowExpression] = []
    if isinstance(node, P.Filter):
        preds.append(node.predicate)
    elif isinstance(node, P.Project):
        preds.extend(node.assignments.values())
    elif isinstance(node, P.Join) and node.filter is not None:
        preds.append(node.filter)
    for p in preds:
        if _ir_refs(p) & outer_syms:
            raise AnalysisError(
                f"unsupported correlated predicate below a "
                f"{type(node).__name__}: {p!r}"
            )
    for s in node.sources:
        _assert_no_outer_refs(s, outer_syms)


def _extract_correlation_through_agg(
    node: P.PlanNode, outer_syms: set[str], symbols: SymbolAllocator
):
    """Decorrelate a scalar aggregate subquery: hoist correlated
    equality keys out of the Filter below the Aggregate and add them as
    group keys."""
    if isinstance(node, P.Project):
        inner, corr = _extract_correlation_through_agg(node.source, outer_syms, symbols)
        assignments = dict(node.assignments)
        outputs = dict(node.outputs)
        for _, isym in corr:
            if isym not in assignments:
                assignments[isym] = InputRef(inner.outputs[isym], isym)
                outputs[isym] = inner.outputs[isym]
        return P.Project(outputs, source=inner, assignments=assignments), corr
    if not isinstance(node, P.Aggregate):
        raise AnalysisError(
            "correlated scalar subquery must be an aggregate query"
        )
    if node.group_keys:
        raise AnalysisError(
            "correlated scalar subquery must not have GROUP BY"
        )
    inner, corr, _ = _extract_correlation(node.source, outer_syms)
    group_keys = [isym for _, isym in corr]
    outputs = {s: inner.outputs[s] for s in group_keys}
    outputs.update({s: a.type for s, a in node.aggregates.items()})
    agg = P.Aggregate(
        outputs, source=inner, group_keys=group_keys, aggregates=node.aggregates
    )
    return agg, corr


def _corr_eq_pair(ir: RowExpression, outer_syms: set[str]):
    if isinstance(ir, Call) and ir.name == "eq":
        a, b = ir.args
        if (
            isinstance(a, InputRef)
            and isinstance(b, InputRef)
            and join_key_compatible(a.type, b.type)
        ):
            if a.name in outer_syms and b.name not in outer_syms:
                return (a.name, b.name)
            if b.name in outer_syms and a.name not in outer_syms:
                return (b.name, a.name)
    return None


def _ir_conjuncts(ir: RowExpression) -> list[RowExpression]:
    if isinstance(ir, Call) and ir.name == "and":
        out = []
        for a in ir.args:
            out.extend(_ir_conjuncts(a))
        return out
    return [ir]


def _ir_refs(ir: RowExpression) -> set[str]:
    if isinstance(ir, InputRef):
        return {ir.name}
    out: set[str] = set()
    if isinstance(ir, Call):
        for a in ir.args:
            out |= _ir_refs(a)
    elif isinstance(ir, Cast):
        out |= _ir_refs(ir.arg)
    return out


def _and_all(parts: list[RowExpression]) -> RowExpression:
    if len(parts) == 1:
        return parts[0]
    return Call(T.BOOLEAN, "and", tuple(parts))


def _equi_pair(ir: RowExpression, left_syms: set[str], right_syms: set[str]):
    if isinstance(ir, Call) and ir.name == "eq":
        a, b = ir.args
        if (
            isinstance(a, InputRef)
            and isinstance(b, InputRef)
            and join_key_compatible(a.type, b.type)
        ):
            if a.name in left_syms and b.name in right_syms:
                return (a.name, b.name)
            if b.name in left_syms and a.name in right_syms:
                return (b.name, a.name)
    return None


def _strip_not(e: ast.Expr) -> tuple[ast.Expr, bool]:
    neg = False
    while isinstance(e, ast.Unary) and e.op == "not":
        neg = not neg
        e = e.arg
    return e, neg


def _derive_name(e: ast.Expr) -> str | None:
    if isinstance(e, ast.Ident):
        return e.parts[-1]
    if isinstance(e, ast.FnCall):
        return e.name
    return None


# ---- expression analysis -------------------------------------------------

class ExprAnalyzer:
    """AST expression -> typed RowExpression over scope symbols.

    The analog of MAIN/sql/analyzer/ExpressionAnalyzer.java: resolves
    names, types every node, inserts casts for numeric coercion, and
    desugars BETWEEN / CASE / LIKE / IN-list forms.
    """

    def __init__(
        self, analyzer: Analyzer, scope: Scope,
        replacements: dict[str, InputRef] | None = None,
        restrict_to: list[str] | None = None,
        outer_refs: set[str] | None = None,
    ):
        self.analyzer = analyzer
        self.scope = scope
        self.replacements = replacements or {}
        self.restrict_to = set(restrict_to) if restrict_to is not None else None
        self.outer_refs = outer_refs if outer_refs is not None else set()

    def analyze(self, e: ast.Expr) -> RowExpression:
        k = _ast_key(e)
        if k in self.replacements:
            return self.replacements[k]
        m = getattr(self, f"_{type(e).__name__}", None)
        if m is None:
            raise AnalysisError(f"unsupported expression {type(e).__name__}")
        return m(e)

    def _AnalyzedExpr(self, e: "AnalyzedExpr"):
        return e.ir

    # literals
    def _IntLit(self, e: ast.IntLit):
        return Literal(T.BIGINT, e.value)

    def _DecimalLit(self, e: ast.DecimalLit):
        digits = e.text.replace(".", "").lstrip("0") or "0"
        scale = len(e.text.split(".")[1]) if "." in e.text else 0
        precision = max(len(digits), scale, 1)
        return Literal(T.DecimalType(min(precision, 18), scale), e.text)

    def _FloatLit(self, e: ast.FloatLit):
        return Literal(T.DOUBLE, e.value)

    def _StrLit(self, e: ast.StrLit):
        return Literal(T.VARCHAR, e.value)

    def _BoolLit(self, e: ast.BoolLit):
        return Literal(T.BOOLEAN, e.value)

    def _NullLit(self, e: ast.NullLit):
        return Literal(T.UNKNOWN, None)

    def _DateLit(self, e: ast.DateLit):
        return Literal(T.DATE, e.text)

    def _TimestampLit(self, e: ast.TimestampLit):
        return Literal(T.TIMESTAMP, e.text)

    def _Ident(self, e: ast.Ident):
        try:
            f, outer = self.scope.resolve(e.parts)
        except AnalysisError:
            # row-field dereference: a.b where a resolves to a
            # ROW-typed column with named field b (the reference's
            # DereferenceExpression resolution order,
            # MAIN/sql/analyzer/ExpressionAnalyzer)
            if len(e.parts) < 2:
                raise
            base = self._Ident(ast.Ident(e.parts[:-1]))
            if not isinstance(base.type, T.RowType):
                raise
            fi = base.type.field_index(e.parts[-1])
            if fi is None:
                raise AnalysisError(
                    f"row type {base.type} has no field {e.parts[-1]!r}"
                )
            return Call(
                base.type.fields[fi][1], "row_field",
                (base, Literal(T.INTEGER, fi)),
            )
        if outer:
            self.outer_refs.add(f.symbol)
        elif self.restrict_to is not None and f.symbol not in self.restrict_to:
            raise AnalysisError(
                f"column {'.'.join(e.parts)!r} must appear in GROUP BY "
                "or be used in an aggregate"
            )
        return InputRef(f.type, f.symbol)

    # operators
    def _Unary(self, e: ast.Unary):
        arg = self.analyze(e.arg)
        if e.op == "not":
            return Call(T.BOOLEAN, "not", (arg,))
        if e.op == "-":
            return Call(arg.type, "negate", (arg,))
        return arg

    def _Binary(self, e: ast.Binary):
        if e.op in ("and", "or"):
            return Call(T.BOOLEAN, e.op, (self.analyze(e.left), self.analyze(e.right)))
        if e.op in ("=", "<>", "<", "<=", ">", ">="):
            return self._comparison(e)
        if e.op in ("+", "-") and isinstance(e.right, ast.IntervalLit):
            return self._date_interval(e)
        if e.op == "||":
            return self._concat(e)
        op_name = {"+": "add", "-": "subtract", "*": "multiply",
                   "/": "divide", "%": "modulus"}[e.op]
        left = self.analyze(e.left)
        right = self.analyze(e.right)
        # date +- integer days
        if isinstance(left.type, T.DateType) and right.type.is_integer:
            return Call(T.DATE, op_name, (left, right))
        rt = arith_result_type(op_name, left.type, right.type)
        if isinstance(rt, (T.DoubleType, T.RealType)):
            left = _cast_to(left, rt)
            right = _cast_to(right, rt)
        return Call(rt, op_name, (left, right))

    def _comparison(self, e: ast.Binary):
        op = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt", ">=": "ge"}[e.op]
        left = self.analyze(e.left)
        right = self.analyze(e.right)
        if isinstance(left.type, T.VarcharType) or isinstance(right.type, T.VarcharType):
            return Call(T.BOOLEAN, op, (left, right))
        if isinstance(left.type, T.DecimalType) and isinstance(right.type, T.DecimalType):
            # keep both operands unscaled: the compiler compares mixed
            # scales exactly via floor-div/remainder at the coarser
            # scale — upscaling to the common scale would overflow
            # int64 (e.g. decimal(18,2) vs decimal(18,12))
            return Call(T.BOOLEAN, op, (left, right))
        if left.type != right.type:
            common = T.common_super_type(left.type, right.type)
            left = _cast_to(left, common)
            right = _cast_to(right, common)
        return Call(T.BOOLEAN, op, (left, right))

    def _date_interval(self, e: ast.Binary):
        left = self.analyze(e.left)
        iv: ast.IntervalLit = e.right  # type: ignore[assignment]
        amount = int(iv.value) * (-1 if iv.negative else 1)
        if e.op == "-":
            amount = -amount
        if isinstance(left.type, T.TimestampType):
            micros = _INTERVAL_MICROS.get(iv.unit)
            if micros is not None:
                return Call(
                    T.TIMESTAMP, "add",
                    (left, Literal(T.BIGINT, amount * micros)),
                )
            months = _INTERVAL_MONTHS.get(iv.unit)
            if months is None:
                raise AnalysisError(f"unsupported interval unit {iv.unit}")
            return Call(
                T.TIMESTAMP, "ts_add_months",
                (left, Literal(T.BIGINT, amount * months)),
            )
        if not isinstance(left.type, T.DateType):
            raise AnalysisError("interval arithmetic requires a date operand")
        if iv.unit in ("day", "week"):
            days = amount * (7 if iv.unit == "week" else 1)
            if isinstance(left, Literal):
                return Literal(T.DATE, T.format_date(T.parse_date(left.value) + days))
            return Call(T.DATE, "add", (left, Literal(T.INTEGER, days)))
        if iv.unit in ("month", "quarter", "year"):
            months = amount * _INTERVAL_MONTHS[iv.unit]
            if isinstance(left, Literal):
                return Literal(T.DATE, _add_months(left.value, months))
            return Call(
                T.DATE, "add_months", (left, Literal(T.BIGINT, months))
            )
        raise AnalysisError(f"unsupported interval unit {iv.unit}")

    def _concat(self, e: ast.Binary):
        left = self.analyze(e.left)
        right = self.analyze(e.right)
        if isinstance(left, Literal) and isinstance(right, Literal):
            return Literal(T.VARCHAR, str(left.value) + str(right.value))
        if isinstance(right, Literal):
            return Call(T.VARCHAR, "concat_suffix", (left, right))
        if isinstance(left, Literal):
            return Call(T.VARCHAR, "concat_prefix", (right, left))
        return Call(T.VARCHAR, "concat_cols", (left, right))

    # predicates
    def _Between(self, e: ast.Between):
        lo = ast.Binary(">=", e.arg, e.low)
        hi = ast.Binary("<=", e.arg, e.high)
        both = ast.Binary("and", lo, hi)
        out = self.analyze(both)
        if e.negated:
            return Call(T.BOOLEAN, "not", (out,))
        return out

    def _InList(self, e: ast.InList):
        arg = self.analyze(e.arg)
        items = [self.analyze(i) for i in e.items]
        if not isinstance(arg.type, T.VarcharType):
            items = [_cast_to(i, arg.type) for i in items]
        out = Call(T.BOOLEAN, "in", (arg, *items))
        if e.negated:
            return Call(T.BOOLEAN, "not", (out,))
        return out

    def _LikeExpr(self, e: ast.LikeExpr):
        arg = self.analyze(e.arg)
        pattern = self.analyze(e.pattern)
        if not isinstance(pattern, Literal):
            raise AnalysisError("LIKE pattern must be a literal")
        name = "not_like" if e.negated else "like"
        return Call(T.BOOLEAN, name, (arg, pattern))

    def _IsNullExpr(self, e: ast.IsNullExpr):
        out = Call(T.BOOLEAN, "is_null", (self.analyze(e.arg),))
        if e.negated:
            return Call(T.BOOLEAN, "not", (out,))
        return out

    def _CaseExpr(self, e: ast.CaseExpr):
        whens = e.whens
        if e.operand is not None:
            whens = [(ast.Binary("=", e.operand, w), r) for w, r in whens]
        conds = [self.analyze(w) for w, _ in whens]
        results = [self.analyze(r) for _, r in whens]
        else_ = self.analyze(e.else_) if e.else_ is not None else Literal(T.UNKNOWN, None)
        rtype = else_.type
        for r in results:
            rtype = T.common_super_type(rtype, r.type)
        results = [_cast_to(r, rtype) for r in results]
        if not isinstance(else_, Literal) or else_.value is not None:
            else_ = _cast_to(else_, rtype)
        else:
            else_ = Literal(rtype, None)
        out = else_
        for cond, res in zip(reversed(conds), reversed(results)):
            out = Call(rtype, "if", (cond, res, out))
        return out

    def _CastExpr(self, e: ast.CastExpr):
        arg = self.analyze(e.arg)
        return Cast(T.type_from_name(e.type_name), arg)

    def _ExtractExpr(self, e: ast.ExtractExpr):
        return self._extract_field(e.field, self.analyze(e.arg))

    def _extract_field(self, field: str, arg) -> Call:
        """EXTRACT(field FROM x) and the function forms year(x),
        quarter(x), day_of_week(x), ... (reference:
        MAIN/operator/scalar/DateTimeFunctions.java:73)."""
        field = _EXTRACT_ALIASES.get(field, field)
        if field in ("hour", "minute", "second"):
            if not isinstance(arg.type, T.TimestampType):
                raise AnalysisError(
                    f"EXTRACT({field}) requires a timestamp"
                )
            return Call(T.BIGINT, f"extract_{field}", (arg,))
        if field not in _DATE_FIELDS:
            raise AnalysisError(f"EXTRACT({field}) not supported yet")
        if isinstance(arg.type, T.TimestampType):
            arg = Cast(T.DATE, arg)
        if not isinstance(arg.type, T.DateType):
            raise AnalysisError(f"EXTRACT({field}) requires a date")
        return Call(T.BIGINT, f"extract_{field}", (arg,))

    def _datetime_unit(self, e: ast.FnCall, arity: int) -> str:
        if len(e.args) != arity:
            raise AnalysisError(f"{e.name} takes {arity} arguments")
        unit_ast = e.args[0]
        if not isinstance(unit_ast, ast.StrLit):
            raise AnalysisError(
                f"{e.name}: unit must be a string literal"
            )
        return unit_ast.value.lower()

    def _date_trunc_fn(self, e: ast.FnCall):
        unit = self._datetime_unit(e, 2)
        arg = self.analyze(e.args[1])
        if isinstance(arg.type, T.TimestampType):
            if unit not in ("year", "quarter", "month", "week", "day",
                            "hour", "minute", "second"):
                raise AnalysisError(f"date_trunc: bad unit {unit!r}")
            return Call(T.TIMESTAMP, f"ts_trunc_{unit}", (arg,))
        if not isinstance(arg.type, T.DateType):
            raise AnalysisError("date_trunc requires a date or timestamp")
        if unit not in ("year", "quarter", "month", "week", "day"):
            raise AnalysisError(
                f"date_trunc: unit {unit!r} invalid for DATE"
            )
        return Call(T.DATE, f"date_trunc_{unit}", (arg,))

    def _date_add_fn(self, e: ast.FnCall):
        unit = self._datetime_unit(e, 3)
        n = self.analyze(e.args[1])
        arg = self.analyze(e.args[2])
        if not n.type.is_integer:
            raise AnalysisError("date_add: amount must be an integer")

        def scaled(mult: int):
            if mult == 1:
                return n
            return Call(T.BIGINT, "multiply", (n, Literal(T.BIGINT, mult)))

        if isinstance(arg.type, T.TimestampType):
            micros = _INTERVAL_MICROS.get(unit)
            if micros is not None:
                return Call(T.TIMESTAMP, "add", (arg, scaled(micros)))
            months = _INTERVAL_MONTHS.get(unit)
            if months is not None:
                return Call(T.TIMESTAMP, "ts_add_months", (arg, scaled(months)))
            raise AnalysisError(f"date_add: bad unit {unit!r}")
        if not isinstance(arg.type, T.DateType):
            raise AnalysisError("date_add requires a date or timestamp")
        if unit in ("day", "week"):
            return Call(T.DATE, "add", (arg, scaled(7 if unit == "week" else 1)))
        months = _INTERVAL_MONTHS.get(unit)
        if months is None:
            raise AnalysisError(f"date_add: unit {unit!r} invalid for DATE")
        return Call(T.DATE, "add_months", (arg, scaled(months)))

    def _date_diff_fn(self, e: ast.FnCall):
        unit = self._datetime_unit(e, 3)
        a = self.analyze(e.args[1])
        b = self.analyze(e.args[2])
        a_ts = isinstance(a.type, T.TimestampType)
        b_ts = isinstance(b.type, T.TimestampType)
        if a_ts != b_ts:
            a, b = (Cast(T.TIMESTAMP, a) if not a_ts else a), (
                Cast(T.TIMESTAMP, b) if not b_ts else b
            )
            a_ts = b_ts = True
        if unit in _INTERVAL_MONTHS:
            months = Call(
                T.BIGINT,
                "ts_months_between" if a_ts else "months_between",
                (a, b),
            )
            per = _INTERVAL_MONTHS[unit]
            if per == 1:
                return months
            return Call(T.BIGINT, "divide", (months, Literal(T.BIGINT, per)))
        if a_ts:
            micros = _INTERVAL_MICROS.get(unit)
            if micros is None:
                raise AnalysisError(f"date_diff: bad unit {unit!r}")
            delta = Call(T.BIGINT, "subtract", (b, a))
            return Call(T.BIGINT, "divide", (delta, Literal(T.BIGINT, micros)))
        if unit not in ("day", "week"):
            raise AnalysisError(f"date_diff: unit {unit!r} invalid for DATE")
        delta = Call(T.BIGINT, "subtract", (b, a))
        if unit == "week":
            return Call(T.BIGINT, "divide", (delta, Literal(T.BIGINT, 7)))
        return delta

    def _FnCall(self, e: ast.FnCall):
        name = e.name.lower()
        if name in AGG_FNS or e.star:
            raise AnalysisError(
                f"aggregate function {name} not allowed in this context"
            )
        if name == "coalesce":
            args = [self.analyze(a) for a in e.args]
            rtype = args[0].type
            for a in args[1:]:
                rtype = T.common_super_type(rtype, a.type)
            args = [_cast_to(a, rtype) for a in args]
            return Call(rtype, "coalesce", tuple(args))
        if name == "nullif":
            # result is ``a`` with validity cleared where a = b (a
            # special form so dictionary-backed varchar flows through)
            a = self.analyze(e.args[0])
            b = self.analyze(e.args[1])
            ct = T.common_super_type(a.type, b.type)
            cond = Call(
                T.BOOLEAN, "eq", (_cast_to(a, ct), _cast_to(b, ct))
            )
            return Call(a.type, "nullif", (a, cond))
        if name in ("least", "greatest"):
            args = [self.analyze(a) for a in e.args]
            rtype = args[0].type
            for a in args[1:]:
                rtype = T.common_super_type(rtype, a.type)
            if isinstance(rtype, T.VarcharType):
                raise AnalysisError(
                    f"{name} over varchar is not supported yet"
                )
            args = [_cast_to(a, rtype) for a in args]
            cmp = "le" if name == "least" else "ge"
            out = args[0]
            for a in args[1:]:
                pick = Call(
                    rtype, "if",
                    (Call(T.BOOLEAN, cmp, (out, a)), out, a),
                )
                # NULL if any argument is NULL (reference semantics)
                either_null = Call(
                    T.BOOLEAN, "or", (
                        Call(T.BOOLEAN, "is_null", (out,)),
                        Call(T.BOOLEAN, "is_null", (a,)),
                    ),
                )
                out = Call(
                    rtype, "if",
                    (either_null, Literal(rtype, None), pick),
                )
            return out
        if name == "date_trunc":
            return self._date_trunc_fn(e)
        if name == "date_add":
            return self._date_add_fn(e)
        if name == "date_diff":
            return self._date_diff_fn(e)
        if name in _DATE_FIELDS or name in _EXTRACT_ALIASES or name in (
            "hour", "minute", "second",
        ):
            if len(e.args) != 1:
                raise AnalysisError(f"{name} takes 1 argument")
            return self._extract_field(name, self.analyze(e.args[0]))
        if name == "last_day_of_month":
            if len(e.args) != 1:
                raise AnalysisError(f"{name} takes 1 argument")
            arg = self.analyze(e.args[0])
            if isinstance(arg.type, T.TimestampType):
                arg = Cast(T.DATE, arg)
            return Call(T.DATE, "last_day_of_month", (arg,))
        if name == "concat":
            if len(e.args) < 2:
                raise AnalysisError("concat requires at least 2 arguments")
            out = self.analyze(e.args[0])
            for a in e.args[1:]:
                out = self._concat(ast.Binary("||", AnalyzedExpr(out), a))
            return out
        if name == "map":
            return self._map_constructor(e)
        if name == "row":
            return self._row_constructor(e)
        if name not in SCALAR_FNS:
            raise AnalysisError(f"unknown function {name}")
        ir_name, rt_fn = SCALAR_FNS[name]
        args = tuple(self.analyze(a) for a in e.args)
        return Call(rt_fn([a.type for a in args]), ir_name, args)

    def _map_constructor(self, e: "ast.FnCall"):
        """MAP(ARRAY[keys], ARRAY[values]) of constants -> a typed map
        Literal whose value is a tuple of (key, value) pairs in STORAGE
        form (the MapConstructor analog, constants-only like ARRAY[])."""
        if len(e.args) == 0:
            return Literal(T.MapType(T.UNKNOWN, T.UNKNOWN), ())
        if len(e.args) != 2:
            raise AnalysisError("map() takes (ARRAY, ARRAY)")
        k = self.analyze(e.args[0])
        v = self.analyze(e.args[1])
        if not (isinstance(k, Literal) and isinstance(k.type, T.ArrayType)
                and isinstance(v, Literal)
                and isinstance(v.type, T.ArrayType)):
            raise AnalysisError(
                "map() arguments must be constant arrays in this context"
            )
        if len(k.value) != len(v.value):
            raise AnalysisError("map() key and value arrays differ in length")
        if len(set(k.value)) != len(k.value):
            # reference: MapConstructor raises on duplicate keys
            raise AnalysisError("Duplicate map keys are not allowed")
        return Literal(
            T.MapType(k.type.element, v.type.element),
            tuple(zip(k.value, v.value)),
        )

    def _row_constructor(self, e: "ast.FnCall"):
        """ROW(e1, ...) of constants -> a typed row Literal (tuple in
        STORAGE form; anonymous fields)."""
        from trino_tpu.expr.compiler import _literal_device_value

        irs = [self.analyze(a) for a in e.args]
        vals = []
        for ir in irs:
            base = ir.arg if isinstance(ir, Cast) else ir
            if not isinstance(base, Literal):
                raise AnalysisError(
                    "ROW fields must be constants in this context"
                )
            if base.value is None:
                vals.append(None)
            elif base.type == ir.type:
                vals.append(_literal_device_value(base))
            else:
                # apply the cast before storage conversion (a Cast
                # wrapper changes the storage form: dates parse,
                # decimals rescale)
                vals.append(
                    _literal_device_value(Literal(ir.type, base.value))
                )
        return Literal(
            T.RowType(tuple((None, ir.type) for ir in irs)),
            tuple(vals),
        )

    def _ArrayLit(self, e: "ast.ArrayLit"):
        """ARRAY[...] of constants -> a typed array Literal whose value
        is a python tuple in STORAGE form (days/unscaled ints/strings).
        Non-constant elements would need a per-row pool build; only
        constants are supported (the dominant SQL shape: IN-style
        arrays, INSERT values, UNNEST literals)."""
        from trino_tpu.expr.compiler import _literal_device_value

        irs = [self.analyze(a) for a in e.items]
        if not irs:
            return Literal(T.ArrayType(T.UNKNOWN), ())
        t = irs[0].type
        for ir in irs[1:]:
            t = T.common_super_type(t, ir.type)
        vals = []
        for ir in irs:
            base = ir
            if isinstance(base, Cast):
                base = base.arg
            if not isinstance(base, Literal):
                raise AnalysisError(
                    "ARRAY elements must be constants in this context"
                )
            if base.value is None:
                raise AnalysisError(
                    "NULL array elements are not supported yet"
                )
            vals.append(_literal_device_value(
                base if base.type == t else Literal(t, base.value)
            ))
        return Literal(T.ArrayType(t), tuple(vals))

    def _Subscript(self, e: "ast.Subscript"):
        base = self.analyze(e.base)
        idx = self.analyze(e.index)
        if isinstance(base.type, T.MapType):
            # map[key] (MapSubscriptOperator; like element_at, absent
            # keys yield NULL rather than raising — the device LUT has
            # no raise path)
            return Call(base.type.value, "subscript", (base, idx))
        if isinstance(base.type, T.RowType):
            # row[ordinal], 1-based (SubscriptExpression over RowType)
            if not isinstance(idx, Literal) or idx.value is None:
                raise AnalysisError("ROW subscript must be a constant")
            k = int(idx.value)
            if not (1 <= k <= len(base.type.fields)):
                raise AnalysisError(
                    f"ROW subscript {k} out of range "
                    f"(1..{len(base.type.fields)})"
                )
            return Call(
                base.type.fields[k - 1][1], "row_field",
                (base, Literal(T.INTEGER, k - 1)),
            )
        if not isinstance(base.type, T.ArrayType):
            raise AnalysisError(
                f"cannot subscript {base.type} (ARRAY/MAP/ROW expected)"
            )
        return Call(base.type.element, "subscript", (base, idx))

    def _ScalarSubquery(self, e):
        raise AnalysisError(
            "scalar subqueries are only supported in WHERE/HAVING conjuncts"
        )

    def _Exists(self, e):
        raise AnalysisError("EXISTS is only supported as a WHERE conjunct")

    def _InSubquery(self, e):
        raise AnalysisError("IN (subquery) is only supported as a WHERE conjunct")


class AnalyzedExpr:
    """AST shim carrying an already-analyzed RowExpression, so rewrite
    helpers can re-enter binary analysis paths (e.g. concat folding)."""

    def __init__(self, ir: RowExpression):
        self.ir = ir


#: EXTRACT field aliases (reference: DateTimeFunctions @ScalarFunction
#: alias lists)
_EXTRACT_ALIASES = {
    "dow": "day_of_week",
    "doy": "day_of_year",
    "day_of_month": "day",
    "week_of_year": "week",
    "yow": "year_of_week",
}

_DATE_FIELDS = {
    "year", "quarter", "month", "week", "day",
    "day_of_week", "day_of_year", "year_of_week",
}

_INTERVAL_MICROS = {
    "second": 1_000_000,
    "minute": 60_000_000,
    "hour": 3_600_000_000,
    "day": 86_400_000_000,
    "week": 7 * 86_400_000_000,
}

_INTERVAL_MONTHS = {"month": 1, "quarter": 3, "year": 12}


def _cast_to(ir: RowExpression, target: T.DataType) -> RowExpression:
    if ir.type == target:
        return ir
    if isinstance(ir, Literal) and ir.value is None:
        return Literal(target, None)
    return Cast(target, ir)


def _add_months(date_text: str, months: int) -> str:
    y, m, d = (int(x) for x in date_text.split("-"))
    m0 = (y * 12 + (m - 1)) + months
    y2, m2 = divmod(m0, 12)
    m2 += 1
    # clamp day to target month length
    for day in range(d, 27, -1):
        try:
            return datetime.date(y2, m2, day).isoformat()
        except ValueError:
            continue
    return datetime.date(y2, m2, min(d, 28)).isoformat()
