"""Memory-governed cross-query caching: HBM hot-data + semantic results.

At serving scale the dominant traffic is repeated — the same dashboard
statements, the same dimension tables, the same build-side hash tables
re-materialized host->device on every query. The reference engine has
no device memory to exploit; this build does. Two tiers share this
module, both evictable under pressure through the MemoryContext /
MemoryPool machinery (memory.py):

* :class:`DeviceTableCache` — device-resident Pages pinned in HBM
  across queries: pruned/split scan outputs and *built* join-side
  pages, keyed by connector fingerprint + column set + pushed domains
  (+ the canonical subtree hash for fragments). Residency is reserved
  on a dedicated ``"cache"`` context of the worker's MemoryPool and the
  cache registers itself as a *revoker*: a query reservation that would
  breach ``query_max_memory_per_node`` evicts cache entries first and
  only raises if eviction cannot free enough — cached bytes can never
  turn into an ``ExceededMemoryLimitError`` for the query.

* :class:`SemanticResultCache` — host-side byte-bounded LRU over final
  result rows, keyed by a canonical plan hash (the blake2b trick the
  StringDictionary uses for cross-query program identity, generalized
  to whole optimized plans via ``plan_to_json``) plus session
  properties. Byte-identical repeat statements are served without
  planning a fragment or dispatching a task.

Staleness is governed by a generation counter per (connector
fingerprint, schema, table): every DML/invalidate path bumps it, and
entries carry the generations (plus connector ``table_version``) they
were built under — a probe revalidates and drops stale entries instead
of serving rows observed before a write.

Connector *fingerprints* fix the identity-keying defect of the original
scan caches: a connector may implement ``cache_fingerprint()``
returning ``(ident, content)`` — ``ident`` names the underlying data
(e.g. a parquet root path) so two connector instances over the same
files share entries, and ``content`` digests what is actually on disk
(footer sizes + mtimes) so an out-of-band rewrite busts them. Without
the hook, a per-instance token preserves the old isolation contract.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import threading
from collections import OrderedDict
from dataclasses import dataclass, field

__all__ = [
    "connector_fingerprint",
    "GENERATIONS",
    "DeviceTableCache",
    "SemanticResultCache",
    "CacheStats",
    "plan_digest",
    "plan_scan_tables",
    "table_tokens",
    "copy_page",
    "DEVICE",
]


# ---- connector fingerprints -------------------------------------------------

_IDENT_LOCK = threading.Lock()
_IDENT_SEQ = itertools.count(1)


def _instance_ident(connector) -> str:
    """Per-instance identity token (monotonic, never reused — unlike
    ``id()`` after a GC) for connectors without a content hook."""
    tok = getattr(connector, "_cache_ident", None)
    if tok is None:
        with _IDENT_LOCK:
            tok = getattr(connector, "_cache_ident", None)
            if tok is None:
                tok = f"id:{next(_IDENT_SEQ)}"
                try:
                    connector._cache_ident = tok
                except Exception:
                    return f"id:{id(connector)}"
    return tok


def connector_fingerprint(connector) -> tuple[str, str]:
    """``(ident, content)`` cache identity for one connector.

    ``ident`` keys cache storage: equal idents share entries. ``content``
    is a change-sensitive digest compared on every lookup — a mismatch
    (files rewritten out-of-band) invalidates everything stored under
    the ident. Connectors opt in by implementing ``cache_fingerprint()``
    (returning ``(ident, content)`` or a bare ident string); the
    fallback is a per-instance token, preserving the historical
    isolation contract of identity keying."""
    hook = getattr(connector, "cache_fingerprint", None)
    if hook is not None:
        try:
            out = hook()
        except Exception:
            out = None
        if out:
            if isinstance(out, tuple) and len(out) == 2:
                return str(out[0]), str(out[1])
            return str(out), ""
    return _instance_ident(connector), ""


# ---- generation counters ----------------------------------------------------

class GenerationCounter:
    """Monotonic write generation per (ident, schema, table).

    The explicit invalidation API of the cache subsystem: every DML /
    invalidate_scan path bumps the generation, and both tiers store the
    generations their entries were built under. A probe whose current
    generation differs drops the entry — no scan-time coordination with
    writers is needed."""

    def __init__(self):
        self._lock = threading.Lock()
        self._gen: dict[tuple, int] = {}

    def get(self, ident: str, schema: str, table: str) -> int:
        with self._lock:
            return self._gen.get((ident, schema, table), 0)

    def bump(self, ident: str, schema: str, table: str) -> int:
        with self._lock:
            g = self._gen.get((ident, schema, table), 0) + 1
            self._gen[(ident, schema, table)] = g
            return g


GENERATIONS = GenerationCounter()


# ---- per-query stats --------------------------------------------------------

@dataclass
class CacheStats:
    """Per-query cache traffic, surfaced on ``QueryResult.cache_stats``
    and as the EXPLAIN ANALYZE ``Cache:`` line. The device tier is
    recorded by whichever executor ran the scans — in fleet mode those
    live worker-side, so coordinator-visible device numbers cover only
    the embedded/local path."""

    result_hit: bool | None = None
    result_bytes: int = 0
    device_hits: int = 0
    device_misses: int = 0
    device_bytes: int = 0

    def record_device(self, hit: bool, nbytes: int = 0) -> None:
        if hit:
            self.device_hits += 1
            self.device_bytes += nbytes
        else:
            self.device_misses += 1

    def as_dict(self) -> dict:
        return {
            "result": {
                "hit": self.result_hit,
                "bytes": self.result_bytes,
            },
            "device": {
                "hits": self.device_hits,
                "misses": self.device_misses,
                "bytes": self.device_bytes,
            },
        }

    def explain_line(self) -> str:
        from trino_tpu.memory import format_bytes

        if self.result_hit:
            res = f"result hit ({format_bytes(self.result_bytes)})"
        elif self.result_hit is None:
            res = "result off"
        else:
            res = "result miss"
        dev = (
            f"device {self.device_hits} hit / "
            f"{self.device_misses} miss"
        )
        if self.device_bytes:
            dev += f" ({format_bytes(self.device_bytes)})"
        return f"Cache: {res}; {dev}"


# ---- plan fingerprinting ----------------------------------------------------

#: calls whose output depends on more than their inputs; plans using
#: them are never cached (none are registered today — future-proofing)
_NONDETERMINISTIC_CALLS = frozenset(
    {"random", "rand", "now", "uuid", "current_timestamp"}
)


def _json_calls(j) -> bool:
    """True when the serialized plan references a nondeterministic
    call anywhere (expressions serialize as {"k": "call", "n": name})."""
    if isinstance(j, dict):
        if j.get("k") == "call" and j.get("n") in _NONDETERMINISTIC_CALLS:
            return True
        return any(_json_calls(v) for v in j.values())
    if isinstance(j, list):
        return any(_json_calls(v) for v in j)
    return False


def plan_digest(plan, session) -> str | None:
    """Canonical semantic hash of an optimized plan (sub)tree.

    blake2b over the sorted-key JSON codec (plan/serde.py) — operators,
    symbols, pushed domains, literals — plus every session property, so
    two sessions that could execute differently never share an entry.
    Returns None for plans the codec cannot serialize (those are not
    cacheable)."""
    from trino_tpu.plan.serde import plan_to_json

    try:
        j = plan_to_json(plan)
    except (TypeError, ValueError):
        return None
    if _json_calls(j):
        return None
    props = {
        str(k): repr(v)
        for k, v in (getattr(session, "properties", None) or {}).items()
    }
    payload = json.dumps(
        {"plan": j, "props": props}, sort_keys=True, default=str
    )
    return hashlib.blake2b(
        payload.encode("utf-8"), digest_size=16
    ).hexdigest()


def plan_scan_tables(plan) -> list[tuple[str, str, str]] | None:
    """Distinct (catalog, schema, table) scanned by a plan, or None
    when the plan cannot be serialized (treat as uncacheable)."""
    from trino_tpu.plan.serde import plan_to_json

    try:
        j = plan_to_json(plan)
    except (TypeError, ValueError):
        return None
    out: list[tuple[str, str, str]] = []
    opaque = []

    def walk(d):
        if isinstance(d, dict):
            kind = d.get("kind")
            if kind == "TableScan":
                t = (d["catalog"], d["schema"], d["table"])
                if t not in out:
                    out.append(t)
            elif kind == "RemoteSource":
                # reads another stage's exchange output: the subtree
                # hash does not address its DATA, so nothing under a
                # RemoteSource is ever cacheable
                opaque.append(kind)
            for v in d.values():
                walk(v)
        elif isinstance(d, list):
            for v in d:
                walk(v)

    walk(j)
    if opaque:
        return None
    return out


def table_tokens(plan, metadata) -> tuple | None:
    """Staleness validators for every table a plan scans: one
    ``(ident, schema, table, generation, table_version)`` tuple per
    scan. None when any scanned connector is uncacheable (live system
    views) or unresolvable — the caller must then skip caching."""
    tables = plan_scan_tables(plan)
    if tables is None:
        return None
    toks = []
    for cat, sch, tab in tables:
        try:
            conn = metadata.connector(cat)
        except Exception:
            return None
        if conn is None or not getattr(conn, "cacheable", True):
            return None
        ident, _content = connector_fingerprint(conn)
        try:
            version = conn.table_version(sch, tab)
        except Exception:
            version = 0
        toks.append(
            (ident, sch, tab, GENERATIONS.get(ident, sch, tab), version)
        )
    return tuple(toks)


# ---- device tier ------------------------------------------------------------

def page_device_bytes(page) -> int:
    """Device bytes pinned by one cached Page (columns + validity)."""
    total = getattr(page.mask, "nbytes", 0) or 0
    for c in page.columns:
        total += getattr(c.data, "nbytes", 0) or 0
        if getattr(c, "valid", None) is not None:
            total += getattr(c.valid, "nbytes", 0) or 0
    return total


def copy_page(page):
    """Shallow copy safe to hand to an executor: operators replace
    entries in ``page.columns`` in place (join dictionary unification),
    so cached pages must never escape by reference."""
    from trino_tpu.page import Page

    return Page(
        list(page.names), list(page.columns), page.mask,
        known_rows=page.known_rows, packed=page.packed,
    )


@dataclass
class _DeviceEntry:
    page: object
    nbytes: int
    #: ((ident, schema, table, generation, version), ...) validators
    tokens: tuple
    #: MemoryContext holding this entry's reservation (None = untracked)
    ctx: object = None


class DeviceTableCache:
    """HBM-resident cross-query Page cache with pool-governed eviction.

    Keys are content-addressed: scans key on connector fingerprint +
    columns + pushed domains (+ split range), fragments on the
    canonical subtree hash — so sharing is safe across executors and
    staleness reduces to generation/content checks. Residency is
    reserved on the owning pool's ``"cache"`` context via
    ``try_reserve`` (never raising into a query) and the cache is
    registered as the pool's revoker: queries under pressure evict
    entries LRU-first before their own reservation can fail."""

    def __init__(self, max_bytes: int = 256 << 20):
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _DeviceEntry]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        #: id(pool) -> (pool, cache MemoryContext); revoker registered once
        self._pools: dict[int, tuple] = {}

    # -- keys ----------------------------------------------------------------

    @staticmethod
    def scan_key(
        connector, schema: str, table: str, columns,
        domains=None, split=None,
    ) -> tuple | None:
        """Key for a scanned Page; None when the connector opted out of
        caching. Pushed domains are part of the key (a pruned row set
        is filter-specific); the connector content digest and
        table_version fold in so external rewrites miss naturally."""
        if not getattr(connector, "cacheable", True):
            return None
        ident, content = connector_fingerprint(connector)
        try:
            version = connector.table_version(schema, table)
        except Exception:
            version = 0
        dkey = None
        if domains:
            dkey = tuple(sorted(
                (c, repr(dom)) for c, dom in domains.items()
            ))
        skey = None
        if split is not None:
            skey = (split.start, split.count)
        return (
            "scan", ident, content, schema, table,
            tuple(columns), dkey, skey, version,
        )

    @staticmethod
    def frag_key(digest: str) -> tuple:
        """Key for a built plan-fragment Page (join build side)."""
        return ("frag", digest)

    # -- pool attachment -----------------------------------------------------

    def _ctx(self, pool):
        if pool is None:
            return None
        with self._lock:
            ent = self._pools.get(id(pool))
            if ent is not None:
                return ent[1]
        # register outside our lock: query_context takes the pool lock
        ctx = pool.query_context("cache")
        add = getattr(pool, "add_revoker", None)
        if add is not None:
            add(self.revoke)
        with self._lock:
            self._pools.setdefault(id(pool), (pool, ctx))
            return self._pools[id(pool)][1]

    # -- traffic -------------------------------------------------------------

    def get(self, key, stats: CacheStats | None = None):
        """Resident Page for ``key`` (a shallow copy) or None. Stale
        entries (bumped generation) are dropped on probe."""
        from trino_tpu import telemetry

        if key is None:
            return None
        freed = None
        try:
            with self._lock:
                e = self._entries.get(key)
                if e is not None and not self._tokens_current(e.tokens):
                    freed = self._pop_locked(key)
                    e = None
                if e is None:
                    self.misses += 1
                    if stats is not None:
                        stats.record_device(False)
                    return None
                self._entries.move_to_end(key)
                self.hits += 1
                if stats is not None:
                    stats.record_device(True, e.nbytes)
                return copy_page(e.page)
        finally:
            self._free_entries(freed)
            telemetry.DEVICE_CACHE_ENTRIES.set(len(self._entries))
            telemetry.DEVICE_CACHE_BYTES.set(self._bytes)

    def put(self, key, page, tokens: tuple | None, pool=None) -> bool:
        """Pin one Page. Reserves its device bytes on the pool's cache
        context (``try_reserve`` — under pressure the pool's revokers,
        including this cache, shed bytes first; if residency still
        doesn't fit the page simply isn't cached). Returns True when
        the page is resident after the call."""
        from trino_tpu import telemetry

        if key is None or tokens is None:
            return False
        nbytes = page_device_bytes(page)
        if nbytes > self.max_bytes:
            return False
        ctx = self._ctx(pool)
        if ctx is not None and not ctx.try_reserve(nbytes):
            return False
        evicted = []
        with self._lock:
            old = self._pop_locked(key)
            if old:
                evicted.extend(old)
            self._entries[key] = _DeviceEntry(
                copy_page(page), nbytes, tokens, ctx
            )
            self._bytes += nbytes
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                k = next(iter(self._entries))
                evicted.extend(self._pop_locked(k))
                self.evictions += 1
                telemetry.DEVICE_CACHE_EVICTIONS.inc()
        self._free_entries(evicted)
        telemetry.DEVICE_CACHE_ENTRIES.set(len(self._entries))
        telemetry.DEVICE_CACHE_BYTES.set(self._bytes)
        return True

    # -- eviction / invalidation --------------------------------------------

    def _tokens_current(self, tokens: tuple) -> bool:
        return all(
            GENERATIONS.get(ident, sch, tab) == gen
            for ident, sch, tab, gen, _version in tokens
        )

    def _pop_locked(self, key) -> list:
        """Remove one entry under the lock; returns it for the caller
        to free OUTSIDE the lock (freeing takes the pool lock)."""
        e = self._entries.pop(key, None)
        if e is None:
            return []
        self._bytes -= e.nbytes
        return [e]

    def _free_entries(self, entries) -> None:
        for e in entries or ():
            if e.ctx is not None:
                e.ctx.free(e.nbytes)

    def revoke(self, nbytes: int) -> int:
        """MemoryPool revoker: shed at least ``nbytes`` LRU-first so a
        query reservation under pressure succeeds instead of raising.
        Called outside the pool lock; returns bytes freed."""
        from trino_tpu import telemetry

        victims = []
        freed = 0
        with self._lock:
            while self._entries and freed < nbytes:
                k = next(iter(self._entries))
                popped = self._pop_locked(k)
                for e in popped:
                    freed += e.nbytes
                victims.extend(popped)
                self.evictions += 1
                telemetry.DEVICE_CACHE_EVICTIONS.inc()
        self._free_entries(victims)
        telemetry.DEVICE_CACHE_ENTRIES.set(len(self._entries))
        telemetry.DEVICE_CACHE_BYTES.set(self._bytes)
        return freed

    def invalidate(self, ident: str, schema: str, table: str) -> None:
        """Drop every entry built over one table (DML path; callers
        bump GENERATIONS too so remote tiers revalidate)."""
        victims = []
        with self._lock:
            dead = [
                k for k, e in self._entries.items()
                if any(
                    t[0] == ident and t[1] == schema and t[2] == table
                    for t in e.tokens
                )
            ]
            for k in dead:
                victims.extend(self._pop_locked(k))
        self._free_entries(victims)

    def clear(self) -> None:
        victims = []
        with self._lock:
            for k in list(self._entries):
                victims.extend(self._pop_locked(k))
        self._free_entries(victims)

    # -- observability -------------------------------------------------------

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }


# ---- result tier ------------------------------------------------------------

def _rows_nbytes(names, rows) -> int:
    """Cheap result-size estimate (python tuples of scalars)."""
    per_cell = 24
    header = 64
    return header + len(names) * 16 + len(rows) * (
        56 + len(names) * per_cell
    )


@dataclass
class _ResultEntry:
    names: list
    rows: list
    ordered: bool
    nbytes: int
    tokens: tuple = field(default_factory=tuple)


class SemanticResultCache:
    """Byte-bounded LRU over final result rows, keyed by plan digest.

    Instances are scoped to one runner (a ServingRunner shares one
    across its per-query FleetRunners; a long-lived QueryRunner owns
    its own) rather than process-global: cache visibility then matches
    session lifetime, and concurrent unrelated runners — fault-injection
    twins, A/B benches — never observe each other's entries."""

    def __init__(self, max_bytes: int = 64 << 20):
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _ResultEntry]" = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, digest: str, tokens: tuple | None) -> _ResultEntry | None:
        """Validated entry for one plan digest. ``tokens`` are the
        CURRENT staleness validators (table_tokens at probe time); a
        mismatch with the stored ones drops the entry — the generation
        counter made it stale."""
        from trino_tpu import telemetry

        with self._lock:
            e = self._entries.get(digest)
            if e is not None and (tokens is None or e.tokens != tokens):
                self._entries.pop(digest)
                self._bytes -= e.nbytes
                e = None
            if e is None:
                self.misses += 1
                telemetry.RESULT_CACHE_MISSES.inc()
                telemetry.RESULT_CACHE_BYTES.set(self._bytes)
                return None
            self._entries.move_to_end(digest)
            self.hits += 1
            telemetry.RESULT_CACHE_HITS.inc()
            return _ResultEntry(
                list(e.names), list(e.rows), e.ordered, e.nbytes, e.tokens
            )

    def put(
        self, digest: str, names, rows, ordered: bool,
        tokens: tuple | None,
    ) -> bool:
        from trino_tpu import telemetry

        if tokens is None:
            return False
        nbytes = _rows_nbytes(names, rows)
        if nbytes > self.max_bytes:
            return False
        with self._lock:
            old = self._entries.pop(digest, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[digest] = _ResultEntry(
                list(names), list(rows), bool(ordered), nbytes, tokens
            )
            self._bytes += nbytes
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                _, ev = self._entries.popitem(last=False)
                self._bytes -= ev.nbytes
                self.evictions += 1
            telemetry.RESULT_CACHE_BYTES.set(self._bytes)
        return True

    def invalidate_all(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "bytes": self._bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
            }


#: the process-wide device tier every LocalExecutor pins through (keys
#: are content-addressed, so sharing across executors is always safe)
DEVICE = DeviceTableCache()

#: result-cache instances alive in this process (ServingRunner /
#: QueryRunner registrants) — feeds system.runtime.caches
_RESULT_INSTANCES: "weakref.WeakSet" = None  # set lazily below


def _result_instances():
    global _RESULT_INSTANCES
    if _RESULT_INSTANCES is None:
        import weakref

        _RESULT_INSTANCES = weakref.WeakSet()
    return _RESULT_INSTANCES


def register_result_cache(cache: SemanticResultCache) -> SemanticResultCache:
    _result_instances().add(cache)
    return cache


def result_tier_snapshot() -> dict:
    """Aggregated stats across every live result-cache instance."""
    agg = {
        "entries": 0, "bytes": 0, "max_bytes": 0,
        "hits": 0, "misses": 0, "evictions": 0,
    }
    for c in list(_result_instances()):
        s = c.snapshot()
        for k in agg:
            agg[k] += s[k]
    return agg
