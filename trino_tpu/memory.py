"""Memory governance: query memory contexts, worker pools, and the
cluster memory manager.

The analog of the reference's memory subsystem
(core/trino-memory-context LocalMemoryContext/AggregatedMemoryContext,
MAIN/memory/MemoryPool.java and MAIN/memory/ClusterMemoryManager.java):

- ``MemoryContext`` — a node in the query → task → operator tree.
  Every executor allocation path reports through ``reserve(nbytes)`` /
  ``free(nbytes)``; reservations roll up the tree into the worker's
  ``MemoryPool``, which enforces ``query_max_memory_per_node``.
- ``MemoryPool`` — one per worker process (owned by the executor).
  Tracks live reservations and the lifetime high-water mark; a
  reservation that would push the pool over the per-node cap raises
  ``ExceededMemoryLimitError`` (typed, and classified non-retryable by
  FTE — an allocation that can never fit must not be hedged/retried).
- ``ClusterMemoryManager`` — coordinator-side. Aggregates per-worker
  pool snapshots shipped on task-status/heartbeat responses, enforces
  the cluster-wide ``query_max_memory``, and runs the low-memory kill
  policy: the query with the largest total reservation is killed with
  a human-readable error carrying per-worker attribution.

Accounting model: execution is batch-synchronous, so operator working
sets are reserved for the duration of one fused device computation and
freed immediately after — the governed quantity is therefore the
*peak* concurrent reservation, which is exactly the high-water mark
the spill-tier tests assert against. Revocation (MemoryRevokingScheme
analog) happens one level up, in the executor: when a hash join's
estimated resident working set exceeds the per-node cap, the operator
is switched into the ``exec/spill.py`` streamed/grace tier before any
over-cap reservation is attempted; only when even the revoked path
cannot fit does the reserve raise.
"""

from __future__ import annotations

import threading

from trino_tpu import fault, telemetry
from trino_tpu import session_properties as SP

__all__ = [
    "ExceededMemoryLimitError",
    "MemoryContext",
    "MemoryPool",
    "ClusterMemoryManager",
    "validate_session_limits",
    "format_bytes",
]


class ExceededMemoryLimitError(RuntimeError):
    """A reservation (or a cluster-wide aggregate) breached a memory
    cap. Classified non-retryable by the fleet's FTE tier: retrying or
    speculating an allocation that can never fit only burns cluster
    time."""


def format_bytes(n: int) -> str:
    """Human-readable data size matching the session-property literals
    ('2GB', '512.0MB') — binary multipliers, like io.airlift DataSize."""
    n = int(n)
    for unit, mult in (("GB", 1 << 30), ("MB", 1 << 20), ("kB", 1 << 10)):
        if n >= mult:
            v = n / mult
            s = f"{v:.2f}".rstrip("0").rstrip(".")
            return f"{s}{unit}"
    return f"{n}B"


class MemoryContext:
    """One node of the query → task → operator accounting tree.

    ``reserve``/``free`` propagate up to the root and into the owning
    pool, where the per-node cap is checked; peaks are maintained at
    every level so attribution survives the frees."""

    __slots__ = ("name", "parent", "pool", "reserved_bytes",
                 "peak_bytes", "_children")

    def __init__(self, name: str, pool: "MemoryPool",
                 parent: "MemoryContext | None" = None):
        self.name = name
        self.pool = pool
        self.parent = parent
        self.reserved_bytes = 0
        self.peak_bytes = 0
        self._children: dict[str, MemoryContext] = {}

    def child(self, name: str) -> "MemoryContext":
        """Get-or-create a named child (task or operator context).
        Reuse by name keeps the tree bounded across repeated operator
        invocations."""
        with self.pool._lock:
            ctx = self._children.get(name)
            if ctx is None:
                ctx = MemoryContext(name, self.pool, parent=self)
                self._children[name] = ctx
            return ctx

    def reserve(self, nbytes: int) -> None:
        """Claim ``nbytes`` against the pool; raises
        ``ExceededMemoryLimitError`` when the pool's per-node cap would
        be breached (nothing is recorded in that case)."""
        if nbytes <= 0:
            return
        # chaos seam: an injected device-oom is a TRANSIENT allocation
        # failure (a busy device), distinct from the semantic
        # ExceededMemoryLimitError — FTE retries the former, never the
        # latter
        fault.check("device-oom", tag=self.name)
        self.pool._reserve(self, int(nbytes))

    def try_reserve(self, nbytes: int) -> bool:
        """Best-effort claim: False (nothing recorded) instead of
        raising when the per-node cap would be breached. Used by the
        direct-exchange buffer pool, where a failed reservation means
        "serve this partition from the spool", not a query error —
        deliberately NOT a device-oom chaos seam, so arming that site
        keeps its existing seeded schedules."""
        if nbytes <= 0:
            return True
        try:
            self.pool._reserve(self, int(nbytes))
        except ExceededMemoryLimitError:
            return False
        return True

    def free(self, nbytes: int) -> None:
        if nbytes <= 0:
            return
        self.pool._free(self, int(nbytes))

    def reserving(self, nbytes: int):
        """Context manager: reserve for the duration of a block and
        free on exit — the streamed-batch working-set idiom (reserve a
        batch, run the compiled chain, release)."""
        import contextlib

        @contextlib.contextmanager
        def _scope():
            self.reserve(nbytes)
            try:
                yield self
            finally:
                self.free(nbytes)

        return _scope()

    def snapshot(self) -> dict:
        with self.pool._lock:
            return {
                "name": self.name,
                "reserved_bytes": self.reserved_bytes,
                "peak_bytes": self.peak_bytes,
            }


class MemoryPool:
    """Worker-local pool enforcing ``query_max_memory_per_node``.

    ``limit_provider`` is read at reservation time (session overrides
    arrive per-task on workers); 0 means unlimited. Thread-safe: the
    spill tier's double-buffered producer reserves from a prefetch
    thread while the consumer runs the chain."""

    #: per-query contexts kept after their query finishes (peaks feed
    #: system.runtime.memory and late coordinator polls); oldest idle
    #: entries beyond this are evicted so long-lived workers stay flat
    MAX_RETAINED_QUERIES = 64

    def __init__(self, limit_provider=None, node_id: str = "local-0"):
        self._lock = threading.Lock()
        self._limit_provider = limit_provider
        self.node_id = node_id
        self.reserved_bytes = 0
        #: lifetime high-water mark across all queries — the quantity
        #: the pre-governance ``tracked_bytes_hwm`` recorded
        self.peak_bytes = 0
        self._queries: dict[str, MemoryContext] = {}
        #: revocable consumers (cache tiers): ``fn(nbytes) -> freed``
        #: called OUTSIDE the pool lock when a reservation would breach
        #: the limit, before the reservation is failed
        self._revokers: list = []

    def limit_bytes(self) -> int:
        if self._limit_provider is None:
            return 0
        return int(self._limit_provider() or 0)

    def query_context(self, query_id: str) -> MemoryContext:
        """Root context for one query (get-or-create)."""
        with self._lock:
            ctx = self._queries.get(query_id)
            if ctx is None:
                self._gc_locked()
                ctx = MemoryContext(query_id, self)
                self._queries[query_id] = ctx
            return ctx

    def _gc_locked(self) -> None:
        while len(self._queries) >= self.MAX_RETAINED_QUERIES:
            victim = next(
                (q for q, c in self._queries.items()
                 if c.reserved_bytes == 0),
                None,
            )
            if victim is None:
                return
            del self._queries[victim]

    def _root(self, ctx: MemoryContext) -> MemoryContext:
        while ctx.parent is not None:
            ctx = ctx.parent
        return ctx

    def add_revoker(self, fn) -> None:
        """Register a revocable consumer. Revokers shed lowest-priority
        bytes (cache residency) when a reservation would otherwise fail,
        so cached data can never turn into a query's memory error."""
        with self._lock:
            if fn not in self._revokers:
                self._revokers.append(fn)

    def _try_commit(self, ctx: MemoryContext, nbytes: int) -> bool:
        limit = self.limit_bytes()
        with self._lock:
            if limit and self.reserved_bytes + nbytes > limit:
                return False
            cur = ctx
            while cur is not None:
                cur.reserved_bytes += nbytes
                if cur.reserved_bytes > cur.peak_bytes:
                    cur.peak_bytes = cur.reserved_bytes
                cur = cur.parent
            self.reserved_bytes += nbytes
            if self.reserved_bytes > self.peak_bytes:
                self.peak_bytes = self.reserved_bytes
            telemetry.MEMORY_RESERVED.set(self.reserved_bytes, pool=self.node_id)
            telemetry.MEMORY_PEAK.set(self.peak_bytes, pool=self.node_id)
            return True

    def _reserve(self, ctx: MemoryContext, nbytes: int) -> None:
        if self._try_commit(ctx, nbytes):
            return
        # would breach: ask revokers (cache tiers) to shed bytes, then
        # retry — outside the lock, since revokers free via _free
        with self._lock:
            revokers = list(self._revokers)
        for fn in revokers:
            try:
                fn(nbytes)
            except Exception:
                continue
            if self._try_commit(ctx, nbytes):
                return
        if revokers and self._try_commit(ctx, nbytes):
            return
        limit = self.limit_bytes()
        with self._lock:
            root = self._root(ctx)
            raise ExceededMemoryLimitError(
                f"Query exceeded per-node memory limit of "
                f"{format_bytes(limit)} "
                f"[query_max_memory_per_node]: requested "
                f"{format_bytes(nbytes)} in {ctx.name!r}, "
                f"{format_bytes(self.reserved_bytes)} already "
                f"reserved on {self.node_id} "
                f"(query {root.name} peak "
                f"{format_bytes(root.peak_bytes)})"
            )

    def _free(self, ctx: MemoryContext, nbytes: int) -> None:
        with self._lock:
            cur = ctx
            while cur is not None:
                cur.reserved_bytes = max(0, cur.reserved_bytes - nbytes)
                cur = cur.parent
            self.reserved_bytes = max(0, self.reserved_bytes - nbytes)
            telemetry.MEMORY_RESERVED.set(self.reserved_bytes, pool=self.node_id)

    def snapshot(self) -> dict:
        """JSON-safe pool state shipped on task-status/heartbeat
        responses and read by system.runtime.memory."""
        with self._lock:
            return {
                "node_id": self.node_id,
                "reserved_bytes": self.reserved_bytes,
                "peak_bytes": self.peak_bytes,
                "limit_bytes": self.limit_bytes(),
                "queries": {
                    qid: {
                        "reserved_bytes": c.reserved_bytes,
                        "peak_bytes": c.peak_bytes,
                    }
                    for qid, c in self._queries.items()
                },
            }


class ClusterMemoryManager:
    """Coordinator-side aggregate over per-worker pool snapshots.

    ``observe`` ingests a pool snapshot from a task-status poll or
    heartbeat; ``enforce`` applies the cluster-wide cap and the kill
    policy. Batch-synchronous reservations are transient, so the
    governed per-query quantity is the peak reservation on each
    worker, summed across workers."""

    def __init__(self):
        self._lock = threading.Lock()
        #: node -> most recent pool snapshot
        self._nodes: dict[str, dict] = {}

    def observe(self, node: str, snapshot: dict | None) -> None:
        if not snapshot:
            return
        with self._lock:
            self._nodes[str(node)] = snapshot

    def nodes(self) -> dict[str, dict]:
        with self._lock:
            return dict(self._nodes)

    def per_worker(self, query_id: str) -> dict[str, int]:
        """node -> peak bytes this query reserved there."""
        out = {}
        with self._lock:
            for node, snap in self._nodes.items():
                q = (snap.get("queries") or {}).get(query_id)
                if q:
                    out[node] = int(q.get("peak_bytes", 0))
        return out

    def query_total(self, query_id: str) -> int:
        return sum(self.per_worker(query_id).values())

    def query_totals(self) -> dict[str, int]:
        totals: dict[str, int] = {}
        with self._lock:
            for snap in self._nodes.values():
                for qid, q in (snap.get("queries") or {}).items():
                    totals[qid] = totals.get(qid, 0) + int(
                        q.get("peak_bytes", 0)
                    )
        return totals

    def pick_victim(
        self, cap_bytes: int, running=None
    ) -> tuple[str, str] | None:
        """Low-memory kill policy, selection only: among ``running``
        queries (all observed ones when None), find the query with the
        LARGEST cluster-total reservation; return ``(query_id, error
        message with per-worker attribution)`` when it exceeds the
        cap, else None. Selection is separated from the raise so a
        multi-query serving layer can kill a victim OTHER than the
        query whose dispatch loop noticed the breach — the reference's
        ClusterMemoryManager kills the biggest query on the cluster,
        not necessarily the one that tripped the check."""
        if not cap_bytes:
            return None
        totals = self.query_totals()
        # the device-cache tier reserves under a pseudo-query context
        # named "cache" on every worker pool; it is revocable storage,
        # never a killable query (pressure evicts it via pool revokers)
        totals.pop("cache", None)
        if running is not None:
            totals = {q: t for q, t in totals.items() if q in running}
        if not totals:
            return None
        victim = max(totals, key=lambda q: totals[q])
        if totals[victim] <= cap_bytes:
            return None
        per = self.per_worker(victim)
        attribution = ", ".join(
            f"{node}={format_bytes(b)}" for node, b in sorted(per.items())
        )
        return victim, (
            f"Query {victim} killed by the cluster memory manager: "
            f"total reservation {format_bytes(totals[victim])} across "
            f"{len(per)} worker(s) exceeds query_max_memory "
            f"{format_bytes(cap_bytes)} ({attribution})"
        )

    def enforce(self, cap_bytes: int, running=None) -> None:
        """Cluster-wide ``query_max_memory`` + low-memory kill policy:
        when any query's cluster-total reservation exceeds the cap,
        the query with the LARGEST total is killed, with per-worker
        attribution in the error text. ``running`` (optional set of
        query ids) restricts the kill candidates: worker pools retain
        finished queries' peaks for observability, and a finished
        query cannot be killed."""
        picked = self.pick_victim(cap_bytes, running)
        if picked is None:
            return
        telemetry.MEMORY_KILLS.inc()
        raise ExceededMemoryLimitError(picked[1])


def validate_session_limits(session) -> None:
    """Statement-time consistency check over the memory caps: an
    inconsistent combination fails fast with a ValueError (already a
    non-retryable class for FTE) instead of being silently accepted."""
    qmax = SP.parse_data_size(SP.get(session, "query_max_memory"))
    per_node = SP.parse_data_size(
        SP.get(session, "query_max_memory_per_node")
    )
    if qmax and per_node > qmax:
        raise ValueError(
            f"query_max_memory_per_node ({format_bytes(per_node)}) "
            f"must not exceed query_max_memory ({format_bytes(qmax)})"
        )
    hbm = int(SP.get(session, "hbm_budget_bytes"))
    if per_node and hbm > per_node:
        raise ValueError(
            f"hbm_budget_bytes ({format_bytes(hbm)}) must not exceed "
            f"query_max_memory_per_node ({format_bytes(per_node)})"
        )
