from trino_tpu.sql.parser import parse_statement

__all__ = ["parse_statement"]
