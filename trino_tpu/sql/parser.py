"""Recursive-descent SQL parser.

The analog of the reference's SqlParser + AstBuilder
(PARSER/parser/SqlParser.java:51) over the SqlBase.g4 grammar. Covers
the query language: SELECT with joins/subqueries/set operations, WITH,
scalar/EXISTS/IN subqueries, CASE, CAST, EXTRACT, BETWEEN, LIKE,
interval/date literals, EXPLAIN [ANALYZE], SHOW/DESCRIBE/USE/SET
SESSION. Grows toward full DDL/DML as the engine does.
"""

from __future__ import annotations

from trino_tpu.sql import ast
from trino_tpu.sql.lexer import SqlSyntaxError, Token, tokenize

__all__ = ["parse_statement", "parse_expression", "SqlSyntaxError"]


def parse_statement(sql: str) -> ast.Statement:
    p = _Parser(tokenize(sql))
    stmt = p.statement()
    p.accept_op(";")
    p.expect_eof()
    return stmt


def parse_expression(sql: str) -> ast.Expr:
    """Parse one standalone scalar expression (row filters / column
    masks — the SPI ViewExpression surface carries SQL text)."""
    p = _Parser(tokenize(sql))
    e = p.expr()
    p.expect_eof()
    return e


class _Parser:
    def __init__(self, tokens: list[Token]):
        self.tokens = tokens
        self.i = 0
        self._param_count = 0  # positional ? placeholders seen

    # ---- token helpers ---------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        return self.tokens[min(self.i + ahead, len(self.tokens) - 1)]

    def next(self) -> Token:
        t = self.tokens[self.i]
        self.i += 1
        return t

    def at_kw(self, *words: str) -> bool:
        t = self.peek()
        return t.kind == "KEYWORD" and t.text in words

    def accept_kw(self, *words: str) -> bool:
        if self.at_kw(*words):
            self.i += 1
            return True
        return False

    def expect_kw(self, word: str) -> None:
        if not self.accept_kw(word):
            t = self.peek()
            raise SqlSyntaxError(f"expected {word.upper()} but found {t.text!r} at {t.pos}")

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "OP" and t.text in ops

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.i += 1
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            t = self.peek()
            raise SqlSyntaxError(f"expected {op!r} but found {t.text!r} at {t.pos}")

    def expect_eof(self) -> None:
        t = self.peek()
        if t.kind != "EOF":
            raise SqlSyntaxError(f"unexpected trailing input {t.text!r} at {t.pos}")

    def ident(self) -> str:
        t = self.peek()
        if t.kind == "IDENT":
            self.i += 1
            return t.text
        # non-reserved keywords usable as identifiers
        if t.kind == "KEYWORD" and t.text in _NONRESERVED:
            self.i += 1
            return t.text
        raise SqlSyntaxError(f"expected identifier but found {t.text!r} at {t.pos}")

    # ---- statements ------------------------------------------------------
    def statement(self) -> ast.Statement:
        if self._at_ident("prepare"):
            self.next()
            name = self.ident()
            self.expect_kw("from")
            return ast.Prepare(name, self.statement())
        if self._at_ident("execute") and self.peek(1).kind == "IDENT":
            self.next()
            name = self.ident()
            args: list[ast.Expr] = []
            if self.accept_kw("using"):
                args.append(self.expr())
                while self.accept_op(","):
                    args.append(self.expr())
            return ast.ExecutePrepared(name, args)
        if self._at_ident("deallocate"):
            self.next()
            self._at_ident("prepare") and self.next()
            return ast.Deallocate(self.ident())
        if self.accept_kw("explain"):
            analyze = self.accept_kw("analyze")
            # "verbose" is not a reserved keyword — it lexes as IDENT
            verbose = analyze and self._at_ident("verbose")
            if verbose:
                self.next()
            return ast.Explain(
                self.statement(), analyze=analyze, verbose=verbose
            )
        if self.accept_kw("show"):
            if self.accept_kw("session"):
                return ast.ShowSession()
            if self.accept_kw("catalogs"):
                return ast.ShowCatalogs()
            if self.accept_kw("schemas"):
                if self.accept_kw("from"):
                    return ast.ShowSchemas(self.ident())
                return ast.ShowSchemas()
            if self.accept_kw("tables"):
                if self.accept_kw("from"):
                    return ast.ShowTables(self.qualified_name())
                return ast.ShowTables()
            t = self.peek()
            raise SqlSyntaxError(f"unsupported SHOW {t.text!r}")
        if self.accept_kw("describe"):
            return ast.DescribeTable(self.qualified_name())
        if self.accept_kw("use"):
            return ast.Use(self.qualified_name())
        if self._at_ident("reset") or self.at_kw("reset"):
            self.next()
            self.expect_kw("session")
            name_parts = [self.ident()]
            while self.accept_op("."):
                name_parts.append(self.ident())
            return ast.SessionReset(".".join(name_parts))
        if self.accept_kw("set"):
            self.expect_kw("session")
            name_parts = [self.ident()]
            while self.accept_op("."):
                name_parts.append(self.ident())
            self.expect_op("=")
            return ast.SessionSet(".".join(name_parts), self.expr())
        if self.accept_kw("create"):
            or_replace = False
            if self.at_kw("or"):
                self.next()
                if not self._at_ident("replace"):
                    raise SqlSyntaxError("expected REPLACE after OR")
                self.next()
                or_replace = True
            if self._at_ident("view"):
                self.next()
                name = self.qualified_name()
                self.expect_kw("as")
                return ast.CreateView(name, self.query(), or_replace)
            if or_replace:
                raise SqlSyntaxError("OR REPLACE applies to VIEW only")
            self.expect_kw("table")
            if_not_exists = False
            if self.accept_kw("if"):
                self.expect_kw("not")
                self.expect_kw("exists")
                if_not_exists = True
            name = self.qualified_name()
            properties = []
            if self.accept_kw("with"):
                self.expect_op("(")
                while True:
                    k = self.ident()
                    self.expect_op("=")
                    properties.append((k, self.expr()))
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            if self.accept_kw("as"):
                return ast.CreateTableAs(
                    name, self.query(), if_not_exists, properties
                )
            if properties:
                raise SqlSyntaxError(
                    "WITH (...) table properties require CREATE TABLE AS"
                )
            self.expect_op("(")
            columns = []
            while True:
                col = self.ident()
                columns.append((col, self._type_name()))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return ast.CreateTable(name, columns, if_not_exists)
        if self.accept_kw("insert"):
            self.expect_kw("into")
            name = self.qualified_name()
            columns = None
            if self.at_op("("):
                # lookahead: column list vs subquery
                save = self.i
                self.next()
                try:
                    cols = [self.ident()]
                    while self.accept_op(","):
                        cols.append(self.ident())
                    self.expect_op(")")
                    columns = cols
                except SqlSyntaxError:
                    self.i = save
            if self.accept_kw("values"):
                rows = []
                while True:
                    self.expect_op("(")
                    row = [self.expr()]
                    while self.accept_op(","):
                        row.append(self.expr())
                    self.expect_op(")")
                    rows.append(row)
                    if not self.accept_op(","):
                        break
                return ast.InsertInto(name, columns, rows=rows)
            return ast.InsertInto(name, columns, query=self.query())
        if self.accept_kw("drop"):
            is_view = False
            if self._at_ident("view"):
                self.next()
                is_view = True
            else:
                self.expect_kw("table")
            if_exists = False
            if self.accept_kw("if"):
                self.expect_kw("exists")
                if_exists = True
            name = self.qualified_name()
            if is_view:
                return ast.DropView(name, if_exists)
            return ast.DropTable(name, if_exists)
        if self._at_ident("delete"):
            self.next()
            self.expect_kw("from")
            name = self.qualified_name()
            where = self.expr() if self.accept_kw("where") else None
            return ast.Delete(name, where)
        if self._at_ident("update"):
            self.next()
            name = self.qualified_name()
            self.expect_kw("set")
            assignments = []
            while True:
                col = self.ident()
                self.expect_op("=")
                assignments.append((col, self.expr()))
                if not self.accept_op(","):
                    break
            where = self.expr() if self.accept_kw("where") else None
            return ast.Update(name, assignments, where)
        return self.query()

    def qualified_name(self) -> tuple[str, ...]:
        parts = [self.ident()]
        while self.accept_op("."):
            parts.append(self.ident())
        return tuple(parts)

    # ---- queries ---------------------------------------------------------
    def query(self) -> ast.Query:
        with_ = []
        if self.accept_kw("with"):
            if self.accept_kw("recursive"):
                # silently swallowing it would resolve the CTE's self-
                # reference against an outer table and return wrong
                # results; reject until recursion is implemented
                raise NotImplementedError(
                    "WITH RECURSIVE is not supported"
                )
            while True:
                name = self.ident()
                self.expect_kw("as")
                self.expect_op("(")
                q = self.query()
                self.expect_op(")")
                with_.append((name, q))
                if not self.accept_op(","):
                    break
        body = self.query_body()
        order_by: list[ast.OrderItem] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by = self.order_items()
        offset = None
        limit = None
        if self.accept_kw("offset"):
            offset = int(self.next().text)
            self.accept_kw("rows") if self.at_kw("rows") else None
        if self.accept_kw("limit"):
            t = self.next()
            if t.kind == "KEYWORD" and t.text == "all":
                limit = None
            else:
                limit = int(t.text)
        return ast.Query(select=body, with_=with_, order_by=order_by, limit=limit, offset=offset)

    def order_items(self) -> list[ast.OrderItem]:
        items = []
        while True:
            e = self.expr()
            asc = True
            if self.accept_kw("asc"):
                asc = True
            elif self.accept_kw("desc"):
                asc = False
            nulls_first = None
            if self.accept_kw("nulls"):
                if self.accept_kw("first"):
                    nulls_first = True
                else:
                    self.expect_kw("last")
                    nulls_first = False
            items.append(ast.OrderItem(e, asc, nulls_first))
            if not self.accept_op(","):
                return items

    def query_body(self):
        left = self.query_term()
        while self.at_kw("union", "except"):
            op = self.next().text
            all_ = self.accept_kw("all")
            if not all_:
                self.accept_kw("distinct")
            right = self.query_term()
            left = ast.SetOp(op, all_, left, right)
        return left

    def query_term(self):
        left = self.query_primary()
        while self.at_kw("intersect"):
            self.next()
            all_ = self.accept_kw("all")
            if not all_:
                self.accept_kw("distinct")
            right = self.query_primary()
            left = ast.SetOp("intersect", all_, left, right)
        return left

    def query_primary(self):
        if self.accept_op("("):
            q = self.query_body()
            self.expect_op(")")
            return q
        if self.accept_kw("values"):
            rows = [self._values_row()]
            while self.accept_op(","):
                rows.append(self._values_row())
            return ast.ValuesQuery(rows)
        return self.select()

    def _values_row(self) -> list[ast.Expr]:
        if self.accept_op("("):
            row = [self.expr()]
            while self.accept_op(","):
                row.append(self.expr())
            self.expect_op(")")
            return row
        return [self.expr()]

    def select(self) -> ast.Select:
        self.expect_kw("select")
        distinct = False
        if self.accept_kw("distinct"):
            distinct = True
        else:
            self.accept_kw("all")
        items = [self.select_item()]
        while self.accept_op(","):
            items.append(self.select_item())
        relations: list[ast.Relation] = []
        where = None
        group_by: list[ast.Expr] = []
        having = None
        if self.accept_kw("from"):
            relations.append(self.relation())
            while self.accept_op(","):
                relations.append(self.relation())
        if self.accept_kw("where"):
            where = self.expr()
        if self.accept_kw("group"):
            self.expect_kw("by")
            group_by.append(self.grouping_element())
            while self.accept_op(","):
                group_by.append(self.grouping_element())
        if self.accept_kw("having"):
            having = self.expr()
        return ast.Select(items, relations, where, group_by, having, distinct)

    def _at_ident(self, word: str, ahead: int = 0) -> bool:
        t = self.peek(ahead)
        return t.kind == "IDENT" and t.text.lower() == word

    def grouping_element(self):
        """One GROUP BY element: expr | ROLLUP(...) | CUBE(...) |
        GROUPING SETS ((..), ..) (SqlBase.g4 groupingElement analog).
        ROLLUP/CUBE/GROUPING lex as plain identifiers, so a following
        '('/SETS token disambiguates from column references."""
        if (
            (self._at_ident("rollup") or self._at_ident("cube"))
            and self.peek(1).kind == "OP" and self.peek(1).text == "("
        ):
            kind = self.next().text.lower()
            self.expect_op("(")
            exprs = [self.expr()]
            while self.accept_op(","):
                exprs.append(self.expr())
            self.expect_op(")")
            return ast.GroupingElement(kind, exprs=exprs)
        if self._at_ident("grouping") and self._at_ident("sets", 1):
            self.next()
            self.next()
            self.expect_op("(")
            sets = [self._grouping_set()]
            while self.accept_op(","):
                sets.append(self._grouping_set())
            self.expect_op(")")
            return ast.GroupingElement("sets", sets=sets)
        return self.expr()

    def _grouping_set(self) -> list:
        if self.accept_op("("):
            if self.accept_op(")"):
                return []  # the grand-total set
            exprs = [self.expr()]
            while self.accept_op(","):
                exprs.append(self.expr())
            self.expect_op(")")
            return exprs
        return [self.expr()]

    def select_item(self) -> ast.SelectItem:
        if self.at_op("*"):
            self.next()
            return ast.SelectItem(ast.Star())
        e = self.expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "IDENT":
            alias = self.ident()
        return ast.SelectItem(e, alias)

    # ---- relations -------------------------------------------------------
    def relation(self) -> ast.Relation:
        left = self.relation_primary()
        while True:
            if self.accept_kw("cross"):
                self.expect_kw("join")
                right = self.relation_primary()
                left = ast.JoinRel("cross", left, right)
                continue
            kind = None
            if self.at_kw("join"):
                kind = "inner"
            elif self.at_kw("inner"):
                self.next()
                kind = "inner"
            elif self.at_kw("left"):
                self.next()
                self.accept_kw("outer")
                kind = "left"
            elif self.at_kw("right"):
                self.next()
                self.accept_kw("outer")
                kind = "right"
            elif self.at_kw("full"):
                self.next()
                self.accept_kw("outer")
                kind = "full"
            if kind is None:
                return left
            self.expect_kw("join")
            right = self.relation_primary()
            if self.accept_kw("on"):
                left = ast.JoinRel(kind, left, right, on=self.expr())
            elif self.accept_kw("using"):
                self.expect_op("(")
                cols = [self.ident()]
                while self.accept_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
                left = ast.JoinRel(kind, left, right, using=cols)
            else:
                raise SqlSyntaxError("JOIN requires ON or USING")

    def relation_primary(self) -> ast.Relation:
        # UNNEST is a soft keyword (a table may be named "unnest")
        if (
            self.peek().kind == "IDENT"
            and self.peek().text == "unnest"
            and self.peek(1).text == "("
        ):
            self.next()
            self.expect_op("(")
            args = [self._array_constructor()]
            while self.accept_op(","):
                args.append(self._array_constructor())
            self.expect_op(")")
            alias = None
            cols = None
            if self.accept_kw("as") or self.peek().kind == "IDENT":
                alias = self.ident()
                if self.accept_op("("):
                    cols = [self.ident()]
                    while self.accept_op(","):
                        cols.append(self.ident())
                    self.expect_op(")")
            return ast.UnnestRel(args, alias, cols)
        if self.accept_op("("):
            # lookahead through nested parens: SELECT/WITH starts a
            # subquery; anything else is a parenthesized join tree
            # ("((a JOIN b ON ...) JOIN c ON ...)")
            k = 0
            while self.peek(k).kind == "OP" and self.peek(k).text == "(":
                k += 1
            starts_query = self.peek(k).kind == "KEYWORD" and self.peek(
                k
            ).text in ("select", "with", "values")
            if starts_query:
                q = self.query()
                self.expect_op(")")
                alias = self._relation_alias()
                return ast.SubqueryRel(q, alias)
            r = self.relation()
            self.expect_op(")")
            return r
        parts = self.qualified_name()
        alias = self._relation_alias()
        return ast.TableRef(parts, alias)

    def _array_constructor(self):
        """UNNEST argument: ARRAY[e1, ...] keeps its element-expression
        list form; any other expression (an ARRAY-typed column
        reference) passes through as one Expr."""
        e = self.expr()
        if isinstance(e, ast.ArrayLit):
            return list(e.items)
        return e

    def _relation_alias(self) -> str | None:
        if self.accept_kw("as"):
            return self.ident()
        if self.peek().kind == "IDENT":
            return self.ident()
        return None

    # ---- expressions -----------------------------------------------------
    def expr(self) -> ast.Expr:
        return self.or_expr()

    def or_expr(self) -> ast.Expr:
        left = self.and_expr()
        while self.accept_kw("or"):
            left = ast.Binary("or", left, self.and_expr())
        return left

    def and_expr(self) -> ast.Expr:
        left = self.not_expr()
        while self.accept_kw("and"):
            left = ast.Binary("and", left, self.not_expr())
        return left

    def not_expr(self) -> ast.Expr:
        if self.accept_kw("not"):
            return ast.Unary("not", self.not_expr())
        return self.predicate()

    def predicate(self) -> ast.Expr:
        left = self.additive()
        while True:
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.next().text
                if op == "!=":
                    op = "<>"
                right = self.additive()
                left = ast.Binary(op, left, right)
                continue
            negated = False
            save = self.i
            if self.accept_kw("not"):
                negated = True
            if self.accept_kw("between"):
                low = self.additive()
                self.expect_kw("and")
                high = self.additive()
                left = ast.Between(left, low, high, negated)
                continue
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.at_kw("select", "with"):
                    q = self.query()
                    self.expect_op(")")
                    left = ast.InSubquery(left, q, negated)
                else:
                    items = [self.expr()]
                    while self.accept_op(","):
                        items.append(self.expr())
                    self.expect_op(")")
                    left = ast.InList(left, items, negated)
                continue
            if self.accept_kw("like"):
                pattern = self.additive()
                escape = None
                if self.accept_kw("escape"):
                    escape = self.additive()
                left = ast.LikeExpr(left, pattern, escape, negated)
                continue
            if negated:
                self.i = save  # NOT belongs to a different production
                return left
            if self.accept_kw("is"):
                neg = self.accept_kw("not")
                self.expect_kw("null")
                left = ast.IsNullExpr(left, neg)
                continue
            return left

    def additive(self) -> ast.Expr:
        left = self.multiplicative()
        while True:
            if self.at_op("+", "-"):
                op = self.next().text
                left = ast.Binary(op, left, self.multiplicative())
            elif self.at_op("||"):
                self.next()
                left = ast.Binary("||", left, self.multiplicative())
            else:
                return left

    def multiplicative(self) -> ast.Expr:
        left = self.unary()
        while self.at_op("*", "/", "%"):
            op = self.next().text
            left = ast.Binary(op, left, self.unary())
        return left

    def unary(self) -> ast.Expr:
        if self.at_op("-"):
            self.next()
            return ast.Unary("-", self.unary())
        if self.at_op("+"):
            self.next()
            return self.unary()
        e = self.primary()
        while self.at_op("["):
            # postfix subscript: arr[i] (1-based, Trino semantics)
            self.next()
            idx = self.expr()
            self.expect_op("]")
            e = ast.Subscript(e, idx)
        return e

    def primary(self) -> ast.Expr:
        t = self.peek()
        if t.kind == "OP" and t.text == "?":
            self.next()
            self._param_count += 1
            return ast.Parameter(self._param_count - 1)
        if t.kind == "NUMBER":
            self.next()
            return _number(t.text)
        if t.kind == "STRING":
            self.next()
            return ast.StrLit(t.text)
        if t.kind == "KEYWORD":
            return self._keyword_primary(t)
        if t.kind == "IDENT":
            if (
                t.text.lower() == "array"
                and self.peek(1).kind == "OP"
                and self.peek(1).text == "["
            ):
                self.next()
                self.expect_op("[")
                items = []
                if not self.at_op("]"):
                    items = [self.expr()]
                    while self.accept_op(","):
                        items.append(self.expr())
                self.expect_op("]")
                return ast.ArrayLit(items)
            return self._ident_primary()
        if self.accept_op("("):
            if self.at_kw("select", "with"):
                q = self.query()
                self.expect_op(")")
                return ast.ScalarSubquery(q)
            e = self.expr()
            self.expect_op(")")
            return e
        raise SqlSyntaxError(f"unexpected token {t.text!r} at {t.pos}")

    def _keyword_primary(self, t: Token) -> ast.Expr:
        kw = t.text
        if kw == "true":
            self.next()
            return ast.BoolLit(True)
        if kw == "false":
            self.next()
            return ast.BoolLit(False)
        if kw == "null":
            self.next()
            return ast.NullLit()
        if kw == "date" and self.peek(1).kind == "STRING":
            self.next()
            return ast.DateLit(self.next().text)
        if kw == "timestamp" and self.peek(1).kind == "STRING":
            self.next()
            return ast.TimestampLit(self.next().text)
        if kw == "interval":
            self.next()
            negative = False
            if self.accept_op("-"):
                negative = True
            value = self.next().text
            unit = self.next().text.rstrip("s")  # day(s), month(s)...
            return ast.IntervalLit(value, unit, negative)
        if kw == "case":
            return self._case()
        if kw in ("cast", "try_cast"):
            self.next()
            self.expect_op("(")
            e = self.expr()
            self.expect_kw("as")
            type_name = self._type_name()
            self.expect_op(")")
            return ast.CastExpr(e, type_name, try_cast=(kw == "try_cast"))
        if kw == "extract":
            self.next()
            self.expect_op("(")
            field = self.next().text
            self.expect_kw("from")
            e = self.expr()
            self.expect_op(")")
            return ast.ExtractExpr(field, e)
        if kw == "exists":
            self.next()
            self.expect_op("(")
            q = self.query()
            self.expect_op(")")
            return ast.Exists(q)
        if kw == "substring":
            self.next()
            self.expect_op("(")
            e = self.expr()
            if self.accept_kw("from"):
                start = self.expr()
                length = None
                if self.accept_kw("for"):
                    length = self.expr()
                self.expect_op(")")
                args = [e, start] + ([length] if length is not None else [])
                return ast.FnCall("substr", args)
            args = [e]
            while self.accept_op(","):
                args.append(self.expr())
            self.expect_op(")")
            return ast.FnCall("substr", args)
        # keyword used as a function name or identifier
        if kw in _NONRESERVED:
            return self._ident_primary()
        raise SqlSyntaxError(f"unexpected keyword {kw!r} at {t.pos}")

    def _case(self) -> ast.Expr:
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.expr()
        whens = []
        while self.accept_kw("when"):
            cond = self.expr()
            self.expect_kw("then")
            whens.append((cond, self.expr()))
        else_ = None
        if self.accept_kw("else"):
            else_ = self.expr()
        self.expect_kw("end")
        return ast.CaseExpr(operand, whens, else_)

    def _ident_primary(self) -> ast.Expr:
        parts = [self.ident()]
        while self.accept_op("."):
            if self.accept_op("*"):
                return ast.Star(tuple(parts))
            parts.append(self.ident())
        if self.at_op("("):
            return self._fn_call(parts[-1] if len(parts) == 1 else ".".join(parts))
        return ast.Ident(tuple(parts))

    def _fn_call(self, name: str) -> ast.Expr:
        self.expect_op("(")
        if self.accept_op("*"):
            self.expect_op(")")
            return self._maybe_over(ast.FnCall(name, [], star=True))
        if self.accept_op(")"):
            return self._maybe_over(ast.FnCall(name, []))
        distinct = self.accept_kw("distinct")
        args = [self.expr()]
        while self.accept_op(","):
            args.append(self.expr())
        self.expect_op(")")
        return self._maybe_over(ast.FnCall(name, args, distinct=distinct))

    def _maybe_over(self, fn: ast.FnCall) -> ast.Expr:
        """Attach an OVER (...) window specification if present."""
        if not self.accept_kw("over"):
            return fn
        self.expect_op("(")
        partition_by: list[ast.Expr] = []
        order_by: list[ast.OrderItem] = []
        frame = None
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition_by.append(self.expr())
            while self.accept_op(","):
                partition_by.append(self.expr())
        if self.accept_kw("order"):
            self.expect_kw("by")
            order_by = self.order_items()
        if self.at_kw("rows") or self.at_kw("range"):
            mode = self.next().text
            if self.accept_kw("between"):
                start = self._frame_bound()
                self.expect_kw("and")
                end = self._frame_bound()
            else:
                start = self._frame_bound()
                end = ("current", None)
            frame = ast.WindowFrame(mode, start, end)
        self.expect_op(")")
        fn.over = ast.Over(partition_by, order_by, frame)
        return fn

    def _frame_bound(self) -> tuple:
        if self.accept_kw("unbounded"):
            if self.accept_kw("preceding"):
                return ("unbounded_preceding", None)
            self.expect_kw("following")
            return ("unbounded_following", None)
        if self.accept_kw("current"):
            self.expect_kw("row")
            return ("current", None)
        n = int(self.next().text)
        if self.accept_kw("preceding"):
            return ("preceding", n)
        self.expect_kw("following")
        return ("following", n)

    def _type_name(self) -> str:
        base = self.next().text
        if self.accept_op("("):
            params = [self._type_param()]
            while self.accept_op(","):
                params.append(self._type_param())
            self.expect_op(")")
            return f"{base}({','.join(params)})"
        return base

    def _type_param(self) -> str:
        """One type parameter: a number, a nested (possibly
        parametric) type name — array(decimal(5,1)) nests — or a
        ROW field "name type" pair (row(x bigint, y double))."""
        if self.peek().kind == "NUMBER":
            return self.next().text
        first = self._type_name()
        if self.peek().kind in ("IDENT", "KEYWORD") and not self.at_op(
            ",", ")"
        ):
            # "name type": first was the field name
            return f"{first} {self._type_name()}"
        return first


#: keywords that may be used as identifiers / function names
_NONRESERVED = {
    "year", "month", "day", "hour", "minute", "second", "date", "timestamp",
    "count", "first", "last", "tables", "schemas", "catalogs", "session",
    "analyze", "show", "use", "set", "values",
    "partition", "rows", "range", "unbounded", "preceding", "following",
    "current", "row",
}


def _number(text: str) -> ast.Expr:
    if "e" in text.lower():
        return ast.FloatLit(float(text))
    if "." in text:
        return ast.DecimalLit(text)
    return ast.IntLit(int(text))
