"""SQL lexer.

The analog of the reference's ANTLR-generated lexer
(core/trino-grammar/.../SqlBase.g4): hand-rolled because the token set
for SQL is small and a regex scanner keeps the front end dependency
free. Keywords are case-insensitive; identifiers are lowercased unless
double-quoted (the reference's rule as well).
"""

from __future__ import annotations

import re
from dataclasses import dataclass

__all__ = ["Token", "tokenize", "SqlSyntaxError"]


class SqlSyntaxError(Exception):
    pass


@dataclass(frozen=True)
class Token:
    kind: str  # KEYWORD | IDENT | NUMBER | STRING | OP | EOF
    text: str  # normalized: keywords/idents lowercased (unless quoted)
    pos: int   # character offset, for error messages


KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "as", "and", "or", "not", "in", "exists", "between", "like",
    "escape", "is", "null", "true", "false", "case", "when", "then", "else",
    "end", "cast", "try_cast", "extract", "interval", "date", "timestamp",
    "join", "inner", "left", "right", "full", "outer", "cross", "on", "using",
    "union", "intersect", "except", "all", "distinct", "with", "recursive",
    "asc", "desc", "nulls", "first", "last", "explain", "analyze", "show",
    "tables", "schemas", "catalogs", "describe", "use", "set", "session",
    "year", "month", "day", "hour", "minute", "second", "substring", "for",
    "values", "create", "table", "insert", "into", "drop", "count",
    "over", "partition", "rows", "range", "unbounded", "preceding",
    "following", "current", "row", "if",
}

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*\n?|/\*.*?\*/)
  | (?P<number>\d+(\.\d*)?([eE][+-]?\d+)?|\.\d+([eE][+-]?\d+)?)
  | (?P<qident>"([^"]|"")*")
  | (?P<string>'([^']|'')*')
  | (?P<ident>[A-Za-z_][A-Za-z0-9_$]*)
  | (?P<op><>|!=|<=|>=|\|\||=>|[-+*/%(),.;<>=\[\]?])
    """,
    re.VERBOSE | re.DOTALL,
)


def tokenize(sql: str) -> list[Token]:
    tokens: list[Token] = []
    pos = 0
    n = len(sql)
    while pos < n:
        m = _TOKEN_RE.match(sql, pos)
        if m is None:
            raise SqlSyntaxError(
                f"unexpected character {sql[pos]!r} at position {pos}: "
                f"...{sql[max(0, pos - 20):pos + 10]}..."
            )
        kind = m.lastgroup
        text = m.group()
        if kind == "ws":
            pos = m.end()
            continue
        if kind == "number":
            tokens.append(Token("NUMBER", text, pos))
        elif kind == "string":
            tokens.append(Token("STRING", text[1:-1].replace("''", "'"), pos))
        elif kind == "qident":
            tokens.append(Token("IDENT", text[1:-1].replace('""', '"'), pos))
        elif kind == "ident":
            low = text.lower()
            tokens.append(
                Token("KEYWORD" if low in KEYWORDS else "IDENT", low, pos)
            )
        else:
            tokens.append(Token("OP", text, pos))
        pos = m.end()
    tokens.append(Token("EOF", "", pos))
    return tokens
