"""Surface-syntax AST.

The analog of the reference's parse tree (PARSER/tree/, 290 node
classes). Untyped; the analyzer resolves names/types and lowers to the
expression IR + logical plan. Only the nodes the grammar supports are
defined — the set grows with the grammar.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

__all__ = [
    "Node", "Expr", "Relation", "Statement",
    "Ident", "IntLit", "DecimalLit", "FloatLit", "StrLit", "BoolLit", "NullLit",
    "DateLit", "TimestampLit", "IntervalLit", "Star",
    "Unary", "Binary", "FnCall", "CastExpr", "CaseExpr", "Between", "InList",
    "ArrayLit", "Subscript",
    "InSubquery", "Exists", "ScalarSubquery", "LikeExpr", "IsNullExpr",
    "ExtractExpr",
    "TableRef", "SubqueryRel", "JoinRel",
    "GroupingElement", "SelectItem", "Select", "OrderItem", "Query", "SetOp",
    "Explain", "ShowTables", "ShowSchemas", "ShowCatalogs", "DescribeTable",
    "SessionSet", "Use", "CreateView", "DropView", "Delete", "Update",
]


class Node:
    pass


class Expr(Node):
    pass


class Relation(Node):
    pass


class Statement(Node):
    pass


# ---- expressions ---------------------------------------------------------

@dataclass
class Ident(Expr):
    parts: tuple[str, ...]  # a, t.a, cat.sch.t.a

    @property
    def name(self) -> str:
        return self.parts[-1]


@dataclass
class IntLit(Expr):
    value: int


@dataclass
class DecimalLit(Expr):
    text: str  # "0.05"


@dataclass
class FloatLit(Expr):
    value: float


@dataclass
class StrLit(Expr):
    value: str


@dataclass
class BoolLit(Expr):
    value: bool


@dataclass
class NullLit(Expr):
    pass


@dataclass
class DateLit(Expr):
    text: str  # "1995-03-15"


@dataclass
class TimestampLit(Expr):
    text: str


@dataclass
class IntervalLit(Expr):
    value: str      # "3"
    unit: str       # day/month/year/hour/minute/second
    negative: bool = False


@dataclass
class Star(Expr):
    qualifier: Optional[tuple[str, ...]] = None  # t.*


@dataclass
class Unary(Expr):
    op: str  # '-', '+', 'not'
    arg: Expr


@dataclass
class Binary(Expr):
    op: str  # + - * / % || = <> < <= > >= and or
    left: Expr
    right: Expr


@dataclass
class FnCall(Expr):
    name: str
    args: list[Expr]
    distinct: bool = False
    star: bool = False  # count(*)
    over: "Optional[Over]" = None  # window specification


@dataclass
class WindowFrame:
    """ROWS/RANGE BETWEEN <start> AND <end>; bounds are
    ("unbounded_preceding" | "preceding" | "current" | "following" |
    "unbounded_following", offset|None)."""

    mode: str  # rows | range
    start: tuple[str, Optional[int]]
    end: tuple[str, Optional[int]]


@dataclass
class Over(Node):
    partition_by: "list[Expr]"
    order_by: "list[OrderItem]"
    frame: Optional[WindowFrame] = None


@dataclass
class CastExpr(Expr):
    arg: Expr
    type_name: str
    try_cast: bool = False


@dataclass
class CaseExpr(Expr):
    operand: Optional[Expr]  # CASE x WHEN ... vs CASE WHEN ...
    whens: list[tuple[Expr, Expr]]
    else_: Optional[Expr]


@dataclass
class ArrayLit(Expr):
    """ARRAY[e1, e2, ...] constructor (PARSER/tree/ArrayConstructor)."""

    items: list[Expr]


@dataclass
class Subscript(Expr):
    """base[index] element access (PARSER/tree/SubscriptExpression)."""

    base: Expr
    index: Expr


@dataclass
class Between(Expr):
    arg: Expr
    low: Expr
    high: Expr
    negated: bool = False


@dataclass
class InList(Expr):
    arg: Expr
    items: list[Expr]
    negated: bool = False


@dataclass
class InSubquery(Expr):
    arg: Expr
    query: "Query"
    negated: bool = False


@dataclass
class Exists(Expr):
    query: "Query"
    negated: bool = False


@dataclass
class ScalarSubquery(Expr):
    query: "Query"


@dataclass
class LikeExpr(Expr):
    arg: Expr
    pattern: Expr
    escape: Optional[Expr] = None
    negated: bool = False


@dataclass
class IsNullExpr(Expr):
    arg: Expr
    negated: bool = False


@dataclass
class ExtractExpr(Expr):
    field: str  # year/month/day/...
    arg: Expr


# ---- relations -----------------------------------------------------------

@dataclass
class TableRef(Relation):
    parts: tuple[str, ...]  # table | schema.table | catalog.schema.table
    alias: Optional[str] = None


@dataclass
class SubqueryRel(Relation):
    query: "Query"
    alias: Optional[str] = None


@dataclass
class JoinRel(Relation):
    kind: str  # inner/left/right/full/cross
    left: Relation
    right: Relation
    on: Optional[Expr] = None
    using: Optional[list[str]] = None


@dataclass
class UnnestRel(Relation):
    """UNNEST(array[...] [, array[...]]*) AS alias(c1, ...) — each arg
    is the element-expression list of one ARRAY[...] constructor
    (multiple args zip, Trino semantics)."""

    args: list[list[Expr]]
    alias: Optional[str] = None
    column_aliases: Optional[list[str]] = None


# ---- query structure -----------------------------------------------------

@dataclass
class GroupingElement(Node):
    """One GROUP BY element beyond a plain expression: ROLLUP, CUBE or
    explicit GROUPING SETS (PARSER/tree/GroupingElement.java analog —
    the reference parses GroupBy into a list of GroupingElements whose
    cross product yields the effective grouping sets)."""

    kind: str  # "rollup" | "cube" | "sets"
    #: for rollup/cube: the element's expressions; for "sets": unused
    exprs: list[Expr] = field(default_factory=list)
    #: for "sets": the explicit list of expression lists
    sets: list[list[Expr]] = field(default_factory=list)


@dataclass
class SelectItem(Node):
    expr: Expr
    alias: Optional[str] = None


@dataclass
class Select(Node):
    items: list[SelectItem]
    relations: list[Relation] = field(default_factory=list)  # FROM a, b = cross
    where: Optional[Expr] = None
    group_by: list[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    distinct: bool = False


@dataclass
class CreateView(Statement):
    """CREATE [OR REPLACE] VIEW name AS query (the analyzed-at-use
    logical view of the reference, MAIN/metadata/MetadataManager.java
    view resolution)."""

    name: tuple[str, ...]
    query: "Query"
    or_replace: bool = False


@dataclass
class DropView(Statement):
    name: tuple[str, ...]
    if_exists: bool = False


@dataclass
class Delete(Statement):
    """DELETE FROM t [WHERE pred] (row-level DML,
    MAIN/operator/MergeWriterOperator.java family)."""

    name: tuple[str, ...]
    where: Optional[Expr] = None


@dataclass
class Update(Statement):
    """UPDATE t SET c = e, ... [WHERE pred]."""

    name: tuple[str, ...]
    assignments: list[tuple[str, Expr]] = field(default_factory=list)
    where: Optional[Expr] = None


@dataclass
class OrderItem(Node):
    expr: Expr
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None = type default


@dataclass
class Query(Statement):
    select: "Select | SetOp"
    with_: list[tuple[str, "Query"]] = field(default_factory=list)
    order_by: list[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None


@dataclass
class SetOp(Node):
    op: str  # union/intersect/except
    all: bool
    left: "Select | SetOp | Query"
    right: "Select | SetOp | Query"


# ---- other statements ----------------------------------------------------

@dataclass
class ValuesQuery(Node):
    """VALUES (r1c1, r1c2), (r2c1, r2c2) as a query body
    (PARSER/tree/Values.java:25)."""

    rows: list[list[Expr]] = field(default_factory=list)


@dataclass
class Parameter(Expr):
    """Positional ? placeholder bound at EXECUTE (reference:
    PARSER/tree/Parameter.java)."""

    index: int = 0


@dataclass
class Prepare(Statement):
    """PREPARE name FROM statement (PARSER/tree/Prepare.java:25)."""

    name: str = ""
    statement: Statement = None  # type: ignore[assignment]


@dataclass
class ExecutePrepared(Statement):
    name: str = ""
    args: list[Expr] = field(default_factory=list)


@dataclass
class Deallocate(Statement):
    name: str = ""


@dataclass
class Explain(Statement):
    statement: Statement
    analyze: bool = False
    type_: str = "logical"  # logical | distributed | io
    #: EXPLAIN ANALYZE VERBOSE: add the kernel-observatory tier
    #: (per-HLO-scope device times, compiled-program footprints)
    verbose: bool = False


@dataclass
class ShowCatalogs(Statement):
    pass


@dataclass
class ShowSchemas(Statement):
    catalog: Optional[str] = None


@dataclass
class ShowTables(Statement):
    schema: Optional[tuple[str, ...]] = None


@dataclass
class DescribeTable(Statement):
    table: tuple[str, ...]


@dataclass
class SessionSet(Statement):
    name: str
    value: Expr


@dataclass
class SessionReset(Statement):
    name: str


@dataclass
class ShowSession(Statement):
    pass


@dataclass
class Use(Statement):
    parts: tuple[str, ...]


@dataclass
class CreateTable(Statement):
    name: tuple[str, ...]
    columns: list[tuple[str, str]]  # (column, type name)
    if_not_exists: bool = False


@dataclass
class CreateTableAs(Statement):
    name: tuple[str, ...]
    query: "Query"
    if_not_exists: bool = False
    #: WITH (k = literal, ...) table properties — e.g.
    #: partitioned_by = ARRAY['k'], row_group_size = 1000
    properties: "list[tuple[str, Expr]]" = field(default_factory=list)


@dataclass
class InsertInto(Statement):
    name: tuple[str, ...]
    columns: "Optional[list[str]]" = None
    query: "Optional[Query]" = None
    #: VALUES rows (each a list of literal expressions), when not a query
    rows: "Optional[list[list[Expr]]]" = None


@dataclass
class DropTable(Statement):
    name: tuple[str, ...]
    if_exists: bool = False
