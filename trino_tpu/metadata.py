"""Metadata layer: catalogs -> connectors -> tables.

The (much smaller) analog of the reference's MetadataManager
(MAIN/metadata/MetadataManager.java:180) + CatalogManager: resolves
qualified names against registered catalogs and exposes connector
metadata to the analyzer/planner.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from trino_tpu.connectors.base import Catalog, Connector, TableSchema

__all__ = ["Metadata", "Session", "QualifiedTable"]


@dataclass
class Session:
    """Per-query context (the reference's io.trino.Session analog)."""

    catalog: str | None = None
    schema: str | None = None
    properties: dict = field(default_factory=dict)
    user: str = "user"
    #: PREPARE name -> statement AST (PARSER/tree/Prepare.java:25)
    prepared: dict = field(default_factory=dict)


@dataclass(frozen=True)
class QualifiedTable:
    catalog: str
    schema: str
    table: str


class Metadata:
    def __init__(self):
        self._catalogs: dict[str, Catalog] = {}
        #: (catalog, schema, name) -> view Query AST; views expand at
        #: analysis time like CTEs (MetadataManager view resolution,
        #: MAIN/metadata/MetadataManager.java)
        self._views: dict = {}
        #: pluggable access control (AccessControlManager analog);
        #: allow-all by default
        from trino_tpu.security import AllowAllAccessControl

        self.access_control = AllowAllAccessControl()
        #: EventListener SPI instances (SPI/eventlistener/): notified
        #: of query completion by every runner sharing this metadata
        self.event_listeners: list = []

    def create_view(self, qualified, query, or_replace: bool = False):
        if qualified in self._views and not or_replace:
            raise ValueError(f"view {'.'.join(qualified)} already exists")
        self._views[qualified] = query

    def drop_view(self, qualified) -> bool:
        return self._views.pop(qualified, None) is not None

    def get_view(self, session: "Session", parts: tuple[str, ...]):
        """(key, view Query) for a (possibly partial) name, or None."""
        if len(parts) == 3:
            key = tuple(parts)
        elif len(parts) == 2:
            key = (session.catalog, parts[0], parts[1])
        elif len(parts) == 1:
            key = (session.catalog, session.schema, parts[0])
        else:
            return None
        q = self._views.get(key)
        return None if q is None else (key, q)

    def register_catalog(self, name: str, connector: Connector, **properties):
        self._catalogs[name] = Catalog(name, connector, properties)

    def catalogs(self) -> list[str]:
        return sorted(self._catalogs)

    def connector(self, catalog: str) -> Connector:
        if catalog not in self._catalogs:
            raise KeyError(f"catalog not found: {catalog}")
        return self._catalogs[catalog].connector

    def resolve_table(
        self, session: Session, parts: tuple[str, ...]
    ) -> tuple[QualifiedTable, TableSchema]:
        """Resolve [catalog.][schema.]table against session defaults."""
        if len(parts) == 3:
            cat, sch, tab = parts
        elif len(parts) == 2:
            cat, (sch, tab) = session.catalog, parts
        elif len(parts) == 1:
            cat, sch, tab = session.catalog, session.schema, parts[0]
        else:
            raise ValueError(f"bad table name: {'.'.join(parts)}")
        if cat is None or sch is None:
            raise ValueError(
                f"table {'.'.join(parts)!r} requires a session catalog/schema"
            )
        connector = self.connector(cat)  # missing catalog reports itself
        try:
            schema = connector.table_schema(sch, tab)
        except KeyError:
            raise KeyError(f"table not found: {cat}.{sch}.{tab}") from None
        return QualifiedTable(cat, sch, tab), schema
