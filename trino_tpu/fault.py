"""Unified, site-addressable, seed-deterministic fault injection.

The chaos backbone for the whole stack — the analog of the reference's
FailureInjector (MAIN/execution/FailureInjector.java) grown to cover
every layer the FTE tiers must survive, not just task execution.
Before this module the repo had two ad-hoc injectors (the mesh
executor's stage-tag ``FailureInjector`` and the worker's ``fail``
request flag); they could not compose, so a chaos run could not, say,
corrupt a spool read *and* drop an RPC in the same query. Both are now
thin adapters over this one (``exec/failure.py`` keeps its public API).

Sites (one string per architectural seam):

    ``rpc``         coordinator->worker HTTP calls (fleet _post/_poll)
    ``spool-write`` durable stage-output commit (exec/spool.py)
    ``spool-read``  spooled partition reads (exec/spool.py)
    ``task-exec``   worker stage-task execution (server/worker.py)
    ``device-oom``  memory reservations (memory.py MemoryPool)
    ``planner``     statement planning (engine.plan_stmt)
    ``scan-read``   streamed storage split reads (exec/stream_scan.py)
    ``exchange-fetch`` direct producer-memory partition fetches
                    (server/worker.py consumer side; a fired fault
                    falls back to the spool, never fails the task)
    ``announce-drop`` worker membership announcements (the PUT
                    /v1/announce client path; a dropped announce is
                    invisible to the worker — the registry just never
                    hears from it that round)
    ``heartbeat-loss`` periodic membership heartbeats after the
                    initial announce (same seam, separate site so a
                    schedule can let a worker join and then go quiet)
    ``journal-write`` query-journal WAL appends (journal.py; a failed
                    append fails the query — recovery must never trust
                    a journal it could not write)
    ``journal-read`` query-journal replay on coordinator restart
                    (journal.py load/scan; a failed read makes the
                    query non-resumable, never silently wrong)
    ``compile-delay`` executor dispatch (exec/local.py); a fired
                    fault does NOT fail anything — it sleeps inside a
                    compile-kind span, simulating an XLA compile storm
                    so the performance sentry's attribution can be
                    exercised end-to-end on warmed statements

Schedules: ``arm`` (attempts 0..times-1 fail — the classic retry
shape), ``arm_nth`` (exactly the n-th matching call fails), and
``arm_probability`` (seed-deterministic coin per *logical operation*:
the decision hashes (seed, site, tag, attempt), never wall-clock or
call order, so the same seed reproduces the same injection schedule
across runs and across processes).

Cross-process: a ``FaultInjector`` serializes with ``to_spec()`` and
ships inside the stage-task request; the worker rebuilds it with
``from_spec`` and installs it as the process-global *active* injector
for the task's duration, so module-level ``fault.check(...)`` hooks in
spool/memory code fire in the worker exactly as they would in-process.
When nothing is armed every hook is a None-check — no lock, no log.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass

__all__ = [
    "InjectedFault", "FaultInjector", "SITES",
    "activate", "deactivate", "active", "check",
]

#: the closed set of injection sites (typo'd arms fail fast)
SITES = frozenset(
    ["rpc", "spool-write", "spool-read", "task-exec", "device-oom",
     "planner", "compile-deserialize", "scan-read", "exchange-fetch",
     "heartbeat-loss", "announce-drop", "journal-write", "journal-read",
     "compile-delay"]
)


class InjectedFault(RuntimeError):
    """A chaos-armed failure. Carries its site/tag coordinates so
    recovery tiers can classify it (always retryable — an injected
    fault models a transient, not a semantic error)."""

    def __init__(self, site: str, tag: str, attempt: int, kind: str):
        self.site = site
        self.tag = tag
        self.attempt = attempt
        self.kind = kind
        super().__init__(
            f"injected fault: site={site} tag={tag!r} "
            f"attempt={attempt} kind={kind}"
        )


@dataclass
class _Rule:
    site: str
    tag: str  # prefix match against the call-site tag ("" = any)
    kind: str  # "times" | "nth" | "prob"
    times: int = 1  # kind=times: attempts 0..times-1 fail
    nth: int = 1  # kind=nth: the nth matching call fails (1-based)
    p: float = 0.0  # kind=prob: per-operation failure probability
    calls: int = 0  # matching-call counter (kind=nth bookkeeping)

    def spec(self) -> dict:
        return {
            "site": self.site, "tag": self.tag, "kind": self.kind,
            "times": self.times, "nth": self.nth, "p": self.p,
        }


def _coin(seed: int, site: str, tag: str, attempt: int) -> float:
    """Deterministic uniform [0,1) for one logical operation. Hashing
    (seed, site, tag, attempt) — never a call counter — means reruns
    and repeated polls of the same operation get the same verdict.
    blake2b (not crc32, whose linearity correlates nearby attempts;
    not ``hash()``, which varies per process) keeps the schedule
    well-mixed AND identical across processes and runs."""
    digest = hashlib.blake2b(
        f"{seed}|{site}|{tag}|{attempt}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") / 2**64


class FaultInjector:
    """Seeded multi-site injector. Thread-safe; cheap when unarmed."""

    #: exception type raised on a fired rule — adapters (the mesh's
    #: legacy FailureInjector) narrow this to their own subtype
    fault_cls = InjectedFault

    def __init__(self, seed: int = 0, max_attempts: int = 4):
        self.seed = int(seed)
        self.max_attempts = max_attempts
        self._rules: list[_Rule] = []
        self._lock = threading.Lock()
        #: every armed-injector check: (site, tag, attempt, fired_kind
        #: or None). Byte-for-byte reproducible for a fixed seed and
        #: call sequence — the chaos determinism tests diff this.
        self.decisions: list[tuple[str, str, int, str | None]] = []
        #: (tag, attempt) of faults actually raised (site recorded in
        #: ``decisions``; tag-only keeps the legacy adapter log shape)
        self.injected: list[tuple[str, int]] = []
        #: attempt used by module-level hooks that have no attempt of
        #: their own (worker sets this to the task attempt in flight)
        self.default_attempt = 0

    # ---- arming ----------------------------------------------------
    def _arm(self, rule: _Rule):
        if rule.site not in SITES:
            raise ValueError(
                f"unknown fault site {rule.site!r} "
                f"(expected one of {sorted(SITES)})"
            )
        with self._lock:
            self._rules.append(rule)

    def arm(self, site: str, tag: str = "", times: int = 1):
        """Fail attempts 0..times-1 of operations matching site+tag
        (the retry-shape schedule: attempt ``times`` succeeds)."""
        self._arm(_Rule(site=site, tag=tag, kind="times", times=times))

    def arm_nth(self, site: str, n: int, tag: str = ""):
        """Fail exactly the n-th matching call (1-based)."""
        if n < 1:
            raise ValueError(f"arm_nth n must be >= 1, got {n}")
        self._arm(_Rule(site=site, tag=tag, kind="nth", nth=n))

    def arm_probability(self, site: str, p: float, tag: str = ""):
        """Fail each logical operation with probability ``p``, decided
        by a deterministic hash of (seed, site, tag, attempt)."""
        if not 0.0 <= p <= 1.0:
            raise ValueError(f"probability must be in [0,1], got {p}")
        self._arm(_Rule(site=site, tag=tag, kind="prob", p=p))

    def reset(self):
        with self._lock:
            self._rules.clear()
            self.decisions.clear()
            self.injected.clear()

    @property
    def armed(self) -> bool:
        return bool(self._rules)

    # ---- checking --------------------------------------------------
    def check(self, site: str, tag: str = "", attempt: int | None = None):
        """Raise InjectedFault if a rule fires for this operation.
        No-op (no lock, no log) when nothing is armed."""
        if not self._rules:
            return
        if attempt is None:
            attempt = self.default_attempt
        with self._lock:
            fired = None
            for rule in self._rules:
                if rule.site != site or not tag.startswith(rule.tag):
                    continue
                rule.calls += 1
                if rule.kind == "times":
                    if attempt < rule.times:
                        fired = rule
                        break
                elif rule.kind == "nth":
                    if rule.calls == rule.nth:
                        fired = rule
                        break
                elif rule.kind == "prob":
                    if _coin(self.seed, site, tag, attempt) < rule.p:
                        fired = rule
                        break
            self.decisions.append(
                (site, tag, attempt, fired.kind if fired else None)
            )
            if fired is not None:
                self.injected.append((tag, attempt))
                from trino_tpu import telemetry

                telemetry.CHAOS_INJECTIONS.inc(site=site)
                raise self.fault_cls(site, tag, attempt, fired.kind)

    # ---- cross-process shipping ------------------------------------
    def to_spec(self) -> dict:
        """JSON-serializable description (rules + seed), suitable for
        riding a stage-task request into a worker process."""
        with self._lock:
            return {
                "seed": self.seed,
                "max_attempts": self.max_attempts,
                "rules": [r.spec() for r in self._rules],
            }

    @classmethod
    def from_spec(cls, spec: dict, default_attempt: int = 0
                  ) -> "FaultInjector":
        inj = cls(seed=spec.get("seed", 0),
                  max_attempts=spec.get("max_attempts", 4))
        inj.default_attempt = default_attempt
        for r in spec.get("rules", []):
            inj._arm(_Rule(
                site=r["site"], tag=r.get("tag", ""),
                kind=r.get("kind", "times"), times=r.get("times", 1),
                nth=r.get("nth", 1), p=r.get("p", 0.0),
            ))
        return inj


# ---- process-global active injector -------------------------------
#
# Layers that have no injector plumbed through their signatures
# (spool, memory) consult the process-global active injector. The
# worker installs one per stage task (serialized under the runner
# lock); tests install one around a block via ``activate``.

_active: FaultInjector | None = None


def activate(inj: FaultInjector | None):
    global _active
    _active = inj


def deactivate():
    global _active
    _active = None


def active() -> FaultInjector | None:
    return _active


def check(site: str, tag: str = "", attempt: int | None = None):
    """Module-level hook: no-op unless an injector is active."""
    inj = _active
    if inj is not None:
        inj.check(site, tag, attempt)
