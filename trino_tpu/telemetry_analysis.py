"""Analysis layer over the telemetry substrate.

The raw instrumentation (trace spans, the Prometheus registry, the
roofline profiler) answers "what happened"; this module answers "where
did the time go":

* **Critical-path attribution** — walk a finished :class:`~trino_tpu.
  telemetry.Trace` span tree and decompose query wall-clock into named
  buckets (queued, slot-wait, planning, XLA compile, scheduler
  admission-wait, scan, compute, exchange, straggler slack). The
  decomposition is exact by construction: a sweep line attributes
  every instant of the root interval to exactly ONE bucket (the
  highest-priority span class active at that instant — work beats
  waiting), so concurrent worker subtrees never double-count and the
  buckets sum to the root span's duration; whatever the trace did not
  cover lands in an explicit ``other`` bucket rather than silently
  vanishing.
* **Partition-skew statistics** — max/mean ratio and coefficient of
  variation over a per-partition row/byte histogram (the derived stats
  the fleet publishes per hash-exchange edge).
* **Clock-skew correction** — per-worker wall-clock offsets estimated
  from task RPC request/response timestamps (the NTP midpoint
  estimate), applied to worker span subtrees before stitching so
  Chrome traces and critical-path math never go negative across
  machines.
* **Cluster time-series recorder** — a bounded in-memory ring of
  periodic registry scrapes (coordinator's own + every worker's
  ``/v1/metrics``), behind ``TRINO_TPU_TIMESERIES_{INTERVAL_MS,
  SAMPLES}``; served at ``GET /v1/cluster/timeseries`` and
  ``system.runtime.cluster_metrics``.
"""

from __future__ import annotations

import os
import statistics
import threading
import time
import urllib.request
from collections import deque
from typing import Any, Dict, Iterable, List, Optional

from trino_tpu import telemetry

__all__ = [
    "BUCKETS",
    "compute_time_breakdown",
    "critical_path",
    "format_breakdown",
    "partition_skew",
    "straggler_slack_ms",
    "shift_span_tree",
    "ClockSkewEstimator",
    "ClusterTimeseriesRecorder",
    "active_recorder",
    "set_active_recorder",
]


# ---------------------------------------------------------------------------
# Critical-path attribution
# ---------------------------------------------------------------------------

#: Bucket names, in the order breakdowns render. ``other`` absorbs
#: trace self-time with no better classification (RPC control plane,
#: coordinator loop overhead) plus any wall-clock the trace missed.
BUCKETS = (
    "queued", "slot_wait", "planning", "xla_compile",
    "admission_wait", "scan", "compute", "exchange",
    "straggler_slack", "other",
)

#: internal marker for execution-span self time, split into scan vs
#: compute afterwards using operator self-time fractions
_EXEC = "_exec"


def _classify(span) -> str:
    """Bucket for one span's self time."""
    kind = span.kind
    if kind == "planning":
        return "planning"
    if kind == "compile":
        return "xla_compile"
    if kind in ("spool", "exchange"):
        return "exchange"
    if kind == "stage":
        # a stage span's self time is the part not covered by its rpc
        # and (stitched) worker task children: admission + poll gaps
        return "admission_wait"
    if kind == "execution":
        return _EXEC
    if kind == "task":
        # worker task overhead outside spool-read/execute/spool-write
        return "compute"
    return "other"


#: when several classes are active at the same instant (concurrent
#: workers, coordinator loop under a busy stage), the wall second goes
#: to the FIRST active class in this order — work beats waiting
_PRIORITY = (
    _EXEC, "xla_compile", "exchange", "compute", "planning",
    "admission_wait", "other",
)


def _self_intervals(span, clip_lo: float, clip_hi: float,
                    out: List[tuple]) -> None:
    """Emit (lo, hi, class) for every span's *self* region — its
    interval (clipped to the parent's) minus the union of its
    children's. The root covers its whole window, so the emitted
    regions cover every instant of the root interval at least once;
    overlap across concurrent subtrees is resolved by the sweep in
    :func:`compute_time_breakdown`."""
    lo = max(float(span.start_ms), clip_lo)
    hi = min(float(span.start_ms) + max(float(span.duration_ms), 0.0),
             clip_hi)
    if hi <= lo:
        return
    child_iv = []
    for c in span.children:
        c_lo = max(float(c.start_ms), lo)
        c_hi = min(float(c.start_ms) + max(float(c.duration_ms), 0.0),
                   hi)
        if c_hi > c_lo:
            child_iv.append((c_lo, c_hi))
        _self_intervals(c, lo, hi, out)
    cls = _classify(span)
    cur = lo
    for i_lo, i_hi in sorted(child_iv):
        if i_lo > cur:
            out.append((cur, i_lo, cls))
        cur = max(cur, i_hi)
    if hi > cur:
        out.append((cur, hi, cls))


def _sweep(intervals: List[tuple]) -> Dict[str, float]:
    """Attribute every instant covered by ≥1 self-interval to exactly
    one class (the highest-priority active one), so the class totals
    partition the covered wall-clock — concurrent spans never
    double-count."""
    events: List[tuple] = []
    for lo, hi, cls in intervals:
        events.append((lo, 0, cls))   # open before close at a tie
        events.append((hi, 1, cls))
    events.sort(key=lambda e: (e[0], e[1]))
    active = {c: 0 for c in _PRIORITY}
    out: Dict[str, float] = {}
    prev = None
    for pos, kind, cls in events:
        if prev is not None and pos > prev:
            best = None
            for c in _PRIORITY:
                if active[c]:
                    best = c
                    break
            if best is not None:
                out[best] = out.get(best, 0.0) + (pos - prev)
        active[cls] = active.get(cls, 0) + (1 if kind == 0 else -1)
        prev = pos
    return out


def straggler_slack_ms(task_stats: Optional[Iterable[dict]]) -> float:
    """Per-stage max-task elapsed minus median-task elapsed, summed —
    wall-clock the query spent waiting on one task after its siblings
    were done (the salted-repartitioning motivation number)."""
    by_stage: Dict[str, List[float]] = {}
    for row in task_stats or ():
        if row.get("state") != "FINISHED":
            continue
        by_stage.setdefault(str(row.get("stage_id")), []).append(
            float(row.get("elapsed_ms", 0.0) or 0.0)
        )
    slack = 0.0
    for times in by_stage.values():
        if len(times) > 1:
            slack += max(times) - statistics.median(times)
    return slack


def _scan_fraction(op_stats: Optional[Iterable[dict]]) -> float:
    """Fraction of operator self time spent in scan operators."""
    scan = total = 0.0
    for row in op_stats or ():
        ms = float(row.get("self_ms", 0.0) or 0.0)
        total += ms
        if "scan" in str(row.get("node_type") or row.get("name") or "").lower():
            scan += ms
    return scan / total if total > 0 else 0.0


def compute_time_breakdown(
    trace, wall_ms: float, *,
    queued_ms: float = 0.0,
    slot_wait_ms: float = 0.0,
    planning_ms: float = 0.0,
    task_stats: Optional[List[dict]] = None,
    op_stats: Optional[List[dict]] = None,
    compile_ms: float = 0.0,
) -> Optional[Dict[str, Any]]:
    """Decompose ``wall_ms`` into the named :data:`BUCKETS`.

    ``queued_ms``/``slot_wait_ms``/``planning_ms`` cover time before
    the trace root opened (the fleet backdates its synthetic planning
    span out of the root interval and passes the measured planning
    wall here instead). ``op_stats`` (or the per-task ``operator_stats`` inside
    ``task_stats``) splits execution self-time into scan vs compute;
    ``compile_ms`` (a compile-counter delta) reroutes compile time the
    span tree could not see out of compute. Straggler slack is carved
    out of the compute bucket — while a straggler runs alone, the wall
    it burns shows up as (unioned) execute time.

    Returns ``{"wall_ms", "buckets": {...}, "coverage",
    "critical_path"}``; buckets sum to ``wall_ms`` up to float noise,
    with the trace-uncovered remainder in ``other``.
    """
    if trace is None or getattr(trace, "root", None) is None:
        return None
    root = trace.root
    buckets: Dict[str, float] = {b: 0.0 for b in BUCKETS}
    buckets["queued"] = max(float(queued_ms), 0.0)
    buckets["slot_wait"] = max(float(slot_wait_ms), 0.0)
    buckets["planning"] = max(float(planning_ms), 0.0)
    root_lo = float(root.start_ms)
    root_hi = root_lo + max(float(root.duration_ms), 0.0)
    intervals: List[tuple] = []
    _self_intervals(root, root_lo, root_hi, intervals)
    raw = _sweep(intervals)
    for k, v in raw.items():
        if k != _EXEC:
            buckets[k] = buckets.get(k, 0.0) + v
    exec_ms = raw.get(_EXEC, 0.0)
    if op_stats is None and task_stats:
        op_stats = [
            row
            for ts in task_stats
            for row in ts.get("operator_stats") or ()
        ]
    frac = _scan_fraction(op_stats)
    buckets["scan"] += exec_ms * frac
    buckets["compute"] += exec_ms * (1.0 - frac)
    # compile work the span tree missed (real backend compiles inside
    # execute spans emit no span of their own — only the counters see
    # them): reroute the counter delta out of compute
    extra_compile = max(float(compile_ms) - buckets["xla_compile"], 0.0)
    moved = min(extra_compile, buckets["compute"])
    buckets["xla_compile"] += moved
    buckets["compute"] -= moved
    slack = min(straggler_slack_ms(task_stats), buckets["compute"])
    buckets["straggler_slack"] += slack
    buckets["compute"] -= slack
    attributed = sum(buckets.values())
    if wall_ms > attributed:
        buckets["other"] += wall_ms - attributed
    total = sum(buckets.values())
    return {
        "wall_ms": round(float(wall_ms), 3),
        "buckets": {b: round(buckets[b], 3) for b in BUCKETS},
        "coverage": round(total / wall_ms, 4) if wall_ms > 0 else 1.0,
        "critical_path": critical_path(trace),
    }


def critical_path(trace, limit: int = 16) -> List[Dict[str, Any]]:
    """The longest chain through the span tree: from the root, descend
    into the latest-*ending* child at every level (the span everything
    after it had to wait for). One entry per hop, root first."""
    path: List[Dict[str, Any]] = []
    sp = getattr(trace, "root", None)
    while sp is not None and len(path) < limit:
        path.append({
            "name": sp.name,
            "kind": sp.kind,
            "node": sp.node or "coordinator",
            "duration_ms": round(max(float(sp.duration_ms), 0.0), 3),
        })
        kids = [c for c in sp.children if float(c.duration_ms) > 0.0]
        if not kids:
            break
        sp = max(
            kids,
            key=lambda c: float(c.start_ms) + float(c.duration_ms),
        )
    return path


def format_breakdown(breakdown: Optional[Dict[str, Any]]) -> List[str]:
    """EXPLAIN ANALYZE footer lines for one time breakdown."""
    if not breakdown:
        return []
    wall = float(breakdown.get("wall_ms", 0.0) or 0.0)
    lines = [
        f"Time breakdown (wall {wall:.1f} ms, "
        f"coverage {float(breakdown.get('coverage', 1.0)) * 100:.0f}%):"
    ]
    buckets = breakdown.get("buckets") or {}
    for name in BUCKETS:
        v = float(buckets.get(name, 0.0) or 0.0)
        if v < 0.05:
            continue
        pct = f" ({v / wall * 100:.1f}%)" if wall > 0 else ""
        lines.append(f"  {name:<16} {v:>10.1f} ms{pct}")
    cp = breakdown.get("critical_path") or []
    if cp:
        tail = cp[-1]
        lines.append(
            f"Critical path: {' -> '.join(seg['name'] for seg in cp)} "
            f"(tail {tail['duration_ms']:.1f} ms on {tail['node']})"
        )
    return lines


# ---------------------------------------------------------------------------
# Partition-skew statistics
# ---------------------------------------------------------------------------


def partition_skew(hist: Optional[Dict[Any, Any]]) -> Dict[str, float]:
    """Skew stats over a per-partition histogram (rows or bytes).

    ``max_mean_ratio`` is 1.0 for a perfectly uniform distribution and
    grows with the hot partition's share; ``cv`` is the population
    coefficient of variation. Keys may be ints or (post-JSON) strings.
    """
    vals = [float(v) for v in (hist or {}).values()]
    if not vals:
        return {"partitions": 0, "max": 0.0, "mean": 0.0,
                "max_mean_ratio": 0.0, "cv": 0.0}
    mean = sum(vals) / len(vals)
    mx = max(vals)
    cv = statistics.pstdev(vals) / mean if mean > 0 else 0.0
    return {
        "partitions": len(vals),
        "max": mx,
        "mean": round(mean, 3),
        "max_mean_ratio": round(mx / mean, 4) if mean > 0 else 0.0,
        "cv": round(cv, 4),
    }


# ---------------------------------------------------------------------------
# Cross-process clock-skew correction
# ---------------------------------------------------------------------------


def shift_span_tree(span_dict: Dict[str, Any],
                    offset_ms: float) -> Dict[str, Any]:
    """Shift a serialized span subtree's wall-clock timestamps in place
    (worker clock -> coordinator clock) before ``Tracer.attach``."""
    if not offset_ms:
        return span_dict
    span_dict["start_ms"] = (
        float(span_dict.get("start_ms", 0.0)) + offset_ms
    )
    for c in span_dict.get("children") or ():
        shift_span_tree(c, offset_ms)
    return span_dict


class ClockSkewEstimator:
    """Per-node wall-clock offset from RPC request/response timestamps.

    Workers stamp their own wall clock (``now_ms``) on task-status
    responses; the coordinator records its send/receive wall times
    around the RPC. The NTP midpoint estimate —
    ``(send + receive) / 2 - remote_now`` — is the milliseconds to ADD
    to a remote timestamp to land it on the coordinator's clock,
    smoothed with an EWMA so one slow response does not jerk the
    estimate. Single-threaded by construction (only the fleet's
    ``_run_dag`` RPC loop touches it)."""

    def __init__(self, alpha: float = 0.25) -> None:
        self.alpha = float(alpha)
        self._offsets: Dict[str, float] = {}

    def observe(self, node: str, send_ms: float, recv_ms: float,
                remote_now_ms: Optional[float]) -> None:
        if remote_now_ms is None:
            return
        est = (float(send_ms) + float(recv_ms)) / 2.0 - float(
            remote_now_ms
        )
        cur = self._offsets.get(node)
        self._offsets[node] = (
            est if cur is None else cur + self.alpha * (est - cur)
        )

    def offset_ms(self, node: str) -> float:
        return float(self._offsets.get(node, 0.0))

    def offsets(self) -> Dict[str, float]:
        return dict(self._offsets)


# ---------------------------------------------------------------------------
# Cluster time-series recorder
# ---------------------------------------------------------------------------


def _parse_prometheus(text: str) -> Dict[str, float]:
    """Prometheus text exposition -> ``{series: value}`` (full series
    names, labels included)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, val = line.rpartition(" ")
        try:
            out[name] = float(val)
        except ValueError:
            continue
    return out


class ClusterTimeseriesRecorder:
    """Bounded ring of periodic cluster-wide metric scrapes.

    Each sample is ``{"ts": epoch_seconds, "nodes": {node: {series:
    value}}}``: the coordinator's own registry snapshot plus one parsed
    ``/v1/metrics`` scrape per reachable worker. Gated entirely by
    ``TRINO_TPU_TIMESERIES_INTERVAL_MS`` — when unset (the default) no
    recorder is constructed and NO background thread runs.
    """

    def __init__(self, worker_uris=(), interval_ms: float = 1000.0,
                 max_samples: int = 512) -> None:
        #: static list or zero-arg callable returning current URIs
        self.worker_uris = worker_uris
        self.interval_ms = float(interval_ms)
        self._samples: deque = deque(maxlen=max(int(max_samples), 1))
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def from_env(worker_uris=()) -> Optional["ClusterTimeseriesRecorder"]:
        """Recorder per env config, or None (= no scrape thread) when
        ``TRINO_TPU_TIMESERIES_INTERVAL_MS`` is unset or non-positive."""
        raw = os.environ.get("TRINO_TPU_TIMESERIES_INTERVAL_MS", "")
        try:
            interval = float(raw)
        except ValueError:
            return None
        if interval <= 0:
            return None
        try:
            samples = int(
                os.environ.get("TRINO_TPU_TIMESERIES_SAMPLES", "512")
            )
        except ValueError:
            samples = 512
        return ClusterTimeseriesRecorder(
            worker_uris=worker_uris, interval_ms=interval,
            max_samples=samples,
        )

    # -- lifecycle ------------------------------------------------------

    def start(self) -> "ClusterTimeseriesRecorder":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="cluster-timeseries", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_ms / 1000.0):
            try:
                self.sample()
            except Exception:
                pass  # the recorder must outlive any one bad scrape

    # -- sampling -------------------------------------------------------

    def _uris(self) -> List[str]:
        uris = self.worker_uris
        if callable(uris):
            uris = uris()
        return list(uris or ())

    def sample(self) -> Dict[str, Any]:
        """Take one scrape round now (also callable from tests without
        the thread)."""
        nodes: Dict[str, Dict[str, float]] = {
            "coordinator": telemetry.REGISTRY.snapshot(),
        }
        for uri in self._uris():
            try:
                with urllib.request.urlopen(
                    f"{uri}/v1/metrics", timeout=2.0
                ) as r:
                    nodes[uri] = _parse_prometheus(r.read().decode())
            except Exception:
                telemetry.TIMESERIES_SCRAPE_FAILURES.inc()
        entry = {"ts": time.time(), "nodes": nodes}
        with self._lock:
            self._samples.append(entry)
        telemetry.TIMESERIES_SAMPLES.inc()
        return entry

    # -- read side ------------------------------------------------------

    def samples(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._samples)

    def rows(self) -> List[tuple]:
        """Flat ``(ts, node, series, value)`` rows for
        ``system.runtime.cluster_metrics``."""
        out: List[tuple] = []
        for s in self.samples():
            ts = float(s["ts"])
            for node, series in s["nodes"].items():
                for name, value in series.items():
                    out.append((ts, node, name, float(value)))
        return out


#: the recorder the system connector reads; set by whichever
#: coordinator started one (None = time-series disabled)
_ACTIVE_RECORDER: Optional[ClusterTimeseriesRecorder] = None


def set_active_recorder(
    rec: Optional[ClusterTimeseriesRecorder],
) -> None:
    global _ACTIVE_RECORDER
    _ACTIVE_RECORDER = rec


def active_recorder() -> Optional[ClusterTimeseriesRecorder]:
    return _ACTIVE_RECORDER
