"""Live cluster membership: announce/heartbeat, drain, size gating.

The fleet's worker list was frozen at construction time; this module is
the membership layer Trino builds from ``DiscoveryNodeManager`` (the
active/inactive node tracker fed by announcements), ``ClusterSizeMonitor``
(scheduling held until a minimum worker count is met), and
``GracefulShutdownHandler`` (ACTIVE -> DRAINING -> DRAINED -> gone).

One :class:`MembershipRegistry` lives on the coordinator. Workers PUT
``/v1/announce`` on boot and then heartbeat the same endpoint every
``ttl_s / 3`` seconds, reporting their lifecycle state
(``server/worker.py``'s ACTIVE / DRAINING / DRAINED). The registry runs
a TTL state machine over the announcements:

* a member whose heartbeat goes stale past ``ttl_s`` turns INACTIVE —
  the transition is *recorded* immediately but the member is not
  **evicted** (handed to ``on_leave`` subscribers, who stop scheduling
  onto it) until INACTIVE has persisted for ``damping_s``. A worker
  that bounces active<->inactive inside the damping window therefore
  never leaves the schedulable set, and its re-announce fires no
  ``on_join`` — no eviction churn, no double admission (the
  HeartbeatFailureDetector's flap suppression, made explicit);
* INACTIVE persisting past ``gone_after_s`` becomes GONE and is
  dropped. A later announce from the same node is a fresh join;
* a DRAINING member is **unschedulable but alive**: ``on_leave`` fires
  (no new tasks) while its HTTP surface keeps serving direct-exchange
  buffers and spool reads. It may deregister only when (a) the worker
  itself reports DRAINED (running tasks finished) AND (b) no residency
  provider still pins one of its buffers — the PR 10 residency hints
  are exactly the coordinator's knowledge of which consumers have not
  yet committed their reads. Until both hold, the announce response is
  ``{"deregister": false}`` and the worker keeps serving.

Eviction here never declares a worker dead: ``on_leave`` consumers mark
it unschedulable (``FleetWorker.draining``) and the FTE tier (poll
eviction, re-admission probes, speculation first-commit-wins) remains
the one crash path.

:class:`ClusterSizeMonitor` gates dispatch: ``wait_for_minimum`` parks
until the schedulable count reaches ``min_workers`` and raises the
typed :class:`InsufficientResourcesError` (coordinator error code 134,
``INSUFFICIENT_RESOURCES``) when the deadline lapses first.

``announce_once`` is the worker-side client half; its fault seams
(``announce-drop`` for the initial announce, ``heartbeat-loss`` for
every later round) make flaky membership a seeded, schedulable chaos
ingredient like any other site.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from dataclasses import dataclass
from typing import Callable, Dict, Iterable, List, Optional

from trino_tpu import fault, telemetry

__all__ = [
    "Member", "Transition", "MembershipRegistry",
    "ClusterSizeMonitor", "InsufficientResourcesError",
    "announce_once",
]

#: membership lifecycle states (worker-reported states plus the
#: registry's own staleness tiers)
STATES = ("ACTIVE", "DRAINING", "DRAINED", "INACTIVE", "GONE")


class InsufficientResourcesError(RuntimeError):
    """The cluster cannot meet ``min_workers`` before the deadline.
    Typed so the coordinator maps it to error code 134
    (``INSUFFICIENT_RESOURCES``) instead of a generic failure."""


@dataclass
class Transition:
    """One membership state-machine edge, for post-mortems and the
    ``system.runtime.nodes`` heartbeat story."""

    node_id: str
    src: str
    dst: str
    at: float
    reason: str = ""


@dataclass
class Member:
    node_id: str
    uri: str
    state: str = "ACTIVE"
    first_seen: float = 0.0
    last_seen: float = 0.0
    active_tasks: int = 0
    #: clock stamp of the ACTIVE->INACTIVE edge (damping window start)
    inactive_since: Optional[float] = None
    #: on_leave already fired — the member left the schedulable set
    evicted: bool = False
    #: clock stamp of the first DRAINING announce
    drain_started: Optional[float] = None
    announces: int = 0
    flaps: int = 0


class MembershipRegistry:
    """TTL-tracked membership with flap damping and drain-aware
    deregistration. Thread-safe; ``on_join``/``on_leave`` callbacks
    fire outside the registry lock (subscribers take their own)."""

    def __init__(
        self,
        ttl_s: float = 3.0,
        *,
        gone_after_s: Optional[float] = None,
        damping_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        max_transitions: int = 256,
    ):
        self.ttl_s = float(ttl_s)
        self.gone_after_s = (
            float(gone_after_s) if gone_after_s is not None
            else 3.0 * self.ttl_s
        )
        self.damping_s = (
            float(damping_s) if damping_s is not None
            else 0.5 * self.ttl_s
        )
        self._clock = clock
        self._lock = threading.Lock()
        self._members: Dict[str, Member] = {}
        self._transitions: List[Transition] = []
        self._max_transitions = int(max_transitions)
        #: set-of-pinned-worker-URI callables — the coordinator's
        #: residency knowledge (scheduler ``_locations`` unions over
        #: live queries). A DRAINED worker deregisters only when no
        #: provider pins it.
        self.residency_providers: List[Callable[[], Iterable[str]]] = []
        #: called with the Member on a fresh (or re-) admission
        self.on_join: List[Callable[[Member], None]] = []
        #: called with (Member, reason) when it leaves the
        #: schedulable set (drain, damped staleness, gone)
        self.on_leave: List[Callable[[Member, str], None]] = []

    # ---- state machine ------------------------------------------------

    def _record(self, m: Member, dst: str, reason: str, now: float):
        src = m.state
        if src == dst:
            return
        m.state = dst
        self._transitions.append(
            Transition(m.node_id, src, dst, now, reason)
        )
        del self._transitions[:-self._max_transitions]
        # "from" is a Python keyword — splat the label dict
        telemetry.MEMBERSHIP_TRANSITIONS.inc(**{"from": src, "to": dst})

    def _gauges_locked(self):
        counts = {"active": 0, "draining": 0, "inactive": 0}
        for m in self._members.values():
            if m.state == "ACTIVE":
                counts["active"] += 1
            elif m.state in ("DRAINING", "DRAINED"):
                counts["draining"] += 1
            elif m.state == "INACTIVE":
                counts["inactive"] += 1
        for state, n in counts.items():
            telemetry.CLUSTER_WORKERS.set(float(n), state=state)

    def announce(
        self,
        node_id: str,
        uri: str,
        *,
        state: str = "ACTIVE",
        active_tasks: int = 0,
    ) -> dict:
        """Process one announcement/heartbeat; returns the wire
        response (``{"state", "ttl_s", "deregister"}``)."""
        state = str(state).upper()
        if state not in STATES:
            state = "ACTIVE"
        now = self._clock()
        joined: Optional[Member] = None
        left: Optional[tuple] = None
        dereg = False
        with self._lock:
            m = self._members.get(node_id)
            if m is None:
                m = Member(
                    node_id=node_id, uri=uri.rstrip("/"),
                    first_seen=now, last_seen=now,
                )
                self._members[node_id] = m
                self._transitions.append(
                    Transition(node_id, "GONE", "ACTIVE", now, "join")
                )
                del self._transitions[:-self._max_transitions]
                telemetry.MEMBERSHIP_TRANSITIONS.inc(
                    **{"from": "GONE", "to": "ACTIVE"}
                )
                if state == "ACTIVE":
                    joined = m
            m.last_seen = now
            m.uri = uri.rstrip("/")
            m.active_tasks = int(active_tasks)
            m.announces += 1
            if state == "ACTIVE":
                if m.state == "INACTIVE":
                    m.flaps += 1
                    self._record(m, "ACTIVE", "reannounce", now)
                    if m.evicted:
                        # past the damping window: it really left and
                        # is really back — re-admit
                        m.evicted = False
                        joined = m
                    # inside the window: damped flap, no on_join
                elif m.state in ("DRAINING", "DRAINED"):
                    # a drain is not reversible through heartbeats;
                    # keep it unschedulable until it deregisters
                    pass
                m.inactive_since = None
            elif state in ("DRAINING", "DRAINED"):
                if m.drain_started is None:
                    m.drain_started = now
                if m.state == "ACTIVE":
                    self._record(m, "DRAINING", "drain", now)
                    m.evicted = True
                    left = (m, "drain")
                if state == "DRAINED" and m.active_tasks == 0:
                    if m.state == "DRAINING":
                        self._record(m, "DRAINED", "tasks finished", now)
                    dereg = self._deregisterable_locked(m)
                    if dereg:
                        self._record(m, "GONE", "drain complete", now)
                        telemetry.DRAIN_DURATION.observe(
                            max(0.0, now - m.drain_started)
                        )
                        self._members.pop(node_id, None)
            swept = self._sweep_locked(now)
            self._gauges_locked()
        for sm, reason in swept:
            self._fire_leave(sm, reason)
        if left is not None:
            self._fire_leave(*left)
        if joined is not None:
            self._fire_join(joined)
        return {
            "state": "GONE" if dereg else m.state,
            "ttl_s": self.ttl_s,
            "deregister": dereg,
        }

    def _deregisterable_locked(self, m: Member) -> bool:
        """True when no residency provider still pins the member's
        URI — every dependent consumer has committed its reads of the
        worker's exchange buffers / spool outputs."""
        for provider in self.residency_providers:
            try:
                pinned = {str(u).rstrip("/") for u in provider()}
            except Exception:
                continue
            if m.uri in pinned:
                return False
        return True

    def _sweep_locked(self, now: float) -> List[tuple]:
        """Advance the TTL tiers; returns (member, reason) evictions
        for the caller to fire outside the lock."""
        leaves: List[tuple] = []
        for node_id in list(self._members):
            m = self._members[node_id]
            stale = now - m.last_seen
            if m.state in ("ACTIVE",) and stale > self.ttl_s:
                self._record(m, "INACTIVE", "heartbeat stale", now)
                m.inactive_since = now
            if m.state == "INACTIVE":
                quiet = now - (m.inactive_since or m.last_seen)
                if not m.evicted and quiet >= self.damping_s:
                    m.evicted = True
                    leaves.append((m, "heartbeat lost"))
                if quiet >= self.gone_after_s:
                    self._record(m, "GONE", "expired", now)
                    self._members.pop(node_id, None)
            elif m.state in ("DRAINING", "DRAINED"):
                # a draining worker that also stops heartbeating is a
                # crash, not a drain: expire it on the same TTL tiers
                if stale > self.gone_after_s:
                    self._record(m, "GONE", "died while draining", now)
                    self._members.pop(node_id, None)
        return leaves

    def sweep(self) -> None:
        """Run the TTL state machine now (announce() also sweeps, so
        an idle cluster still ages via any reader calling this)."""
        with self._lock:
            leaves = self._sweep_locked(self._clock())
            self._gauges_locked()
        for m, reason in leaves:
            self._fire_leave(m, reason)

    def _fire_join(self, m: Member):
        for cb in list(self.on_join):
            try:
                cb(m)
            except Exception:
                pass

    def _fire_leave(self, m: Member, reason: str):
        for cb in list(self.on_leave):
            try:
                cb(m, reason)
            except Exception:
                pass

    # ---- read side ----------------------------------------------------

    def members(self) -> List[Member]:
        self.sweep()
        with self._lock:
            return list(self._members.values())

    def schedulable(self) -> List[Member]:
        """Members new tasks may be placed on: ACTIVE and not inside
        an eviction (a damped INACTIVE member is *still schedulable*
        — that is the whole point of the damping window)."""
        self.sweep()
        with self._lock:
            return [
                m for m in self._members.values()
                if not m.evicted and m.state in ("ACTIVE", "INACTIVE")
            ]

    def heartbeat_age(self, node_id: str) -> Optional[float]:
        with self._lock:
            m = self._members.get(node_id)
            return None if m is None else max(
                0.0, self._clock() - m.last_seen
            )

    def transitions(self) -> List[Transition]:
        with self._lock:
            return list(self._transitions)

    def snapshot(self) -> dict:
        """JSON-able summary for post-mortem bundles and debugging."""
        self.sweep()
        with self._lock:
            now = self._clock()
            return {
                "members": [
                    {
                        "node_id": m.node_id,
                        "uri": m.uri,
                        "state": m.state,
                        "evicted": m.evicted,
                        "active_tasks": m.active_tasks,
                        "heartbeat_age_s": round(now - m.last_seen, 3),
                        "announces": m.announces,
                        "flaps": m.flaps,
                    }
                    for m in self._members.values()
                ],
                "transitions": [
                    {
                        "node_id": t.node_id, "from": t.src,
                        "to": t.dst, "reason": t.reason,
                    }
                    for t in self._transitions[-32:]
                ],
            }


class ClusterSizeMonitor:
    """Holds dispatch until the schedulable membership meets
    ``min_workers`` (Trino's ClusterSizeMonitor: queries park rather
    than fail while the fleet is still forming / mid-scale-down, and
    fail *typed* when the wait is hopeless)."""

    def __init__(
        self,
        registry: MembershipRegistry,
        min_workers: int,
        *,
        poll_s: float = 0.02,
    ):
        self.registry = registry
        self.min_workers = int(min_workers)
        self.poll_s = float(poll_s)

    def wait_for_minimum(self, timeout_s: float = 10.0) -> int:
        """Park until ``min_workers`` schedulable members exist;
        returns the count, or raises InsufficientResourcesError once
        ``timeout_s`` lapses."""
        deadline = time.monotonic() + float(timeout_s)
        while True:
            n = len(self.registry.schedulable())
            if n >= self.min_workers:
                return n
            if time.monotonic() >= deadline:
                raise InsufficientResourcesError(
                    f"cluster has {n} schedulable workers; query "
                    f"requires {self.min_workers} "
                    f"(waited {timeout_s:.1f}s)"
                )
            time.sleep(self.poll_s)


def announce_once(
    coordinator_uri: str,
    node_id: str,
    uri: str,
    *,
    state: str = "ACTIVE",
    active_tasks: int = 0,
    timeout_s: float = 2.0,
    initial: bool = False,
    attempt: int = 0,
) -> dict:
    """One announce/heartbeat PUT against the coordinator. Raises on
    transport failure (the caller's loop just skips the round — a
    missed heartbeat is exactly what the TTL machine absorbs). The
    fault seams make membership flakiness seed-schedulable;
    ``attempt`` is the heartbeat round, so ``times``/``prob``
    schedules vary per round instead of firing forever."""
    fault.check(
        "announce-drop" if initial else "heartbeat-loss",
        tag=node_id, attempt=int(attempt),
    )
    body = json.dumps({
        "node_id": node_id,
        "uri": uri,
        "state": state,
        "active_tasks": int(active_tasks),
    }).encode()
    req = urllib.request.Request(
        coordinator_uri.rstrip("/") + "/v1/announce",
        data=body,
        method="PUT",
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode())
