"""Durable query journal — the coordinator's write-ahead log for FTE
crash recovery.

Every fault domain below the coordinator already recovers from durable
state: task attempts commit first-wins to the CRC'd spool (`.done`
markers), whole queries re-execute under fresh spool epochs, workers
drain or die under the membership registry. The coordinator itself was
the last single point of failure — its ``QueryTracker`` / dispatch
books live in memory, so a ``kill -9`` stranded running worker tasks
and lost committed spool work that was already durable on disk. This
module closes that gap with the smallest durable record that makes the
in-memory books reconstructible.

Layout: ``{spool_root}/_journal/{query_id}.wal`` — one JSONL file per
query, one JSON object per line, each append ``flush`` + ``os.fsync``'d
before the action it describes is allowed to proceed (classic WAL
discipline: journal the dispatch *before* the POST, so a crash can
over-report dispatches but never under-report them — recovery treats
journaled-but-never-posted tasks as adoptable-or-redispatch, which is
safe, while the reverse would silently double-execute).

Record types (``"t"`` field):

    ``client``   slug, user, sql — written by the HTTP coordinator at
                 submit time so a restart can re-serve the query at its
                 old ``/v1/statement/{qid}/{slug}/{token}`` URI
    ``begin``    sql, user, session-property snapshot, retry_policy
    ``epoch``    the attempt-local spool epoch id + fragmented-plan
                 digest + partition count (one per QUERY-tier attempt)
    ``stage``    stage id, task ids and per-task spec *fingerprints*
                 (task split enumeration depends on fleet liveness, so
                 a committed attempt is only trusted on resume when the
                 regenerated spec hashes to the same work)
    ``dispatch`` task posted to a worker (tid, attempt, worker uri)
    ``commit``   task attempt committed on the spool
    ``resumed``  recovery stats stamped by a resuming run
    ``done``     terminal state, rows / error, elapsed, and (on
                 failure) the embedded post-mortem diagnostics bundle

Torn tails are expected — a crash mid-append leaves a partial last
line, which ``load`` silently drops (everything before it was fsync'd).

Fault sites: ``journal-write`` fires on appends (a query that cannot
journal must fail — recovery never trusts a journal it couldn't
write), ``journal-read`` on replay (an unreadable journal makes the
query unresumable, never silently wrong).

This module also houses the recovery-adjacent error types and the
cluster-wide :class:`RetryBudget` — both are shared by the fleet
runner, the HTTP coordinator, and the protocol error-code table, and
this is the one module all three already import.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time

from trino_tpu import fault, telemetry

__all__ = [
    "QueryJournal", "plan_digest", "spec_fingerprint",
    "CoordinatorRestartedError", "RetryBudgetExhaustedError",
    "RetryBudget", "JOURNAL_DIRNAME",
]

JOURNAL_DIRNAME = "_journal"


class CoordinatorRestartedError(RuntimeError):
    """A restarted coordinator cannot resume this query (not journaled,
    not fault-tolerant, or its journal was unreadable). Typed so the
    protocol layer reports ``COORDINATOR_RESTARTED`` and clients know
    the statement itself was fine — resubmission is the remedy."""


class RetryBudgetExhaustedError(RuntimeError):
    """The query spent its cluster-wide task-retry budget inside the
    sliding window. Deliberately *non-retryable* at every tier: the
    budget exists to stop recovery storms from melting a small fleet,
    so escalating to a QUERY-tier re-execution would defeat it.

    The message embeds the token "non-retryable" so tier classifiers
    that key on generic RuntimeError text also refuse to retry it."""

    def __init__(self, spent: int, limit: int, window_s: float):
        self.spent = spent
        self.limit = limit
        self.window_s = window_s
        super().__init__(
            f"retry budget exhausted (non-retryable): {spent} task "
            f"retries in the last {window_s:g}s exceeds the budget of "
            f"{limit} (session property retry_budget)"
        )


class RetryBudget:
    """Sliding-window cap on total task retries for one query.

    ``spend()`` records one retry and raises
    :class:`RetryBudgetExhaustedError` once more than ``limit`` retries
    land inside ``window_s`` seconds. ``limit <= 0`` disables the
    budget (the default — existing retry behaviour is unchanged)."""

    def __init__(self, limit: int, window_s: float = 60.0):
        self.limit = int(limit)
        self.window_s = float(window_s)
        self._spent: list[float] = []
        self._lock = threading.Lock()

    def spend(self, now: float | None = None) -> None:
        if self.limit <= 0:
            return
        t = time.monotonic() if now is None else now
        with self._lock:
            cutoff = t - self.window_s
            self._spent = [s for s in self._spent if s > cutoff]
            self._spent.append(t)
            if len(self._spent) > self.limit:
                raise RetryBudgetExhaustedError(
                    len(self._spent), self.limit, self.window_s
                )


def plan_digest(plan) -> str:
    """Stable digest of an optimized plan's wire form. Resume
    re-plans the journaled SQL and only proceeds when the digests
    match — catalog drift between crash and restart must force a
    fresh epoch, never a half-trusted one."""
    from trino_tpu.plan.serde import plan_to_json

    j = json.dumps(plan_to_json(plan), sort_keys=True, default=str)
    return hashlib.sha256(j.encode()).hexdigest()


def spec_fingerprint(spec) -> str:
    """Digest of one task spec's *work*: wire plan + partition +
    salt. Task ids alone are not stable across restarts — scan-stage
    split enumeration depends on how many workers are alive — so a
    spool-committed attempt is only adopted when the regenerated
    spec's fingerprint matches the journaled one."""
    basis = json.dumps(
        {
            "plan": getattr(spec, "plan_json", None),
            "partition": getattr(spec, "partition", None),
            "salt": getattr(spec, "salt", None),
        },
        sort_keys=True, default=str,
    )
    return hashlib.sha256(basis.encode()).hexdigest()


class QueryJournal:
    """One journal directory under the spool root; thread-safe appends
    keyed by query id. One instance is shared by the HTTP coordinator
    and the fleet runner of a process."""

    def __init__(self, spool_root: str):
        self.root = os.path.join(spool_root, JOURNAL_DIRNAME)
        os.makedirs(self.root, exist_ok=True)
        self._locks: dict[str, threading.Lock] = {}
        self._guard = threading.Lock()

    # -- paths -----------------------------------------------------------
    def path(self, query_id: str) -> str:
        return os.path.join(self.root, f"{query_id}.wal")

    def _lock_for(self, query_id: str) -> threading.Lock:
        with self._guard:
            return self._locks.setdefault(query_id, threading.Lock())

    # -- writes ----------------------------------------------------------
    def append(self, query_id: str, record: dict) -> None:
        """fsync'd JSONL append; the WAL rule is append-before-act."""
        fault.check("journal-write", tag=query_id)
        line = json.dumps(record, sort_keys=True, default=str)
        with self._lock_for(query_id):
            with open(self.path(query_id), "a", encoding="utf-8") as f:
                f.write(line + "\n")
                f.flush()
                os.fsync(f.fileno())
        telemetry.JOURNAL_APPENDS.inc(type=record.get("t", "?"))

    def note_client(self, query_id: str, slug: str, user: str,
                    sql: str) -> None:
        self.append(query_id, {
            "t": "client", "slug": slug, "user": user, "sql": sql,
            "ts": time.time(),
        })

    def begin(self, query_id: str, sql: str, user: str,
              session_properties: dict, retry_policy: str) -> None:
        self.append(query_id, {
            "t": "begin", "sql": sql, "user": user,
            "session": dict(session_properties),
            "retry_policy": retry_policy, "ts": time.time(),
        })

    def epoch(self, query_id: str, epoch_id: str, digest: str,
              n_partitions: int) -> None:
        self.append(query_id, {
            "t": "epoch", "epoch": epoch_id, "plan_digest": digest,
            "n_partitions": n_partitions, "ts": time.time(),
        })

    def stage(self, query_id: str, stage_id: str,
              fingerprints: dict) -> None:
        """``fingerprints``: task_id -> spec fingerprint."""
        self.append(query_id, {
            "t": "stage", "sid": stage_id, "specs": dict(fingerprints),
        })

    def dispatch(self, query_id: str, stage_id: str, task_id: str,
                 attempt: int, worker: str) -> None:
        self.append(query_id, {
            "t": "dispatch", "sid": stage_id, "tid": task_id,
            "a": attempt, "worker": worker,
        })

    def commit(self, query_id: str, stage_id: str, task_id: str,
               attempt: int) -> None:
        self.append(query_id, {
            "t": "commit", "sid": stage_id, "tid": task_id, "a": attempt,
        })

    def resumed(self, query_id: str, stats: dict) -> None:
        self.append(query_id, {
            "t": "resumed", "ts": time.time(), **dict(stats),
        })

    def finish(self, query_id: str, state: str, rows: int = 0,
               error: str | None = None, elapsed_ms: float = 0.0,
               diagnostics: dict | None = None) -> None:
        rec = {
            "t": "done", "state": state, "rows": int(rows),
            "error": error, "elapsed_ms": float(elapsed_ms),
            "ts": time.time(),
        }
        if diagnostics is not None:
            rec["diagnostics"] = diagnostics
        self.append(query_id, rec)

    # -- reads -----------------------------------------------------------
    def load(self, query_id: str) -> list[dict]:
        """All intact records, in append order. A torn final line
        (crash mid-append) is dropped; a torn *interior* line cannot
        occur because every append fsyncs its own newline."""
        fault.check("journal-read", tag=query_id)
        path = self.path(query_id)
        if not os.path.exists(path):
            return []
        records = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except ValueError:
                    break  # torn tail — everything after is garbage
        return records

    def entry(self, query_id: str) -> "JournalEntry | None":
        records = self.load(query_id)
        if not records:
            return None
        return JournalEntry(query_id, records)

    def scan(self) -> list["JournalEntry"]:
        """Every journaled query, oldest file first (recovery replays
        in submission order so rehydrated tracker rows keep their
        relative history)."""
        if not os.path.isdir(self.root):
            return []
        names = [n for n in os.listdir(self.root) if n.endswith(".wal")]
        paths = sorted(
            (os.path.join(self.root, n) for n in names),
            key=lambda p: (os.path.getmtime(p), p),
        )
        out = []
        for p in paths:
            qid = os.path.basename(p)[:-len(".wal")]
            try:
                e = self.entry(qid)
            except Exception:
                # journal-read fault or corrupt file: surface as an
                # entry with no records so recovery can fail it typed
                e = JournalEntry(qid, [])
            if e is not None:
                out.append(e)
        return out

    def delete(self, query_id: str) -> None:
        try:
            os.remove(self.path(query_id))
        except OSError:
            pass

    def gc(self, max_age_s: float = 7 * 24 * 3600.0) -> int:
        """Drop journals for terminal queries older than the TTL (a
        terminal journal is only history — the tracker rehydrates from
        it, but it need not live forever). Returns files removed."""
        removed = 0
        now = time.time()
        for e in self.scan():
            if e.done is None:
                continue
            ts = e.done.get("ts", now)
            if now - ts > max_age_s:
                self.delete(e.query_id)
                removed += 1
        return removed


class JournalEntry:
    """A parsed per-query journal: indexed views over the record list
    that recovery consumes directly."""

    def __init__(self, query_id: str, records: list[dict]):
        self.query_id = query_id
        self.records = records
        self.client = next(
            (r for r in records if r.get("t") == "client"), None)
        self.begin = next(
            (r for r in records if r.get("t") == "begin"), None)
        self.done = next(
            (r for r in reversed(records) if r.get("t") == "done"), None)
        #: last epoch wins — each QUERY-tier attempt journals its own
        self.epoch = next(
            (r for r in reversed(records) if r.get("t") == "epoch"), None)

    @property
    def sql(self) -> str | None:
        if self.begin is not None:
            return self.begin.get("sql")
        return self.client.get("sql") if self.client else None

    @property
    def resumable(self) -> bool:
        """RUNNING (no terminal record) + a begin with a fault-tolerant
        retry policy + at least one epoch to anchor the spool state."""
        return (
            self.done is None
            and self.begin is not None
            and self.begin.get("retry_policy", "NONE") != "NONE"
            and self.epoch is not None
        )

    def stage_fingerprints(self) -> dict:
        """task_id -> journaled spec fingerprint, scoped to the records
        appended *after* the last epoch (earlier epochs' stages carry
        stale task enumerations)."""
        out: dict = {}
        seen_epoch = self.epoch is None
        for r in self.records:
            if r is self.epoch:
                seen_epoch = True
                out = {}
                continue
            if seen_epoch and r.get("t") == "stage":
                out.update(r.get("specs", {}))
        return out

    def dispatches(self) -> dict:
        """(tid, attempt) -> worker uri, post-last-epoch only."""
        out: dict = {}
        seen_epoch = self.epoch is None
        for r in self.records:
            if r is self.epoch:
                seen_epoch = True
                out = {}
                continue
            if seen_epoch and r.get("t") == "dispatch":
                out[(r["tid"], int(r["a"]))] = r.get("worker")
        return out

    def commits(self) -> dict:
        """tid -> committed attempt number, post-last-epoch only."""
        out: dict = {}
        seen_epoch = self.epoch is None
        for r in self.records:
            if r is self.epoch:
                seen_epoch = True
                out = {}
                continue
            if seen_epoch and r.get("t") == "commit":
                out[r["tid"]] = int(r["a"])
        return out
