"""System session properties: the typed, validated, discoverable
knob registry.

The analog of the reference's SystemSessionProperties
(MAIN/SystemSessionProperties.java, ~200 properties): every property
has a type, a default, a description, and a validator; SET SESSION
rejects unknown names and mistyped values at statement time instead of
failing (or silently no-op-ing) deep inside execution, and
SHOW SESSION lists the full surface with current values.

Typed access goes through ``get(session, name)`` so call sites share
one parse/validate path.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

__all__ = [
    "PropertyMetadata", "SESSION_PROPERTIES", "get", "set_property",
    "show_rows",
]


@dataclass(frozen=True)
class PropertyMetadata:
    name: str
    description: str
    sql_type: str  # "boolean" | "bigint" | "double" | "varchar"
    default: object
    #: optional extra validation over the typed value
    validate: Callable[[object], None] | None = None
    #: internal/test properties hidden from SHOW SESSION
    hidden: bool = False


def _positive(name):
    def check(v):
        if v <= 0:
            raise ValueError(f"{name} must be positive, got {v}")

    return check


def _non_negative(name):
    def check(v):
        if v < 0:
            raise ValueError(f"{name} must be >= 0, got {v}")

    return check


def _one_of(name, allowed):
    def check(v):
        if str(v).upper() not in allowed:
            raise ValueError(
                f"{name} must be one of {sorted(allowed)}, got {v!r}"
            )

    return check


_P = PropertyMetadata

SESSION_PROPERTIES: dict[str, PropertyMetadata] = {
    p.name: p
    for p in [
        # ---- memory / spill tier (exec.spill) -------------------------
        _P(
            "hbm_budget_bytes",
            "Device-memory budget; above it scans stream, aggregations "
            "chunk, and joins take the streamed-probe/grace paths "
            "(query_max_memory_per_node analog)",
            "bigint", 0, _non_negative("hbm_budget_bytes"),
        ),
        _P(
            "max_chunk_rows",
            "Row cap per streamed-scan chunk in the budgeted tier",
            "bigint", 0, _non_negative("max_chunk_rows"),
        ),
        _P(
            "grace_partitions",
            "Fan-out of grace-hash-join recursion in the budgeted tier",
            "bigint", 8, _positive("grace_partitions"),
        ),
        # ---- data layout ---------------------------------------------
        _P(
            "varchar_hash_ndv",
            "Distinct-count threshold above which a VARCHAR column "
            "scans as hash codes instead of a sorted dictionary",
            "bigint", 1_000_000, _positive("varchar_hash_ndv"),
        ),
        # ---- distribution planning (plan.distribute) ------------------
        _P(
            "join_distribution_type",
            "AUTOMATIC (costed), BROADCAST, or PARTITIONED "
            "(DetermineJoinDistributionType analog)",
            "varchar", "AUTOMATIC",
            _one_of(
                "join_distribution_type",
                {"AUTOMATIC", "BROADCAST", "PARTITIONED"},
            ),
        ),
        _P(
            "broadcast_join_row_limit",
            "Never broadcast a build side estimated above this many "
            "rows (join_max_broadcast_table_size analog)",
            "double", 2_000_000.0, _positive("broadcast_join_row_limit"),
        ),
        _P(
            "join_reordering_strategy",
            "AUTOMATIC (stats-driven) or NONE (syntactic order; "
            "ReorderJoins analog)",
            "varchar", "AUTOMATIC",
            _one_of("join_reordering_strategy", {"AUTOMATIC", "NONE"}),
        ),
        # ---- local execution (exec.local) -----------------------------
        _P(
            "cross_join_chunk_rows",
            "Output-row bound per cross-join materialization chunk",
            "bigint", 8_000_000, _positive("cross_join_chunk_rows"),
        ),
        _P(
            "dynamic_filtering_enabled",
            "Prune probe rows by build-side key bounds before "
            "joins (enable_dynamic_filtering analog)",
            "boolean", True,
        ),
        # ---- client/worker protocol -----------------------------------
        _P(
            "result_batch_rows",
            "Rows per paged result batch over the worker protocol",
            "bigint", 65_536, _positive("result_batch_rows"),
        ),
        # ---- fleet / fault tolerance ----------------------------------
        _P(
            "retry_max_attempts",
            "Attempts per fleet task before the query fails "
            "(task_retry_attempts_per_task analog)",
            "bigint", 3, _positive("retry_max_attempts"),
        ),
        # ---- test/failure injection (hidden) --------------------------
        _P(
            "task_delay_ms",
            "Test hook: delay before worker task execution",
            "double", 0.0, _non_negative("task_delay_ms"), hidden=True,
        ),
        _P(
            "fleet_task_delay_ms",
            "Test hook: delay before fleet stage-task execution",
            "double", 0.0, _non_negative("fleet_task_delay_ms"),
            hidden=True,
        ),
    ]
}


def _coerce(meta: PropertyMetadata, value):
    try:
        if meta.sql_type == "bigint":
            if isinstance(value, bool):
                raise ValueError("boolean is not bigint")
            return int(value)
        if meta.sql_type == "double":
            if isinstance(value, bool):
                raise ValueError("boolean is not double")
            return float(value)
        if meta.sql_type == "boolean":
            if isinstance(value, bool):
                return value
            s = str(value).strip().lower()
            if s in ("true", "1"):
                return True
            if s in ("false", "0"):
                return False
            raise ValueError(f"not a boolean: {value!r}")
        return str(value)
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"session property {meta.name} is {meta.sql_type}: {e}"
        ) from None


def set_property(session, name: str, value) -> None:
    """Validate + store one property (the SET SESSION entry point)."""
    meta = SESSION_PROPERTIES.get(name)
    if meta is None:
        raise ValueError(f"unknown session property: {name}")
    typed = _coerce(meta, value)
    if meta.validate is not None:
        meta.validate(typed)
    session.properties[name] = typed


def get(session, name: str):
    """Typed current value (session override or registry default)."""
    meta = SESSION_PROPERTIES[name]
    raw = (session.properties if session is not None else {}).get(name)
    if raw is None:
        return meta.default
    typed = _coerce(meta, raw)
    if meta.validate is not None:
        meta.validate(typed)
    return typed


def show_rows(session) -> list[tuple]:
    """SHOW SESSION rows: (name, value, default, type, description)."""
    out = []
    for name in sorted(SESSION_PROPERTIES):
        meta = SESSION_PROPERTIES[name]
        if meta.hidden:
            continue
        out.append((
            name,
            str(get(session, name)),
            str(meta.default),
            meta.sql_type,
            meta.description,
        ))
    return out
