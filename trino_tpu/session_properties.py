"""System session properties: the typed, validated, discoverable
knob registry.

The analog of the reference's SystemSessionProperties
(MAIN/SystemSessionProperties.java, ~200 properties): every property
has a type, a default, a description, and a validator; SET SESSION
rejects unknown names and mistyped values at statement time instead of
failing (or silently no-op-ing) deep inside execution, and
SHOW SESSION lists the full surface with current values.

Typed access goes through ``get(session, name)`` so call sites share
one parse/validate path.
"""

from __future__ import annotations

import os as _os
from dataclasses import dataclass
from typing import Callable

__all__ = [
    "PropertyMetadata", "SESSION_PROPERTIES", "get", "set_property",
    "show_rows", "parse_data_size", "parse_duration",
]

#: DataSize units (io.airlift.units.DataSize analog): decimal suffixes
#: with binary multipliers, matching the reference's "1GB" = 2^30
_DATA_SIZE_UNITS = {
    "B": 1,
    "kB": 1 << 10,
    "MB": 1 << 20,
    "GB": 1 << 30,
    "TB": 1 << 40,
    "PB": 1 << 50,
}


def parse_data_size(value: str) -> int:
    """Parse a Trino data-size literal ('1GB', '512MB', '2.5kB') to
    bytes. Raises ValueError on malformed input — SET SESSION rejects
    a bad size at statement time, not deep inside memory accounting."""
    s = str(value).strip()
    for unit in sorted(_DATA_SIZE_UNITS, key=len, reverse=True):
        if s.endswith(unit):
            num = s[: -len(unit)].strip()
            try:
                n = float(num)
            except ValueError:
                raise ValueError(f"invalid data size: {value!r}") from None
            if n < 0:
                raise ValueError(f"data size must be >= 0: {value!r}")
            return int(n * _DATA_SIZE_UNITS[unit])
    try:
        return int(s)  # bare byte count
    except ValueError:
        raise ValueError(
            f"invalid data size: {value!r} (expected e.g. '1GB', "
            f"'512MB', or a byte count)"
        ) from None


def _data_size(name):
    def check(v):
        parse_data_size(v)

    return check


#: Duration units (io.airlift.units.Duration analog); longest-suffix
#: match first so 'ms' does not parse as minutes-of-'s'
_DURATION_UNITS = {
    "ns": 1e-9,
    "us": 1e-6,
    "ms": 1e-3,
    "s": 1.0,
    "m": 60.0,
    "h": 3600.0,
    "d": 86400.0,
}


def parse_duration(value: str) -> float:
    """Parse a Trino duration literal ('100ms', '30s', '5m', '100d')
    to seconds. A value of 0 (any unit) means unlimited at the call
    sites that consume deadlines. Raises ValueError on malformed
    input so SET SESSION rejects it at statement time."""
    s = str(value).strip()
    for unit in sorted(_DURATION_UNITS, key=len, reverse=True):
        if s.endswith(unit):
            num = s[: -len(unit)].strip()
            try:
                n = float(num)
            except ValueError:
                raise ValueError(f"invalid duration: {value!r}") from None
            if n < 0:
                raise ValueError(f"duration must be >= 0: {value!r}")
            return n * _DURATION_UNITS[unit]
    raise ValueError(
        f"invalid duration: {value!r} (expected e.g. '100ms', '30s', "
        f"'5m', '2h', '100d')"
    )


def _duration(name):
    def check(v):
        parse_duration(v)

    return check


@dataclass(frozen=True)
class PropertyMetadata:
    name: str
    description: str
    sql_type: str  # "boolean" | "bigint" | "double" | "varchar"
    default: object
    #: optional extra validation over the typed value
    validate: Callable[[object], None] | None = None
    #: internal/test properties hidden from SHOW SESSION
    hidden: bool = False


def _positive(name):
    def check(v):
        if v <= 0:
            raise ValueError(f"{name} must be positive, got {v}")

    return check


def _non_negative(name):
    def check(v):
        if v < 0:
            raise ValueError(f"{name} must be >= 0, got {v}")

    return check


def _one_of(name, allowed):
    def check(v):
        if str(v).upper() not in allowed:
            raise ValueError(
                f"{name} must be one of {sorted(allowed)}, got {v!r}"
            )

    return check


_P = PropertyMetadata

SESSION_PROPERTIES: dict[str, PropertyMetadata] = {
    p.name: p
    for p in [
        # ---- memory / spill tier (exec.spill) -------------------------
        _P(
            "hbm_budget_bytes",
            "Device-memory budget; above it scans stream, aggregations "
            "chunk, and joins take the streamed-probe/grace paths "
            "(query_max_memory_per_node analog)",
            "bigint", 0, _non_negative("hbm_budget_bytes"),
        ),
        _P(
            "max_chunk_rows",
            "Row cap per streamed-scan chunk in the budgeted tier",
            "bigint", 0, _non_negative("max_chunk_rows"),
        ),
        _P(
            "grace_partitions",
            "Fan-out of grace-hash-join recursion in the budgeted tier",
            "bigint", 8, _positive("grace_partitions"),
        ),
        # ---- data layout ---------------------------------------------
        _P(
            "varchar_hash_ndv",
            "Distinct-count threshold above which a VARCHAR column "
            "scans as hash codes instead of a sorted dictionary",
            "bigint", 1_000_000, _positive("varchar_hash_ndv"),
        ),
        # ---- distribution planning (plan.distribute) ------------------
        _P(
            "join_distribution_type",
            "AUTOMATIC (costed), BROADCAST, or PARTITIONED "
            "(DetermineJoinDistributionType analog)",
            "varchar", "AUTOMATIC",
            _one_of(
                "join_distribution_type",
                {"AUTOMATIC", "BROADCAST", "PARTITIONED"},
            ),
        ),
        _P(
            "broadcast_join_row_limit",
            "Never broadcast a build side estimated above this many "
            "rows (join_max_broadcast_table_size analog)",
            "double", 2_000_000.0, _positive("broadcast_join_row_limit"),
        ),
        _P(
            "join_reordering_strategy",
            "AUTOMATIC (stats-driven) or NONE (syntactic order; "
            "ReorderJoins analog)",
            "varchar", "AUTOMATIC",
            _one_of("join_reordering_strategy", {"AUTOMATIC", "NONE"}),
        ),
        # ---- caching (cache.py) ---------------------------------------
        _P(
            "result_cache_enabled",
            "Serve byte-identical repeat statements from the semantic "
            "result cache (canonical plan-hash keyed, generation-"
            "invalidated). Registry default off so single-statement "
            "runs keep exact execution semantics; the serving layer "
            "turns it on for its shared session (A/B-off per session). "
            "Seedable via TRINO_TPU_RESULT_CACHE",
            "boolean",
            _os.environ.get("TRINO_TPU_RESULT_CACHE", "false").lower()
            in ("true", "1"),
        ),
        _P(
            "device_cache_enabled",
            "Pin hot scanned columns and built join-side pages in HBM "
            "across queries (device table cache; pool-governed, "
            "evicted under memory pressure before any query "
            "reservation can fail). Registry default off; the serving "
            "layer turns it on. Seedable via TRINO_TPU_DEVICE_CACHE",
            "boolean",
            _os.environ.get("TRINO_TPU_DEVICE_CACHE", "false").lower()
            in ("true", "1"),
        ),
        _P(
            "result_cache_max_bytes",
            "Byte bound for one runner's semantic result cache (LRU)",
            "bigint", 64 << 20, _positive("result_cache_max_bytes"),
        ),
        # ---- plan sanity checking (plan.validate) ---------------------
        _P(
            "plan_validation",
            "Plan invariant checking (PlanSanityChecker analog): OFF, "
            "FINAL (validate the optimized plan, the distributed plan, "
            "and the fragmented stage DAG), or FULL (additionally "
            "validate after every optimizer pass, attributing a "
            "violation to the pass that introduced it). Tests default "
            "to FULL via TRINO_TPU_PLAN_VALIDATION",
            "varchar",
            _os.environ.get("TRINO_TPU_PLAN_VALIDATION", "FINAL"),
            _one_of("plan_validation", {"OFF", "FINAL", "FULL"}),
        ),
        _P(
            "check_exchange_coverage",
            "Debug assertion: verify every exchange edge conserves "
            "rows — mesh collectives compare live rows before/after "
            "the all_to_all, the fleet coordinator compares per-edge "
            "consumer reads against producer commits — raising "
            "ExchangeCoverageError naming the edge that dropped rows. "
            "Forces host syncs; keep OFF outside debugging",
            "boolean", False, hidden=True,
        ),
        _P(
            "exchange_partition_counters",
            "Record per-destination live-row counts on every mesh "
            "all_to_all edge (the trino_exchange_partition_rows metric "
            "family plus exchange_stats histograms) — the skew "
            "observability feed behind salted repartitioning. Forces a "
            "host sync per exchange; keep OFF outside skew debugging "
            "(the spool boundary records its histograms unconditionally "
            "and cheaply)",
            "boolean", False, hidden=True,
        ),
        _P(
            "exchange_partition_counter_sample",
            "Sampled counting mode for the mesh-tier per-destination "
            "exchange histograms: count every Nth all_to_all "
            "invocation instead of every one, amortizing the host "
            "sync the counters force to 1/N of exchanges so skew "
            "observability can stay on by default. Sampled counts "
            "preserve the max/mean ratio in expectation but "
            "under-report absolute rows by ~N; set "
            "exchange_partition_counters=true for exact per-exchange "
            "counts (full sync tax), or 0 to disable sampling",
            "bigint", 16,
            _non_negative("exchange_partition_counter_sample"),
        ),
        _P(
            "skew_salt_threshold",
            "Salted-repartition trigger: when a completed exchange "
            "edge's per-partition row histogram shows max/mean above "
            "this ratio, the fleet re-plans the consumer stage as a "
            "SALTED exchange — hot partitions fan out across "
            "skew_salt_factor salt tasks, co-aligned inputs replicate "
            "to every salt (SkewedPartitionRebalancer generalized to "
            "joins). 0 disables. Detection needs producer histograms, "
            "so enabling this holds aligned consumer stages until "
            "their producers complete (stage-materialization "
            "semantics, as under stage_admission=BARRIER)",
            "double", 0.0, _non_negative("skew_salt_threshold"),
        ),
        _P(
            "skew_salt_factor",
            "Salt tasks per hot partition under salted repartitioning "
            "(the fan-out of skew_salt_threshold)",
            "bigint", 4, _positive("skew_salt_factor"),
        ),
        _P(
            "adaptive_partition_growth_factor",
            "Runtime-adaptive partition count "
            "(RuntimeAdaptivePartitioningRewriter analog): when a "
            "completed stage's observed output rows exceed its CBO "
            "estimate by this factor, un-admitted consumer stages "
            "grow their own output partition count before task "
            "construction (recorded as stage_stats[*]."
            "adaptive_repartitions). 0 disables. Like "
            "skew_salt_threshold, holds aligned consumers until "
            "producers complete",
            "double", 0.0,
            _non_negative("adaptive_partition_growth_factor"),
        ),
        _P(
            "adaptive_partition_max",
            "Upper bound on an adaptively grown stage output "
            "partition count",
            "bigint", 32, _positive("adaptive_partition_max"),
        ),
        # ---- local execution (exec.local) -----------------------------
        _P(
            "cross_join_chunk_rows",
            "Output-row bound per cross-join materialization chunk",
            "bigint", 8_000_000, _positive("cross_join_chunk_rows"),
        ),
        _P(
            "shape_bucketing",
            "ON routes executor cache keys through exec.shapes: "
            "operator capacities quantize onto the canonical bucket "
            "family and fused chains canonicalize to nameless form, so "
            "different queries sharing an operator mix (and the same "
            "query across scale factors) reuse one XLA program; OFF "
            "restores per-name, per-capacity cache keys",
            "varchar", "ON", _one_of("shape_bucketing", {"ON", "OFF"}),
        ),
        _P(
            "dynamic_filtering_enabled",
            "Prune probe rows by build-side key bounds before "
            "joins (enable_dynamic_filtering analog)",
            "boolean", True,
        ),
        _P(
            "streaming_scan_enabled",
            "Route big scans over streamable connectors (parquet) "
            "through the out-of-core split-granular reader "
            "(exec.stream_scan) when the estimated scan exceeds a "
            "quarter of the memory budget; OFF materializes the scan "
            "resident (and over-budget tables then fail loudly)",
            "boolean", True,
        ),
        # ---- client/worker protocol -----------------------------------
        _P(
            "result_batch_rows",
            "Rows per paged result batch over the worker protocol",
            "bigint", 65_536, _positive("result_batch_rows"),
        ),
        # ---- memory governance (trino_tpu.memory: worker pools +
        # ---- cluster memory manager enforce these) --------------------
        _P(
            "query_max_memory",
            "Cluster-wide memory cap per query, as a data size "
            "('20GB'); the ClusterMemoryManager kills the query with "
            "the largest total reservation when breached "
            "(SystemSessionProperties QUERY_MAX_MEMORY analog)",
            "varchar", "20GB", _data_size("query_max_memory"),
        ),
        _P(
            "query_max_memory_per_node",
            "Per-worker memory cap per query, as a data size ('2GB'); "
            "enforced by the worker MemoryPool: over-cap joins are "
            "revoked into the spill tier, and reservations that still "
            "cannot fit raise ExceededMemoryLimitError",
            "varchar", "2GB", _data_size("query_max_memory_per_node"),
        ),
        # ---- query lifecycle / deadline governance (tracker.py) -------
        _P(
            "query_max_execution_time",
            "Wall-clock cap on query execution, as a duration "
            "('30s'); enforced cooperatively at executor boundaries "
            "and by the coordinator QueryTracker reaper; 0 = "
            "unlimited (QUERY_MAX_EXECUTION_TIME analog)",
            "varchar", "100d", _duration("query_max_execution_time"),
        ),
        _P(
            "query_max_planning_time",
            "Wall-clock cap on statement planning, as a duration "
            "('10m'); 0 = unlimited (QUERY_MAX_PLANNING_TIME analog)",
            "varchar", "10m", _duration("query_max_planning_time"),
        ),
        _P(
            "query_max_queued_time",
            "Cap on time a query may wait for resource-group "
            "admission, as a duration ('5m'); enforced by the "
            "QueryTracker reaper; 0 = unlimited "
            "(QUERY_MAX_QUEUED_TIME analog)",
            "varchar", "5m", _duration("query_max_queued_time"),
        ),
        # ---- write path ------------------------------------------------
        _P(
            "task_writer_count",
            "Writer tasks an unpartitioned INSERT/CTAS fans out to in "
            "fleet mode (round-robin page routing; task_writer_count "
            "analog). Partitioned writes partition on the target's "
            "partition keys instead",
            "bigint", 4, _positive("task_writer_count"),
        ),
        _P(
            "writer_scaling",
            "Scale unpartitioned writes across task_writer_count "
            "writer tasks; false pins a single writer task "
            "(scale_writers analog)",
            "boolean", True,
        ),
        # ---- fleet / fault tolerance ----------------------------------
        _P(
            "retry_policy",
            "FTE tier: NONE (fail fast), TASK (per-task retry from "
            "spooled stage outputs), or QUERY (task tier plus "
            "whole-statement re-execution under a fresh spool epoch "
            "when a retryable failure escapes the task tier) "
            "(RetryPolicy analog)",
            "varchar", "TASK",
            _one_of("retry_policy", {"NONE", "TASK", "QUERY"}),
        ),
        _P(
            "query_retry_attempts",
            "Whole-statement re-executions under retry_policy=QUERY "
            "before QueryRetriesExhaustedError surfaces "
            "(query_retry_attempts analog)",
            "bigint", 4, _positive("query_retry_attempts"),
        ),
        _P(
            "retry_max_attempts",
            "Attempts per fleet task before the query fails "
            "(task_retry_attempts_per_task analog)",
            "bigint", 3, _positive("retry_max_attempts"),
        ),
        _P(
            "retry_budget",
            "Cluster-wide cap on total task retries per query inside "
            "a sliding window; 0 disables. Exhaustion raises the "
            "non-retryable RETRY_BUDGET_EXHAUSTED error so recovery "
            "storms after a coordinator restart cannot melt a small "
            "fleet",
            "bigint", 0, _non_negative("retry_budget"),
        ),
        _P(
            "retry_budget_window_ms",
            "Sliding-window width for retry_budget accounting",
            "bigint", 60_000, _positive("retry_budget_window_ms"),
            hidden=True,
        ),
        _P(
            "retry_initial_delay_ms",
            "Base delay before a failed fleet task's first retry; "
            "doubles per failure up to retry_max_delay_ms, with full "
            "jitter (retry_initial_delay analog)",
            "bigint", 100, _non_negative("retry_initial_delay_ms"),
        ),
        _P(
            "retry_max_delay_ms",
            "Upper bound on the exponential retry backoff "
            "(retry_max_delay analog)",
            "bigint", 5_000, _positive("retry_max_delay_ms"),
        ),
        _P(
            "speculation_enabled",
            "Launch a backup attempt of a straggling fleet task on an "
            "idle worker; first committed attempt wins "
            "(the Tail-at-Scale hedge / MapReduce speculative "
            "execution)",
            "boolean", True,
        ),
        _P(
            "speculation_multiplier",
            "A RUNNING task is a straggler when its age exceeds this "
            "multiple of the median runtime of completed tasks in its "
            "stage",
            "double", 3.0, _positive("speculation_multiplier"),
        ),
        _P(
            "speculation_min_task_age_ms",
            "Never speculate a task younger than this (keeps tiny "
            "tasks from hedging on scheduling noise)",
            "bigint", 500, _non_negative("speculation_min_task_age_ms"),
        ),
        _P(
            "stage_admission",
            "Fleet stage-admission granularity: BARRIER admits a "
            "consumer stage only after every producer stage fully "
            "commits; PIPELINED (EventDrivenScheduler) admits each "
            "consumer task the moment its input partition is "
            "committed across all producer tasks, overlapping "
            "producer tails with consumer heads "
            "(EventDrivenFaultTolerantQueryScheduler analog)",
            "varchar", "PIPELINED",
            _one_of("stage_admission", {"BARRIER", "PIPELINED"}),
        ),
        _P(
            "exchange_mode",
            "Inter-stage exchange data path: DIRECT serves committed "
            "partitions straight from the producer worker's in-memory "
            "output buffer (spool write becomes an async background "
            "commit, read falls back to the spool on miss/eviction/"
            "producer death); SPOOL forces every edge through the "
            "on-disk spool (the FTE filesystem-exchange analog)",
            "varchar", "DIRECT",
            _one_of("exchange_mode", {"DIRECT", "SPOOL"}),
        ),
        # ---- observability --------------------------------------------
        _P(
            "slow_query_log_threshold",
            "Statements slower than this duration ('5s') emit one "
            "structured slow-query JSON line (profile summary, top-3 "
            "operators by self time) through the EventListener path; "
            "0 = disabled",
            "varchar", "0s", _duration("slow_query_log_threshold"),
        ),
        _P(
            "kernel_profile",
            "Device profile capture around query execution: ON traces "
            "every statement and attaches per-HLO-scope device times "
            "to QueryResult.kernel_profile; AUTO captures too but "
            "attaches the attribution to the slow-query record only "
            "when slow_query_log_threshold fires; OFF disables",
            "varchar", "OFF",
            _one_of("kernel_profile", {"OFF", "ON", "AUTO"}),
        ),
        # ---- test/failure injection (hidden) --------------------------
        _P(
            "task_delay_ms",
            "Test hook: delay before worker task execution",
            "double", 0.0, _non_negative("task_delay_ms"), hidden=True,
        ),
        _P(
            "fleet_task_delay_ms",
            "Test hook: delay before fleet stage-task execution",
            "double", 0.0, _non_negative("fleet_task_delay_ms"),
            hidden=True,
        ),
        _P(
            "retry_backoff_seed",
            "Test hook: seed for the retry-jitter RNG (0 = entropy); "
            "a seeded run produces a deterministic delay sequence",
            "bigint", 0, _non_negative("retry_backoff_seed"),
            hidden=True,
        ),
        _P(
            "execution_delay_ms",
            "Test hook: wedge the engine in a sleep before execution "
            "(exercises the QueryTracker reaper against a query that "
            "never reaches a cooperative boundary check)",
            "double", 0.0, _non_negative("execution_delay_ms"),
            hidden=True,
        ),
        _P(
            "planning_delay_ms",
            "Test hook: delay inside statement planning (exercises "
            "query_max_planning_time enforcement)",
            "double", 0.0, _non_negative("planning_delay_ms"),
            hidden=True,
        ),
        _P(
            "spool_partition_delay_ms",
            "Test hook: sleep after each committed spool partition "
            "write (widens producer write tails so pipelined-"
            "admission overlap is observable on tiny data)",
            "double", 0.0, _non_negative("spool_partition_delay_ms"),
            hidden=True,
        ),
    ]
}


def _coerce(meta: PropertyMetadata, value):
    try:
        if meta.sql_type == "bigint":
            if isinstance(value, bool):
                raise ValueError("boolean is not bigint")
            return int(value)
        if meta.sql_type == "double":
            if isinstance(value, bool):
                raise ValueError("boolean is not double")
            return float(value)
        if meta.sql_type == "boolean":
            if isinstance(value, bool):
                return value
            s = str(value).strip().lower()
            if s in ("true", "1"):
                return True
            if s in ("false", "0"):
                return False
            raise ValueError(f"not a boolean: {value!r}")
        return str(value)
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"session property {meta.name} is {meta.sql_type}: {e}"
        ) from None


def set_property(session, name: str, value) -> None:
    """Validate + store one property (the SET SESSION entry point)."""
    meta = SESSION_PROPERTIES.get(name)
    if meta is None:
        raise ValueError(f"unknown session property: {name}")
    typed = _coerce(meta, value)
    if meta.validate is not None:
        meta.validate(typed)
    session.properties[name] = typed


def get(session, name: str):
    """Typed current value (session override or registry default)."""
    meta = SESSION_PROPERTIES[name]
    raw = (session.properties if session is not None else {}).get(name)
    if raw is None:
        return meta.default
    typed = _coerce(meta, raw)
    if meta.validate is not None:
        meta.validate(typed)
    return typed


def show_rows(session) -> list[tuple]:
    """SHOW SESSION rows: (name, value, default, type, description)."""
    out = []
    for name in sorted(SESSION_PROPERTIES):
        meta = SESSION_PROPERTIES[name]
        if meta.hidden:
            continue
        out.append((
            name,
            str(get(session, name)),
            str(meta.default),
            meta.sql_type,
            meta.description,
        ))
    return out
