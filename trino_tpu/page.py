"""Device-resident columnar pages.

The analog of the reference's columnar batch (SPI/Page.java:31 holding a
sealed Block hierarchy, SPI/block/Block.java:26). A ``Page`` here is a
struct-of-arrays over *device* memory:

- every column is one fixed-width JAX array (``Column.data``)
- nulls are a separate boolean validity array per column — exactly the
  reference's separate null masks in ValueBlocks, which map 1:1 onto
  TPU masks
- a page-level ``mask`` marks live rows: filters do not compact (that
  would be a dynamic shape); they clear mask bits. Compaction happens
  only at host materialization or before expensive downstream ops
  (the analog of Page.compact, SPI/Page.java:180)
- VARCHAR columns carry a host-side sorted ``StringDictionary``; device
  data holds int32 codes (replacing VariableWidthBlock's pointer
  chasing with a dictionary-encode-early strategy)

Capacities are padded to power-of-two buckets so XLA compiles one
program per pipeline, not per batch size.
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Sequence

import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T

__all__ = [
    "StringDictionary", "HashStringPool", "HashCollision", "ArrayPool",
    "MapPool", "RowPool",
    "Column", "Page", "pad_capacity", "content_hash64",
]


def content_hash64(strings: np.ndarray) -> np.ndarray:
    """Deterministic 64-bit content hash of a string array, vectorized.

    Views the fixed-width UCS4 representation as uint32 lanes and folds
    them with an FNV-style polynomial — one vector op per character
    column instead of a per-row Python loop. Unlike ``hash()`` (which
    PYTHONHASHSEED randomizes per process) the result is identical in
    every process, so fleet workers hashing the same value — for spool
    partitioning or HLL registers — always agree."""
    arr = np.asarray(strings)
    if arr.dtype.kind != "U":
        arr = arr.astype(str)
    n = len(arr)
    if n == 0:
        return np.zeros(0, dtype=np.uint64)
    width = max(arr.dtype.itemsize // 4, 1)
    lanes = np.ascontiguousarray(arr).view(np.uint32).reshape(n, width)
    h = np.full(n, 0xCBF29CE484222325, dtype=np.uint64)
    prime = np.uint64(0x100000001B3)
    with np.errstate(over="ignore"):
        for j in range(width):
            lane = lanes[:, j].astype(np.uint64)
            # skip zero lanes (UCS4 tail padding): the hash must not
            # depend on the array's fixed width, or the same string in
            # two differently-sized columns lands in different spool
            # partitions
            upd = (h ^ lane) * prime
            h = np.where(lane != 0, upd, h)
        # final avalanche so short strings spread over all 64 bits
        h ^= h >> np.uint64(33)
        h *= np.uint64(0xFF51AFD7ED558CCD)
        h ^= h >> np.uint64(33)
    return h


def pad_capacity(n: int, minimum: int = 8) -> int:
    """Round up to a power of two or 1.5x a power of two (>= 96).

    The bucket family bounds the number of XLA programs while keeping
    worst-case padding waste at 33% instead of 100%; every bucket stays
    divisible by 8 so mesh sharding divides evenly."""
    c = max(int(n), minimum)
    p = 1 << (c - 1).bit_length()
    mid = (p // 4) * 3
    if mid >= max(c, 96):
        return mid
    return p


class StringDictionary:
    """Sorted, de-duplicated host-side string pool.

    Code order == lexicographic order, so <, >, ORDER BY and MIN/MAX on
    VARCHAR run entirely on device codes.
    """

    __slots__ = ("values", "_index", "_fp")

    def __init__(self, values: np.ndarray):
        # values must be sorted & unique (callers use from_strings)
        self.values = values
        self._index: dict[str, int] | None = None
        self._fp: bytes | None = None

    @property
    def fingerprint(self) -> bytes:
        """Content digest — the cache identity for compiled programs.
        Programs bake dictionary-dependent constants (encoded literal
        codes), so equal CONTENTS means an equal program; object
        identity (``id``) is too strict and makes every spool-rebuilt
        dictionary a fresh jit key (unbounded retrace + retained
        jaxprs under multi-statement serving)."""
        fp = self._fp
        if fp is None:
            import hashlib

            arr = np.asarray(self.values, dtype=str)
            h = hashlib.blake2b(digest_size=16)
            h.update(str(arr.dtype).encode())
            h.update(arr.tobytes())
            fp = self._fp = h.digest()
        return fp

    @staticmethod
    def from_strings(strings: Sequence[str]) -> tuple["StringDictionary", np.ndarray]:
        """Build a dictionary and return (dict, int32 codes)."""
        arr = np.asarray(strings, dtype=object)
        uniq, codes = np.unique(arr.astype(str), return_inverse=True)
        return StringDictionary(uniq), codes.astype(np.int32)

    def __len__(self) -> int:
        return len(self.values)

    def encode_one(self, s: str) -> int:
        """Code for s, or -1 if absent."""
        i = int(np.searchsorted(self.values, s))
        if i < len(self.values) and self.values[i] == s:
            return i
        return -1

    def decode(self, codes: np.ndarray) -> np.ndarray:
        out = self.values[np.clip(codes, 0, len(self.values) - 1)]
        return out

    def union(self, other: "StringDictionary"):
        """Merge two dictionaries.

        Returns (merged, remap_self, remap_other) where remap_x is an
        int32 host array mapping old codes -> merged codes (applied on
        device with a gather).
        """
        merged = np.union1d(self.values, other.values)
        remap_a = np.searchsorted(merged, self.values).astype(np.int32)
        remap_b = np.searchsorted(merged, other.values).astype(np.int32)
        return StringDictionary(merged), remap_a, remap_b


_POOL_TOKENS = iter(range(1, 1 << 62))


class HashStringPool:
    """High-cardinality VARCHAR representation (SURVEY §7 hard-parts):
    the device column carries [hash64, source_row_id] lanes; this pool
    holds the HOST strings the id lane indexes, plus a one-time
    injectivity proof.

    Unlike sorted-dictionary codes, hash codes are GLOBALLY consistent
    (hash(s) is the same in every column), so joins/exchanges never
    remap — only the cross-pool injectivity check must pass. Building
    one costs a single hash pass (~0.6 s for 6M strings vs ~15 s for
    the sorted np.unique dictionary at 4.8M NDV).

    ``token`` is a process-unique id for cache keys (``id()`` can
    alias a freed pool's address; tokens never repeat).
    """

    __slots__ = (
        "values", "token", "_hashes", "_sorted", "_joinable",
    )

    def __init__(self, values: np.ndarray):
        self.values = values  # host object array, id lane indexes it
        self.token = next(_POOL_TOKENS)
        self._hashes: np.ndarray | None = None
        #: (unique sorted hashes, one representative string per hash)
        self._sorted: tuple[np.ndarray, np.ndarray] | None = None
        self._joinable: set[int] = set()

    def hashes(self) -> np.ndarray:
        if self._hashes is None:
            self._hashes = np.fromiter(
                (hash(s) for s in self.values),
                dtype=np.int64, count=len(self.values),
            )
        return self._hashes

    def verify_injective(self) -> None:
        """Prove hash64 is injective on this pool's values (memoized,
        vectorized: sort hashes, string-compare only within equal-hash
        runs — any run holding two distinct strings has an adjacent
        differing pair). A collision (probability ~n^2/2^64) raises —
        callers rebuild with a sorted dictionary."""
        if self._sorted is not None:
            return
        h = self.hashes()
        if len(h) == 0:
            self._sorted = (h, self.values)
            return
        order = np.argsort(h, kind="stable")
        hs = h[order]
        vs = self.values[order]
        same_h = hs[1:] == hs[:-1]
        if same_h.any():
            diff = same_h & (vs[1:] != vs[:-1])
            if diff.any():
                i = int(np.argmax(diff))
                raise HashCollision(vs[i], vs[i + 1])
        # dedupe to one representative per hash (injectivity proven)
        first = np.concatenate([[True], ~same_h])
        self._sorted = (hs[first], vs[first])

    def verify_joinable(self, other: "HashStringPool") -> None:
        """Prove injectivity across BOTH pools (join exactness);
        memoized per pool pair and fully vectorized: compare the
        representative strings at hash values common to both sides."""
        if other.token in self._joinable or other is self:
            return
        self.verify_injective()
        other.verify_injective()
        ha, va = self._sorted
        hb, vb = other._sorted
        common, ia, ib = np.intersect1d(
            ha, hb, assume_unique=True, return_indices=True
        )
        if len(common) and (va[ia] != vb[ib]).any():
            bad = int(np.argmax(va[ia] != vb[ib]))
            raise HashCollision(va[ia][bad], vb[ib][bad])
        self._joinable.add(other.token)
        other._joinable.add(self.token)


class ArrayPool:
    """Host-side offsets+values columnar store for ARRAY columns (the
    ArrayBlock analog, SPI/block/ArrayBlock.java): ``offsets`` is
    int64[n+1], ``values`` the flat element buffer in STORAGE form
    (objects for varchar elements). Device columns carry int32 handles
    into this pool; descriptor gathers on device never disturb the
    flat buffer, exactly like dictionary codes vs the string pool."""

    __slots__ = ("offsets", "values", "element", "token")

    def __init__(self, offsets: np.ndarray, values: np.ndarray, element):
        self.offsets = offsets
        self.values = values
        self.element = element
        self.token = next(_POOL_TOKENS)

    def __len__(self) -> int:
        return len(self.offsets) - 1

    @staticmethod
    def from_pylists(lists, element) -> tuple["ArrayPool", np.ndarray]:
        """Build a pool from python sequences; returns (pool, handles).
        None entries produce handle 0 with the caller masking validity."""
        offsets = np.zeros(len(lists) + 1, dtype=np.int64)
        flat = []
        for i, v in enumerate(lists):
            if v is None:
                offsets[i + 1] = offsets[i]
                continue
            flat.extend(v)
            offsets[i + 1] = offsets[i] + len(v)
        from trino_tpu import types as T

        if isinstance(element, T.VarcharType):
            values = np.asarray(flat, dtype=object)
        else:
            values = np.asarray(
                flat if flat else [], dtype=element.np_dtype
            )
        return (
            ArrayPool(offsets, values, element),
            np.arange(len(lists), dtype=np.int32),
        )

    def lengths(self) -> np.ndarray:
        return np.diff(self.offsets).astype(np.int64)

    def get(self, handle: int) -> list:
        lo, hi = self.offsets[handle], self.offsets[handle + 1]
        return list(self.values[lo:hi])

    def decode(self, handles: np.ndarray) -> np.ndarray:
        """Handles -> object array of python lists (the one shared
        decode for result fetch / host spill / page_to_host)."""
        out = np.empty(len(handles), dtype=object)
        for i, h in enumerate(handles):
            out[i] = self.get(int(h))
        return out


def _storage_buffer(flat: list, element) -> np.ndarray:
    if isinstance(
        element, (T.VarcharType, T.MapType, T.RowType, T.ArrayType)
    ) or any(v is None for v in flat):
        # NULL entries keep the buffer in object form so decode
        # round-trips None (fixed-width functions over such a pool
        # reject at compile time)
        return np.asarray(flat, dtype=object)
    return np.asarray(flat if flat else [], dtype=element.np_dtype)


class MapPool:
    """Host-side store for MAP columns (SPI/type/MapType.java:58 /
    SPI/block/MapBlock.java analog): one offsets array, two parallel
    flat buffers (keys, values) in STORAGE form. Device columns carry
    int32 handles; map functions compile host LUTs over the pool and
    gather by handle — the ArrayPool design with a second buffer."""

    __slots__ = ("offsets", "keys", "values", "key_type", "value_type", "token")

    def __init__(self, offsets, keys, values, key_type, value_type):
        self.offsets = offsets
        self.keys = keys
        self.values = values
        self.key_type = key_type
        self.value_type = value_type
        self.token = next(_POOL_TOKENS)

    def __len__(self) -> int:
        return len(self.offsets) - 1

    @staticmethod
    def from_pymaps(maps, key_type, value_type) -> tuple["MapPool", np.ndarray]:
        """Build from python dicts / (k, v)-pair sequences; returns
        (pool, handles). None entries produce an empty map with the
        caller masking validity."""
        offsets = np.zeros(len(maps) + 1, dtype=np.int64)
        ks, vs = [], []
        for i, m in enumerate(maps):
            if m is None:
                offsets[i + 1] = offsets[i]
                continue
            pairs = list(m.items()) if isinstance(m, dict) else list(m)
            # keep-FIRST dedup so get() and the subscript LUT (which
            # takes the first match) agree; the map() constructor
            # rejects explicit duplicates before reaching here
            seen = set()
            n_kept = 0
            for k, v in pairs:
                if k in seen:
                    continue
                seen.add(k)
                ks.append(k)
                vs.append(v)
                n_kept += 1
            offsets[i + 1] = offsets[i] + n_kept
        return (
            MapPool(
                offsets,
                _storage_buffer(ks, key_type),
                _storage_buffer(vs, value_type),
                key_type,
                value_type,
            ),
            np.arange(len(maps), dtype=np.int32),
        )

    def lengths(self) -> np.ndarray:
        return np.diff(self.offsets).astype(np.int64)

    def get(self, handle: int) -> dict:
        lo, hi = self.offsets[handle], self.offsets[handle + 1]
        return dict(zip(self.keys[lo:hi], self.values[lo:hi]))

    def decode(self, handles: np.ndarray) -> np.ndarray:
        out = np.empty(len(handles), dtype=object)
        for i, h in enumerate(handles):
            out[i] = self.get(int(h))
        return out


class RowPool:
    """Host-side store for ROW columns (SPI/type/RowType.java:67 /
    SPI/block/RowBlock.java analog): one storage-form column (+ null
    mask) per field; device columns carry int32 handles. Field access
    is a host LUT over the field column + one device gather."""

    __slots__ = ("fields", "type", "token")

    def __init__(self, fields, type_):
        #: list[(np.ndarray values, np.ndarray | None valid)] per field
        self.fields = fields
        self.type = type_
        self.token = next(_POOL_TOKENS)

    def __len__(self) -> int:
        return len(self.fields[0][0]) if self.fields else 0

    @staticmethod
    def from_pytuples(tuples, type_) -> tuple["RowPool", np.ndarray]:
        n = len(tuples)
        fields = []
        for fi, (_fn, ft) in enumerate(type_.fields):
            raw = [
                None if t is None else t[fi] for t in tuples
            ]
            valid = np.asarray([v is not None for v in raw], dtype=np.bool_)
            # storage: nulls become the type's zero value; the mask
            # carries the truth
            filled = [
                ("" if isinstance(ft, T.VarcharType) else 0)
                if v is None else v
                for v in raw
            ]
            vals = _storage_buffer(filled, ft)
            fields.append((vals, None if valid.all() else valid))
        return (
            RowPool(fields, type_),
            np.arange(n, dtype=np.int32),
        )

    def get(self, handle: int):
        out = []
        for vals, valid in self.fields:
            if valid is not None and not valid[handle]:
                out.append(None)
            else:
                v = vals[handle]
                out.append(v.item() if isinstance(v, np.generic) else v)
        return tuple(out)

    def decode(self, handles: np.ndarray) -> np.ndarray:
        out = np.empty(len(handles), dtype=object)
        for i, h in enumerate(handles):
            out[i] = self.get(int(h))
        return out


class HashCollision(RuntimeError):
    """Two distinct strings share a hash64 — astronomically rare; the
    caller rebuilds the column with a sorted dictionary."""

    def __init__(self, a, b):
        super().__init__(f"hash collision: {a!r} vs {b!r}")


@dataclass
class Column:
    """One device column: fixed-width data + optional validity + dict.

    VARCHAR columns take one of two encodings: sorted-dictionary codes
    (``dictionary`` set — supports ordering/range ops) or hash codes
    (``hash_pool`` set, data is [cap, 2] = (hash64, source_row_id) —
    equality-only, for high-NDV columns where a sorted dictionary build
    is the startup cliff)."""

    type: T.DataType
    data: jnp.ndarray
    valid: jnp.ndarray | None = None  # None => all valid
    dictionary: StringDictionary | None = None
    hash_pool: HashStringPool | None = None
    #: ARRAY/MAP/ROW columns: host variable-width store indexed by the
    #: int32 handle lanes in ``data`` (ArrayPool | MapPool | RowPool —
    #: all expose get()/decode() so downstream code treats them
    #: uniformly)
    array_pool: "ArrayPool | MapPool | RowPool | None" = None

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def with_dictionary(self, d: StringDictionary) -> "Column":
        return replace(self, dictionary=d)

    @staticmethod
    def from_numpy(
        type_: T.DataType,
        values: np.ndarray,
        valid: np.ndarray | None = None,
        capacity: int | None = None,
        dictionary: StringDictionary | None = None,
    ) -> "Column":
        n = len(values)
        cap = capacity or pad_capacity(n)
        if isinstance(type_, (T.ArrayType, T.MapType, T.RowType)):
            if isinstance(type_, T.ArrayType):
                pool, handles = ArrayPool.from_pylists(values, type_.element)
            elif isinstance(type_, T.MapType):
                pool, handles = MapPool.from_pymaps(
                    values, type_.key, type_.value
                )
            else:
                pool, handles = RowPool.from_pytuples(values, type_)
            data = np.zeros(cap, dtype=np.int32)
            data[:n] = handles
            col_valid = None
            nulls = np.asarray(
                [v is None for v in values], dtype=np.bool_
            )
            if valid is not None or nulls.any():
                v = np.zeros(cap, dtype=np.bool_)
                v[:n] = (
                    np.ones(n, dtype=np.bool_) if valid is None
                    else np.asarray(valid, dtype=np.bool_)
                ) & ~nulls
                col_valid = jnp.asarray(v)
            return Column(
                type_, jnp.asarray(data), col_valid, array_pool=pool
            )
        if type_.is_dictionary and dictionary is None:
            dictionary, values = StringDictionary.from_strings(values)
        arr = np.asarray(values)
        if arr.dtype != object and arr.ndim == 2:
            # two-limb decimal columns are [n, 2]
            data = np.zeros((cap, arr.shape[1]), dtype=type_.np_dtype)
        else:
            data = np.zeros(cap, dtype=type_.np_dtype)
        data[:n] = np.asarray(values, dtype=type_.np_dtype)
        col_valid = None
        if valid is not None:
            v = np.zeros(cap, dtype=np.bool_)
            v[:n] = valid
            col_valid = jnp.asarray(v)
        return Column(type_, jnp.asarray(data), col_valid, dictionary)

    def to_numpy(self, sel: np.ndarray | None = None):
        """Materialize to host values (Python-friendly), None for nulls."""
        data = np.asarray(self.data)
        valid = None if self.valid is None else np.asarray(self.valid)
        if sel is not None:
            data = data[sel]
            valid = None if valid is None else valid[sel]
        if self.dictionary is not None:
            out = self.dictionary.decode(data).astype(object)
        elif self.hash_pool is not None:
            out = self.hash_pool.values[data[:, 1]].astype(object)
        elif self.array_pool is not None:
            out = self.array_pool.decode(data)
        elif isinstance(self.type, T.DecimalType):
            out = data  # unscaled; rendering applies the scale
        else:
            out = data
        return out, valid


@dataclass
class Page:
    """A batch of rows: named device columns + a live-row mask.

    ``names`` mirror planner symbols; operators address columns by
    position like the reference's channels
    (MAIN/sql/planner/LocalExecutionPlanner.java layout maps).
    """

    names: list[str]
    columns: list[Column]
    mask: jnp.ndarray  # bool[capacity]; True = live row
    #: host-known live-row count (avoids a device sync when set)
    known_rows: int | None = None
    #: True when live rows occupy positions [0, known_rows) exactly
    packed: bool = False

    def __post_init__(self):
        assert len(self.names) == len(self.columns)

    @property
    def capacity(self) -> int:
        return self.mask.shape[0]

    def column(self, name: str) -> Column:
        return self.columns[self.names.index(name)]

    def num_rows(self) -> int:
        """Live row count (device sync unless already host-known)."""
        if self.known_rows is not None:
            return self.known_rows
        return int(jnp.sum(self.mask))

    @staticmethod
    def from_arrays(
        named: dict[str, tuple[T.DataType, np.ndarray]],
        capacity: int | None = None,
    ) -> "Page":
        lengths = {name: len(v[1]) for name, v in named.items()}
        if len(set(lengths.values())) > 1:
            raise ValueError(f"ragged columns: {lengths}")
        n = next(iter(lengths.values()))
        cap = capacity or pad_capacity(n)
        names, cols = [], []
        for name, (type_, values) in named.items():
            names.append(name)
            cols.append(Column.from_numpy(type_, values, capacity=cap))
        mask = np.zeros(cap, dtype=np.bool_)
        mask[:n] = True
        return Page(names, cols, jnp.asarray(mask))

    def to_pylist(self, extra=None) -> list[tuple]:
        """Materialize live rows on host as python tuples (result fetch).

        One batched device->host transfer for the whole page (the
        serialized-results fetch of the client protocol; batching
        matters when the device link has per-call latency). Packed
        pages with a host-known row count transfer only the live
        prefix — the capacity padding never crosses the link.

        ``extra``: optional device pytree fetched IN THE SAME transfer
        (deferred overflow flags ride along with the result data);
        when given, returns (rows, extra_host)."""
        import jax

        k = self.known_rows if self.packed else None
        if k is not None:
            device_arrays = []
            for c in self.columns:
                device_arrays.append(c.data[:k])
                if c.valid is not None:
                    device_arrays.append(c.valid[:k])
            host, extra_host = jax.device_get((device_arrays, extra))
            sel = np.arange(k)
            i = 0
        else:
            device_arrays = [self.mask]
            for c in self.columns:
                device_arrays.append(c.data)
                if c.valid is not None:
                    device_arrays.append(c.valid)
            host, extra_host = jax.device_get((device_arrays, extra))
            mask = host[0]
            sel = np.nonzero(mask)[0]
            i = 1
        cols = []
        for c in self.columns:
            data = host[i]
            i += 1
            valid = None
            if c.valid is not None:
                valid = host[i][sel]
                i += 1
            data = data[sel]
            if c.dictionary is not None:
                data = c.dictionary.decode(data).astype(object)
            elif c.hash_pool is not None:
                data = c.hash_pool.values[data[:, 1]].astype(object)
            elif c.array_pool is not None:
                data = c.array_pool.decode(data)
            vals = [
                None if (valid is not None and not valid[j]) else _pyvalue(c.type, data[j])
                for j in range(len(sel))
            ]
            cols.append(vals)
        rows = [tuple(col[i] for col in cols) for i in range(len(sel))]
        if extra is not None:
            return rows, extra_host
        return rows


def _pyvalue(type_: T.DataType, v):
    if isinstance(type_, T.ArrayType):
        return [_pyvalue(type_.element, x) for x in v]
    if isinstance(type_, T.MapType):
        return {
            _pyvalue(type_.key, k): _pyvalue(type_.value, x)
            for k, x in v.items()
        }
    if isinstance(type_, T.RowType):
        return tuple(
            None if x is None else _pyvalue(ft, x)
            for (_fn, ft), x in zip(type_.fields, v)
        )
    if isinstance(type_, T.BooleanType):
        return bool(v)
    if isinstance(type_, T.DecimalType):
        # render as exact scaled decimal string -> Fraction-free float is
        # lossy; expose as python int unscaled? Tests want comparable
        # values, so render as a scaled decimal using integer math.
        import decimal

        if type_.is_long:
            # two-limb reconstruction in python ints: exact
            unscaled = int(v[0]) * (1 << 32) + int(v[1])
            return decimal.Decimal(unscaled).scaleb(-type_.scale)
        return decimal.Decimal(int(v)).scaleb(-type_.scale)
    if isinstance(type_, T.DateType):
        return T.format_date(int(v))
    if isinstance(type_, T.TimestampType):
        return T.format_timestamp(int(v))
    if isinstance(type_, (T.DoubleType, T.RealType)):
        return float(v)
    if isinstance(type_, (T.VarcharType,)):
        return str(v)
    if isinstance(type_, T.IntegerKind):
        return int(v)
    return v


def unify_dictionaries(a: Column, b: Column) -> tuple[Column, Column]:
    """Remap two VARCHAR columns onto one shared sorted dictionary.

    Host computes the merged dictionary; the code remap itself is a
    device-side gather.
    """
    if a.dictionary is None or b.dictionary is None:
        raise ValueError("both columns must be dictionary-encoded")
    if a.dictionary is b.dictionary:
        return a, b
    merged, ra, rb = a.dictionary.union(b.dictionary)
    return _remap(a, ra, merged), _remap(b, rb, merged)


def _remap(col: Column, remap: np.ndarray, merged: StringDictionary) -> Column:
    if len(remap) == 0:
        # empty dictionary: no live codes exist; keep data as-is
        return replace(col, dictionary=merged)
    return replace(
        col, data=jnp.take(jnp.asarray(remap), col.data, mode="clip"), dictionary=merged
    )
