"""TPC-H connector: schemas tiny/sf1/sf10/sf100 of generated tables."""

from __future__ import annotations

import json
import os

import numpy as np

from trino_tpu.connectors.base import (
    ColumnStats,
    Connector,
    Split,
    TableSchema,
    TableStats,
    compute_column_stats,
)
from trino_tpu.connectors.tpch.generator import SCHEMAS, SCHEMA_SF, TpchData

__all__ = ["TpchConnector"]


class TpchConnector(Connector):
    def __init__(self):
        self._data: dict[float, TpchData] = {}
        self._stats: dict[tuple[float, str], dict[str, ColumnStats]] = {}

    def data(self, schema: str) -> TpchData:
        sf = self._sf(schema)
        if sf not in self._data:
            self._data[sf] = TpchData(sf)
        return self._data[sf]

    @staticmethod
    def _sf(schema: str) -> float:
        if schema in SCHEMA_SF:
            return SCHEMA_SF[schema]
        if schema.startswith("sf"):
            try:
                return float(schema[2:])
            except ValueError:
                pass
        raise KeyError(f"unknown tpch schema: {schema}")

    def list_schemas(self) -> list[str]:
        return list(SCHEMA_SF)

    def list_tables(self, schema: str) -> list[str]:
        return list(SCHEMAS)

    def table_schema(self, schema: str, table: str) -> TableSchema:
        return SCHEMAS[table]

    def row_count(self, schema: str, table: str) -> int:
        return self.data(schema).row_count(table)

    def column_stats(self, schema: str, table: str, column: str) -> ColumnStats:
        """Exact per-column stats (the reference tpch connector ships
        column statistics the same way, plugin/trino-tpch
        TpchMetadata.getTableStatistics), computed LAZILY per column so
        planning a query never materializes columns it doesn't touch
        (generating SF100 comment text just for stats would take
        minutes). Disk-cached incrementally beside the data cache; the
        generated data is deterministic per (sf, table), so the cache
        never goes stale."""
        sf = self._sf(schema)
        key = (sf, table)
        cols = self._stats.setdefault(key, {})
        if column in cols:
            return cols[column]
        data = self.data(schema)
        path = data.stats_path(table)
        if not cols and path is not None and os.path.exists(path):
            with open(path) as f:
                raw = json.load(f)
            # older cache files stored integer bounds as floats; value-
            # range packing requires exact ints (2^53 rounding), so
            # coerce integral floats back (exact below 2^53)
            for v in raw.values():
                for b in ("lo", "hi"):
                    x = v.get(b)
                    if (
                        isinstance(x, float) and x.is_integer()
                        and abs(x) < 2**53
                    ):
                        v[b] = int(x)
            cols.update({c: ColumnStats(**v) for c, v in raw.items()})
            if column in cols:
                return cols[column]
        cols[column] = compute_column_stats(data.column(table, column))
        if path is not None:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({c: vars(s) for c, s in cols.items()}, f)
            os.replace(tmp, path)
        return cols[column]

    def table_stats(self, schema: str, table: str) -> TableStats:
        """Full-table stats: forces every column (tests use this; the
        planner prefers column_stats)."""
        n = self.data(schema).row_count(table)
        cols = {
            c: self.column_stats(schema, table, c)
            for c in SCHEMAS[table].column_names
        }
        return TableStats(float(n), cols)

    def scan(
        self, schema: str, table: str, columns: list[str], split: Split | None = None
    ) -> dict[str, np.ndarray]:
        data = self.data(schema)
        out = {}
        for c in columns:
            arr = data.column(table, c)
            if split is not None:
                arr = arr[split.start : split.start + split.count]
            out[c] = arr
        return out
