"""TPC-H connector: schemas tiny/sf1/sf10/sf100 of generated tables."""

from __future__ import annotations

import numpy as np

from trino_tpu.connectors.base import Connector, Split, TableSchema
from trino_tpu.connectors.tpch.generator import SCHEMAS, SCHEMA_SF, TpchData

__all__ = ["TpchConnector"]


class TpchConnector(Connector):
    def __init__(self):
        self._data: dict[float, TpchData] = {}

    def data(self, schema: str) -> TpchData:
        sf = self._sf(schema)
        if sf not in self._data:
            self._data[sf] = TpchData(sf)
        return self._data[sf]

    @staticmethod
    def _sf(schema: str) -> float:
        if schema in SCHEMA_SF:
            return SCHEMA_SF[schema]
        if schema.startswith("sf"):
            try:
                return float(schema[2:])
            except ValueError:
                pass
        raise KeyError(f"unknown tpch schema: {schema}")

    def list_schemas(self) -> list[str]:
        return list(SCHEMA_SF)

    def list_tables(self, schema: str) -> list[str]:
        return list(SCHEMAS)

    def table_schema(self, schema: str, table: str) -> TableSchema:
        return SCHEMAS[table]

    def row_count(self, schema: str, table: str) -> int:
        return self.data(schema).row_count(table)

    def scan(
        self, schema: str, table: str, columns: list[str], split: Split | None = None
    ) -> dict[str, np.ndarray]:
        data = self.data(schema)
        out = {}
        for c in columns:
            arr = data.column(table, c)
            if split is not None:
                arr = arr[split.start : split.start + split.count]
            out[c] = arr
        return out
