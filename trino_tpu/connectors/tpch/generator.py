"""Deterministic, vectorized TPC-H data generation.

The analog of the reference's in-process TPC-H generator connector
(plugin/trino-tpch/.../TpchConnectorFactory.java:38, backed by the
io.trino.tpch dbgen port). Same schema, same distributions and
structural rules (sparse customer keys, per-order line counts,
retail-price formula, return-flag/status date logic), generated as
numpy columns so a scan at any scale factor is a vectorized array
computation, not a row loop.

Not yet bit-identical to dbgen's RNG streams — correctness tests load
*this* data into sqlite so engine results are checked against golden
results over identical inputs. Columns are generated on demand and
cached, so projection pushdown avoids materializing unused text.
"""

from __future__ import annotations

import os
import zlib

import numpy as np

from trino_tpu import types as T
from trino_tpu.connectors.base import TableSchema
from trino_tpu.connectors.tpch import text
from trino_tpu.types import parse_date

__all__ = ["TpchData", "SCHEMAS", "SCHEMA_SF"]

CURRENT_DATE = parse_date("1995-06-17")
MIN_ORDER_DATE = parse_date("1992-01-01")
MAX_ORDER_DATE = parse_date("1998-08-02")

D152 = T.DecimalType(15, 2)

#: canonical TPC-H column prefixes per table
PREFIX = {
    "region": "r_", "nation": "n_", "supplier": "s_", "customer": "c_",
    "part": "p_", "partsupp": "ps_", "orders": "o_", "lineitem": "l_",
}

_BASE_COLUMNS: dict[str, list[tuple[str, T.DataType]]] = {
    "region": [
        ("regionkey", T.BIGINT), ("name", T.VARCHAR), ("comment", T.VARCHAR)],
    "nation": [
        ("nationkey", T.BIGINT), ("name", T.VARCHAR), ("regionkey", T.BIGINT),
        ("comment", T.VARCHAR)],
    "supplier": [
        ("suppkey", T.BIGINT), ("name", T.VARCHAR), ("address", T.VARCHAR),
        ("nationkey", T.BIGINT), ("phone", T.VARCHAR), ("acctbal", D152),
        ("comment", T.VARCHAR)],
    "customer": [
        ("custkey", T.BIGINT), ("name", T.VARCHAR), ("address", T.VARCHAR),
        ("nationkey", T.BIGINT), ("phone", T.VARCHAR), ("acctbal", D152),
        ("mktsegment", T.VARCHAR), ("comment", T.VARCHAR)],
    "part": [
        ("partkey", T.BIGINT), ("name", T.VARCHAR), ("mfgr", T.VARCHAR),
        ("brand", T.VARCHAR), ("type", T.VARCHAR), ("size", T.INTEGER),
        ("container", T.VARCHAR), ("retailprice", D152), ("comment", T.VARCHAR)],
    "partsupp": [
        ("partkey", T.BIGINT), ("suppkey", T.BIGINT), ("availqty", T.INTEGER),
        ("supplycost", D152), ("comment", T.VARCHAR)],
    "orders": [
        ("orderkey", T.BIGINT), ("custkey", T.BIGINT), ("orderstatus", T.VARCHAR),
        ("totalprice", D152), ("orderdate", T.DATE), ("orderpriority", T.VARCHAR),
        ("clerk", T.VARCHAR), ("shippriority", T.INTEGER), ("comment", T.VARCHAR)],
    "lineitem": [
        ("orderkey", T.BIGINT), ("partkey", T.BIGINT), ("suppkey", T.BIGINT),
        ("linenumber", T.INTEGER), ("quantity", D152), ("extendedprice", D152),
        ("discount", D152), ("tax", D152), ("returnflag", T.VARCHAR),
        ("linestatus", T.VARCHAR), ("shipdate", T.DATE), ("commitdate", T.DATE),
        ("receiptdate", T.DATE), ("shipinstruct", T.VARCHAR),
        ("shipmode", T.VARCHAR), ("comment", T.VARCHAR)],
}

#: external schemas use the canonical prefixed names (l_orderkey, ...)
SCHEMAS: dict[str, TableSchema] = {
    t: TableSchema(t, [(PREFIX[t] + c, ty) for c, ty in cols])
    for t, cols in _BASE_COLUMNS.items()
}

#: named schema -> scale factor, mirroring the reference's tpch schemas
SCHEMA_SF = {"tiny": 0.01, "sf1": 1.0, "sf10": 10.0, "sf100": 100.0}


def _seed(sf: float, table: str, stream: str) -> list[int]:
    return [zlib.crc32(table.encode()), zlib.crc32(stream.encode()), int(sf * 1000)]


class TpchData:
    """All eight TPC-H tables at one scale factor, columns on demand."""

    def __init__(self, sf: float):
        self.sf = sf
        self._cache: dict[tuple[str, str], np.ndarray] = {}

    # ---- row counts ------------------------------------------------------
    @property
    def n_supplier(self) -> int:
        return max(1, round(10_000 * self.sf))

    @property
    def n_customer(self) -> int:
        return max(1, round(150_000 * self.sf))

    @property
    def n_part(self) -> int:
        return max(1, round(200_000 * self.sf))

    @property
    def n_orders(self) -> int:
        return max(1, round(1_500_000 * self.sf))

    @property
    def n_partsupp(self) -> int:
        return 4 * self.n_part

    def row_count(self, table: str) -> int:
        if table == "lineitem":
            return self.n_lineitem  # from per-order counts, no column materialization
        return {
            "region": 5,
            "nation": 25,
            "supplier": self.n_supplier,
            "customer": self.n_customer,
            "part": self.n_part,
            "partsupp": self.n_partsupp,
            "orders": self.n_orders,
        }[table]

    def _rng(self, table: str, stream: str) -> np.random.Generator:
        return np.random.default_rng(_seed(self.sf, table, stream))

    # ---- public API ------------------------------------------------------
    def column(self, table: str, name: str) -> np.ndarray:
        # accept both canonical prefixed (l_orderkey) and bare names
        prefix = PREFIX.get(table, "")
        if prefix and name.startswith(prefix):
            name = name[len(prefix):]
        key = (table, name)
        if key not in self._cache:
            arr = self._disk_load(table, name)
            if arr is None:
                gen = getattr(self, f"_{table}_{name}", None)
                if gen is None:
                    raise KeyError(f"no column {table}.{name}")
                arr = gen()
                self._disk_store(table, name, arr)
            arr.setflags(write=False)  # cached arrays are shared with scans
            self._cache[key] = arr
        return self._cache[key]

    # Generated columns are deterministic functions of (sf, table,
    # column), so a host disk cache is safe and makes a fresh process
    # at SF>=1 start in seconds instead of minutes (the reference pays
    # the same cost once per JVM via its in-process tpch generator).
    def _disk_path(self, table: str, name: str) -> str | None:
        root = os.environ.get(
            "TRINO_TPU_DATA_CACHE",
            os.path.join(
                os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))
                ))),
                ".tpch_cache",
            ),
        )
        if root == "off" or self.sf < 0.5:
            return None
        return os.path.join(root, f"sf{self.sf:g}_{table}_{name}.npy")

    def stats_path(self, table: str) -> str | None:
        """Disk-cache path for the table's column stats (JSON)."""
        p = self._disk_path(table, "stats")
        return None if p is None else p[:-4] + ".json"

    def _disk_load(self, table: str, name: str) -> np.ndarray | None:
        path = self._disk_path(table, name)
        if path is None or not os.path.exists(path):
            return None
        # never unpickle: the cache dir is overridable/shared, and
        # pickled .npy files are an arbitrary-code-execution surface.
        # Strings are stored as fixed-width unicode (see _disk_store).
        try:
            arr = np.load(path, allow_pickle=False)
        except ValueError:  # legacy pickled file: regenerate instead
            return None
        if arr.dtype.kind == "U":
            arr = arr.astype(object)
        return arr

    def _disk_store(self, table: str, name: str, arr: np.ndarray) -> None:
        path = self._disk_path(table, name)
        if path is None:
            return
        os.makedirs(os.path.dirname(path), exist_ok=True)
        if arr.dtype == object:  # varchar columns: pickle-free layout
            arr = arr.astype("U")
        # np.save appends .npy unless the name already ends with it
        tmp = f"{path[:-4]}.tmp{os.getpid()}.npy"
        np.save(tmp, arr, allow_pickle=False)
        os.replace(tmp, path)

    def table(self, table: str) -> dict[str, np.ndarray]:
        return {c: self.column(table, c) for c in SCHEMAS[table].column_names}

    # ---- helpers ---------------------------------------------------------
    def _words(self, rng, n, k, vocab=text.COMMENT_WORDS) -> np.ndarray:
        """n comments of k words each, vectorized."""
        vocab_arr = np.asarray(vocab)
        idx = rng.integers(0, len(vocab_arr), size=(n, k))
        parts = vocab_arr[idx]
        out = parts[:, 0]
        for j in range(1, k):
            out = np.char.add(np.char.add(out, " "), parts[:, j])
        return out.astype(object)

    @staticmethod
    def _numbered(prefix: str, keys: np.ndarray, width: int = 9) -> np.ndarray:
        return np.array([f"{prefix}#{k:0{width}d}" for k in keys], dtype=object)

    @staticmethod
    def _phone(nationkeys: np.ndarray, keys: np.ndarray) -> np.ndarray:
        cc = nationkeys + 10
        a = (keys * 31 + 7) % 900 + 100
        b = (keys * 17 + 3) % 900 + 100
        c = (keys * 13 + 11) % 9000 + 1000
        return np.array(
            [f"{w}-{x}-{y}-{z}" for w, x, y, z in zip(cc, a, b, c)], dtype=object
        )

    def _acctbal(self, table: str) -> np.ndarray:
        n = {"supplier": self.n_supplier, "customer": self.n_customer}[table]
        rng = self._rng(table, "acctbal")
        return rng.integers(-99_999, 999_999, size=n, dtype=np.int64)  # cents

    # ---- region / nation -------------------------------------------------
    def _region_regionkey(self):
        return np.arange(5, dtype=np.int64)

    def _region_name(self):
        return np.asarray(text.REGIONS, dtype=object)

    def _region_comment(self):
        return self._words(self._rng("region", "comment"), 5, 6)

    def _nation_nationkey(self):
        return np.arange(25, dtype=np.int64)

    def _nation_name(self):
        return np.asarray([n for n, _ in text.NATIONS], dtype=object)

    def _nation_regionkey(self):
        return np.asarray([r for _, r in text.NATIONS], dtype=np.int64)

    def _nation_comment(self):
        return self._words(self._rng("nation", "comment"), 25, 8)

    # ---- supplier --------------------------------------------------------
    def _supplier_suppkey(self):
        return np.arange(1, self.n_supplier + 1, dtype=np.int64)

    def _supplier_name(self):
        return self._numbered("Supplier", self.column("supplier", "suppkey"))

    def _supplier_address(self):
        return self._words(self._rng("supplier", "address"), self.n_supplier, 3)

    def _supplier_nationkey(self):
        return self._rng("supplier", "nation").integers(
            0, 25, size=self.n_supplier, dtype=np.int64
        )

    def _supplier_phone(self):
        return self._phone(
            self.column("supplier", "nationkey"), self.column("supplier", "suppkey")
        )

    def _supplier_acctbal(self):
        return self._acctbal("supplier")

    def _supplier_comment(self):
        # spec: ~5 in 10k get "Customer ... Complaints" (q16 anti-filter)
        out = self._words(self._rng("supplier", "comment"), self.n_supplier, 9)
        rng = self._rng("supplier", "complaints")
        hits = rng.integers(0, self.n_supplier, size=max(1, self.n_supplier // 2000))
        for i in hits:
            out[i] = out[i] + " Customer extra care Complaints"
        return out

    # ---- customer --------------------------------------------------------
    def _customer_custkey(self):
        return np.arange(1, self.n_customer + 1, dtype=np.int64)

    def _customer_name(self):
        return self._numbered("Customer", self.column("customer", "custkey"))

    def _customer_address(self):
        return self._words(self._rng("customer", "address"), self.n_customer, 3)

    def _customer_nationkey(self):
        return self._rng("customer", "nation").integers(
            0, 25, size=self.n_customer, dtype=np.int64
        )

    def _customer_phone(self):
        return self._phone(
            self.column("customer", "nationkey"), self.column("customer", "custkey")
        )

    def _customer_acctbal(self):
        return self._acctbal("customer")

    def _customer_mktsegment(self):
        idx = self._rng("customer", "segment").integers(
            0, len(text.SEGMENTS), size=self.n_customer
        )
        return np.asarray(text.SEGMENTS, dtype=object)[idx]

    def _customer_comment(self):
        return self._words(self._rng("customer", "comment"), self.n_customer, 8)

    # ---- part ------------------------------------------------------------
    def _part_partkey(self):
        return np.arange(1, self.n_part + 1, dtype=np.int64)

    def _part_name(self):
        rng = self._rng("part", "name")
        return self._words(rng, self.n_part, 5, vocab=text.PART_NAME_WORDS)

    def _part_mfgr(self):
        m = self._mfgr_num()
        return np.array([f"Manufacturer#{v}" for v in m], dtype=object)

    def _mfgr_num(self):
        return self._rng("part", "mfgr").integers(1, 6, size=self.n_part)

    def _part_brand(self):
        m = self._mfgr_num()
        n = self._rng("part", "brand").integers(1, 6, size=self.n_part)
        return np.array([f"Brand#{a}{b}" for a, b in zip(m, n)], dtype=object)

    def _part_type(self):
        rng = self._rng("part", "type")
        i1 = rng.integers(0, len(text.TYPE_SYLLABLE_1), size=self.n_part)
        i2 = rng.integers(0, len(text.TYPE_SYLLABLE_2), size=self.n_part)
        i3 = rng.integers(0, len(text.TYPE_SYLLABLE_3), size=self.n_part)
        s1 = np.asarray(text.TYPE_SYLLABLE_1, dtype=object)[i1]
        s2 = np.asarray(text.TYPE_SYLLABLE_2, dtype=object)[i2]
        s3 = np.asarray(text.TYPE_SYLLABLE_3, dtype=object)[i3]
        return s1 + " " + s2 + " " + s3

    def _part_size(self):
        return self._rng("part", "size").integers(
            1, 51, size=self.n_part, dtype=np.int32
        )

    def _part_container(self):
        rng = self._rng("part", "container")
        i1 = rng.integers(0, len(text.CONTAINER_SYLLABLE_1), size=self.n_part)
        i2 = rng.integers(0, len(text.CONTAINER_SYLLABLE_2), size=self.n_part)
        s1 = np.asarray(text.CONTAINER_SYLLABLE_1, dtype=object)[i1]
        s2 = np.asarray(text.CONTAINER_SYLLABLE_2, dtype=object)[i2]
        return s1 + " " + s2

    @staticmethod
    def _retail_cents(pk: np.ndarray) -> np.ndarray:
        # spec formula, in cents: 90000 + ((pk/10) mod 20001) + 100*(pk mod 1000)
        return 90_000 + (pk // 10) % 20_001 + 100 * (pk % 1_000)

    def _part_retailprice(self):
        return self._retail_cents(self.column("part", "partkey"))

    def _part_comment(self):
        return self._words(self._rng("part", "comment"), self.n_part, 4)

    # ---- partsupp --------------------------------------------------------
    def _partsupp_partkey(self):
        return np.repeat(self.column("part", "partkey"), 4)

    def _partsupp_suppkey(self):
        # spec: supplier j of part pk is
        # (pk + j*(S/4 + (pk-1)/S)) mod S + 1   — spreads the 4 suppliers
        pk = self.column("partsupp", "partkey")
        j = np.tile(np.arange(4, dtype=np.int64), self.n_part)
        s = self.n_supplier
        return (pk + j * (s // 4 + (pk - 1) // s)) % s + 1

    def _partsupp_availqty(self):
        return self._rng("partsupp", "availqty").integers(
            1, 10_000, size=self.n_partsupp, dtype=np.int32
        )

    def _partsupp_supplycost(self):
        return self._rng("partsupp", "supplycost").integers(
            100, 100_001, size=self.n_partsupp, dtype=np.int64
        )

    def _partsupp_comment(self):
        return self._words(self._rng("partsupp", "comment"), self.n_partsupp, 10)

    # ---- orders ----------------------------------------------------------
    def _orders_orderkey(self):
        # spec: order keys are sparse — 8 used out of every 32
        i = np.arange(self.n_orders, dtype=np.int64)
        return (i // 8) * 32 + (i % 8) + 1

    def _orders_custkey(self):
        # spec: only customers with custkey % 3 != 0 place orders
        rng = self._rng("orders", "custkey")
        raw = rng.integers(1, self.n_customer + 1, size=self.n_orders, dtype=np.int64)
        raw[raw % 3 == 0] += 1
        raw[raw > self.n_customer] = 1
        return raw

    def _orders_orderdate(self):
        rng = self._rng("orders", "orderdate")
        return rng.integers(
            MIN_ORDER_DATE, MAX_ORDER_DATE + 1, size=self.n_orders, dtype=np.int32
        )

    def _orders_orderpriority(self):
        idx = self._rng("orders", "priority").integers(
            0, len(text.PRIORITIES), size=self.n_orders
        )
        return np.asarray(text.PRIORITIES, dtype=object)[idx]

    def _orders_clerk(self):
        n_clerks = max(1, int(1000 * self.sf))
        c = self._rng("orders", "clerk").integers(1, n_clerks + 1, size=self.n_orders)
        return self._numbered("Clerk", c)

    def _orders_shippriority(self):
        return np.zeros(self.n_orders, dtype=np.int32)

    def _orders_comment(self):
        return self._words(self._rng("orders", "comment"), self.n_orders, 6)

    def _orders_totalprice(self):
        ok = self.column("lineitem", "orderkey")
        ext = self.column("lineitem", "extendedprice")
        disc = self.column("lineitem", "discount")
        tax = self.column("lineitem", "tax")
        line = ext * (100 - disc) * (100 + tax) // 10_000
        # lineitem rows are grouped by order in generation order
        counts = self._line_counts()
        ends = np.cumsum(line)
        idx = np.cumsum(counts) - 1
        totals = ends[idx]
        totals[1:] -= ends[idx[:-1]]
        return totals

    def _orders_orderstatus(self):
        status = self.column("lineitem", "linestatus")
        counts = self._line_counts()
        is_f = (status == "F").astype(np.int64)
        ends = np.cumsum(is_f)
        idx = np.cumsum(counts) - 1
        f_per_order = ends[idx].copy()
        f_per_order[1:] -= ends[idx[:-1]]
        out = np.full(self.n_orders, "P", dtype=object)
        out[f_per_order == counts] = "F"
        out[f_per_order == 0] = "O"
        return out

    # ---- lineitem --------------------------------------------------------
    def _line_counts(self) -> np.ndarray:
        key = ("lineitem", "__counts__")
        if key not in self._cache:
            rng = self._rng("lineitem", "counts")
            self._cache[key] = rng.integers(
                1, 8, size=self.n_orders, dtype=np.int64
            )
        return self._cache[key]

    @property
    def n_lineitem(self) -> int:
        return int(self._line_counts().sum())

    def _lineitem_orderkey(self):
        return np.repeat(self.column("orders", "orderkey"), self._line_counts())

    def _lineitem_linenumber(self):
        counts = self._line_counts()
        total = counts.sum()
        starts = np.repeat(np.cumsum(counts) - counts, counts)
        return (np.arange(total) - starts + 1).astype(np.int32)

    def _lineitem_partkey(self):
        rng = self._rng("lineitem", "partkey")
        return rng.integers(1, self.n_part + 1, size=self.n_lineitem, dtype=np.int64)

    def _lineitem_suppkey(self):
        pk = self.column("lineitem", "partkey")
        j = self._rng("lineitem", "suppsel").integers(
            0, 4, size=self.n_lineitem, dtype=np.int64
        )
        s = self.n_supplier
        return (pk + j * (s // 4 + (pk - 1) // s)) % s + 1

    def _lineitem_quantity(self):
        q = self._rng("lineitem", "quantity").integers(
            1, 51, size=self.n_lineitem, dtype=np.int64
        )
        return q * 100  # decimal(15,2) cents

    def _lineitem_extendedprice(self):
        # spec: extendedprice = quantity * part.retailprice
        retail = self._retail_cents(self.column("lineitem", "partkey"))
        qty = self.column("lineitem", "quantity") // 100
        return qty * retail

    def _lineitem_discount(self):
        return self._rng("lineitem", "discount").integers(
            0, 11, size=self.n_lineitem, dtype=np.int64
        )

    def _lineitem_tax(self):
        return self._rng("lineitem", "tax").integers(
            0, 9, size=self.n_lineitem, dtype=np.int64
        )

    def _lineitem_shipdate(self):
        od = np.repeat(self.column("orders", "orderdate"), self._line_counts())
        d = self._rng("lineitem", "shipdate").integers(
            1, 122, size=self.n_lineitem, dtype=np.int32
        )
        return (od + d).astype(np.int32)

    def _lineitem_commitdate(self):
        od = np.repeat(self.column("orders", "orderdate"), self._line_counts())
        d = self._rng("lineitem", "commitdate").integers(
            30, 91, size=self.n_lineitem, dtype=np.int32
        )
        return (od + d).astype(np.int32)

    def _lineitem_receiptdate(self):
        sd = self.column("lineitem", "shipdate")
        d = self._rng("lineitem", "receiptdate").integers(
            1, 31, size=self.n_lineitem, dtype=np.int32
        )
        return (sd + d).astype(np.int32)

    def _lineitem_returnflag(self):
        rd = self.column("lineitem", "receiptdate")
        coin = self._rng("lineitem", "returnflag").integers(0, 2, size=self.n_lineitem)
        out = np.full(self.n_lineitem, "N", dtype=object)
        returned = rd <= CURRENT_DATE
        out[returned & (coin == 0)] = "R"
        out[returned & (coin == 1)] = "A"
        return out

    def _lineitem_linestatus(self):
        sd = self.column("lineitem", "shipdate")
        out = np.full(self.n_lineitem, "O", dtype=object)
        out[sd <= CURRENT_DATE] = "F"
        return out

    def _lineitem_shipinstruct(self):
        idx = self._rng("lineitem", "shipinstruct").integers(
            0, len(text.SHIP_INSTRUCTIONS), size=self.n_lineitem
        )
        return np.asarray(text.SHIP_INSTRUCTIONS, dtype=object)[idx]

    def _lineitem_shipmode(self):
        idx = self._rng("lineitem", "shipmode").integers(
            0, len(text.SHIP_MODES), size=self.n_lineitem
        )
        return np.asarray(text.SHIP_MODES, dtype=object)[idx]

    def _lineitem_comment(self):
        return self._words(self._rng("lineitem", "comment"), self.n_lineitem, 4)
