"""TPC-H text pools: the fixed vocabularies the spec draws from.

These are the public TPC-H spec word lists (the reference embeds the
same lists via the io.trino.tpch generator library used by
plugin/trino-tpch/.../TpchConnectorFactory.java:38).
"""

NATIONS = [
    # (name, regionkey)
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]

REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]

PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]

SHIP_INSTRUCTIONS = ["DELIVER IN PERSON", "COLLECT COD", "NONE", "TAKE BACK RETURN"]

SHIP_MODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]

PART_NAME_WORDS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished", "chartreuse",
    "chiffon", "chocolate", "coral", "cornflower", "cornsilk", "cream", "cyan",
    "dark", "deep", "dim", "dodger", "drab", "firebrick", "floral", "forest",
    "frosted", "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
    "hot", "indian", "ivory", "khaki", "lace", "lavender", "lawn", "lemon",
    "light", "lime", "linen", "magenta", "maroon", "medium", "metallic", "midnight",
    "mint", "misty", "moccasin", "navajo", "navy", "olive", "orange", "orchid",
    "pale", "papaya", "peach", "peru", "pink", "plum", "powder", "puff",
    "purple", "red", "rose", "rosy", "royal", "saddle", "salmon", "sandy",
    "seashell", "sienna", "sky", "slate", "smoke", "snow", "spring", "steel",
    "tan", "thistle", "tomato", "turquoise", "violet", "wheat", "white", "yellow",
]

TYPE_SYLLABLE_1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
TYPE_SYLLABLE_2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
TYPE_SYLLABLE_3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]

CONTAINER_SYLLABLE_1 = ["SM", "LG", "MED", "JUMBO", "WRAP"]
CONTAINER_SYLLABLE_2 = ["CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN", "DRUM"]

# Vocabulary for comment text. Includes every word the TPC-H query
# predicates grep for (special/requests q13, Customer/Complaints q22 …)
# so LIKE filters stay selective-but-nonempty on generated data.
COMMENT_WORDS = [
    "final", "furious", "sly", "careful", "express", "bold", "regular",
    "special", "pending", "ironic", "even", "silent", "unusual", "blithe",
    "quick", "daring", "dogged", "stealthy", "deposits", "requests",
    "accounts", "packages", "instructions", "foxes", "pinto", "beans",
    "theodolites", "dependencies", "excuses", "platelets", "asymptotes",
    "courts", "dolphins", "multipliers", "sauternes", "warthogs",
    "frets", "dinos", "attainments", "somas", "Tiresias", "nag",
    "sleep", "wake", "haggle", "cajole", "detect", "integrate", "solve",
    "Customer", "Complaints", "Recommends", "among", "carefully",
    "quickly", "slyly", "furiously", "above", "under", "about",
]
