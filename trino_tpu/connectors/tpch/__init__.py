from trino_tpu.connectors.tpch.connector import TpchConnector

__all__ = ["TpchConnector"]
