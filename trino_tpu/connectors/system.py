"""System connector: engine state as queryable tables.

The analog of the reference's internal system connector
(MAIN/connector/system/: system.runtime.nodes / queries / tasks):
``system.runtime.queries`` exposes the coordinator's query tracker and
``system.runtime.nodes`` the mesh topology, so operators can introspect
the engine with plain SQL.
"""

from __future__ import annotations

import time

import numpy as np

from trino_tpu import types as T
from trino_tpu.connectors.base import Connector, Split, TableSchema

__all__ = ["SystemConnector"]

_QUERIES_SCHEMA = TableSchema("queries", [
    ("query_id", T.VARCHAR),
    ("state", T.VARCHAR),
    ("query", T.VARCHAR),
    ("error", T.VARCHAR),
    ("elapsed_ms", T.DOUBLE),
    ("rows", T.BIGINT),
    ("user", T.VARCHAR),
    ("peak_memory_bytes", T.BIGINT),
    ("resource_group", T.VARCHAR),
    ("queued_time_ms", T.DOUBLE),
    ("recovered", T.BOOLEAN),
])

_NODES_SCHEMA = TableSchema("nodes", [
    ("node_id", T.VARCHAR),
    ("kind", T.VARCHAR),
    ("state", T.VARCHAR),
    ("heartbeat_age_s", T.DOUBLE),
])

#: per-task runtime stats (system.runtime.tasks analog,
#: MAIN/connector/system/TaskSystemTable.java): rows come straight
#: from QueryResult.task_stats, the same dicts EXPLAIN ANALYZE and
#: QueryResult.stage_stats aggregate — the three views always agree
_TASKS_SCHEMA = TableSchema("tasks", [
    ("query_id", T.VARCHAR),
    ("stage_id", T.VARCHAR),
    ("task_id", T.VARCHAR),
    ("attempt", T.BIGINT),
    ("state", T.VARCHAR),
    ("worker", T.VARCHAR),
    ("rows_in", T.BIGINT),
    ("rows_out", T.BIGINT),
    ("bytes_out", T.BIGINT),
    ("elapsed_ms", T.DOUBLE),
    ("peak_memory_bytes", T.BIGINT),
    ("admission_wait_ms", T.DOUBLE),
])

#: live memory-governance state (system.runtime "memory" view — the
#: reference exposes the same via MemoryResource / JMX memory pools):
#: one row per (node, query) reservation plus the pool line per node
_MEMORY_SCHEMA = TableSchema("memory", [
    ("node_id", T.VARCHAR),
    ("query_id", T.VARCHAR),
    ("reserved_bytes", T.BIGINT),
    ("peak_bytes", T.BIGINT),
    ("pool_reserved_bytes", T.BIGINT),
    ("pool_peak_bytes", T.BIGINT),
    ("pool_limit_bytes", T.BIGINT),
])


#: cluster time-series (flight-recorder view): one row per (sample
#: timestamp, node, metric series) from the coordinator's background
#: recorder ring; empty when time-series recording is disabled
_CLUSTER_METRICS_SCHEMA = TableSchema("cluster_metrics", [
    ("sample_ts", T.DOUBLE),
    ("node", T.VARCHAR),
    ("metric", T.VARCHAR),
    ("value", T.DOUBLE),
])


#: compiled-program catalog (kernel observatory): one row per XLA
#: program the process compiled or deserialized — XLA's cost model
#: (FLOPs / bytes) and HBM footprint (memory_analysis) per canonical
#: bucket, queryable with plain SQL
_PROGRAMS_SCHEMA = TableSchema("programs", [
    ("program_id", T.VARCHAR),
    ("source", T.VARCHAR),
    ("operators", T.VARCHAR),
    ("hits", T.BIGINT),
    ("compile_ms", T.DOUBLE),
    ("flops", T.DOUBLE),
    ("bytes_accessed", T.DOUBLE),
    ("argument_bytes", T.BIGINT),
    ("output_bytes", T.BIGINT),
    ("temp_bytes", T.BIGINT),
    ("generated_code_bytes", T.BIGINT),
    ("hlo_hash", T.VARCHAR),
    ("hlo_scopes", T.BIGINT),
])


#: cross-query cache subsystem state (cache.py + exec/scan_cache.py):
#: one row per tier — resident entries/bytes, configured bound, and
#: lifetime hit/miss/eviction counters
_CACHES_SCHEMA = TableSchema("caches", [
    ("tier", T.VARCHAR),
    ("entries", T.BIGINT),
    ("bytes", T.BIGINT),
    ("max_bytes", T.BIGINT),
    ("hits", T.BIGINT),
    ("misses", T.BIGINT),
    ("evictions", T.BIGINT),
])


#: durable query history (the performance sentry's record): one row
#: per completed statement the process retained, keyed by the journal
#: plan digest + session-property fingerprint the baselines use
_QUERY_HISTORY_SCHEMA = TableSchema("query_history", [
    ("query_id", T.VARCHAR),
    ("ts", T.DOUBLE),
    ("user", T.VARCHAR),
    ("state", T.VARCHAR),
    ("plan_digest", T.VARCHAR),
    ("fingerprint", T.VARCHAR),
    ("wall_ms", T.DOUBLE),
    ("rows", T.BIGINT),
    ("peak_memory_bytes", T.BIGINT),
    ("compiles", T.BIGINT),
    ("cache_hit_tier", T.VARCHAR),
    ("exchange_skew", T.DOUBLE),
    ("top_bucket", T.VARCHAR),
    ("top_bucket_ms", T.DOUBLE),
    ("critical_path_tail", T.VARCHAR),
])


#: typed AnomalyVerdicts the sentry emitted this process lifetime
_ANOMALIES_SCHEMA = TableSchema("anomalies", [
    ("query_id", T.VARCHAR),
    ("ts", T.DOUBLE),
    ("plan_digest", T.VARCHAR),
    ("fingerprint", T.VARCHAR),
    ("wall_ms", T.DOUBLE),
    ("baseline_p50_ms", T.DOUBLE),
    ("ratio", T.DOUBLE),
    ("driver", T.VARCHAR),
    ("driver_delta_ms", T.DOUBLE),
    ("samples", T.BIGINT),
    ("message", T.VARCHAR),
])


class SystemConnector(Connector):
    """Read-only views over live engine state. ``source`` is the
    owning Coordinator (queries) and/or runner (nodes); either may be
    absent (empty tables)."""

    cacheable = False  # live state: never device-cache these scans

    def __init__(self, coordinator=None, runner=None):
        self.coordinator = coordinator
        self.runner = runner

    def list_schemas(self) -> list[str]:
        return ["runtime"]

    def list_tables(self, schema: str) -> list[str]:
        if schema == "runtime":
            return [
                "queries", "nodes", "memory", "tasks",
                "cluster_metrics", "programs", "caches",
                "query_history", "anomalies",
            ]
        return []

    def table_schema(self, schema: str, table: str) -> TableSchema:
        if schema != "runtime":
            raise KeyError(f"{schema}.{table}")
        if table == "queries":
            return _QUERIES_SCHEMA
        if table == "nodes":
            return _NODES_SCHEMA
        if table == "memory":
            return _MEMORY_SCHEMA
        if table == "tasks":
            return _TASKS_SCHEMA
        if table == "cluster_metrics":
            return _CLUSTER_METRICS_SCHEMA
        if table == "programs":
            return _PROGRAMS_SCHEMA
        if table == "caches":
            return _CACHES_SCHEMA
        if table == "query_history":
            return _QUERY_HISTORY_SCHEMA
        if table == "anomalies":
            return _ANOMALIES_SCHEMA
        raise KeyError(f"{schema}.{table}")

    def _query_rows(self):
        from trino_tpu import tracker

        live = {
            r["query_id"]: r for r in tracker.QUERY_INFO.list()
        }
        out = []
        if self.coordinator is not None:
            # the coordinator sees every statement: it IS the query
            # list; the registry only enriches it (peak memory). The
            # process-global registry may also hold other runners'
            # queries — mixing those in here would double-count.
            with self.coordinator._lock:
                states = list(self.coordinator._queries.values())
            for q in states:
                end = q.finished_at or time.time()
                queued_end = q.started_at or q.finished_at or time.time()
                r = live.get(q.query_id) or {}
                out.append((
                    q.query_id, q.state, q.sql, q.error or "",
                    (end - q.created_at) * 1e3,
                    len(q.result.rows) if q.result is not None else 0,
                    q.user,
                    int(r.get("peak_memory_bytes", 0)),
                    q.resource_group,
                    (queued_end - q.created_at) * 1e3,
                    bool(r.get("recovered")),
                ))
            return out
        # runner-direct statements (no coordinator) come from the
        # live registry — including the one reading this table
        for r in live.values():
            out.append((
                r["query_id"], r["state"], r.get("sql") or "",
                r.get("error") or "",
                float(r.get("elapsed_ms", 0.0)),
                int(r.get("rows") or 0),
                r.get("user") or "",
                int(r.get("peak_memory_bytes", 0)),
                r.get("resource_group") or "",
                float(r.get("queued_time_ms", 0.0)),
                bool(r.get("recovered")),
            ))
        return out

    def _node_rows(self):
        # live membership wins: announced workers carry real lifecycle
        # state and heartbeat age; the mesh-topology synthesis remains
        # the fixed-fleet / embedded fallback
        registry = getattr(self.coordinator, "membership", None)
        members = registry.members() if registry is not None else []
        if members:
            return [("local-0", "coordinator", "ACTIVE", 0.0)] + [
                (
                    m.node_id,
                    "worker",
                    m.state,
                    round(registry.heartbeat_age(m.node_id) or 0.0, 3),
                )
                for m in members
            ]
        runner = self.runner
        if runner is None and self.coordinator is not None:
            runner = self.coordinator.runner
        if runner is None or runner.mesh is None:
            return [("local-0", "coordinator+worker", "ACTIVE", 0.0)]
        return [("local-0", "coordinator", "ACTIVE", 0.0)] + [
            (f"shard-{i}", "worker", "ACTIVE", 0.0)
            for i in range(runner.mesh.devices.size)
        ]

    def _memory_rows(self):
        """One row per (node, query) reservation. The local pool is
        read live; remote workers come from the coordinator's
        ClusterMemoryManager (their latest observed snapshots)."""
        runner = self.runner
        if runner is None and self.coordinator is not None:
            runner = self.coordinator.runner
        snaps = {}
        pool = getattr(
            getattr(runner, "executor", None), "memory_pool", None
        )
        if pool is not None:
            snaps[pool.node_id] = pool.snapshot()
        cmm = getattr(self.coordinator, "cluster_memory", None)
        if cmm is not None:
            for node, snap in cmm.nodes().items():
                snaps.setdefault(node, snap)
        out = []
        for node in sorted(snaps):
            snap = snaps[node]
            pool_row = (
                int(snap.get("reserved_bytes", 0)),
                int(snap.get("peak_bytes", 0)),
                int(snap.get("limit_bytes", 0)),
            )
            queries = snap.get("queries") or {}
            if not queries:
                out.append((node, "", 0, 0) + pool_row)
            for qid in sorted(queries):
                q = queries[qid]
                out.append((
                    node, qid,
                    int(q.get("reserved_bytes", 0)),
                    int(q.get("peak_bytes", 0)),
                ) + pool_row)
        return out

    def _task_rows(self):
        if self.coordinator is None:
            return []
        with self.coordinator._lock:
            states = list(self.coordinator._queries.values())
        out = []
        for q in states:
            for t in getattr(q.result, "task_stats", None) or []:
                out.append((
                    str(t.get("query_id") or q.query_id),
                    str(t.get("stage_id", "")),
                    str(t.get("task_id", "")),
                    int(t.get("attempt", 0)),
                    str(t.get("state", "")),
                    str(t.get("worker", "")),
                    int(t.get("rows_in", 0)),
                    int(t.get("rows_out", 0)),
                    int(t.get("bytes_out", 0)),
                    float(t.get("elapsed_ms", 0.0)),
                    int(t.get("peak_memory_bytes", 0)),
                    float(t.get("admission_wait_ms", 0.0)),
                ))
        return out

    def _cluster_metric_rows(self):
        from trino_tpu import telemetry_analysis

        rec = getattr(self.coordinator, "timeseries", None) or (
            telemetry_analysis.active_recorder()
        )
        if rec is None:
            return []
        return [
            (float(ts), str(node), str(metric), float(value))
            for ts, node, metric, value in rec.rows()
        ]

    def _program_rows(self):
        from trino_tpu import program_catalog

        out = []
        for e in program_catalog.CATALOG.snapshot(resolve=True):
            out.append((
                e["program_id"],
                e["source"],
                e["label"],
                int(e["hits"]),
                float(e["compile_s"]) * 1e3,
                float(e["flops"] or 0.0),
                float(e["bytes_accessed"] or 0.0),
                int(e["argument_bytes"] or 0),
                int(e["output_bytes"] or 0),
                int(e["temp_bytes"] or 0),
                int(e["generated_code_bytes"] or 0),
                e["hlo_hash"] or "",
                int(e["scope_count"]),
            ))
        return out

    def _cache_rows(self):
        from trino_tpu import cache as cache_mod
        from trino_tpu.exec import scan_cache

        res = cache_mod.result_tier_snapshot()
        dev = cache_mod.DEVICE.snapshot()
        pages = scan_cache.SHARED.snapshot()
        return [
            (
                "result", res["entries"], res["bytes"], res["max_bytes"],
                res["hits"], res["misses"], res["evictions"],
            ),
            (
                "device", dev["entries"], dev["bytes"], dev["max_bytes"],
                dev["hits"], dev["misses"], dev["evictions"],
            ),
            (
                "scan_pages", pages["entries"], pages["bytes"], 0,
                0, 0, 0,
            ),
            (
                "split_batches", len(scan_cache.SHARED_SPLITS),
                scan_cache.SHARED_SPLITS.resident_bytes,
                scan_cache.SHARED_SPLITS.max_bytes, 0, 0, 0,
            ),
        ]

    def _query_history_rows(self):
        from trino_tpu import history as history_mod

        out = []
        for e in history_mod.active().entries():
            buckets = e.get("buckets") or {}
            top, top_ms = "", 0.0
            for name, ms in buckets.items():
                if float(ms or 0.0) > top_ms:
                    top, top_ms = str(name), float(ms)
            tail = e.get("critical_path_tail") or {}
            tail_str = (
                f"{tail.get('name')}@{tail.get('node')} "
                f"({float(tail.get('duration_ms') or 0.0):.1f} ms)"
                if tail else ""
            )
            out.append((
                str(e.get("query_id") or ""),
                float(e.get("ts") or 0.0),
                str(e.get("user") or ""),
                str(e.get("state") or ""),
                str(e.get("plan_digest") or ""),
                str(e.get("fingerprint") or ""),
                float(e.get("wall_ms") or 0.0),
                int(e.get("rows") or 0),
                int(e.get("peak_memory_bytes") or 0),
                int(e.get("compiles") or 0),
                str(e.get("cache_hit_tier") or ""),
                float(e.get("exchange_skew") or 0.0),
                top, top_ms, tail_str,
            ))
        return out

    def _anomaly_rows(self):
        from trino_tpu import sentry as sentry_mod

        return [
            (
                v.query_id, v.ts, v.plan_digest, v.fingerprint,
                v.wall_ms, v.baseline_p50_ms, v.ratio, v.driver,
                v.driver_delta_ms, int(v.samples), v.message,
            )
            for v in sentry_mod.active().anomalies()
        ]

    def _rows(self, table: str):
        if table == "queries":
            return self._query_rows()
        if table == "memory":
            return self._memory_rows()
        if table == "tasks":
            return self._task_rows()
        if table == "cluster_metrics":
            return self._cluster_metric_rows()
        if table == "programs":
            return self._program_rows()
        if table == "caches":
            return self._cache_rows()
        if table == "query_history":
            return self._query_history_rows()
        if table == "anomalies":
            return self._anomaly_rows()
        return self._node_rows()

    def row_count(self, schema: str, table: str) -> int:
        return len(self._rows(table))

    def scan(
        self, schema: str, table: str, columns: list[str],
        split: Split | None = None,
    ):
        ts = self.table_schema(schema, table)
        rows = self._rows(table)
        if split is not None:
            rows = rows[split.start: split.start + split.count]
        idx = {c: i for i, c in enumerate(ts.column_names)}
        out = {}
        for c in columns:
            i = idx[c]
            t = ts.column_type(c)
            if isinstance(t, T.VarcharType):
                out[c] = np.array([r[i] for r in rows], dtype=object)
            else:
                out[c] = np.array(
                    [r[i] for r in rows], dtype=t.np_dtype
                )
        return out
