"""In-memory tables connector.

The analog of the reference's trino-memory plugin
(plugin/trino-memory, ~2.3k LoC): tables live as host numpy columns,
support CREATE TABLE / CREATE TABLE AS / INSERT / DROP and scans.
Heavily used by tests, exactly like the reference uses it.
"""

from __future__ import annotations

import threading

import numpy as np

from trino_tpu import types as T
from trino_tpu.connectors.base import (
    Connector,
    Split,
    TableSchema,
    TableStats,
    WriteSink,
    compute_column_stats,
    handle_table_schema,
)

__all__ = ["MemoryConnector", "BlackholeConnector"]


class _Table:
    def __init__(self, schema: TableSchema):
        self.schema = schema
        self.columns: dict[str, np.ndarray] = {
            c: np.empty((0,), dtype=_storage_dtype(t))
            for c, t in schema.columns
        }
        self.valid: dict[str, np.ndarray | None] = {
            c: None for c, _ in schema.columns
        }
        self.n_rows = 0
        #: memoized TableStats; dropped on insert
        self.stats: TableStats | None = None
        #: monotonic write version (DML conflict detection)
        self.version = 1


def _storage_dtype(t: T.DataType):
    if isinstance(t, (T.VarcharType, T.ArrayType, T.MapType, T.RowType)):
        # variable-width values store python objects (lists / pair
        # lists / tuples; None = NULL)
        return object
    return t.np_dtype


class MemoryConnector(Connector):
    def __init__(self):
        self._schemas: dict[str, dict[str, _Table]] = {"default": {}}
        self._lock = threading.Lock()

    # ---- metadata --------------------------------------------------------

    def list_schemas(self) -> list[str]:
        return list(self._schemas)

    def list_tables(self, schema: str) -> list[str]:
        return list(self._schemas.get(schema, {}))

    def table_schema(self, schema: str, table: str) -> TableSchema:
        return self._table(schema, table).schema

    def row_count(self, schema: str, table: str) -> int:
        return self._table(schema, table).n_rows

    def table_stats(self, schema: str, table: str) -> TableStats:
        """Exact stats computed on demand and memoized per table
        version (planning hits this several times per query; a
        recompute per call would sort every column each time) —
        invalidated on insert, like the reference's
        MemoryMetadata.getTableStatistics."""
        t = self._table(schema, table)
        with self._lock:
            if t.stats is None:
                cols = {
                    c: compute_column_stats(t.columns[c], t.valid[c])
                    for c, _ in t.schema.columns
                }
                t.stats = TableStats(float(t.n_rows), cols)
            return t.stats

    def _table(self, schema: str, table: str) -> _Table:
        try:
            return self._schemas[schema][table]
        except KeyError:
            raise KeyError(f"table {schema}.{table} does not exist")

    # ---- DDL / DML -------------------------------------------------------

    def create_table(self, schema: str, table: str, table_schema: TableSchema):
        with self._lock:
            tables = self._schemas.setdefault(schema, {})
            if table in tables:
                raise ValueError(f"table {schema}.{table} already exists")
            tables[table] = _Table(table_schema)

    def drop_table(self, schema: str, table: str):
        with self._lock:
            tables = self._schemas.get(schema, {})
            if table not in tables:
                raise KeyError(f"table {schema}.{table} does not exist")
            del tables[table]

    def insert(self, schema: str, table: str, columns: dict) -> int:
        t = self._table(schema, table)
        with self._lock:
            n_new = None
            for c, _typ in t.schema.columns:
                vals = columns[c]
                valid = None
                if isinstance(vals, tuple):
                    vals, valid = vals
                if t.columns[c].dtype == object:
                    # element-wise fill: np.asarray would collapse
                    # same-length nested lists into a 2-D array
                    arr = np.empty(len(vals), dtype=object)
                    for i, v in enumerate(vals):
                        arr[i] = v
                    vals = arr
                else:
                    vals = np.asarray(vals, dtype=t.columns[c].dtype)
                n_new = len(vals) if n_new is None else n_new
                t.columns[c] = np.concatenate([t.columns[c], vals])
                old_valid = t.valid[c]
                if valid is not None or old_valid is not None:
                    ov = (
                        np.ones(t.n_rows, dtype=bool)
                        if old_valid is None else old_valid
                    )
                    nv = (
                        np.ones(len(vals), dtype=bool)
                        if valid is None else np.asarray(valid, dtype=bool)
                    )
                    t.valid[c] = np.concatenate([ov, nv])
            t.n_rows += n_new or 0
            t.stats = None  # stats reflect the pre-insert version
            t.version += 1
        return n_new or 0

    # ---- distributed write (TableWriter subsystem) -----------------------

    def begin_insert(self, schema: str, table: str) -> dict:
        ts = self.table_schema(schema, table)  # raises if missing
        return {
            "schema": schema, "table": table, "mode": "insert",
            "columns": [[c, str(t)] for c, t in ts.columns],
            "partition_by": [],
        }

    def begin_create(
        self, schema: str, table: str, table_schema: TableSchema,
        partition_by=None, properties=None,
    ) -> dict:
        if partition_by:
            raise ValueError(
                "memory connector does not support partitioned tables"
            )
        return {
            "schema": schema, "table": table, "mode": "create",
            "columns": [[c, str(t)] for c, t in table_schema.columns],
            "partition_by": [],
        }

    def write_sink(self, handle: dict, ctx: dict | None = None) -> WriteSink:
        return _MemorySink(handle)

    def finish_write(
        self, handle: dict, fragments: list[str], token: str = "",
    ) -> int:
        """Apply the winning fragments as ONE insert under the table
        lock — the transactional swap: readers see either none or all
        of the write. Idempotent in ``token`` (a replayed commit after
        a coordinator crash observes the recorded row count)."""
        import json

        ts = handle_table_schema(handle)
        schema, table = handle["schema"], handle["table"]
        with self._lock:
            applied = getattr(self, "_applied_tokens", None)
            if applied is None:
                applied = self._applied_tokens = {}
            key = (schema, table, token)
            if token and key in applied:
                return applied[key]
        if handle["mode"] == "create":
            try:
                self.create_table(schema, table, ts)
            except ValueError:
                # replayed create whose token record was lost with a
                # restarted connector process: tolerate the existing
                # table only when its schema matches the handle
                if self.table_schema(schema, table).columns != ts.columns:
                    raise
        cols: dict[str, list] = {c: [] for c, _ in ts.columns}
        vflags: dict[str, list] = {c: [] for c, _ in ts.columns}
        total = 0
        for frag in fragments:
            d = json.loads(frag)
            total += int(d["rows"])
            for (c, t) in ts.columns:
                vals, valid = d["columns"][c]
                typ = ts.column_type(c)
                cols[c].extend(_restore(v, typ) for v in vals)
                vflags[c].extend(
                    [True] * len(vals) if valid is None else valid
                )
        payload = {}
        for c, t in ts.columns:
            valid = np.asarray(vflags[c], dtype=bool)
            if _storage_dtype(t) == object:
                payload[c] = (cols[c], None if valid.all() else valid)
            else:
                payload[c] = (
                    np.asarray(
                        [0 if v is None else v for v in cols[c]],
                        dtype=_storage_dtype(t),
                    ),
                    None if valid.all() else valid,
                )
        if total:
            self.insert(schema, table, payload)
        with self._lock:
            if token:
                self._applied_tokens[key] = total
        return total

    def abort_write(self, handle: dict, token: str = ""):
        """Memory fragments hold their data inline (nothing staged in
        the connector), so abort is a no-op."""

    def table_version(self, schema: str, table: str) -> int:
        t = self._table(schema, table)
        with self._lock:
            return t.version

    def _check_version(self, t, expected_version: int):
        if expected_version and t.version != expected_version:
            raise RuntimeError(
                "concurrent modification: table changed while the DML "
                "predicate evaluated (retry the statement)"
            )

    def delete_rows(
        self, schema: str, table: str, keep, expected_version: int = 0
    ) -> int:
        t = self._table(schema, table)
        with self._lock:
            self._check_version(t, expected_version)
            keep = np.asarray(keep, dtype=bool)
            deleted = int((~keep).sum())
            for c in list(t.columns):
                t.columns[c] = t.columns[c][keep]
                if t.valid[c] is not None:
                    t.valid[c] = t.valid[c][keep]
            t.n_rows -= deleted
            t.stats = None
            t.version += 1
        return deleted

    def update_rows(
        self, schema: str, table: str, columns: dict, mask,
        expected_version: int = 0,
    ) -> int:
        t = self._table(schema, table)
        with self._lock:
            self._check_version(t, expected_version)
            mask = np.asarray(mask, dtype=bool)
            for c, raw in columns.items():
                vals, valid = raw if isinstance(raw, tuple) else (raw, None)
                cur = t.columns[c].copy()
                cur[mask] = np.asarray(
                    vals, dtype=t.columns[c].dtype
                )[mask]
                t.columns[c] = cur
                new_valid = (
                    np.ones(len(cur), dtype=bool)
                    if valid is None else np.asarray(valid, dtype=bool)
                )
                old_valid = t.valid[c]
                if old_valid is None and not new_valid[mask].all():
                    old_valid = np.ones(len(cur), dtype=bool)
                if old_valid is not None:
                    ov = old_valid.copy()
                    ov[mask] = new_valid[mask]
                    t.valid[c] = ov
            t.stats = None
            t.version += 1
        return int(mask.sum())

    # ---- scan ------------------------------------------------------------

    def scan(
        self, schema: str, table: str, columns: list[str],
        split: Split | None = None,
    ):
        t = self._table(schema, table)
        out = {}
        for c in columns:
            vals = t.columns[c]
            valid = t.valid[c]
            if split is not None:
                vals = vals[split.start:split.start + split.count]
                valid = None if valid is None else valid[
                    split.start:split.start + split.count
                ]
            out[c] = vals if valid is None else (vals, valid)
        return out


class _MemorySink(WriteSink):
    """Memory write sink: fragments CARRY the row data (JSON storage
    lists). Nothing touches the target table until finish_write — a
    losing speculated attempt's fragments simply evaporate with its
    spool partition."""

    def __init__(self, handle: dict):
        super().__init__(handle)
        self._cols: dict[str, list] = {
            c: [] for c, _t in handle["columns"]
        }
        self._valid: dict[str, list] = {
            c: [] for c, _t in handle["columns"]
        }

    def append(self, columns: dict, n_rows: int):
        for c, _t in self.handle["columns"]:
            vals, valid = columns[c]
            pyvals = (
                vals.tolist() if isinstance(vals, np.ndarray) else list(vals)
            )
            self._cols[c].extend(_jsonable(v) for v in pyvals)
            self._valid[c].extend(
                [True] * n_rows if valid is None
                else np.asarray(valid, dtype=bool).tolist()
            )
            self.buffered_bytes += _approx_bytes(vals)
        self.rows_written += n_rows

    def finish(self) -> list[str]:
        import json

        if not self.rows_written:
            return []
        payload = {
            "rows": self.rows_written,
            "columns": {
                c: [
                    self._cols[c],
                    None if all(self._valid[c]) else self._valid[c],
                ]
                for c, _t in self.handle["columns"]
            },
        }
        frag = json.dumps(payload)
        self.bytes_written = len(frag)
        self.files_written = 1
        self.buffered_bytes = 0
        return [frag]

    def abort(self):
        self._cols = {c: [] for c in self._cols}
        self._valid = {c: [] for c in self._valid}
        self.buffered_bytes = 0


def _jsonable(v):
    """One storage value -> a JSON-representable twin (numpy scalars
    to python, tuples survive as lists and are restored by type; map
    dicts flatten to [key, value] pair lists — the shape _restore
    rebuilds MapType storage from)."""
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, dict):
        return [[_jsonable(k), _jsonable(x)] for k, x in v.items()]
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v


def _restore(v, t: T.DataType):
    """Undo JSON's tuple->list flattening by column type (map pairs
    and ROW values are tuples in memory-table storage)."""
    if v is None:
        return None
    if isinstance(t, T.MapType):
        return [
            (_restore(k, t.key), _restore(x, t.value)) for k, x in v
        ]
    if isinstance(t, T.RowType):
        return tuple(
            _restore(x, ft) for x, (_fn, ft) in zip(v, t.fields)
        )
    if isinstance(t, T.ArrayType):
        return [_restore(x, t.element) for x in v]
    return v


def _approx_bytes(vals) -> int:
    if isinstance(vals, np.ndarray) and vals.dtype != object:
        return int(vals.nbytes)
    return sum(len(str(v)) + 8 for v in vals)


class BlackholeConnector(Connector):
    """Null sink/source (plugin/trino-blackhole analog): accepts any
    DDL/insert, scans are empty — for perf isolation tests."""

    def __init__(self):
        self._tables: dict[tuple[str, str], TableSchema] = {}

    def list_schemas(self) -> list[str]:
        return ["default"]

    def list_tables(self, schema: str) -> list[str]:
        return [t for (s, t) in self._tables if s == schema]

    def table_schema(self, schema: str, table: str) -> TableSchema:
        return self._tables[(schema, table)]

    def row_count(self, schema: str, table: str) -> int:
        return 0

    def create_table(self, schema: str, table: str, table_schema: TableSchema):
        self._tables[(schema, table)] = table_schema

    def drop_table(self, schema: str, table: str):
        if (schema, table) not in self._tables:
            raise KeyError(f"table {schema}.{table} does not exist")
        del self._tables[(schema, table)]

    def insert(self, schema: str, table: str, columns: dict) -> int:
        first = next(iter(columns.values()), None)
        if first is None:
            return 0
        vals = first[0] if isinstance(first, tuple) else first
        return len(vals)

    def scan(self, schema: str, table: str, columns: list[str], split=None):
        ts = self._tables[(schema, table)]
        return {
            c: np.empty((0,), dtype=_storage_dtype(ts.column_type(c)))
            for c in columns
        }

    # ---- write SPI: data vanishes, counts stay honest --------------------

    def begin_insert(self, schema: str, table: str) -> dict:
        ts = self.table_schema(schema, table)
        return {
            "schema": schema, "table": table, "mode": "insert",
            "columns": [[c, str(t)] for c, t in ts.columns],
            "partition_by": [],
        }

    def begin_create(
        self, schema: str, table: str, table_schema: TableSchema,
        partition_by=None, properties=None,
    ) -> dict:
        return {
            "schema": schema, "table": table, "mode": "create",
            "columns": [[c, str(t)] for c, t in table_schema.columns],
            "partition_by": list(partition_by or []),
        }

    def write_sink(self, handle: dict, ctx: dict | None = None):
        return _BlackholeSink(handle)

    def finish_write(
        self, handle: dict, fragments: list[str], token: str = "",
    ) -> int:
        import json

        if handle["mode"] == "create" and (
            (handle["schema"], handle["table"]) not in self._tables
        ):
            self.create_table(
                handle["schema"], handle["table"],
                handle_table_schema(handle),
            )
        return sum(int(json.loads(f)["rows"]) for f in fragments)


class _BlackholeSink(WriteSink):
    def append(self, columns: dict, n_rows: int):
        self.rows_written += n_rows

    def finish(self) -> list[str]:
        import json

        return [json.dumps({"rows": self.rows_written})]
