"""In-memory tables connector.

The analog of the reference's trino-memory plugin
(plugin/trino-memory, ~2.3k LoC): tables live as host numpy columns,
support CREATE TABLE / CREATE TABLE AS / INSERT / DROP and scans.
Heavily used by tests, exactly like the reference uses it.
"""

from __future__ import annotations

import threading

import numpy as np

from trino_tpu import types as T
from trino_tpu.connectors.base import (
    Connector,
    Split,
    TableSchema,
    TableStats,
    compute_column_stats,
)

__all__ = ["MemoryConnector", "BlackholeConnector"]


class _Table:
    def __init__(self, schema: TableSchema):
        self.schema = schema
        self.columns: dict[str, np.ndarray] = {
            c: np.empty((0,), dtype=_storage_dtype(t))
            for c, t in schema.columns
        }
        self.valid: dict[str, np.ndarray | None] = {
            c: None for c, _ in schema.columns
        }
        self.n_rows = 0
        #: memoized TableStats; dropped on insert
        self.stats: TableStats | None = None
        #: monotonic write version (DML conflict detection)
        self.version = 1


def _storage_dtype(t: T.DataType):
    if isinstance(t, (T.VarcharType, T.ArrayType, T.MapType, T.RowType)):
        # variable-width values store python objects (lists / pair
        # lists / tuples; None = NULL)
        return object
    return t.np_dtype


class MemoryConnector(Connector):
    def __init__(self):
        self._schemas: dict[str, dict[str, _Table]] = {"default": {}}
        self._lock = threading.Lock()

    # ---- metadata --------------------------------------------------------

    def list_schemas(self) -> list[str]:
        return list(self._schemas)

    def list_tables(self, schema: str) -> list[str]:
        return list(self._schemas.get(schema, {}))

    def table_schema(self, schema: str, table: str) -> TableSchema:
        return self._table(schema, table).schema

    def row_count(self, schema: str, table: str) -> int:
        return self._table(schema, table).n_rows

    def table_stats(self, schema: str, table: str) -> TableStats:
        """Exact stats computed on demand and memoized per table
        version (planning hits this several times per query; a
        recompute per call would sort every column each time) —
        invalidated on insert, like the reference's
        MemoryMetadata.getTableStatistics."""
        t = self._table(schema, table)
        with self._lock:
            if t.stats is None:
                cols = {
                    c: compute_column_stats(t.columns[c], t.valid[c])
                    for c, _ in t.schema.columns
                }
                t.stats = TableStats(float(t.n_rows), cols)
            return t.stats

    def _table(self, schema: str, table: str) -> _Table:
        try:
            return self._schemas[schema][table]
        except KeyError:
            raise KeyError(f"table {schema}.{table} does not exist")

    # ---- DDL / DML -------------------------------------------------------

    def create_table(self, schema: str, table: str, table_schema: TableSchema):
        with self._lock:
            tables = self._schemas.setdefault(schema, {})
            if table in tables:
                raise ValueError(f"table {schema}.{table} already exists")
            tables[table] = _Table(table_schema)

    def drop_table(self, schema: str, table: str):
        with self._lock:
            tables = self._schemas.get(schema, {})
            if table not in tables:
                raise KeyError(f"table {schema}.{table} does not exist")
            del tables[table]

    def insert(self, schema: str, table: str, columns: dict) -> int:
        t = self._table(schema, table)
        with self._lock:
            n_new = None
            for c, _typ in t.schema.columns:
                vals = columns[c]
                valid = None
                if isinstance(vals, tuple):
                    vals, valid = vals
                if t.columns[c].dtype == object:
                    # element-wise fill: np.asarray would collapse
                    # same-length nested lists into a 2-D array
                    arr = np.empty(len(vals), dtype=object)
                    for i, v in enumerate(vals):
                        arr[i] = v
                    vals = arr
                else:
                    vals = np.asarray(vals, dtype=t.columns[c].dtype)
                n_new = len(vals) if n_new is None else n_new
                t.columns[c] = np.concatenate([t.columns[c], vals])
                old_valid = t.valid[c]
                if valid is not None or old_valid is not None:
                    ov = (
                        np.ones(t.n_rows, dtype=bool)
                        if old_valid is None else old_valid
                    )
                    nv = (
                        np.ones(len(vals), dtype=bool)
                        if valid is None else np.asarray(valid, dtype=bool)
                    )
                    t.valid[c] = np.concatenate([ov, nv])
            t.n_rows += n_new or 0
            t.stats = None  # stats reflect the pre-insert version
            t.version += 1
        return n_new or 0

    def table_version(self, schema: str, table: str) -> int:
        t = self._table(schema, table)
        with self._lock:
            return t.version

    def _check_version(self, t, expected_version: int):
        if expected_version and t.version != expected_version:
            raise RuntimeError(
                "concurrent modification: table changed while the DML "
                "predicate evaluated (retry the statement)"
            )

    def delete_rows(
        self, schema: str, table: str, keep, expected_version: int = 0
    ) -> int:
        t = self._table(schema, table)
        with self._lock:
            self._check_version(t, expected_version)
            keep = np.asarray(keep, dtype=bool)
            deleted = int((~keep).sum())
            for c in list(t.columns):
                t.columns[c] = t.columns[c][keep]
                if t.valid[c] is not None:
                    t.valid[c] = t.valid[c][keep]
            t.n_rows -= deleted
            t.stats = None
            t.version += 1
        return deleted

    def update_rows(
        self, schema: str, table: str, columns: dict, mask,
        expected_version: int = 0,
    ) -> int:
        t = self._table(schema, table)
        with self._lock:
            self._check_version(t, expected_version)
            mask = np.asarray(mask, dtype=bool)
            for c, raw in columns.items():
                vals, valid = raw if isinstance(raw, tuple) else (raw, None)
                cur = t.columns[c].copy()
                cur[mask] = np.asarray(
                    vals, dtype=t.columns[c].dtype
                )[mask]
                t.columns[c] = cur
                new_valid = (
                    np.ones(len(cur), dtype=bool)
                    if valid is None else np.asarray(valid, dtype=bool)
                )
                old_valid = t.valid[c]
                if old_valid is None and not new_valid[mask].all():
                    old_valid = np.ones(len(cur), dtype=bool)
                if old_valid is not None:
                    ov = old_valid.copy()
                    ov[mask] = new_valid[mask]
                    t.valid[c] = ov
            t.stats = None
            t.version += 1
        return int(mask.sum())

    # ---- scan ------------------------------------------------------------

    def scan(
        self, schema: str, table: str, columns: list[str],
        split: Split | None = None,
    ):
        t = self._table(schema, table)
        out = {}
        for c in columns:
            vals = t.columns[c]
            valid = t.valid[c]
            if split is not None:
                vals = vals[split.start:split.start + split.count]
                valid = None if valid is None else valid[
                    split.start:split.start + split.count
                ]
            out[c] = vals if valid is None else (vals, valid)
        return out


class BlackholeConnector(Connector):
    """Null sink/source (plugin/trino-blackhole analog): accepts any
    DDL/insert, scans are empty — for perf isolation tests."""

    def __init__(self):
        self._tables: dict[tuple[str, str], TableSchema] = {}

    def list_schemas(self) -> list[str]:
        return ["default"]

    def list_tables(self, schema: str) -> list[str]:
        return [t for (s, t) in self._tables if s == schema]

    def table_schema(self, schema: str, table: str) -> TableSchema:
        return self._tables[(schema, table)]

    def row_count(self, schema: str, table: str) -> int:
        return 0

    def create_table(self, schema: str, table: str, table_schema: TableSchema):
        self._tables[(schema, table)] = table_schema

    def drop_table(self, schema: str, table: str):
        if (schema, table) not in self._tables:
            raise KeyError(f"table {schema}.{table} does not exist")
        del self._tables[(schema, table)]

    def insert(self, schema: str, table: str, columns: dict) -> int:
        first = next(iter(columns.values()), None)
        if first is None:
            return 0
        vals = first[0] if isinstance(first, tuple) else first
        return len(vals)

    def scan(self, schema: str, table: str, columns: list[str], split=None):
        ts = self._tables[(schema, table)]
        return {
            c: np.empty((0,), dtype=_storage_dtype(ts.column_type(c)))
            for c in columns
        }
