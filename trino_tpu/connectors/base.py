"""Connector SPI.

The analog of the reference's connector SPI (SPI/connector/, 110
files): a ``Connector`` exposes metadata (tables, schemas) and a scan
path. The TPU twist: a scan yields *host numpy columns* (optionally a
row range of the table, the analog of a ConnectorSplit) which the
engine marshals to device pages; pruned columns are never produced
(projection pushdown, the analog of ConnectorMetadata.applyProjection).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from trino_tpu import types as T

__all__ = [
    "TableSchema", "Connector", "Catalog", "Split", "ColumnDomain",
    "ColumnStats", "TableStats", "compute_column_stats",
    "WriteSink", "handle_table_schema", "rows_to_columns", "to_unscaled",
]


@dataclass(frozen=True)
class TableSchema:
    name: str
    columns: list[tuple[str, T.DataType]]

    @property
    def column_names(self) -> list[str]:
        return [c for c, _ in self.columns]

    def column_type(self, name: str) -> T.DataType:
        for c, t in self.columns:
            if c == name:
                return t
        raise KeyError(name)


@dataclass(frozen=True)
class Split:
    """A row range of a table — the unit of source parallelism
    (SPI/connector/ConnectorSplit.java analog).

    ``size_bytes`` is the estimated storage footprint of the range
    (0 = unknown) so schedulers can balance by bytes, not rows.
    ``stats`` carries per-column (name, lo, hi) storage-domain bounds
    from file footers — the scheduler-side pruning surface: a consumer
    holding a ColumnDomain may drop a split whose bounds are disjoint
    without ever opening the file."""

    table: str
    start: int
    count: int
    size_bytes: int = 0
    stats: tuple = ()

    def disjoint(self, domains: dict) -> bool:
        """True when any domain is provably disjoint with this split's
        column bounds (pruning-safe: unknown columns never prune)."""
        for col, lo, hi in self.stats:
            dom = domains.get(col)
            if dom is not None and dom.disjoint(lo, hi):
                return True
        return False


@dataclass(frozen=True)
class ColumnDomain:
    """Per-column value interval in STORAGE domain (ints for dates and
    short decimals, floats, python strings for varchar) — the
    TupleDomain-lite predicate model (SPI/predicate/TupleDomain.java,
    Domain.java collapsed to one range per column). ``None`` bounds are
    unbounded; strict flags mark open ends. Pruning-safe semantics
    only: a connector may skip storage units whose [min, max] cannot
    intersect the domain (NULLs never satisfy a comparison, so
    stats-disjoint units cannot contribute rows); the engine always
    re-applies the full filter."""

    lo: object = None
    hi: object = None
    lo_strict: bool = False
    hi_strict: bool = False

    def disjoint(self, stat_min, stat_max) -> bool:
        """True when no value in [stat_min, stat_max] can satisfy the
        domain (the rowgroup-skip test)."""
        try:
            if self.lo is not None and stat_max is not None:
                if stat_max < self.lo or (
                    self.lo_strict and stat_max == self.lo
                ):
                    return True
            if self.hi is not None and stat_min is not None:
                if stat_min > self.hi or (
                    self.hi_strict and stat_min == self.hi
                ):
                    return True
        except TypeError:
            return False  # incomparable stat types: never skip
        return False


@dataclass(frozen=True)
class ColumnStats:
    """Per-column statistics (SPI/statistics/ColumnStatistics.java
    analog). ``lo``/``hi`` are EXACT bounds in the column's storage
    order-domain (ints as-is, dates as day numbers, decimals as
    unscaled ints, doubles as floats; None for varchar) — the planner
    relies on exactness for value-range key packing, so connectors
    must only report bounds they can guarantee, and integer-domain
    bounds must be Python ints (float64 rounds beyond 2^53)."""

    ndv: float | None = None
    lo: float | int | None = None
    hi: float | int | None = None
    null_fraction: float = 0.0


@dataclass(frozen=True)
class TableStats:
    """Table statistics (SPI/statistics/TableStatistics.java analog)."""

    row_count: float
    columns: dict[str, ColumnStats] = field(default_factory=dict)


#: string columns beyond this row count estimate NDV from a sample
#: (exact np.unique over tens of millions of objects is a minutes-long
#: host sort; numeric columns stay exact — their unique is a fast
#: vectorized sort and their lo/hi must be exact anyway)
_NDV_SAMPLE_THRESHOLD = 2_000_000
_NDV_SAMPLE_SIZE = 500_000


def compute_column_stats(
    vals: np.ndarray, valid: np.ndarray | None = None
) -> ColumnStats:
    """Stats of a host column (the ANALYZE primitive). lo/hi are exact;
    NDV is exact except for very large string columns, where it uses
    the Duj1 estimator over a uniform sample (the reference's ANALYZE
    does the same kind of sampling via connector stats collection)."""
    n = len(vals)
    if valid is not None:
        vals = vals[valid]
    nulls = n - len(vals)
    if len(vals) == 0:
        return ColumnStats(ndv=0.0, null_fraction=1.0 if n else 0.0)
    is_str = vals.dtype == object or vals.dtype.kind in ("U", "S")
    if is_str and len(vals) > _NDV_SAMPLE_THRESHOLD:
        rng = np.random.default_rng(0)
        k = _NDV_SAMPLE_SIZE
        sample = vals[rng.choice(len(vals), size=k, replace=False)]
        uniq, counts = np.unique(sample, return_counts=True)
        d = len(uniq)
        f1 = int((counts == 1).sum())
        # Duj1: D = d / (1 - (N-k)/N * f1/k)
        denom = 1.0 - (len(vals) - k) / len(vals) * (f1 / k)
        ndv = min(float(d) / max(denom, 1e-9), float(len(vals)))
    else:
        ndv = float(len(np.unique(vals)))
    if is_str:
        lo = hi = None
    elif vals.dtype.kind in ("i", "u"):
        # keep integer bounds EXACT as Python ints: float64 rounds
        # beyond 2^53, and a lo rounded UP would corrupt value-range
        # key packing (distinct keys silently collapsing)
        lo, hi = int(vals.min()), int(vals.max())
    else:
        lo, hi = float(vals.min()), float(vals.max())
    return ColumnStats(
        ndv=ndv, lo=lo, hi=hi, null_fraction=nulls / n if n else 0.0
    )


class Connector:
    """Base connector: metadata + split enumeration + column scan."""

    #: False for live views (system tables): the executor must not
    #: device-cache their scans between queries
    cacheable = True

    #: True when scan() accepts ``domains`` and can prune storage units
    #: by footer statistics (the applyFilter capability flag,
    #: SPI/connector/ConnectorMetadata.java applyFilter)
    supports_domains = False

    def list_schemas(self) -> list[str]:
        return []

    def list_tables(self, schema: str) -> list[str]:
        raise NotImplementedError

    def table_schema(self, schema: str, table: str) -> TableSchema:
        raise NotImplementedError

    def row_count(self, schema: str, table: str) -> int:
        raise NotImplementedError

    def table_stats(self, schema: str, table: str) -> TableStats:
        """Statistics for the planner (ConnectorMetadata.getTableStatistics
        analog, SPI/connector/ConnectorMetadata.java). The default
        reports the row count only; connectors override to add column
        stats."""
        return TableStats(float(self.row_count(schema, table)))

    def column_stats(self, schema: str, table: str, column: str):
        """Per-column stats — the planner asks column-by-column so a
        generator-backed connector never materializes columns the query
        doesn't touch (a full table_stats over SF100 lineitem would
        generate 60M comment strings just to throw them away).
        Default: delegate to table_stats."""
        return self.table_stats(schema, table).columns.get(column)

    def splits(
        self, schema: str, table: str, target_splits: int,
        domains: dict | None = None,
    ) -> list[Split]:
        """Enumerate splits. ``domains`` (column -> ColumnDomain) lets a
        supports_domains connector prune storage units at enumeration
        time; the default row-range division ignores it."""
        n = self.row_count(schema, table)
        target_splits = max(1, target_splits)
        per = -(-n // target_splits)
        out = []
        start = 0
        while start < n:
            c = min(per, n - start)
            out.append(Split(table, start, c))
            start += c
        return out or [Split(table, 0, 0)]

    def scan(
        self, schema: str, table: str, columns: list[str], split: Split | None = None
    ) -> dict[str, np.ndarray]:
        """Produce host arrays for the requested columns (row range).

        A value may also be a ``(values, valid)`` tuple for nullable
        columns (valid=None means all valid)."""
        raise NotImplementedError

    # ---- write path (ConnectorMetadata DDL + ConnectorPageSink analog,
    # SPI/connector/ConnectorMetadata.java, ConnectorPageSink.java) ----

    def create_table(self, schema: str, table: str, table_schema: TableSchema):
        raise NotImplementedError(f"{type(self).__name__} is read-only")

    def drop_table(self, schema: str, table: str):
        raise NotImplementedError(f"{type(self).__name__} is read-only")

    def insert(self, schema: str, table: str, columns: dict) -> int:
        """Append rows; ``columns`` maps column name ->
        (values, valid|None) host arrays. Returns the row count."""
        raise NotImplementedError(f"{type(self).__name__} is read-only")

    # ---- distributed write (TableWriter subsystem) -------------------

    def begin_insert(self, schema: str, table: str) -> dict:
        """Validate an INSERT target and return a JSON-safe write
        handle (ConnectorMetadata.beginInsert analog). MUST be free of
        side effects: the plan holding the handle may be replanned,
        speculated, or retried; all mutation happens in finish_write.

        Handle shape (shared across connectors; individual connectors
        may add keys): ``{"schema", "table", "mode": "insert",
        "columns": [[name, type_str], ...], "partition_by": [...]}``.
        The analyzer adds ``"catalog"`` after the call."""
        raise NotImplementedError(f"{type(self).__name__} is read-only")

    def begin_create(
        self, schema: str, table: str, table_schema: TableSchema,
        partition_by: list[str] | None = None,
        properties: dict | None = None,
    ) -> dict:
        """Validate a CTAS target and return a write handle with
        ``"mode": "create"`` (ConnectorMetadata.beginCreateTable
        analog). Side-effect free like begin_insert — the table only
        comes into existence at finish_write."""
        raise NotImplementedError(f"{type(self).__name__} is read-only")

    def write_sink(self, handle: dict, ctx: dict | None = None) -> "WriteSink":
        """Open a per-task sink for the handle (ConnectorPageSink
        analog). ``ctx`` carries the writing task's identity
        ``{"epoch", "task", "attempt"}`` so staged artifacts of
        distinct (speculated) attempts never collide."""
        raise NotImplementedError(f"{type(self).__name__} is read-only")

    def finish_write(
        self, handle: dict, fragments: list[str], token: str = "",
    ) -> int:
        """Commit the fragments of the winning writer attempts in one
        atomic step (ConnectorMetadata.finishInsert/finishCreateTable
        analog). ``token`` identifies the committing query epoch;
        implementations MUST be idempotent in it — a coordinator that
        crashed between commit and acknowledgment replays the same
        token and must observe the already-committed result, not a
        double apply. Returns total rows written."""
        raise NotImplementedError(f"{type(self).__name__} is read-only")

    def abort_write(self, handle: dict, token: str = ""):
        """Discard staged artifacts of a dead epoch (QUERY-tier retry
        or terminal failure). Best-effort; never raises."""

    def table_version(self, schema: str, table: str) -> int:
        """Monotonic write version (0 = versioning unsupported): DML
        reads it before evaluating its row mask and passes it back as
        ``expected_version`` so a concurrent write turns into a loud
        conflict instead of a misaligned positional mask."""
        return 0

    def delete_rows(
        self, schema: str, table: str, keep, expected_version: int = 0
    ) -> int:
        """Row-level DELETE: keep[i] marks surviving rows (table
        order). Returns deleted count (MergeWriterOperator analog)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support DELETE"
        )

    def update_rows(
        self, schema: str, table: str, columns: dict, mask,
        expected_version: int = 0,
    ) -> int:
        """Row-level UPDATE: overwrite ``columns`` (name ->
        (values, valid|None), full-length in table order) where
        mask[i]. Returns updated count."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support UPDATE"
        )


class WriteSink:
    """Per-task write sink (SPI/connector/ConnectorPageSink.java
    analog): the TableWriter operator appends host storage columns,
    then calls finish() exactly once to seal the task's output into
    *fragments* — opaque JSON strings that ride the exchange fabric to
    TableFinish, which hands the winning attempts' fragment set to
    ``Connector.finish_write``. Nothing a sink does is visible to
    readers until that commit.

    ``buffered_bytes`` is the sink's current host-memory footprint;
    the operator accounts its deltas against the task MemoryContext so
    buffered writes obey query_max_memory_per_node."""

    def __init__(self, handle: dict):
        self.handle = handle
        self.rows_written = 0
        self.bytes_written = 0
        self.files_written = 0
        self.buffered_bytes = 0

    def append(self, columns: dict, n_rows: int):
        """Buffer one page; ``columns`` maps column name ->
        (values, valid|None) host storage arrays, in handle order."""
        raise NotImplementedError

    def finish(self) -> list[str]:
        """Seal the sink: flush + fsync buffered data, return the
        fragment manifest (list of JSON strings)."""
        raise NotImplementedError

    def abort(self):
        """Drop buffered state (task failed mid-write). Best-effort."""
        self.buffered_bytes = 0


def handle_table_schema(handle: dict) -> TableSchema:
    """Reconstruct the target TableSchema from a write handle's
    JSON-safe ``columns`` list."""
    return TableSchema(
        handle["table"],
        [(c, T.type_from_name(t)) for c, t in handle["columns"]],
    )


@dataclass
class Catalog:
    name: str
    connector: Connector
    properties: dict = field(default_factory=dict)


# ---- host storage codec ----------------------------------------------------
# Python result rows -> the storage-form (values, valid) columns every
# write surface shares: the memory connector's insert, both WriteSink
# implementations, and the engine's host-side VALUES path. Lives here
# (not engine.py) so exec/write.py can use it without a circular import.

def rows_to_columns(ts: TableSchema, names: list[str], rows: list) -> dict:
    """Python result rows -> host storage columns (values, valid)."""
    out = {}
    for i, (c, t) in enumerate(zip(names, [ts.column_type(n) for n in names])):
        raw = [r[i] for r in rows]
        valid = np.array([v is not None for v in raw], dtype=bool)
        if isinstance(t, T.ArrayType):
            vals = np.empty(len(raw), dtype=object)
            for j, v in enumerate(raw):
                vals[j] = None if v is None else [
                    _elem_storage(x, t.element) for x in v
                ]
        elif isinstance(t, T.MapType):
            vals = np.empty(len(raw), dtype=object)
            for j, v in enumerate(raw):
                vals[j] = None if v is None else [
                    (_elem_storage(k, t.key),
                     None if x is None else _elem_storage(x, t.value))
                    for k, x in (
                        v.items() if isinstance(v, dict) else v
                    )
                ]
        elif isinstance(t, T.RowType):
            vals = np.empty(len(raw), dtype=object)
            for j, v in enumerate(raw):
                vals[j] = None if v is None else tuple(
                    None if x is None else _elem_storage(x, ft)
                    for x, (_fn, ft) in zip(v, t.fields)
                )
        elif isinstance(t, T.VarcharType):
            vals = np.array(
                ["" if v is None else str(v) for v in raw], dtype=object
            )
        elif isinstance(t, T.DecimalType):
            vals = np.array(
                [
                    0 if v is None else to_unscaled(v, t.scale)
                    for v in raw
                ],
                dtype=np.int64,
            )
        elif isinstance(t, T.DateType):
            vals = np.array(
                [
                    0 if v is None else (
                        T.parse_date(v) if isinstance(v, str) else int(v)
                    )
                    for v in raw
                ],
                dtype=t.np_dtype,
            )
        elif isinstance(t, T.TimestampType):
            vals = np.array(
                [
                    0 if v is None else (
                        T.parse_timestamp(v) if isinstance(v, str) else int(v)
                    )
                    for v in raw
                ],
                dtype=t.np_dtype,
            )
        else:
            vals = np.array(
                [0 if v is None else v for v in raw], dtype=t.np_dtype
            )
        out[c] = (vals, None if valid.all() else valid)
    return out


def _elem_storage(v, t):
    """One array ELEMENT -> the element type's storage form (mirrors
    the scalar branches of rows_to_columns: days for dates, unscaled
    ints for decimals, micros for timestamps)."""
    if isinstance(t, T.DecimalType):
        return to_unscaled(v, t.scale)
    if isinstance(t, T.DateType):
        return T.parse_date(v) if isinstance(v, str) else int(v)
    if isinstance(t, T.TimestampType):
        return T.parse_timestamp(v) if isinstance(v, str) else int(v)
    if isinstance(t, T.VarcharType):
        return str(v)
    return v


def to_unscaled(v, scale: int) -> int:
    from decimal import Decimal

    if isinstance(v, Decimal):
        return int(v.scaleb(scale))
    if isinstance(v, int):
        return v * 10**scale
    if isinstance(v, str):
        return int(Decimal(v).scaleb(scale))
    return round(float(v) * 10**scale)
