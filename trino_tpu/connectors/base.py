"""Connector SPI.

The analog of the reference's connector SPI (SPI/connector/, 110
files): a ``Connector`` exposes metadata (tables, schemas) and a scan
path. The TPU twist: a scan yields *host numpy columns* (optionally a
row range of the table, the analog of a ConnectorSplit) which the
engine marshals to device pages; pruned columns are never produced
(projection pushdown, the analog of ConnectorMetadata.applyProjection).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from trino_tpu import types as T

__all__ = ["TableSchema", "Connector", "Catalog", "Split"]


@dataclass(frozen=True)
class TableSchema:
    name: str
    columns: list[tuple[str, T.DataType]]

    @property
    def column_names(self) -> list[str]:
        return [c for c, _ in self.columns]

    def column_type(self, name: str) -> T.DataType:
        for c, t in self.columns:
            if c == name:
                return t
        raise KeyError(name)


@dataclass(frozen=True)
class Split:
    """A row range of a table — the unit of source parallelism
    (SPI/connector/ConnectorSplit.java analog)."""

    table: str
    start: int
    count: int


class Connector:
    """Base connector: metadata + split enumeration + column scan."""

    def list_schemas(self) -> list[str]:
        return []

    def list_tables(self, schema: str) -> list[str]:
        raise NotImplementedError

    def table_schema(self, schema: str, table: str) -> TableSchema:
        raise NotImplementedError

    def row_count(self, schema: str, table: str) -> int:
        raise NotImplementedError

    def splits(self, schema: str, table: str, target_splits: int) -> list[Split]:
        n = self.row_count(schema, table)
        target_splits = max(1, target_splits)
        per = -(-n // target_splits)
        out = []
        start = 0
        while start < n:
            c = min(per, n - start)
            out.append(Split(table, start, c))
            start += c
        return out or [Split(table, 0, 0)]

    def scan(
        self, schema: str, table: str, columns: list[str], split: Split | None = None
    ) -> dict[str, np.ndarray]:
        """Produce host arrays for the requested columns (row range).

        A value may also be a ``(values, valid)`` tuple for nullable
        columns (valid=None means all valid)."""
        raise NotImplementedError

    # ---- write path (ConnectorMetadata DDL + ConnectorPageSink analog,
    # SPI/connector/ConnectorMetadata.java, ConnectorPageSink.java) ----

    def create_table(self, schema: str, table: str, table_schema: TableSchema):
        raise NotImplementedError(f"{type(self).__name__} is read-only")

    def drop_table(self, schema: str, table: str):
        raise NotImplementedError(f"{type(self).__name__} is read-only")

    def insert(self, schema: str, table: str, columns: dict) -> int:
        """Append rows; ``columns`` maps column name ->
        (values, valid|None) host arrays. Returns the row count."""
        raise NotImplementedError(f"{type(self).__name__} is read-only")


@dataclass
class Catalog:
    name: str
    connector: Connector
    properties: dict = field(default_factory=dict)
